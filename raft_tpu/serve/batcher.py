"""Dynamic micro-batcher: many callers, few program shapes.

Every search entry point in this repo is a bare library call with a static
batch shape — on TPU each new batch size is a new XLA program (cold jit).
The reference leaves request scheduling entirely to the user (its
parallelism is intra-kernel, SURVEY §5); the host-side leverage on TPU is to
aggregate concurrent single-query callers into a SMALL FIXED SET of padded
batch shapes so the serving path runs exactly the programs that were warmed
and nothing else.

Mechanics: callers :meth:`MicroBatcher.submit` row blocks and get
``concurrent.futures.Future`` objects; a background worker drains the queue
into the next power-of-two *bucket* (1, 2, 4, ... ``max_batch``), flushing
when ``max_batch`` rows are pending or the oldest request has waited
``max_wait_us``, pads the concatenated rows up to the bucket, runs the flush
function ONCE, and scatters per-row results back to the futures. The bucket
ladder bounds the jitted-program set to ``log2(max_batch)+1`` shapes per
stream — the set :func:`raft_tpu.serve.registry.IndexRegistry.publish`
pre-warms so a hot-swap never cold-jits on the serving path.

**Pipelined flushes** (``pipeline_depth > 0``): the flush worker no longer
blocks on the device — a flush function may return a :class:`PendingFlush`
(an un-materialized device result plus a ``materialize()`` hook), which the
worker hands to a bounded in-flight completion stage and immediately drains
the next batch. Under jax's async dispatch the H2D/compute/D2H of
consecutive flushes overlap; a completion worker materializes results in
FIFO order and resolves each batch's futures. Failure semantics are
per-batch on both sides of the handoff: a flush function that raises at
dispatch fails only its batch, and an in-flight flush whose
``materialize()`` raises fails exactly its batch while the stage keeps
draining. ``staging=`` (a :class:`~raft_tpu.serve.staging.StagingBuffers`)
replaces the per-flush concat/pad allocations with reusable per-bucket
buffers and starts the device upload at drain time (docs/serving.md
"Pipelined flush").

Determinism for tests: the wall clock is injected (``clock``) and the worker
thread is optional (``start=False``); :meth:`pump` performs one synchronous
drain-and-flush, so every queue policy (deadline expiry, bucket choice,
occupancy) is assertable without sleeping. The background worker is a thin
loop around the same drain path. In pipelined mode :meth:`pump` also drains
the completion stage (pass ``complete=False`` to hold flushes in flight and
:meth:`complete` them explicitly — the out-of-order test hook).
"""

from __future__ import annotations

import collections
import contextlib
import functools
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np

from ..core import tracing
from ..core.errors import expects
from ..obs import dispatch as obs_dispatch
from ..obs import metrics, requestlog
from .errors import DeadlineExceededError, ServiceClosedError

__all__ = ["MicroBatcher", "PendingFlush", "bucket_sizes", "bucket_for"]

# occupancy = valid rows / bucket rows, in (0, 1]; the ladder resolves the
# half-full-vs-full distinction that drives padding waste
_OCCUPANCY_BUCKETS = (0.0625, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


@functools.lru_cache(maxsize=None)
def _queue_depth():
    return metrics.gauge(
        "raft_tpu_serve_queue_depth",
        "rows currently queued in a serve stream (pre-batching)")


@functools.lru_cache(maxsize=None)
def _queue_wait_seconds():
    # paired with _flush_seconds below so a request's p99 decomposes into
    # queue wait vs device/compute — the attribution the autotuner
    # (raft_tpu.tune) and SLO debugging both read (ISSUE 7 satellite)
    return metrics.histogram(
        "raft_tpu_serve_queue_wait_seconds",
        "per-request queue wait from admission (submit) to flush pickup — "
        "the queue share of request latency, device time excluded",
        unit="seconds")


@functools.lru_cache(maxsize=None)
def _flush_seconds():
    return metrics.histogram(
        "raft_tpu_serve_flush_seconds",
        "flush_fn wall per flush (search + materialize) — the "
        "device/compute share of request latency", unit="seconds")


@functools.lru_cache(maxsize=None)
def _occupancy():
    return metrics.histogram(
        "raft_tpu_serve_batch_occupancy",
        "valid rows / bucket rows per flush (1.0 = no padding waste)",
        buckets=_OCCUPANCY_BUCKETS)


@functools.lru_cache(maxsize=None)
def _flush_total():
    return metrics.counter(
        "raft_tpu_serve_flush_total", "flushes per serve stream and bucket")


@functools.lru_cache(maxsize=None)
def _deadline_total():
    return metrics.counter(
        "raft_tpu_serve_deadline_expired_total",
        "requests dropped at drain (or refused at submit) past deadline")


@functools.lru_cache(maxsize=None)
def _error_total():
    return metrics.counter(
        "raft_tpu_serve_flush_errors_total",
        "flushes whose flush_fn raised (all rows in the batch fail)")


@functools.lru_cache(maxsize=None)
def _inflight_gauge():
    return metrics.gauge(
        "raft_tpu_serve_inflight_flushes",
        "flushes dispatched but not yet materialized in a serve stream's "
        "bounded completion stage (pipelined mode; bounded by "
        "pipeline_depth)")


@functools.lru_cache(maxsize=None)
def _dispatches_hist():
    # the scatter-gather fusion meter (obs/dispatch.py): instrumented
    # dispatch sites — program calls + host->device transfers on the
    # serve/stream path — executed per flush
    return metrics.histogram(
        "raft_tpu_serve_dispatches_per_flush",
        "instrumented device dispatches (program calls + transfers at the "
        "serve/stream sites) per flush — relative fusion meter, not an "
        "XLA op count",
        buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0))


def _fail(future: Future, exc: Exception) -> None:
    """set_exception tolerant of a caller's concurrent ``cancel()`` — a
    cancelled future is already resolved, and failing to fail it must not
    kill the worker thread (the rest of the batch still needs its results)."""
    try:
        future.set_exception(exc)
    except Exception:  # cancelled/already-resolved: the caller moved on
        pass


def bucket_sizes(max_batch: int) -> tuple[int, ...]:
    """The power-of-two bucket ladder ``(1, 2, 4, ..., max_batch)``."""
    expects(max_batch >= 1 and (max_batch & (max_batch - 1)) == 0,
            "max_batch must be a power of two, got %d", max_batch)
    sizes, b = [], 1
    while b <= max_batch:
        sizes.append(b)
        b *= 2
    return tuple(sizes)


def bucket_for(n_rows: int, max_batch: int) -> int:
    """Smallest ladder bucket holding ``n_rows``."""
    b = 1
    while b < n_rows:
        b *= 2
    return min(b, max_batch)


@dataclass
class _Request:
    rows: object           # (r, d) array, r >= 1
    n: int
    future: Future
    enqueued: float        # clock() at submit
    deadline: float | None  # clock()-domain absolute deadline, or None
    rid: str | None = None  # request-log id minted at admission


@dataclass
class _Drained:
    """One drain's outcome: the batch to flush + expired requests to fail."""

    batch: list = field(default_factory=list)
    rows: int = 0
    expired: list = field(default_factory=list)


class PendingFlush:
    """An un-materialized flush result — what a flush function returns to
    opt into the pipelined completion stage. ``materialize()`` blocks until
    the device work completes and returns the tuple of host result arrays
    (leading dimension = the bucket); it also owns releasing any resource
    the dispatch pinned (the service's flush holds its registry lease until
    here, so an in-flight flush still finishes on the version it leased).
    ``dispatches`` optionally carries the flush's instrumented dispatch
    count (:mod:`raft_tpu.obs.dispatch`) for the per-flush histogram.

    A flush function may return one of these in SYNC mode too (the batcher
    materializes inline, identical semantics) — which is how the service
    ships one flush implementation for both modes."""

    __slots__ = ("materialize", "dispatches")

    def __init__(self, materialize: Callable[[], Sequence],
                 dispatches: int | None = None):
        self.materialize = materialize
        self.dispatches = dispatches


@dataclass
class _InFlight:
    """One dispatched-but-unmaterialized flush in the completion stage."""

    result: object        # PendingFlush, or an already-materialized tuple
    batch: list
    q_host: object        # host view of the padded queries (canary tap)
    n_valid: int
    bucket: int
    now: float            # drain pickup instant (queue-wait boundary)
    t_flush: float        # dispatch start (flush-wall start)
    col: object           # requestlog collector to resume, or None


class MicroBatcher:
    """Thread-safe dynamic micro-batcher for one serve stream.

    ``flush_fn(padded_queries) -> tuple_of_arrays`` receives a
    ``(bucket, d)`` array (zero-padded past the valid rows) and must return
    a tuple/list of arrays whose leading dimension is ``bucket`` (e.g.
    ``(distances, ids)``); the batcher slices rows back per request. Rows
    beyond the valid count are padding — their results are discarded, so
    the flush function never needs a mask.

    One batcher serves ONE stream (one index name at one ``k``): all
    submissions must share ``d`` and dtype, otherwise they could not share
    a program shape. The service layer keys batchers by ``(name, k)``.

    ``pipeline_depth`` bounds the in-flight completion stage (0 = fully
    synchronous, the pre-pipeline behavior): a flush function returning a
    :class:`PendingFlush` is handed off un-materialized and the worker
    immediately drains the next batch; a dedicated completion worker
    (``start=True``) materializes FIFO. ``staging`` (a
    :class:`~raft_tpu.serve.staging.StagingBuffers` matching this stream's
    bucket ladder and row contract) replaces concat/pad assembly with
    reusable buffers and an early device upload.
    """

    def __init__(self, flush_fn: Callable[[object], Sequence],
                 *, max_batch: int = 64, max_wait_us: float = 1000.0,
                 clock: Callable[[], float] = time.monotonic,
                 stream: str = "default", start: bool = True,
                 on_dequeue: Callable[[int], None] | None = None,
                 request_log=None, slo=None,
                 on_result: Callable | None = None,
                 pipeline_depth: int = 0, staging=None):
        expects(max_wait_us >= 0, "max_wait_us must be >= 0")
        expects(pipeline_depth >= 0, "pipeline_depth must be >= 0")
        self._flush_fn = flush_fn
        # observability taps (all optional, all OFF the result path):
        # request_log records per-request span traces, slo feeds the
        # latency objective from the queue-wait/flush decomposition, and
        # on_result(valid_queries, valid_outputs) is the recall canary's
        # flush tap — a raising tap must never fail the batch
        self._request_log = request_log
        self._slo = slo
        self._on_result = on_result
        self.max_batch = int(max_batch)
        self.buckets = bucket_sizes(self.max_batch)
        self.max_wait_s = float(max_wait_us) * 1e-6
        self._clock = clock
        self.stream = stream
        self._cond = threading.Condition()
        self._pending: list[_Request] = []
        self._pending_rows = 0
        # rows must share one program shape: the first submission pins the
        # stream's (d, dtype) and mismatches fail at the door — a mismatch
        # reaching batch assembly would kill the worker mid-flush instead
        self._row_shape: tuple | None = None
        # notified (rows removed) whenever queued rows leave the queue —
        # the service's O(1) admission counter; must only take leaf locks
        self._on_dequeue = on_dequeue
        self._closed = False
        self.pipeline_depth = int(pipeline_depth)
        self._staging = staging
        # the bounded in-flight completion stage: dispatched flushes whose
        # device results have not materialized yet (pipelined mode only)
        self._inflight: collections.deque = collections.deque()
        self._inflight_cond = threading.Condition()
        # set (under _inflight_cond) when the flush worker's final drain is
        # done — the completion worker must outlive the PRODUCER, not just
        # the closed flag: exiting on a momentarily-empty stage while the
        # worker still drains backlog would strand it blocked on the bound
        self._flush_worker_done = False
        self._worker: threading.Thread | None = None
        self._completer: threading.Thread | None = None
        if start:
            self._worker = threading.Thread(
                target=self._run, name=f"raft-serve-{stream}", daemon=True)
            self._worker.start()
            if self.pipeline_depth > 0:
                self._completer = threading.Thread(
                    target=self._run_completions,
                    name=f"raft-serve-{stream}-complete", daemon=True)
                self._completer.start()

    # -- submission ---------------------------------------------------------
    def submit(self, rows, *, deadline: float | None = None,
               rid: str | None = None) -> Future:
        """Enqueue a ``(r, d)`` row block; returns a Future resolving to the
        per-row slice of the flush result. ``deadline`` is absolute, in the
        injected clock's domain; ``rid`` is the request-log id minted at
        admission (traced through the flush). Raises
        :class:`ServiceClosedError` after :meth:`close`; a request wider
        than ``max_batch`` is refused (split at the caller — one request
        never spans two flushes)."""
        expects(getattr(rows, "ndim", 0) == 2,
                "submit expects a (rows, d) block")
        n = int(rows.shape[0])
        expects(1 <= n <= self.max_batch,
                "request rows (%d) must be in [1, max_batch=%d]",
                n, self.max_batch)
        shape = (int(rows.shape[1]), str(rows.dtype))
        fut: Future = Future()
        now = self._clock()
        with self._cond:
            if self._closed:
                raise ServiceClosedError(f"stream {self.stream!r} is closed")
            if self._row_shape is None:
                self._row_shape = shape
            else:
                expects(shape == self._row_shape,
                        "stream %r batches (*, %d) %s rows; got (*, %d) %s",
                        self.stream, self._row_shape[0], self._row_shape[1],
                        shape[0], shape[1])
            self._pending.append(_Request(rows, n, fut, now, deadline, rid))
            self._pending_rows += n
            if metrics._enabled:
                _queue_depth().set(self._pending_rows, stream=self.stream)
            self._cond.notify()
        return fut

    def pending_rows(self) -> int:
        with self._cond:
            return self._pending_rows

    # -- draining -----------------------------------------------------------
    def _next_deadline_locked(self) -> float | None:
        dls = [r.deadline for r in self._pending if r.deadline is not None]
        return min(dls) if dls else None

    def _ready_locked(self, now: float) -> bool:
        if not self._pending:
            return False
        if self._closed or self._pending_rows >= self.max_batch:
            return True
        return now - self._pending[0].enqueued >= self.max_wait_s

    def _sweep_expired_locked(self, now: float) -> list:
        """Remove expired requests ANYWHERE in the queue — before batching,
        so they consume no device time. Expiry is decoupled from flush
        readiness on purpose: one tight-deadline client must not trigger an
        early under-full flush of its fresh queue-mates (the worker wakes
        for the earliest deadline, sweeps, and goes back to waiting)."""
        expired = [r for r in self._pending
                   if r.deadline is not None and now >= r.deadline]
        if not expired:
            return []
        self._pending = [r for r in self._pending
                         if r.deadline is None or now < r.deadline]
        removed = sum(r.n for r in expired)
        self._pending_rows = max(self._pending_rows - removed, 0)
        if metrics._enabled:
            _queue_depth().set(self._pending_rows, stream=self.stream)
            _deadline_total().inc(len(expired), stream=self.stream)
        if self._on_dequeue is not None:
            self._on_dequeue(removed)
        return expired

    def _drain_locked(self, now: float) -> _Drained:
        """Pop up to ``max_batch`` rows of whole requests (expired ones were
        already swept by the caller at the same ``now``). Caller-cancelled
        futures are dropped (cancellation is honored as long as the request
        has not been drained; once drained, ``set_running_or_notify_cancel``
        pins the future so the flush's ``set_result`` cannot race a late
        ``cancel()``)."""
        out = _Drained()
        removed_start = self._pending_rows
        while self._pending:
            r = self._pending[0]
            if out.rows + r.n > self.max_batch:
                break
            self._pending.pop(0)
            if not r.future.set_running_or_notify_cancel():
                self._pending_rows -= r.n  # cancelled while queued: drop
                continue
            out.batch.append(r)
            out.rows += r.n
        self._pending_rows = max(self._pending_rows - out.rows, 0)
        if metrics._enabled:
            _queue_depth().set(self._pending_rows, stream=self.stream)
        removed = removed_start - self._pending_rows
        if removed and self._on_dequeue is not None:
            self._on_dequeue(removed)
        return out

    def _flush_expired(self, drained: _Drained, now: float) -> None:
        for r in drained.expired:
            if self._request_log is not None:
                self._request_log.complete(
                    r.rid, stream=self.stream, rows=r.n,
                    spans={"queue": now - r.enqueued},
                    outcome="expired")
            if self._slo is not None:
                # an expired request IS a latency-bad outcome: the caller
                # waited its full deadline and got an error — a saturated
                # service shedding at the deadline must burn the latency
                # budget, not report 'ready' over the surviving minority
                self._slo.record_request(now - r.enqueued, float("inf"))
            _fail(r.future, DeadlineExceededError(
                f"deadline expired after {now - r.enqueued:.6f}s in queue "
                f"(stream {self.stream!r})"))

    def _flush(self, drained: _Drained, now: float) -> int:
        # Batch assembly and result scatter are PURE NumPy on purpose: eager
        # jnp concats/slices would be a fresh tiny XLA program per request-
        # size combination, breaking the serving path's zero-cold-compile
        # property (the warmed program set must be exactly the bucket
        # shapes). The device sees only the padded (bucket, d) array.
        self._flush_expired(drained, now)
        batch = drained.batch
        if not batch:
            return 0
        n_valid = drained.rows
        bucket = bucket_for(n_valid, self.max_batch)
        if metrics._enabled:
            # `now` is the drain/pickup instant: submit -> here is pure
            # queueing; dispatch->materialize below is the flush share, so
            # the two histograms decompose the request's latency
            for r in batch:
                _queue_wait_seconds().observe(now - r.enqueued,
                                              stream=self.stream)
            _occupancy().observe(n_valid / bucket, stream=self.stream)
            _flush_total().inc(1, stream=self.stream, bucket=bucket)
        t_flush = now  # assembly failures still get a sane flush wall
        col = None
        try:
            # assembly stays INSIDE the guard: the drained futures are
            # already pinned (set_running_or_notify_cancel), so any escape
            # here would kill the worker and strand them unresolved
            staged_dispatches = 0
            if self._staging is not None:
                # reusable per-bucket staging: rows written in place, pad
                # zeroed, device upload started at drain time (the H2D for
                # this flush overlaps the previous flush's compute). The
                # upload is a counted dispatch site, but the flush_fn's
                # counter is not open yet — meter it here and fold it into
                # this flush's dispatch observation below
                with obs_dispatch.count() as sdc:
                    q_host, q = self._staging.stage(
                        [np.asarray(r.rows) for r in batch], n_valid,
                        bucket)
                staged_dispatches = sdc.total
            else:
                q = (np.asarray(batch[0].rows) if len(batch) == 1
                     else np.concatenate([np.asarray(r.rows) for r in batch]))
                if n_valid < bucket:
                    pad = np.zeros((bucket - n_valid,) + q.shape[1:], q.dtype)
                    q = np.concatenate([q, pad])
                q_host = q
            with tracing.range("serve/flush/%d", bucket):
                t_flush = self._clock()
                # span collector: the flush fn (and anything below it —
                # registry lease, stream search) records its stage walls
                # against this batch's request ids; completion RESUMES it
                collector = (requestlog.collect()
                             if self._request_log is not None
                             else contextlib.nullcontext())
                with collector as col:
                    res = self._flush_fn(q)
        except Exception as e:
            _error_total().inc(1, stream=self.stream)
            flush_dt = self._clock() - t_flush
            for r in batch:
                _fail(r.future, e)
            spans, notes = (col.spans, col.notes) if col is not None \
                else ({}, {})
            self._observe_batch(batch, now, bucket, flush_dt, spans, notes,
                                outcome="error")
            return n_valid
        if metrics._enabled:
            d = getattr(res, "dispatches", None)
            if d is not None:
                _dispatches_hist().observe(d + staged_dispatches,
                                           stream=self.stream)
        entry = _InFlight(res, batch, q_host, n_valid, bucket, now, t_flush,
                          col)
        if self.pipeline_depth > 0 and isinstance(res, PendingFlush):
            # async dispatch: the device result rides to the bounded
            # completion stage and THIS thread immediately drains the next
            # batch — consecutive flushes overlap under jax async dispatch
            self._hand_off(entry)
        else:
            self._complete_entry(entry)
        return n_valid

    # -- completion stage ----------------------------------------------------
    def _complete_entry(self, e: _InFlight) -> None:
        """Materialize one flush and resolve exactly its batch's futures.
        Runs inline (sync mode / pump) or on the completion worker; a
        materialize that raises fails ONLY this batch — per-batch failure
        attribution survives the handoff."""
        batch = e.batch
        try:
            # resume the dispatch-time span collector so completion-side
            # spans (serve/search) land on the same batch's trace
            collector = (requestlog.collect(resume=e.col)
                         if e.col is not None else contextlib.nullcontext())
            with collector:
                res = e.result
                if isinstance(res, PendingFlush):
                    res = res.materialize()
                out = tuple(np.asarray(a) for a in res)
            flush_dt = self._clock() - e.t_flush
            if metrics._enabled:
                # flush share = dispatch -> materialized (includes any wait
                # in the completion stage): queue_wait + flush still covers
                # a request's life exactly
                _flush_seconds().observe(flush_dt, stream=self.stream)
        except Exception as exc:
            _error_total().inc(1, stream=self.stream)
            flush_dt = self._clock() - e.t_flush
            for r in batch:
                _fail(r.future, exc)
            spans, notes = (e.col.spans, e.col.notes) if e.col is not None \
                else ({}, {})
            self._observe_batch(batch, e.now, e.bucket, flush_dt, spans,
                                notes, outcome="error")
            return
        off = 0
        for r in batch:
            r.future.set_result(tuple(a[off:off + r.n] for a in out))
            off += r.n
        # observability taps run AFTER the futures resolve: the request
        # log / SLO loops and the canary's per-row sampling must never add
        # to any caller's observed latency
        spans, notes = (e.col.spans, e.col.notes) if e.col is not None \
            else ({}, {})
        self._observe_batch(batch, e.now, e.bucket, flush_dt, spans, notes,
                            outcome="ok")
        if self._on_result is not None:
            try:
                # the staging host view stays valid through completion (the
                # buffer rotation covers the in-flight window) and the
                # canary copies the rows it keeps
                self._on_result(e.q_host[:e.n_valid],
                                tuple(a[:e.n_valid] for a in out))
            except Exception:  # a canary tap must never fail the batch
                pass

    def _set_inflight_gauge(self, n: int) -> None:
        if metrics._enabled:
            _inflight_gauge().set(n, stream=self.stream)

    def _hand_off(self, entry: _InFlight) -> None:
        """Queue one dispatched flush for completion, enforcing the bound:
        with a live completion worker the flush worker BLOCKS here when
        ``pipeline_depth`` flushes are in flight (backpressure keeps the
        device queue bounded); without one (pump-driven tests) the oldest
        entry completes inline to preserve the bound deterministically."""
        to_complete = []
        with self._inflight_cond:
            if self._completer is not None:
                # the bound holds even while closing: the shutdown drain
                # flushes the backlog through this same path, and an
                # unbounded stage would outrun the staging-buffer
                # rotation (sized depth+2). Blocking stays live — the
                # completion worker only exits once the stage is empty,
                # so it keeps popping while anything is in flight
                while len(self._inflight) >= self.pipeline_depth:
                    self._inflight_cond.wait()
            else:
                while len(self._inflight) >= self.pipeline_depth:
                    to_complete.append(self._inflight.popleft())
            self._inflight.append(entry)
            n = len(self._inflight)
            self._inflight_cond.notify_all()
        self._set_inflight_gauge(n)
        for e in to_complete:
            self._complete_entry(e)

    def complete(self, max_n: int | None = None) -> int:
        """Materialize up to ``max_n`` in-flight flushes inline, oldest
        first (all of them when ``None``); returns how many completed. The
        deterministic test/drain hook for pipelined mode — with running
        workers the completion thread does this continuously."""
        done = 0
        while max_n is None or done < max_n:
            with self._inflight_cond:
                if not self._inflight:
                    break
                e = self._inflight.popleft()
                n = len(self._inflight)
                self._inflight_cond.notify_all()
            self._set_inflight_gauge(n)
            self._complete_entry(e)
            done += 1
        return done

    def inflight(self) -> int:
        with self._inflight_cond:
            return len(self._inflight)

    def _run_completions(self) -> None:
        while True:
            with self._inflight_cond:
                # exit requires closed AND the flush worker finished its
                # final drain: a momentarily-empty stage mid-shutdown does
                # not mean the producer is done, and leaving early would
                # strand it blocked on the in-flight bound
                while not self._inflight and not (self._closed
                                                  and self._flush_worker_done):
                    self._inflight_cond.wait()
                if not self._inflight:
                    return  # closed and the producer drained
                e = self._inflight.popleft()
                n = len(self._inflight)
                self._inflight_cond.notify_all()
            self._set_inflight_gauge(n)
            try:
                self._complete_entry(e)
            except BaseException:  # pragma: no cover - _complete_entry
                pass  # already guards; the completion worker must not die

    def _observe_batch(self, batch, now: float, bucket: int, flush_dt: float,
                       spans: dict, notes: dict, outcome: str) -> None:
        """Per-request observability after one flush: the request-log trace
        (queue span per request + the batch's shared flush/stage spans) and
        the SLO latency objective (queue wait + flush wall vs the bound; a
        failed flush counts as latency-bad — the caller got an error after
        waiting)."""
        if self._request_log is None and self._slo is None:
            return
        for r in batch:
            wait = now - r.enqueued
            if self._request_log is not None:
                self._request_log.complete(
                    r.rid, stream=self.stream, rows=r.n, bucket=bucket,
                    spans={"queue": wait, "flush": flush_dt, **spans},
                    notes=notes, outcome=outcome)
            if self._slo is not None:
                self._slo.record_request(
                    wait, flush_dt if outcome == "ok" else float("inf"))

    def pump(self, *, force: bool = False, complete: bool = True) -> int:
        """Synchronously sweep expired requests, then drain-and-flush once if
        the flush condition holds; returns rows flushed (0 when nothing
        flushed — pass ``force=True`` to flush regardless, e.g. when
        draining at shutdown). This is the deterministic test/drain entry;
        the worker thread uses the same sweep/drain path. In pipelined mode
        the completion stage is drained afterwards so a pumped flush's
        futures are resolved on return; ``complete=False`` leaves flushes
        in flight (drive them with :meth:`complete` — the out-of-order
        completion test hook). With a live completion worker that thread
        owns completion and ``complete`` is ignored."""
        now = self._clock()
        with self._cond:
            expired = self._sweep_expired_locked(now)
            drained = (self._drain_locked(now)
                       if force or self._ready_locked(now) else _Drained())
            drained.expired = expired
        n = self._flush(drained, now)
        if complete and self._completer is None:
            self.complete()
        return n

    # -- worker -------------------------------------------------------------
    def _run(self) -> None:
        try:
            self._run_loop()
        finally:
            # however this thread exits (clean drain or an escape), the
            # completion worker may now stop once the stage empties
            with self._inflight_cond:
                self._flush_worker_done = True
                self._inflight_cond.notify_all()

    def _run_loop(self) -> None:
        while True:
            with self._cond:
                now = self._clock()
                expired = self._sweep_expired_locked(now)
                while (not expired and not self._closed
                       and not self._ready_locked(now)):
                    if self._pending:
                        elapsed = now - self._pending[0].enqueued
                        timeout = self.max_wait_s - elapsed
                        nd = self._next_deadline_locked()
                        if nd is not None:  # wake for the earliest deadline
                            timeout = min(timeout, nd - now)
                        self._cond.wait(max(timeout, 0.0))
                    else:
                        self._cond.wait()
                    now = self._clock()
                    expired = self._sweep_expired_locked(now)
                if self._closed and not self._pending and not expired:
                    return
                drained = (self._drain_locked(now)
                           if self._closed or self._ready_locked(now)
                           else _Drained())
                drained.expired = expired
            self._flush(drained, now)

    def close(self, *, drain: bool = True, timeout_s: float = 10.0) -> None:
        """Stop the stream. ``drain=True`` flushes everything still queued
        (each remaining request completes normally); ``drain=False`` fails
        pending futures with :class:`ServiceClosedError`. Idempotent."""
        with self._cond:
            self._closed = True
            if not drain:
                pending, self._pending = self._pending, []
                cleared, self._pending_rows = self._pending_rows, 0
                if metrics._enabled:
                    _queue_depth().set(0, stream=self.stream)
                if cleared and self._on_dequeue is not None:
                    self._on_dequeue(cleared)
            self._cond.notify_all()
        with self._inflight_cond:
            # wake the completion worker's idle wait (it checks _closed);
            # a flush worker blocked on backpressure stays bounded and is
            # released flush by flush as the completer drains the stage
            self._inflight_cond.notify_all()
        if not drain:
            for r in pending:
                _fail(r.future, ServiceClosedError(
                    f"stream {self.stream!r} shut down with drain=False"))
        if self._worker is not None:
            self._worker.join(timeout_s)
            self._worker = None
        if self._completer is not None:
            # after the flush worker joined nothing appends; the completion
            # worker drains the stage and exits
            self._completer.join(timeout_s)
            self._completer = None
        if drain:
            # whether or not a worker existed, anything still queued (e.g.
            # submitted in the join race, or no-worker mode) flushes here
            while self.pump(force=True):
                pass
        # in-flight flushes complete either way: their futures are already
        # pinned running, and no future is ever left unresolved
        self.complete()
        if self._staging is not None:
            self._staging.release()
