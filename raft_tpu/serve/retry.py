"""Client-side bounded retry for transient serve refusals.

:class:`~raft_tpu.serve.errors.OverloadedError` is the service saying "not
right now" — the queue is at its bound, a delta memtable is full, a memory
budget refused admission. Those clear in milliseconds (a flush drains the
queue, a compaction folds the delta), so the right client response is a
short, bounded, jittered retry — not an immediate failure and not an
unbounded hammer. :class:`~raft_tpu.serve.errors.DeadlineExceededError` is
the opposite: the request's time budget is SPENT, and retrying it would
serve an answer nobody is waiting for — it never retries, by construction.

:func:`submit_with_retry` wraps :meth:`SearchService.submit` with exactly
that policy: exponential backoff (``base_s`` doubling up to
``max_backoff_s``) with multiplicative jitter (de-synchronizes a thundering
herd of clients that were all refused by the same full queue), a bounded
attempt count, and deadline awareness — with ``timeout_s`` set, the backoff
never sleeps past the caller's deadline, and the per-attempt submit carries
the REMAINING budget so the service's own deadline accounting stays
truthful. Worked example + when-to-retry table: docs/serving.md
("Failover & retries").

Everything is injectable (``clock``/``sleep``/``rng``) so the policy is
unit-testable without wall-clock sleeps.
"""

from __future__ import annotations

import functools
import random
import time
from typing import Callable

from ..core.errors import expects
from ..obs import metrics
from .errors import DeadlineExceededError, OverloadedError

__all__ = ["submit_with_retry"]


@functools.lru_cache(maxsize=None)
def _c_retries():
    return metrics.counter(
        "raft_tpu_serve_retries_total",
        "client-side submit retries after OverloadedError "
        "(submit_with_retry; outcome: admitted/exhausted)")


def submit_with_retry(service, name: str, queries, k: int = 10, *,
                      timeout_s: float | None = None,
                      max_attempts: int = 5, base_s: float = 0.01,
                      max_backoff_s: float = 1.0, jitter: float = 0.5,
                      clock: Callable[[], float] = time.monotonic,
                      sleep: Callable[[float], None] = time.sleep,
                      rng: random.Random | None = None):
    """Submit to ``service`` with bounded, jittered retries on
    :class:`OverloadedError` ONLY; returns the admitted request's Future.

    Backoff before attempt ``n+1`` is ``base_s * 2**n`` capped at
    ``max_backoff_s``, scaled by a uniform jitter in
    ``[1 - jitter, 1 + jitter]``. ``max_attempts`` bounds total submits;
    when the last one is still refused, ITS ``OverloadedError`` re-raises
    (the caller sees the service's own refusal, structured fields intact).
    With ``timeout_s``, every attempt submits with the remaining budget
    and a backoff that would cross the deadline raises
    :class:`DeadlineExceededError` immediately instead of sleeping into
    it. ``DeadlineExceededError`` (and every other error) propagates on
    the first occurrence — a spent deadline must never burn more queue
    slots. ``clock``/``sleep``/``rng`` are injectable for tests.

    A refusal carrying a ``retry_after_s`` attribute — the server's own
    drain estimate, set from queue depth by the net front door's
    ``Retry-After`` header (:meth:`SearchService.retry_after_hint`) —
    overrides the exponential schedule for THAT attempt: the client
    sleeps the hint scaled by a jitter in ``[1, 1 + jitter]`` (upward
    only — never less than the server asked, uncapped by
    ``max_backoff_s`` because the server's estimate beats the client's
    blind doubling). Refusals without the hint fall back to the
    exponential backoff above; the deadline check applies either way."""
    expects(max_attempts >= 1, "max_attempts must be >= 1, got %d",
            max_attempts)
    expects(0.0 <= jitter <= 1.0, "jitter must be in [0, 1], got %g", jitter)
    rng = rng or random.Random()
    deadline = None if timeout_s is None else clock() + float(timeout_s)
    for attempt in range(int(max_attempts)):
        remaining = None if deadline is None else deadline - clock()
        try:
            fut = service.submit(name, queries, k, timeout_s=remaining)
        except OverloadedError as exc:
            if attempt + 1 >= int(max_attempts):
                if metrics._enabled:
                    _c_retries().inc(1, name=name, outcome="exhausted")
                raise
            hint = getattr(exc, "retry_after_s", None)
            if hint is not None and float(hint) > 0:
                # server-supplied drain estimate: jitter upward only
                delay = float(hint) * (1.0 + jitter * rng.random())
            else:
                delay = min(base_s * (2.0 ** attempt), max_backoff_s)
                delay *= 1.0 - jitter + 2.0 * jitter * rng.random()
            if deadline is not None and clock() + delay >= deadline:
                raise DeadlineExceededError(
                    f"deadline would expire during retry backoff "
                    f"({delay * 1e3:.1f} ms sleep vs "
                    f"{max(deadline - clock(), 0) * 1e3:.1f} ms left) — "
                    "not retrying into a spent budget") from None
            sleep(delay)
            continue
        if attempt and metrics._enabled:
            _c_retries().inc(attempt, name=name, outcome="admitted")
        return fut
    raise AssertionError("unreachable")  # pragma: no cover
