"""Reusable staging buffers for the serve flush path.

Before this module, every flush allocated: the batcher ``np.concatenate``d
the drained rows into a fresh host array, padded it with another fresh
array, and handed the result to the searcher, whose internal
``jnp.asarray`` started the H2D transfer. Three allocations and a late
transfer per flush — host allocator work on the hot path and no chance
for flush N+1's transfer to begin while N computes.

:class:`StagingBuffers` replaces that with the pipeline shape ROADMAP 5
asks for:

- **Per-bucket reusable host buffers.** Each bucket shape owns
  ``pipeline_depth + 2`` preallocated host arrays used round-robin:
  assembly writes rows in place and zeroes the pad tail (no allocations),
  and the rotation guarantees a buffer is never rewritten before the
  flush that staged it has completed — which is what lets the device
  transfer (and the canary tap) read it without a defensive copy. The
  window is depth + 2, not depth + 1: the completion worker POPS an
  entry from the bounded stage before materializing it, so at the
  moment the flush worker unblocks and stages the next batch, the
  popped flush's buffer is still pending its canary tap alongside the
  ``depth`` queued ones and the one being staged.
- **Early upload.** :meth:`stage` starts the device transfer at drain
  time, before the searcher is even called; under jax's async dispatch
  the H2D for flush N+1 overlaps flush N's compute.
- **Donation across flushes** (``device=`` pinned). Each bucket keeps a
  persistent device slot; the upload runs through a per-bucket jitted
  stage program with ``donate_argnums`` on the previous slot, so XLA may
  reuse the old flush's query-buffer memory for the new upload instead of
  growing the arena — steady-state staging bytes are CONSTANT, which the
  obs.mem ledger entry for ``serve/staging`` proves (and ``stats()``
  counts the actual donation frees). Donation rides the device's in-order
  execution: the previous flush's scans were dispatched before the next
  stage, so the reuse can never overtake a read. Without a pin the upload
  is a plain uncommitted ``jax.device_put`` — REQUIRED for multi-device
  searchers (a sharded mesh's per-shard programs take committed arrays on
  their own devices, and a query committed elsewhere would conflict) —
  and old slots free by reference drop instead of donation; bytes stay
  flat either way.

The stage programs are shape-keyed like every other serve program:
:func:`warm_staging` (called from ``SearchService.publish`` under the
ordinary ``warm=True``) compiles one per bucket BEFORE the flip, so
staging adds zero cold compiles to the loaded window (asserted by the
pipeline tests and ``bench.py --serve-pipeline``).
"""

from __future__ import annotations

import threading

import numpy as np

from ..core.errors import expects
from ..obs import dispatch as obs_dispatch
from ..obs import mem as obs_mem

__all__ = ["StagingBuffers", "warm_staging"]


def _stage_fns():
    # donated refill (pinned mode): the old slot is an OPERAND (the select
    # is degenerate but keeps the donated buffer aliasable as the output —
    # an identity body lets XLA pass the upload through and leaves the
    # donation unused), so XLA reuses its memory for the staged output.
    # The program itself now lives in core.chunked (the out-of-core build
    # stager stages through the SAME donated identity), this module keeps
    # its historical name for the serve-side callers.
    from ..core.chunked import stage_fns

    return stage_fns()


class StagingBuffers:
    """Per-bucket double-buffered staging for one serve stream (see module
    docstring). ``buckets`` is the stream's batch ladder, ``dim``/``dtype``
    the stream row contract, ``depth`` the pipeline depth the buffer
    rotation must cover, ``device`` the optional staging pin (enables
    donation; must be None for multi-device searchers)."""

    def __init__(self, buckets, dim: int, dtype: str, *, depth: int = 2,
                 device=None, stream: str = "default"):
        expects(int(dim) >= 1, "staging dim must be >= 1")
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.device = device
        self.stream = stream
        self._n_host = max(2, int(depth) + 2)
        self._lock = threading.Lock()
        # host side: n_host preallocated buffers per bucket, rotated per
        # flush — flush N's buffer is not reused until N has completed
        # (rotation length covers the bounded in-flight window PLUS the
        # entry the completion worker has popped but not finished, see
        # module docstring)
        self._host = {b: [np.zeros((b, self.dim), self.dtype)
                          for _ in range(self._n_host)]
                      for b in self.buckets}
        self._turn = {b: 0 for b in self.buckets}
        # device side: one persistent slot per bucket (pinned mode), the
        # donation target across flushes
        self._slots: dict[int, object] = {}
        self._uploads = 0
        self._donation_frees = 0
        # ledger: staging bytes are serve-owned long-lived allocations —
        # attributed so capacity planning sees them and the no-growth
        # contract is provable from /debug/mem
        host = [buf for bufs in self._host.values() for buf in bufs]
        self._mem = obs_mem.account("serve/staging", name=stream,
                                    host=host, owner=self)

    def _reaccount(self) -> None:
        if self._mem is None:
            return
        host = [buf for bufs in self._host.values() for buf in bufs]
        with self._lock:
            device = list(self._slots.values())
        obs_mem.reaccount(self._mem, host=host, device=device)

    def stage(self, blocks, n_valid: int, bucket: int):
        """Assemble ``blocks`` (a list of (r, dim) host arrays totalling
        ``n_valid`` rows) into the bucket's next staging buffer, zero the
        pad tail, and start the device upload. Returns ``(host_view,
        device_array)`` — the host view stays valid until this flush's
        completion (the rotation contract), the device array is what the
        flush function dispatches on."""
        expects(bucket in self._host,
                "bucket %d is not on the staging ladder %s", bucket,
                self.buckets)
        with self._lock:
            turn = self._turn[bucket]
            self._turn[bucket] = (turn + 1) % self._n_host
        buf = self._host[bucket][turn]
        off = 0
        for r in blocks:
            nr = len(r)
            buf[off:off + nr] = r
            off += nr
        if n_valid < bucket:
            buf[n_valid:] = 0
        dev = self._upload(bucket, buf)
        obs_dispatch.note(1)
        return buf, dev

    def _upload(self, bucket: int, buf):
        import jax

        self._uploads += 1
        if self.device is None:
            # uncommitted upload: composes with committed per-shard
            # programs (jax moves it); slots still track the latest upload
            # so accounted bytes mean the same thing in both modes
            dev = jax.device_put(buf)
            with self._lock:  # a concurrent stats() iterates _slots
                grew = bucket not in self._slots
                self._slots[bucket] = dev
            if grew:
                self._reaccount()
            return dev
        with self._lock:
            old = self._slots.get(bucket)
        if old is None:
            dev = jax.device_put(buf, self.device)
            with self._lock:
                self._slots[bucket] = dev
            self._reaccount()
            return dev
        dev = _stage_fns()(old, buf)
        if old.is_deleted():
            self._donation_frees += 1
        # same bytes, new buffer — the ledger entry's totals are unchanged,
        # so no reaccount (the no-growth contract IS the claim)
        with self._lock:
            self._slots[bucket] = dev
        return dev

    def stats(self) -> dict:
        """Staging counters for the bench row / debug: uploads, actual
        donation frees (pinned mode; 0 unpinned), and the accounted
        byte levels that must stay flat across flushes."""
        host = sum(buf.nbytes for bufs in self._host.values()
                   for buf in bufs)
        with self._lock:  # the flush worker inserts slots concurrently
            slots = list(self._slots.values())
        dev = sum(int(np.prod(s.shape)) * s.dtype.itemsize for s in slots)
        return {"uploads": self._uploads,
                "donation_frees": self._donation_frees,
                "host_bytes": int(host), "device_bytes": int(dev),
                "buckets_resident": len(slots),
                "pinned": self.device is not None}

    def release(self) -> None:
        """Drop the ledger entry and slots (stream close)."""
        if self._mem is not None:
            obs_mem.release(self._mem)
            self._mem = None
        with self._lock:
            self._slots.clear()


def warm_staging(buckets, dim: int, dtype: str, device=None,
                 searcher=None, ks=()) -> int:
    """Compile the per-bucket stage programs ahead of the hot path — the
    staging leg of the publish warm ladder. A no-op set of transfers in
    unpinned mode (``device_put`` compiles nothing); in pinned mode one
    tiny donated program per bucket shape compiles here so the first
    pipelined flush finds it hot.

    ``searcher``/``ks``: in PINNED mode the staged queries are COMMITTED
    to the staging device, and placement is part of jax's executable key
    (the sharded warm's lesson) — so the registry's uncommitted-query warm
    does NOT cover the flush path's programs. Pass the published searcher
    and its serving widths to run it once per (bucket, k) on staged
    queries, compiling exactly the executables the pipelined hot path
    dispatches. Returns the number of buckets warmed."""
    import jax

    n = 0
    dt = np.dtype(dtype)
    for b in sorted(set(int(b) for b in buckets)):
        buf = np.zeros((b, int(dim)), dt)
        if device is None:
            staged = jax.device_put(buf)
        else:
            old = jax.device_put(buf, device)
            staged = _stage_fns()(old, buf)
        if searcher is not None:
            for k in ks:
                jax.block_until_ready(jax.tree_util.tree_leaves(
                    searcher(staged, int(k)))[0])
        n += 1
    return n
