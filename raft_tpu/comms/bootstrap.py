"""Cluster bootstrap.

Re-design of the reference's two bootstrap paths — raft-dask's
NCCL-unique-id + UCX endpoint exchange over a Dask cluster
(raft_dask/common/comms.py:85-230, SURVEY.md §3.F) and mpi_comms' MPI-driven
id broadcast (comms/mpi_comms.hpp). On TPU both collapse into
``jax.distributed.initialize`` + mesh construction: the TPU runtime already
knows the pod topology, so there is no id exchange to orchestrate.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from .comms import Comms

__all__ = ["initialize", "local_mesh", "global_mesh"]


def initialize(coordinator_address: str | None = None, num_processes: int | None = None, process_id: int | None = None) -> None:
    """Multi-host bootstrap (reference analogue: Comms.init,
    raft_dask/common/comms.py:172 — NCCL id broadcast + handle injection).

    On a TPU pod slice each host calls this once before building meshes; with
    no arguments JAX auto-discovers the topology from the TPU environment.
    """
    jax.distributed.initialize(coordinator_address, num_processes, process_id)


def global_mesh(axis_names: tuple[str, ...] = ("data",), shape: tuple[int, ...] | None = None) -> Mesh:
    """Build a mesh over ALL processes' devices after :func:`initialize` —
    the multi-host analogue of raft-dask's per-worker handle injection.
    ``shape`` defaults to all devices on the first axis; heavy axes should map
    to ICI (inner/fastest-varying dimensions)."""
    devs = np.array(jax.devices())
    if shape is None:
        shape = (devs.size,) + (1,) * (len(axis_names) - 1)
    return Mesh(devs.reshape(shape), axis_names)


def local_mesh(axis: str = "data", n_devices: int | None = None) -> Comms:
    """Build a 1-D mesh over (up to) all visible devices and return its
    communicator — the single-host analogue of a raft-dask session."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Comms(Mesh(np.array(devs), (axis,)), axis)
