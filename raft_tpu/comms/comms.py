"""The Comms veneer: reference comms_t surface → XLA collectives.

Method-by-method mapping to the reference (core/comms.hpp:242-530):

| reference comms_t         | here (inside shard_map)            |
|---------------------------|------------------------------------|
| allreduce(SUM/MIN/MAX)    | allreduce / psum, pmin, pmax       |
| bcast(root)               | bcast — select root shard + psum   |
| reduce(root)              | reduce — psum, value kept at root  |
| allgather / allgatherv    | allgather (lax.all_gather)         |
| gather(v)(root)           | allgather (XLA has no rooted tree; |
|                           | rooted variants return full copy)  |
| reducescatter             | reducescatter (lax.psum_scatter)   |
| device_send/recv, sendrecv| ppermute (lax.ppermute)            |
| comm_split                | sub-axis Comms over the same mesh  |
| barrier                   | barrier — psum of a scalar 1       |
| sync_stream               | host-side block_until_ready        |
| get_rank / get_size       | rank() / size() via axis_index     |
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.errors import expects

__all__ = ["Comms", "shard_along", "replicated"]


def _payload_bytes(x) -> int:
    """Per-shard payload bytes of a collective operand — works on tracers
    (shape/dtype are known at trace time; scalars count their promoted
    size)."""
    shape = getattr(x, "shape", ())
    dtype = getattr(x, "dtype", None)
    itemsize = dtype.itemsize if dtype is not None else 4
    return int(math.prod(shape)) * itemsize


def shard_along(mesh: Mesh, axis: str, x, dim: int = 0):
    """Place ``x`` row-sharded along a mesh axis (the user-side data
    distribution step that raft-dask leaves to Dask partitioning)."""
    spec = [None] * jnp.asarray(x).ndim
    spec[dim] = axis
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def replicated(mesh: Mesh, x):
    """Place ``x`` fully replicated over the mesh."""
    return jax.device_put(x, NamedSharding(mesh, P()))


@dataclasses.dataclass(frozen=True)
class Comms:
    """Communicator bound to one mesh axis (reference: comms_t, core/comms.hpp:242).

    Collective methods must be called inside a ``shard_map`` whose mesh
    includes ``self.axis`` — the same way comms_t methods must run on the
    handle's stream. Use :meth:`shard_map` to enter that region.
    """

    mesh: Mesh
    axis: str = "data"

    def __post_init__(self):
        expects(self.axis in self.mesh.axis_names, "axis %r not in mesh %s", self.axis, self.mesh)

    # -- observability ------------------------------------------------------
    def _record(self, op: str, x) -> None:
        """Per-collective counters (docs/observability.md). Collectives run
        inside jitted shard_map programs, so this fires at TRACE time: the
        counters measure the comms volume of each newly staged program (per
        shard), not per-execution traffic — re-running a cached program adds
        nothing. That is the zero-overhead contract: nothing rides the
        executed hot path, and a program's collective footprint is exactly
        what a capacity planner needs alongside its QPS."""
        from ..obs import metrics as _m

        if not _m._enabled:
            return
        lbl = dict(op=op, axis=self.axis, size=self.size())
        _m.counter("raft_tpu_collective_calls_total",
                   "collectives staged per traced program").inc(1, **lbl)
        _m.counter("raft_tpu_collective_bytes_total",
                   "per-shard payload bytes of staged collectives",
                   unit="bytes").inc(_payload_bytes(x), **lbl)

    # -- topology ----------------------------------------------------------
    def size(self) -> int:
        """Static clique size (reference: get_size)."""
        return self.mesh.shape[self.axis]

    def rank(self):
        """Traced rank of the calling shard (reference: get_rank)."""
        return lax.axis_index(self.axis)

    def comm_split(self, axis: str) -> "Comms":
        """Sub-communicator over another mesh axis (reference: comm_split
        :329 — here sub-cliques are mesh axes, declared not negotiated)."""
        return Comms(self.mesh, axis)

    # -- collectives (inside shard_map) ------------------------------------
    def allreduce(self, x, op: str = "sum"):
        """Reference: allreduce :371 with op_t{SUM,PROD,MIN,MAX} :34."""
        self._record("allreduce", x)
        return self._allreduce(x, op)

    def _allreduce(self, x, op: str):
        if op == "sum":
            return lax.psum(x, self.axis)
        if op == "min":
            return lax.pmin(x, self.axis)
        if op == "max":
            return lax.pmax(x, self.axis)
        if op == "prod":
            # exp(psum(log|x|)) with sign and zero handled explicitly so
            # arbitrary reals reduce correctly (reference op_t::PROD).
            x = jnp.asarray(x)
            has_zero = lax.psum((x == 0).astype(jnp.int32), self.axis) > 0
            neg = lax.psum((x < 0).astype(jnp.int32), self.axis)
            sign = jnp.where(neg % 2 == 1, -1.0, 1.0)
            mag = jnp.exp(lax.psum(jnp.log(jnp.where(x == 0, 1.0, jnp.abs(x))), self.axis))
            return jnp.where(has_zero, 0.0, sign * mag).astype(x.dtype)
        from ..core.errors import fail

        fail("unknown reduction op %s", op)

    def bcast(self, x, root: int = 0):
        """Reference: bcast :391 — zero out non-root shards, sum."""
        self._record("bcast", x)
        return lax.psum(jnp.where(self.rank() == root, x, jnp.zeros_like(x)), self.axis)

    def reduce(self, x, root: int = 0, op: str = "sum"):
        """Reference: reduce :411 — XLA collectives are all-to-all by nature;
        the reduced value lands everywhere and non-root shards may ignore it."""
        self._record("reduce", x)
        return self._allreduce(x, op)

    def allgather(self, x, tiled: bool = False):
        """Reference: allgather :431 (allgatherv is the ragged variant — on
        TPU pad to the max shard size first; static shapes are the contract)."""
        self._record("allgather", x)
        return lax.all_gather(x, self.axis, tiled=tiled)

    def gather(self, x, root: int = 0, tiled: bool = False):
        """Reference: gather :451 — implemented as allgather (no rooted tree
        on ICI; root semantics are a host-side concern)."""
        self._record("gather", x)
        return lax.all_gather(x, self.axis, tiled=tiled)

    def reducescatter(self, x, op: str = "sum"):
        """Reference: reducescatter :511 → psum_scatter (rides ICI as a ring)."""
        expects(op == "sum", "reducescatter supports sum (XLA psum_scatter)")
        self._record("reducescatter", x)
        return lax.psum_scatter(x, self.axis, tiled=True)

    def ppermute(self, x, perm: Sequence[tuple[int, int]]):
        """Point-to-point pattern (reference: device_send/device_recv
        :530-570 pairs, device_sendrecv) — one lax.ppermute, the ICI-native
        form of neighbor exchange."""
        self._record("ppermute", x)
        return lax.ppermute(x, self.axis, perm)

    def shift(self, x, offset: int = 1):
        """Ring shift helper (send to rank+offset) — the common sendrecv use."""
        self._record("shift", x)
        n = self.size()
        perm = [(i, (i + offset) % n) for i in range(n)]
        return lax.ppermute(x, self.axis, perm)

    def alltoall(self, x):
        """Reference: device_multicast_sendrecv :590 generalization — XLA
        all_to_all over the leading dim (must be divisible by size())."""
        self._record("alltoall", x)
        return lax.all_to_all(x, self.axis, split_axis=0, concat_axis=0, tiled=True)

    def barrier(self):
        """Reference: barrier :620 — a collective no shard can pass alone."""
        self._record("barrier", jnp.ones((), jnp.int32))
        return lax.psum(jnp.ones((), jnp.int32), self.axis)

    # -- host-side helpers --------------------------------------------------
    def shard_map(self, fn, in_specs, out_specs, check_vma: bool = False):
        """Enter the SPMD region this communicator's collectives live in."""
        if hasattr(jax, "shard_map"):
            return jax.shard_map(
                fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_vma
            )
        # pre-0.6 jax: shard_map lives in jax.experimental and the
        # replication-check knob is spelled check_rep
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(
            fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma
        )

    def sync_stream(self, *arrays):
        """Reference: sync_stream (core/comms.hpp:290) incl. the NCCL
        async-error surface — XLA raises on a failed collective here."""
        jax.block_until_ready(arrays)
