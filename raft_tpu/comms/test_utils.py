"""Collective self-tests, runnable on any mesh.

Re-design of the reference's comms test kernels
(cpp/include/raft/comms/comms_test.hpp, detail/test.hpp:
test_collective_allreduce/broadcast/reduce/allgather/gather/gatherv/
reducescatter, test_pointToPoint_sendrecv, test_commsplit — the functions
raft-dask exposes as perform_test_comms_* (comms_utils.pyx:78-244)). Each
returns True iff every shard observed the mathematically expected value, and
runs as one small shard_map program.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .comms import Comms

__all__ = [
    "test_collective_allreduce",
    "test_collective_broadcast",
    "test_collective_reduce",
    "test_collective_allgather",
    "test_collective_reducescatter",
    "test_pointtopoint_ring",
    "test_commsplit",
    "run_all",
]


def _all_shards_ok(comms: Comms, ok_fn):
    """Run ok_fn per shard; AND the verdicts across the clique."""

    def prog():
        ok = ok_fn(comms)
        return comms.allreduce(ok.astype(jnp.int32), "min")

    out = comms.shard_map(prog, in_specs=(), out_specs=P())()
    return bool(out == 1)


def test_collective_allreduce(comms: Comms) -> bool:
    """Each rank contributes 1; everyone must see size (ref: detail/test.hpp:45)."""
    return _all_shards_ok(
        comms, lambda c: c.allreduce(jnp.ones(()), "sum") == c.size()
    )


def test_collective_broadcast(comms: Comms) -> bool:
    """Root holds its rank+42; everyone must see 42 (ref: test_collective_bcast)."""
    return _all_shards_ok(
        comms, lambda c: c.bcast(jnp.where(c.rank() == 0, 42.0, -1.0), root=0) == 42.0
    )


def test_collective_reduce(comms: Comms) -> bool:
    return _all_shards_ok(
        comms,
        lambda c: c.reduce(c.rank().astype(jnp.float32), root=0)
        == c.size() * (c.size() - 1) / 2,
    )


def test_collective_allgather(comms: Comms) -> bool:
    """Rank r contributes r; gathered vector must be 0..size-1."""

    def ok(c: Comms):
        g = c.allgather(c.rank().astype(jnp.float32)[None])
        want = jnp.arange(c.size(), dtype=jnp.float32)[:, None]
        return jnp.all(g == want)

    return _all_shards_ok(comms, ok)


def test_collective_reducescatter(comms: Comms) -> bool:
    """Each rank contributes ones(size); each shard gets back size (its slot's sum)."""

    def ok(c: Comms):
        out = c.reducescatter(jnp.ones((c.size(),)))
        return jnp.all(out == c.size())

    return _all_shards_ok(comms, ok)


def test_pointtopoint_ring(comms: Comms) -> bool:
    """Ring sendrecv: after one +1 shift every rank holds its left neighbor's
    rank (ref: test_pointToPoint_simple_send_recv)."""

    def ok(c: Comms):
        got = c.shift(c.rank().astype(jnp.float32)[None], offset=1)
        want = (c.rank() - 1) % c.size()
        return jnp.all(got == want)

    return _all_shards_ok(comms, ok)


def test_commsplit(comms: Comms, sub_axis: str) -> bool:
    """Collectives over a sub-axis only span that axis (ref: test_commsplit)."""

    def ok(c: Comms):
        sub = c.comm_split(sub_axis)
        return sub.allreduce(jnp.ones(()), "sum") == sub.size()

    return _all_shards_ok(comms, ok)


def run_all(comms: Comms) -> dict:
    """The perform_test_comms_* battery (raft-dask test_comms.py analogue)."""
    return {
        "allreduce": test_collective_allreduce(comms),
        "broadcast": test_collective_broadcast(comms),
        "reduce": test_collective_reduce(comms),
        "allgather": test_collective_allgather(comms),
        "reducescatter": test_collective_reducescatter(comms),
        "p2p_ring": test_pointtopoint_ring(comms),
    }
