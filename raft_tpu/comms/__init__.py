"""raft_tpu.comms — raft/comms (M1-M6). Under construction."""
