"""raft_tpu.comms — the communicator, TPU-native.

Re-design of the reference's raft::comms stack (cpp/include/raft/core/comms.hpp:
comms_iface :125-230 / comms_t :242; NCCL+UCX std_comms comms/std_comms.hpp:69,
MPI alt-impl comms/mpi_comms.hpp; Dask bootstrap raft_dask/common/comms.py:39).

On TPU the transport is ICI/DCN driven by XLA collectives, so the communicator
is not a handle owning sockets — it is a *naming veneer* over mesh axes:

- construction = pick a ``jax.sharding.Mesh`` + axis name(s) (the analogue of
  building an NCCL clique; ``jax.distributed.initialize()`` is the multi-host
  bootstrap, replacing the NCCL-unique-id exchange of std_comms :69-115);
- the collective *methods* (allreduce/allgather/reducescatter/ppermute/...)
  are meaningful **inside** ``shard_map`` over that mesh — each lowers to one
  XLA collective on ICI (SURVEY.md §2.2 mapping);
- ``comm_split`` = operating over a different mesh axis (XLA partitions
  collectives per axis, which is what sub-communicators exist for);
- sync/abort semantics (comms/detail/util.hpp:109-136 NCCL async-error
  polling) collapse into XLA/PJRT error propagation — a failed collective
  raises at block_until_ready.

``Comms`` carries (mesh, axis) so distributed algorithms are written against
the same vocabulary the reference documents in docs/source/using_comms.rst.
"""

from . import test_utils
from .bootstrap import initialize, local_mesh
from .comms import Comms, replicated, shard_along

__all__ = ["Comms", "shard_along", "replicated", "initialize", "local_mesh", "test_utils"]
