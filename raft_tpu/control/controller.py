"""The serving-plane controller (see package doc and docs/control.md).

Design rules, in the repo's established discipline:

- **The journal tap queues and returns.** Taps run inside the journal
  lock, so :meth:`Controller._tap` only appends the sensor event to a
  bounded deque; all actuation happens in :meth:`Controller.step` —
  driven directly by tests (injected clock, no sleeps) or by the
  background worker ``start()`` spawns for deployments, exactly the
  :class:`raft_tpu.stream.Compactor` split.
- **Every decision is evidence-logged.** Acting, skipping and failing
  each emit one ``control/*`` event whose evidence embeds the triggering
  sensor event's ``seq`` and evidence dict inline — a decision is
  replayable from the journal alone, and the ``seq`` chain
  (sensor → ``control/decision`` → outcome event) is the causal record
  the bench rows assert.
- **Bounded everywhere.** Per-action cooldowns (armed on success AND
  failure — a crashing actuator must not retry-storm), one heavy
  actuation at a time across all actions, a bounded event queue
  (overflow counts, oldest dropped), and ``dry_run=`` which logs
  decisions without acting.
- **The r5 non-transfer rule is a hard guard.** Before ANY publish the
  controller re-measures the index's shape family and refuses a decision
  whose balance class differs (:class:`NonTransferError`): cross-class
  transfer is the measured 0.31-vs-0.82 recall collapse
  (``tune.decisions`` module doc), so even a restore of the original pin
  is refused if the corpus left its class — the only safe action then is
  a fresh sweep.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from collections import deque
from typing import Callable

from ..core.errors import RaftError, expects
from ..obs import events as obs_events
from ..obs import metrics

__all__ = ["Controller", "ControlPolicy", "NonTransferError"]

# sensor kinds the tap queues; everything else passes through untouched
_SENSOR_KINDS = ("retune_advised", "reshard_advised")
_ACTIONS = ("retune", "reshard", "degrade", "restore")


class NonTransferError(RaftError):
    """A decision's balance class does not match the live index's
    measured class — applying it is the BASELINE-r5 recall collapse, so
    the controller refuses (the hard guard; see docs/control.md)."""


@functools.lru_cache(maxsize=None)
def _c_actions():
    return metrics.counter(
        "raft_tpu_control_actions_total",
        "controller decisions by action and outcome (completed/failed/"
        "skipped/dry_run) — the closed-loop serving plane's activity")


@functools.lru_cache(maxsize=None)
def _g_inflight():
    return metrics.gauge(
        "raft_tpu_control_inflight",
        "1 while the controller's single heavy-actuation slot is held "
        "(labelled by the action holding it)")


@functools.lru_cache(maxsize=None)
def _g_degraded():
    return metrics.gauge(
        "raft_tpu_control_degraded",
        "1 while a watched name serves the controller's degraded (cheap) "
        "operating point instead of its pinned decision")


@dataclasses.dataclass(frozen=True)
class ControlPolicy:
    """Bounds and thresholds for one :class:`Controller` (all times on
    the controller's injected clock).

    Cooldowns arm after an actuation COMPLETES OR FAILS (never after a
    skip) and gate the next decision for that action. ``restore_clear_s``
    is the hysteresis: latency burn must stay below ``degrade_burn`` for
    that long, continuously, before a degraded name is restored — one
    good window must not flap the operating point back into a still-hot
    serving path. ``burn_window_s=None`` consults the SLO policy's
    shortest configured window. ``min_headroom_frac`` is the device-
    budget headroom a heavy reshard must see (spillable tier mirrors
    count as reclaimable); with no budget armed the check passes."""

    retune_cooldown_s: float = 600.0
    reshard_cooldown_s: float = 900.0
    degrade_cooldown_s: float = 120.0
    restore_clear_s: float = 120.0
    burn_window_s: float | None = None
    degrade_burn: float = 1.0
    reshard_max_burn: float = 1.0
    min_headroom_frac: float = 0.10
    queue_capacity: int = 256

    def cooldown_s(self, action: str) -> float:
        return {"retune": self.retune_cooldown_s,
                "reshard": self.reshard_cooldown_s,
                "degrade": self.degrade_cooldown_s,
                "restore": self.degrade_cooldown_s}[action]


class _Target:
    """One watched serve name: everything a bounded retune needs at
    decision time, registered up front so the controller never probes at
    actuation time (``watch()`` docstring)."""

    __slots__ = ("name", "index", "queries", "dataset", "gt", "k", "ks",
                 "grid", "base_params", "repeats", "recall_target",
                 "warm_data", "decision", "degrade_params", "degraded",
                 "clear_since")

    def __init__(self, name, index, queries, dataset, gt, k, ks, grid,
                 base_params, repeats, recall_target, warm_data, decision,
                 degrade_params):
        self.name = name
        self.index = index
        self.queries = queries
        self.dataset = dataset
        self.gt = gt
        self.k = k
        self.ks = ks
        self.grid = grid
        self.base_params = base_params
        self.repeats = repeats
        self.recall_target = recall_target
        self.warm_data = warm_data
        self.decision = decision          # the live pin (Decision | None)
        self.degrade_params = degrade_params
        self.degraded = False
        self.clear_since: float | None = None


class Controller:
    """Closed-loop controller over journal sensors and mesh actuators.

    Construction wires the *capabilities*; :meth:`watch` /
    :meth:`attach_mesh` / :meth:`attach_compactor` register the targets;
    :meth:`arm` subscribes the journal tap. Tests drive :meth:`step`
    directly (injected ``clock``, no sleeps); deployments call
    :meth:`start` for the polling worker.

    ``publisher`` is anything with ``publish()`` (a
    :class:`~raft_tpu.serve.SearchService` or
    :class:`~raft_tpu.serve.IndexRegistry`); ``slo`` an
    :class:`~raft_tpu.obs.slo.SLOTracker` (burn admission + the degrade
    loop need one); ``res`` a :class:`~raft_tpu.core.Resources` whose
    ``memory_budget_bytes`` arms the headroom admission check.
    ``dry_run=True`` logs every decision with its evidence but actuates
    nothing — the recommended first deployment (docs/control.md)."""

    def __init__(self, *, publisher=None, slo=None, res=None,
                 policy: ControlPolicy = ControlPolicy(),
                 dry_run: bool = False,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "default"):
        expects(publisher is None or hasattr(publisher, "publish"),
                "publisher must expose publish() (SearchService or "
                "IndexRegistry)")
        self.name = str(name)
        self.policy = policy
        self.dry_run = bool(dry_run)
        self._publisher = publisher
        self._slo = slo
        self._res = res
        self._clock = clock
        self._lock = threading.RLock()
        self._queue: deque = deque(maxlen=int(policy.queue_capacity))
        self._dropped = 0
        self._targets: dict[str, _Target] = {}
        self._mesh = None
        self._mesh_warm_buckets = None
        self._mesh_ks = (10,)
        self._mesh_warm_data = None
        self._mesh_publish_name: str | None = None
        self._compactors: list = []
        self._cooldowns: dict[str, float] = {}
        self._inflight: str | None = None
        self._armed = False
        self._last_action: dict | None = None
        self._counts: dict[str, dict[str, int]] = {}
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None

    # -- registration --------------------------------------------------------
    def watch(self, name: str, index, queries, *, dataset=None, gt=None,
              k: int = 10, ks=None, grid: list | None = None,
              base_params=None, repeats: int = 1,
              recall_target="default", warm_data=None, decision=None,
              degrade_params: dict | None = None) -> None:
        """Register a published name for the retune and degrade loops.

        ``index`` is the plain built index serving under ``name``;
        ``queries``/``dataset``/``gt`` are the canary/corpus samples a
        bounded sweep measures against (registered NOW so no sensor is
        re-probed at decision time); ``grid`` bounds the sweep (default
        :func:`raft_tpu.tune.smoke_grid` — three arms); ``decision`` is
        the currently-pinned :class:`~raft_tpu.tune.Decision` (what a
        restore republishes); ``degrade_params`` the explicit cheap
        operating point for latency-burn degradation (default: the pin
        minus its ``refine_ratio`` epilogue)."""
        expects(self._publisher is not None,
                "watch() needs a publisher (the retune/degrade loops "
                "republish through it)")
        expects(degrade_params is None or decision is not None,
                "degrade_params needs the pinned decision for its "
                "kind/family key — pass decision= too")
        kks = (k,) if ks is None else ((ks,) if isinstance(ks, int)
                                       else tuple(ks))
        with self._lock:
            self._targets[str(name)] = _Target(
                str(name), index, queries, dataset, gt, int(k), kks,
                grid, base_params, int(repeats), recall_target, warm_data,
                decision, degrade_params)

    def attach_mesh(self, mesh, *, warm_buckets=None, ks=(10,),
                    warm_data=None, publish_name: str | None = None)\
            -> None:
        """Register the :class:`~raft_tpu.stream.ShardedMutableIndex`
        the reshard loop drives. ``warm_buckets`` (library mode) or
        ``publish_name`` (+ the controller's publisher: the registry
        warm-before-flip seam) pre-warms the successor topology's
        programs — either way the flip is compile-free to serving
        traffic (:meth:`~raft_tpu.stream.ShardedMutableIndex.reshard`)."""
        expects(hasattr(mesh, "reshard"),
                "attach_mesh needs a reshard()-capable mesh "
                "(stream.ShardedMutableIndex)")
        with self._lock:
            self._mesh = mesh
            self._mesh_warm_buckets = warm_buckets
            self._mesh_ks = (ks,) if isinstance(ks, int) else tuple(ks)
            self._mesh_warm_data = warm_data
            self._mesh_publish_name = publish_name

    def attach_compactor(self, compactor) -> None:
        """Wire the compaction-pacing hint: while latency burn crosses
        ``policy.degrade_burn``, the compactor defers non-forced folds
        (:meth:`raft_tpu.stream.Compactor.set_pacing`) instead of
        competing with the serve path at the worst moment."""
        expects(hasattr(compactor, "set_pacing"),
                "attach_compactor needs set_pacing() "
                "(stream.Compactor)")
        compactor.set_pacing(self._pacing_defer)
        with self._lock:
            self._compactors.append(compactor)

    # -- lifecycle -----------------------------------------------------------
    def arm(self) -> "Controller":
        """Subscribe the journal tap; idempotent. Returns self."""
        with self._lock:
            if not self._armed:
                obs_events.subscribe(self._tap)
                self._armed = True
        return self

    def disarm(self) -> None:
        with self._lock:
            if self._armed:
                obs_events.unsubscribe(self._tap)
                self._armed = False

    def start(self, poll_interval_s: float = 0.05) -> "Controller":
        """Arm and spawn the background worker polling :meth:`step` —
        the deployment mode; tests drive :meth:`step` directly."""
        self.arm()
        with self._lock:
            if self._worker is not None:
                return self
            self._stop.clear()
            self._worker = threading.Thread(
                target=self._run, name=f"raft-control-{self.name}",
                daemon=True)
            self._worker.start()
        return self

    def stop(self, timeout_s: float = 30.0) -> None:
        """Stop the worker (waits out an in-flight actuation) and
        disarm the tap. Idempotent."""
        self._stop.set()
        with self._lock:
            worker, self._worker = self._worker, None
        if worker is not None:
            worker.join(timeout_s)
        self.disarm()

    def _run(self) -> None:
        while not self._stop.wait(0.05):
            try:
                self.step()
            except Exception:  # pragma: no cover - never kill the worker
                pass

    # -- the tap (journal-lock context: queue and return) --------------------
    def _tap(self, ev: dict) -> None:
        if ev.get("kind") not in _SENSOR_KINDS:
            return
        with self._lock:
            if len(self._queue) == self._queue.maxlen:
                self._dropped += 1  # deque drops the oldest on append
            self._queue.append(ev)

    # -- the loop ------------------------------------------------------------
    def step(self) -> int:
        """Drain queued sensor events and run one burn-loop check;
        returns how many sensor events were handled. The deterministic
        unit tests and the bench drive this directly."""
        handled = 0
        while True:
            with self._lock:
                if not self._queue:
                    break
                ev = self._queue.popleft()
            if ev["kind"] == "retune_advised":
                self._consider_retune(ev)
            elif ev["kind"] == "reshard_advised":
                self._consider_reshard(ev)
            handled += 1
        self._poll_burn()
        return handled

    # -- shared decision plumbing -------------------------------------------
    def _trigger_evidence(self, ev: dict) -> dict:
        return {"trigger_kind": ev["kind"], "trigger_seq": ev.get("seq"),
                "trigger": dict(ev.get("evidence") or {})}

    def _count(self, action: str, outcome: str) -> None:
        with self._lock:
            per = self._counts.setdefault(action, {})
            per[outcome] = per.get(outcome, 0) + 1
        if metrics._enabled:
            _c_actions().inc(1, action=action, outcome=outcome)

    def _skip(self, action: str, name, reason: str, trigger: dict,
              detail: dict | None = None) -> None:
        self._count(action, "skipped")
        obs_events.emit(
            "control/skipped", subject=("control", name),
            evidence={"action": action, "reason": reason, **trigger,
                      **(detail or {})})

    def _admit(self, action: str, name, trigger: dict) -> bool:
        """Cooldown + single-heavy-actuation admission (shared by every
        action). True reserves nothing — the caller takes the heavy slot
        via :meth:`_heavy` after the decision event."""
        now = self._clock()
        with self._lock:
            until = self._cooldowns.get(action, 0.0)
            inflight = self._inflight
        if now < until:
            self._skip(action, name, "cooldown", trigger,
                       {"retry_after_s": round(until - now, 3)})
            return False
        if inflight is not None:
            self._skip(action, name, "inflight", trigger,
                       {"inflight": inflight})
            return False
        return True

    def _arm_cooldown(self, action: str) -> None:
        with self._lock:
            self._cooldowns[action] = (self._clock()
                                       + self.policy.cooldown_s(action))

    class _Heavy:
        def __init__(self, ctl, action):
            self._ctl, self._action = ctl, action

        def __enter__(self):
            ctl = self._ctl
            with ctl._lock:
                expects(ctl._inflight is None,
                        "heavy actuation slot already held by %r",
                        ctl._inflight)
                ctl._inflight = self._action
            if metrics._enabled:
                _g_inflight().set(1.0, action=self._action)
            return self

        def __exit__(self, *exc):
            ctl = self._ctl
            with ctl._lock:
                ctl._inflight = None
            if metrics._enabled:
                _g_inflight().set(0.0, action=self._action)

    def _heavy(self, action: str) -> "_Heavy":
        return Controller._Heavy(self, action)

    def _record_outcome(self, action: str, outcome: str, name,
                        trigger: dict, decision_seq, detail: dict,
                        error: BaseException | None = None) -> None:
        """One actuation outcome: counter + journal event + last_action
        + cooldown, atomically enough that status() never shows a
        completed action without its cooldown armed."""
        self._arm_cooldown(action)
        self._count(action, outcome)
        evidence = {"action": action, "outcome": outcome,
                    "decision_seq": decision_seq, **trigger, **detail}
        if error is not None:
            evidence["error"] = (f"{type(error).__name__}: "
                                 f"{str(error)[:200]}")
        subject = ("control", name)
        # literal kind strings: the catalogue lint pins every KINDS entry
        # to a greppable emit site
        if outcome == "failed":
            ev = obs_events.emit(
                "control/action_failed", subject=subject,
                evidence=evidence,
                message="controller %s failed for %r — %s",
                log_args=(action, name, evidence.get("error")))
        elif outcome == "degraded":
            ev = obs_events.emit("control/degraded", subject=subject,
                                 evidence=evidence)
        elif outcome == "restored":
            ev = obs_events.emit("control/restored", subject=subject,
                                 evidence=evidence)
        else:
            ev = obs_events.emit("control/action_completed",
                                 subject=subject, evidence=evidence)
        with self._lock:
            self._last_action = {
                "action": action, "outcome": outcome, "name": name,
                "at": round(self._clock(), 6),
                "seq": ev["seq"] if ev else None,
                "trigger_seq": trigger.get("trigger_seq"),
                "error": evidence.get("error")}
        if outcome == "failed":
            # bundle the incident while its evidence is still in the
            # ring; a no-op when no flight recorder is armed
            obs_events.snapshot(reason=f"control_{action}_failed")

    def _decide(self, action: str, name, trigger: dict,
                detail: dict | None = None):
        """Emit the ``control/decision`` event (the acted-on decision
        record). Returns ``(go, decision_seq)`` — ``go`` False under
        ``dry_run`` (the decision is logged, nothing actuates)."""
        ev = obs_events.emit(
            "control/decision", subject=("control", name),
            evidence={"action": action, "dry_run": self.dry_run,
                      **trigger, **(detail or {})})
        seq = ev["seq"] if ev else None
        if self.dry_run:
            self._count(action, "dry_run")
            return False, seq
        return True, seq

    # -- the r5 non-transfer hard guard --------------------------------------
    def _guard_transfer(self, decision, target: _Target) -> None:
        """Refuse any decision whose balance class differs from the
        index's measured class (see module doc). Re-measures via
        :func:`raft_tpu.tune.family_of` at decision time — the corpus
        may have drifted since the pin."""
        from ..tune import family_of

        measured = family_of(target.index, target.dataset)
        have = str(decision.family).split("-")[-1]
        want = measured.split("-")[-1]
        if have != want:
            raise NonTransferError(
                f"decision {decision.key!r} pins balance class {have!r} "
                f"but the live index measures {measured!r}: operating "
                "points never transfer across balance classes (BASELINE "
                "r5, 0.31 vs 0.82 recall) — run a fresh sweep instead")

    # -- retune --------------------------------------------------------------
    def _consider_retune(self, ev: dict) -> None:
        name = ev.get("name")
        with self._lock:
            target = self._targets.get(name)
        trigger = self._trigger_evidence(ev)
        if target is None:
            return  # not watched; another controller's (or operator's) name
        if not self._admit("retune", name, trigger):
            return
        go, seq = self._decide("retune", name, trigger)
        if not go:
            return
        try:
            with self._heavy("retune"):
                decision, report = self._retune(target, trigger, seq)
        except Exception as e:
            self._record_outcome("retune", "failed", name, trigger, seq,
                                 {}, error=e)
            return
        self._record_outcome(
            "retune", "completed", name, trigger, seq,
            {"decision_key": decision.key, "params": dict(decision.params),
             "chosen_recall": decision.evidence.get("chosen_recall"),
             "target_met": decision.evidence.get("target_met"),
             "version": report.get("version")})

    def _retune(self, target: _Target, trigger: dict, seq):
        from .. import tune

        grid = target.grid
        if grid is None:
            grid = tune.smoke_grid(tune.kind_of(target.index))
        decision = tune.sweep(
            target.index, target.queries, k=target.k,
            dataset=target.dataset, gt=target.gt,
            recall_target=target.recall_target, grid=grid,
            base_params=target.base_params, repeats=target.repeats)
        self._guard_transfer(decision, target)
        report = self._publish(target, decision, "retune", trigger, seq)
        with self._lock:
            target.decision = decision
            target.degraded = False
            target.clear_since = None
        if metrics._enabled:
            _g_degraded().set(0.0, name=target.name)
        return decision, report

    def _publish(self, target: _Target, decision, action: str,
                 trigger: dict, decision_seq) -> dict:
        """Republish ``target`` at ``decision`` through the warm-before-
        flip seam; the cause dict rides the registry's
        ``serve_published`` evidence, closing the sensor → actuation
        seq chain inside the registry's own event."""
        return self._publisher.publish(
            target.name, target.index, tuned=decision, k=target.ks,
            warm_data=target.warm_data, res=self._res,
            cause={"controller": self.name, "action": action,
                   "trigger_seq": trigger.get("trigger_seq"),
                   "decision_seq": decision_seq})

    # -- reshard -------------------------------------------------------------
    def _consider_reshard(self, ev: dict) -> None:
        with self._lock:
            mesh = self._mesh
        trigger = self._trigger_evidence(ev)
        name = ev.get("name")
        if mesh is None or name != getattr(mesh, "name", None):
            return
        advice = dict(ev.get("evidence") or {})
        target_shards = advice.get("target")
        if not target_shards or target_shards == mesh.n_shards:
            self._skip("reshard", name, "stale", trigger,
                       {"n_shards": mesh.n_shards})
            return
        if not self._admit("reshard", name, trigger):
            return
        # admission: the heavy migration must not start into a memory
        # squeeze or a latency burn — abort cleanly, evidence inline
        head = self._headroom()
        if (head is not None
                and head["headroom_frac"] + head.get("spillable_frac", 0.0)
                < self.policy.min_headroom_frac):
            self._skip("reshard", name, "headroom", trigger, head)
            return
        burn = self._burn_snapshot()
        if (burn is not None
                and burn["latency"] >= self.policy.reshard_max_burn):
            self._skip("reshard", name, "slo_burn", trigger, {"burn": burn})
            return
        detail = {"target_shards": int(target_shards),
                  "headroom": head, "burn": burn}
        go, seq = self._decide("reshard", name, trigger, detail)
        if not go:
            return
        try:
            with self._heavy("reshard"):
                rep = mesh.reshard(
                    int(target_shards),
                    publisher=(self._publisher
                               if self._mesh_publish_name else None),
                    name=self._mesh_publish_name, ks=self._mesh_ks,
                    warm_buckets=self._mesh_warm_buckets,
                    warm_data=self._mesh_warm_data, res=self._res,
                    cause={"controller": self.name, "action": "reshard",
                           "trigger_seq": trigger.get("trigger_seq"),
                           "decision_seq": seq})
        except Exception as e:
            self._record_outcome("reshard", "failed", name, trigger, seq,
                                 detail, error=e)
            return
        self._record_outcome(
            "reshard", "completed", name, trigger, seq,
            {"from": rep["from"], "to": rep["to"],
             "rows_moved": rep["rows_moved"], "epoch": rep["epoch"],
             "wall_s": rep["wall_s"]})

    # -- degrade / restore (the burn loop) -----------------------------------
    def _burn_snapshot(self) -> dict | None:
        if self._slo is None:
            return None
        return self._slo.burn_snapshot(self.policy.burn_window_s)

    def _headroom(self) -> dict | None:
        from ..obs import mem as obs_mem

        return obs_mem.headroom(self._res)

    def _pacing_defer(self) -> bool:
        """The compactor pacing hint: defer non-forced folds while
        latency burn crosses the degrade threshold."""
        burn = self._burn_snapshot()
        return (burn is not None
                and burn["latency"] >= self.policy.degrade_burn)

    def _poll_burn(self) -> None:
        burn = self._burn_snapshot()
        if burn is None:
            return
        hot = burn["latency"] >= self.policy.degrade_burn
        now = self._clock()
        with self._lock:
            targets = list(self._targets.values())
        for target in targets:
            if not target.degraded:
                if hot:
                    self._consider_degrade(target, burn)
                continue
            if hot:
                target.clear_since = None
                continue
            if target.clear_since is None:
                target.clear_since = now
                continue
            if now - target.clear_since >= self.policy.restore_clear_s:
                self._consider_restore(target, burn)

    def _degraded_decision(self, target: _Target):
        """The cheap operating point: explicit ``degrade_params`` when
        registered, else the live pin minus its exact-refine epilogue
        (``refine_ratio=1`` — the dominant serve-path cost knob). Stays
        in the pin's family: degradation is never a class transfer."""
        from ..tune import Decision

        pin = target.decision
        if target.degrade_params is not None:
            expects(pin is not None,
                    "degrade_params needs the pinned decision for its "
                    "kind/family key — pass decision= to watch()")
            params = dict(target.degrade_params)
        else:
            if pin is None or int(pin.params.get("refine_ratio", 1)) <= 1:
                return None  # nothing cheaper to fall back to
            params = {kk: v for kk, v in pin.params.items()
                      if kk != "refine_ratio"}
        return Decision(
            kind=pin.kind, dtype=pin.dtype, family=pin.family,
            params=params,
            evidence={"derived_from": pin.key, "degraded": True})

    def _consider_degrade(self, target: _Target, burn: dict) -> None:
        with self._lock:
            until = self._cooldowns.get("degrade", 0.0)
        if self._clock() < until:
            # the burn loop polls every step — while the degrade cooldown
            # is armed, return silently instead of journaling one
            # cooldown/no_cheaper_point skip per poll for the whole burn
            return
        trigger = {"trigger_kind": "slo_burn", "trigger_seq": None,
                   "trigger": {"burn": burn,
                               "threshold": self.policy.degrade_burn}}
        cheap = self._degraded_decision(target)
        if cheap is None:
            self._skip("degrade", target.name, "no_cheaper_point", trigger)
            # hold the skip from repeating every poll while the burn lasts
            self._arm_cooldown("degrade")
            return
        if not self._admit("degrade", target.name, trigger):
            return
        go, seq = self._decide("degrade", target.name, trigger,
                               {"params": dict(cheap.params)})
        if not go:
            return
        try:
            with self._heavy("degrade"):
                self._guard_transfer(cheap, target)
                self._publish(target, cheap, "degrade", trigger, seq)
        except Exception as e:
            self._record_outcome("degrade", "failed", target.name,
                                 trigger, seq, {}, error=e)
            return
        with self._lock:
            target.degraded = True
            target.clear_since = None
        if metrics._enabled:
            _g_degraded().set(1.0, name=target.name)
        self._record_outcome(
            "degrade", "degraded", target.name, trigger, seq,
            {"params": dict(cheap.params), "pinned": target.decision.key})

    def _consider_restore(self, target: _Target, burn: dict) -> None:
        trigger = {"trigger_kind": "slo_burn_cleared", "trigger_seq": None,
                   "trigger": {"burn": burn,
                               "clear_s": self.policy.restore_clear_s}}
        if not self._admit("restore", target.name, trigger):
            return
        go, seq = self._decide("restore", target.name, trigger,
                               {"pinned": target.decision.key})
        if not go:
            return
        try:
            with self._heavy("restore"):
                self._guard_transfer(target.decision, target)
                self._publish(target, target.decision, "restore", trigger,
                              seq)
        except Exception as e:
            self._record_outcome("restore", "failed", target.name,
                                 trigger, seq, {}, error=e)
            return
        with self._lock:
            target.degraded = False
            target.clear_since = None
        if metrics._enabled:
            _g_degraded().set(0.0, name=target.name)
        self._record_outcome(
            "restore", "restored", target.name, trigger, seq,
            {"pinned": target.decision.key})

    # -- observability -------------------------------------------------------
    def status(self) -> dict:
        """The /debug/control (and /healthz ``controller``) payload:
        enabled/dry-run, the in-flight actuation, last action + outcome,
        active cooldowns (seconds remaining), degraded names, queue
        depth and per-action outcome counts."""
        now = self._clock()
        with self._lock:
            cooldowns = {a: round(t - now, 3)
                         for a, t in self._cooldowns.items() if t > now}
            degraded = sorted(t.name for t in self._targets.values()
                              if t.degraded)
            return {
                "enabled": self._armed,
                "dry_run": self.dry_run,
                "inflight": self._inflight,
                "last_action": (dict(self._last_action)
                                if self._last_action else None),
                "cooldowns": cooldowns,
                "degraded": degraded,
                "targets": sorted(self._targets),
                "mesh": getattr(self._mesh, "name", None),
                "queue": len(self._queue),
                "queue_dropped": self._dropped,
                "actions": {a: dict(c) for a, c in self._counts.items()},
            }
