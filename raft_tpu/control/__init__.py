"""Closed-loop serving-plane control: sensors → bounded actuation.

The :class:`Controller` subscribes to the operations event journal
(:mod:`raft_tpu.obs.events`) and closes the loops the stack previously
left to an operator:

- ``retune_advised`` family drift → a bounded background sweep
  (:func:`raft_tpu.tune.sweep`) over canary/corpus samples, republished
  ``tuned=`` through the registry's warm-before-flip seam — recall
  recovers with zero cold compiles and no operator;
- ``reshard_advised`` topology watermarks →
  :meth:`raft_tpu.stream.ShardedMutableIndex.reshard` under a
  headroom/SLO-burn admission check, aborted cleanly when either says no;
- SLO latency burn → degrade to a cheaper pinned operating point instead
  of shedding (and pace compaction off the worst moment), restored with
  hysteresis once the burn clears.

Every decision is a ``control/*`` journal event carrying its triggering
evidence inline; the BASELINE-r5 non-transfer rule (an operating point
never crosses balance classes) is a hard guard in the controller, not a
convention. See docs/control.md.
"""

from .controller import ControlPolicy, Controller, NonTransferError

__all__ = ["Controller", "ControlPolicy", "NonTransferError"]
