"""raft_tpu.runtime — native host runtime (C++ via ctypes).

Reference analogue: the precompiled runtime layer (cpp/src + raft_runtime
headers, SURVEY.md §2.7) and the bench harness's C++ dataset IO
(cpp/bench/ann/src/common/dataset.h). See cpp/runtime.cpp.
"""

from .native import (
    available,
    bin_info,
    load_bin,
    merge_parts_host,
    read_bin_chunk,
    refine_host,
    write_bin,
    BinDataset,
)

__all__ = [
    "available",
    "bin_info",
    "load_bin",
    "read_bin_chunk",
    "write_bin",
    "refine_host",
    "merge_parts_host",
    "BinDataset",
]
