"""ctypes bindings for the native host runtime (cpp/runtime.cpp).

The library is built on demand with the repo Makefile (g++ -O3 -shared);
everything degrades gracefully to pure-numpy fallbacks when no compiler is
present, so the Python package never hard-depends on the native build —
mirroring the reference's header-only vs RAFT_COMPILE_LIBRARY duality
(cpp/CMakeLists.txt:62-70).
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import threading

import numpy as np

__all__ = [
    "available",
    "bin_info",
    "load_bin",
    "read_bin_chunk",
    "write_bin",
    "refine_host",
    "merge_parts_host",
    "BinDataset",
]

_CPP_DIR = pathlib.Path(__file__).resolve().parents[2] / "cpp"
_SO_PATH = _CPP_DIR / "libraft_tpu_rt.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_failed = False

_SUFFIX_DTYPES = {
    ".fbin": np.float32,
    ".u8bin": np.uint8,
    ".i8bin": np.int8,
    ".ibin": np.int32,
}


def _load() -> ctypes.CDLL | None:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not _SO_PATH.exists():
            try:
                subprocess.run(
                    ["make", "-s"], cwd=_CPP_DIR, check=True, capture_output=True
                )
            except (OSError, subprocess.CalledProcessError):
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(str(_SO_PATH))
        except OSError:
            _build_failed = True
            return None

        lib.rt_num_threads.restype = ctypes.c_int64
        lib.rt_bin_info.restype = ctypes.c_int
        lib.rt_bin_info.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.rt_bin_read_chunk.restype = ctypes.c_int
        lib.rt_bin_read_chunk.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.rt_bin_write.restype = ctypes.c_int
        lib.rt_bin_write.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.rt_refine_host_f32.restype = ctypes.c_int
        lib.rt_refine_host_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
        ]
        lib.rt_knn_merge_parts_f32.restype = ctypes.c_int
        lib.rt_knn_merge_parts_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native library is (or can be) built and loaded."""
    return _load() is not None


def _dtype_for(path: str):
    suffix = pathlib.Path(path).suffix
    if suffix not in _SUFFIX_DTYPES:
        raise ValueError(f"unknown big-ANN binary suffix {suffix!r} (expected one of {sorted(_SUFFIX_DTYPES)})")
    return np.dtype(_SUFFIX_DTYPES[suffix])


def bin_info(path: str) -> tuple[int, int]:
    """(n_rows, dim) of a big-ANN binary file (ref: dataset.h BinFile header)."""
    lib = _load()
    if lib is None:
        with open(path, "rb") as f:
            hdr = np.fromfile(f, np.uint32, 2)
        return int(hdr[0]), int(hdr[1])
    n = ctypes.c_int64()
    d = ctypes.c_int64()
    rc = lib.rt_bin_info(str(path).encode(), ctypes.byref(n), ctypes.byref(d))
    if rc != 0:
        raise OSError(f"rt_bin_info({path}) failed: {rc}")
    return n.value, d.value


def read_bin_chunk(path: str, row_start: int, n_rows: int) -> np.ndarray:
    """Read rows [row_start, row_start+n_rows) of a .fbin/.u8bin/.i8bin/.ibin
    file via parallel pread (native) or numpy (fallback)."""
    dtype = _dtype_for(path)
    total, dim = bin_info(path)
    n_rows = min(n_rows, total - row_start)
    if n_rows <= 0:
        return np.empty((0, dim), dtype)
    lib = _load()
    out = np.empty((n_rows, dim), dtype)
    if lib is None:
        with open(path, "rb") as f:
            f.seek(8 + row_start * dim * dtype.itemsize)
            out = np.fromfile(f, dtype, n_rows * dim).reshape(n_rows, dim)
        return out
    rc = lib.rt_bin_read_chunk(
        str(path).encode(), row_start, n_rows, dim, dtype.itemsize,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    if rc != 0:
        raise OSError(f"rt_bin_read_chunk({path}) failed: {rc}")
    return out


def load_bin(path: str) -> np.ndarray:
    """Load a whole big-ANN binary file."""
    n, _ = bin_info(path)
    return read_bin_chunk(path, 0, n)


def write_bin(path: str, data: np.ndarray) -> None:
    """Write a big-ANN binary file (header + rows) matching the suffix dtype."""
    dtype = _dtype_for(path)
    data = np.ascontiguousarray(data, dtype)
    lib = _load()
    if lib is None:
        with open(path, "wb") as f:
            np.array(data.shape, np.uint32).tofile(f)
            data.tofile(f)
        return
    rc = lib.rt_bin_write(
        str(path).encode(), data.ctypes.data_as(ctypes.c_void_p),
        data.shape[0], data.shape[1], dtype.itemsize,
    )
    if rc != 0:
        raise OSError(f"rt_bin_write({path}) failed: {rc}")


def refine_host(dataset, queries, candidates, k: int, metric: str = "sqeuclidean"):
    """Exact host-side re-rank of ANN candidates (ref: refine_host,
    neighbors/detail/refine.cuh:169). Returns (distances (m,k), ids (m,k));
    invalid candidate ids (-1) sort last with +inf distance."""
    dataset = np.ascontiguousarray(dataset, np.float32)
    queries = np.ascontiguousarray(queries, np.float32)
    candidates = np.ascontiguousarray(candidates, np.int32)
    m, k_in = candidates.shape
    if k > k_in:
        raise ValueError(f"k={k} > candidate width {k_in}")
    metric_id = {"sqeuclidean": 0, "euclidean": 0, "l2": 0, "inner_product": 1}[metric]
    lib = _load()
    if lib is not None:
        out_i = np.empty((m, k), np.int32)
        out_d = np.empty((m, k), np.float32)
        rc = lib.rt_refine_host_f32(
            dataset.ctypes.data_as(ctypes.c_void_p), dataset.shape[0], dataset.shape[1],
            queries.ctypes.data_as(ctypes.c_void_p), m,
            candidates.ctypes.data_as(ctypes.c_void_p), k_in,
            out_i.ctypes.data_as(ctypes.c_void_p),
            out_d.ctypes.data_as(ctypes.c_void_p), k, metric_id,
        )
        if rc != 0:
            raise RuntimeError(f"rt_refine_host_f32 failed: {rc}")
        return out_d, out_i
    # numpy fallback
    safe = np.clip(candidates, 0, dataset.shape[0] - 1)
    vecs = dataset[safe]  # (m, k_in, d)
    if metric_id == 1:
        scores = -np.einsum("md,mkd->mk", queries, vecs)
    else:
        diff = queries[:, None, :] - vecs
        scores = np.einsum("mkd,mkd->mk", diff, diff)
    scores = np.where(candidates >= 0, scores, np.inf)
    order = np.argsort(scores, axis=1)[:, :k]
    out_i = np.take_along_axis(candidates, order, axis=1)
    out_d = np.take_along_axis(scores, order, axis=1)
    if metric_id == 1:
        out_d = np.where(out_i >= 0, -out_d, out_d)
    return out_d.astype(np.float32), out_i


def merge_parts_host(part_dists, part_ids, k: int | None = None, select_min: bool = True):
    """Merge per-shard top-k candidate lists on the host (ref:
    knn_merge_parts, neighbors/detail/knn_merge_parts.cuh)."""
    part_dists = np.ascontiguousarray(part_dists, np.float32)
    part_ids = np.ascontiguousarray(part_ids, np.int32)
    n_parts, m, k_in = part_dists.shape
    k = k or k_in
    lib = _load()
    if lib is not None:
        out_d = np.empty((m, k), np.float32)
        out_i = np.empty((m, k), np.int32)
        rc = lib.rt_knn_merge_parts_f32(
            part_dists.ctypes.data_as(ctypes.c_void_p),
            part_ids.ctypes.data_as(ctypes.c_void_p),
            n_parts, m, k_in,
            out_d.ctypes.data_as(ctypes.c_void_p),
            out_i.ctypes.data_as(ctypes.c_void_p), k, int(select_min),
        )
        if rc != 0:
            raise RuntimeError(f"rt_knn_merge_parts_f32 failed: {rc}")
        return out_d, out_i
    flat_d = np.moveaxis(part_dists, 0, 1).reshape(m, n_parts * k_in)
    flat_i = np.moveaxis(part_ids, 0, 1).reshape(m, n_parts * k_in)
    order = np.argsort(flat_d if select_min else -flat_d, axis=1)[:, :k]
    return (
        np.take_along_axis(flat_d, order, axis=1),
        np.take_along_axis(flat_i, order, axis=1),
    )


class BinDataset:
    """Streaming reader over a big-ANN binary file — the data-loader role of
    the reference bench harness's BinFile/mmap path (dataset.h), reworked as
    chunked parallel pread so host RAM holds only one chunk while the previous
    one is transferred to device."""

    def __init__(self, path: str):
        self.path = str(path)
        self.n_rows, self.dim = bin_info(self.path)
        self.dtype = _dtype_for(self.path)

    def __len__(self) -> int:
        return self.n_rows

    def chunks(self, chunk_rows: int):
        """Yield (row_start, ndarray) chunks."""
        for start in range(0, self.n_rows, chunk_rows):
            yield start, read_bin_chunk(self.path, start, chunk_rows)

    def __getitem__(self, sl):
        if isinstance(sl, slice):
            start, stop, step = sl.indices(self.n_rows)
            if step != 1:
                raise ValueError("BinDataset slicing requires step 1")
            return read_bin_chunk(self.path, start, stop - start)
        raise TypeError("BinDataset supports contiguous slice access only")
