"""Sparse containers: padded COO / CSR matrices.

TPU-first design: unlike the reference's exact-nnz device buffers
(cpp/include/raft/core/sparse_types.hpp, core/device_coo_matrix.hpp,
core/device_csr_matrix.hpp, sparse/coo.hpp, sparse/csr.hpp), these
containers carry a *static* capacity ``cap`` with a dynamic valid count
``nnz`` — XLA requires static shapes, so every structural op masks by
position rather than reallocating. Padding convention:

  * COO: padding entries have ``rows == shape[0]`` (one past the last valid
    row) so scatter ops drop them with ``mode='drop'``; vals are 0.
  * CSR: ``indptr[-1] == nnz``; entries at positions >= nnz are padding with
    ``indices == shape[1]`` and ``data == 0``.

Both are registered pytrees (shape/cap are static aux data) so they pass
through jit/vmap/shard_map transparently.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["CooMatrix", "CsrMatrix", "make_coo", "make_csr"]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class CooMatrix:
    """Padded COO matrix (reference: raft/core/device_coo_matrix.hpp, sparse/coo.hpp).

    rows/cols: int32[cap], vals: float[cap]; entries past ``nnz`` are padding
    with ``rows == shape[0]``.
    """

    rows: jax.Array
    cols: jax.Array
    vals: jax.Array
    nnz: jax.Array  # int32 scalar (dynamic)
    shape: Tuple[int, int]  # static

    @property
    def cap(self) -> int:
        return self.rows.shape[0]

    @property
    def dtype(self):
        return self.vals.dtype

    def valid_mask(self) -> jax.Array:
        return jnp.arange(self.cap, dtype=jnp.int32) < self.nnz

    def todense(self) -> jax.Array:
        out = jnp.zeros(self.shape, self.vals.dtype)
        return out.at[self.rows, self.cols].add(self.vals, mode="drop")

    def tree_flatten(self):
        return (self.rows, self.cols, self.vals, self.nnz), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        rows, cols, vals, nnz = children
        return cls(rows, cols, vals, nnz, aux[0])


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class CsrMatrix:
    """Padded CSR matrix (reference: raft/core/device_csr_matrix.hpp, sparse/csr.hpp).

    indptr: int32[n_rows+1] (indptr[-1] == nnz), indices: int32[cap],
    data: float[cap]; entries past ``nnz`` are padding with
    ``indices == shape[1]``.
    """

    indptr: jax.Array
    indices: jax.Array
    data: jax.Array
    shape: Tuple[int, int]  # static

    @property
    def cap(self) -> int:
        return self.indices.shape[0]

    @property
    def nnz(self) -> jax.Array:
        return self.indptr[-1]

    @property
    def dtype(self):
        return self.data.dtype

    def valid_mask(self) -> jax.Array:
        return jnp.arange(self.cap, dtype=jnp.int32) < self.nnz

    def row_ids(self) -> jax.Array:
        """Expand indptr to a per-entry row id (padding entries get shape[0])."""
        # row of entry e = (# of row starts <= e) - 1, computed via searchsorted
        pos = jnp.arange(self.cap, dtype=jnp.int32)
        rows = jnp.searchsorted(self.indptr[1:], pos, side="right").astype(jnp.int32)
        return jnp.where(self.valid_mask(), rows, self.shape[0])

    def todense(self) -> jax.Array:
        out = jnp.zeros(self.shape, self.data.dtype)
        return out.at[self.row_ids(), self.indices].add(self.data, mode="drop")

    def tree_flatten(self):
        return (self.indptr, self.indices, self.data), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        indptr, indices, data = children
        return cls(indptr, indices, data, aux[0])


def make_coo(rows, cols, vals, shape, cap: int | None = None) -> CooMatrix:
    """Build a padded CooMatrix from exact-length host/device triplets."""
    rows = jnp.asarray(rows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)
    vals = jnp.asarray(vals)
    nnz = int(rows.shape[0])
    cap = nnz if cap is None else int(cap)
    if cap < nnz:
        raise ValueError(f"cap {cap} < nnz {nnz}")
    pad = cap - nnz
    rows = jnp.concatenate([rows, jnp.full((pad,), shape[0], jnp.int32)])
    cols = jnp.concatenate([cols, jnp.full((pad,), shape[1], jnp.int32)])
    vals = jnp.concatenate([vals, jnp.zeros((pad,), vals.dtype)])
    return CooMatrix(rows, cols, vals, jnp.int32(nnz), (int(shape[0]), int(shape[1])))


def make_csr(indptr, indices, data, shape, cap: int | None = None) -> CsrMatrix:
    """Build a padded CsrMatrix from exact-length host/device CSR arrays."""
    indptr = jnp.asarray(indptr, jnp.int32)
    indices = jnp.asarray(indices, jnp.int32)
    data = jnp.asarray(data)
    nnz = int(indices.shape[0])
    cap = nnz if cap is None else int(cap)
    if cap < nnz:
        raise ValueError(f"cap {cap} < nnz {nnz}")
    pad = cap - nnz
    indices = jnp.concatenate([indices, jnp.full((pad,), shape[1], jnp.int32)])
    data = jnp.concatenate([data, jnp.zeros((pad,), data.dtype)])
    return CsrMatrix(indptr, indices, data, (int(shape[0]), int(shape[1])))


def from_scipy(sp, cap: int | None = None):
    """Convenience ingestion from a scipy.sparse matrix (tests/tooling)."""
    if sp.format == "coo":
        return make_coo(sp.row, sp.col, sp.data, sp.shape, cap)
    csr = sp.tocsr()
    return make_csr(
        np.asarray(csr.indptr), np.asarray(csr.indices), np.asarray(csr.data), csr.shape, cap
    )
