"""Sparse linear algebra (reference: raft/sparse/linalg/{spmm,add,degree,norm,
symmetrize,transpose}.cuh).

SpMV/SpMM are gather + scatter-add formulations — XLA lowers the scatter-add
to an efficient on-chip combine; for the MXU-heavy regime (dense RHS, many
columns) the gather of B rows feeds dense FMAs directly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .convert import coo_to_csr, csr_to_coo, sort_coo
from .types import CooMatrix, CsrMatrix

__all__ = [
    "spmv",
    "spmm",
    "add",
    "degree",
    "row_norm",
    "normalize_rows",
    "transpose",
    "symmetrize",
    "laplacian",
]


def spmv(a: CsrMatrix, x: jax.Array) -> jax.Array:
    """CSR @ vector (reference: sparse/linalg/spmm.cuh with 1 column)."""
    return spmm(a, x[:, None])[:, 0]


def spmm(a: CsrMatrix, b: jax.Array) -> jax.Array:
    """CSR @ dense (reference: raft/sparse/linalg/spmm.cuh — cusparse SpMM).

    out[r, :] = sum_e vals[e] * b[cols[e], :] for entries e of row r.
    """
    rows = a.row_ids()
    gathered = jnp.take(b, jnp.minimum(a.indices, b.shape[0] - 1), axis=0)
    contrib = a.data[:, None] * gathered
    out = jnp.zeros((a.shape[0], b.shape[1]), contrib.dtype)
    return out.at[rows].add(contrib, mode="drop")


def add(a: CsrMatrix, b: CsrMatrix) -> CsrMatrix:
    """C = A + B, duplicates merged (reference: raft/sparse/linalg/add.cuh
    csr_add_calc_inds/csr_add_finalize)."""
    from .op import sum_duplicates

    assert a.shape == b.shape
    ac, bc = csr_to_coo(a), csr_to_coo(b)
    rows = jnp.concatenate([ac.rows, bc.rows])
    cols = jnp.concatenate([ac.cols, bc.cols])
    vals = jnp.concatenate([ac.vals, bc.vals])
    merged = CooMatrix(rows, cols, vals, ac.nnz + bc.nnz, a.shape)
    return coo_to_csr(sum_duplicates(sort_coo(merged)), assume_sorted=True)


def degree(a) -> jax.Array:
    """Per-row entry count (reference: raft/sparse/linalg/degree.cuh coo_degree)."""
    if isinstance(a, CsrMatrix):
        return (a.indptr[1:] - a.indptr[:-1]).astype(jnp.int32)
    counts = jnp.zeros((a.shape[0],), jnp.int32)
    return counts.at[a.rows].add(a.valid_mask().astype(jnp.int32), mode="drop")


def row_norm(a: CsrMatrix, norm: str = "l2") -> jax.Array:
    """Per-row L1/L2/Linf norms (reference: raft/sparse/linalg/norm.cuh
    csr_row_normalize_* companions)."""
    rows = a.row_ids()
    if norm == "l1":
        contrib = jnp.abs(a.data)
    elif norm == "l2":
        contrib = a.data * a.data
    elif norm == "linf":
        out = jnp.zeros((a.shape[0],), a.data.dtype)
        return out.at[rows].max(jnp.abs(a.data), mode="drop")
    else:
        raise ValueError(f"unknown norm {norm!r}")
    out = jnp.zeros((a.shape[0],), a.data.dtype)
    return out.at[rows].add(contrib, mode="drop")


def normalize_rows(a: CsrMatrix, norm: str = "l1") -> CsrMatrix:
    """Scale each row to unit norm (reference: sparse/linalg/norm.cuh
    csr_row_normalize_l1 / csr_row_normalize_max)."""
    norms = row_norm(a, norm)
    if norm == "l2":
        norms = jnp.sqrt(norms)
    scale = jnp.where(norms > 0, 1.0 / jnp.maximum(norms, 1e-30), 0.0)
    rows = jnp.minimum(a.row_ids(), a.shape[0] - 1)
    return CsrMatrix(a.indptr, a.indices, a.data * scale[rows], a.shape)


def transpose(a: CsrMatrix) -> CsrMatrix:
    """Aᵀ (reference: raft/sparse/linalg/transpose.cuh — cusparse csr2csc)."""
    coo = csr_to_coo(a)
    t = CooMatrix(
        jnp.where(coo.valid_mask(), coo.cols, a.shape[1]),
        jnp.where(coo.valid_mask(), coo.rows, a.shape[0]),
        coo.vals,
        coo.nnz,
        (a.shape[1], a.shape[0]),
    )
    return coo_to_csr(t)


def symmetrize(a: CsrMatrix, mode: str = "sum") -> CsrMatrix:
    """Symmetrize: sum mode gives A + Aᵀ; max mode gives max(A, Aᵀ) — the kNN
    graph symmetrization (reference: raft/sparse/linalg/symmetrize.cuh
    coo_symmetrize / symmetrize)."""
    from .op import max_duplicates, sum_duplicates

    ac = csr_to_coo(a)
    rows = jnp.concatenate([ac.rows, jnp.where(ac.valid_mask(), ac.cols, a.shape[0])])
    cols = jnp.concatenate([ac.cols, jnp.where(ac.valid_mask(), ac.rows, a.shape[1])])
    vals = jnp.concatenate([ac.vals, ac.vals])
    merged = sort_coo(CooMatrix(rows, cols, vals, ac.nnz * 2, a.shape))
    reducer = sum_duplicates if mode == "sum" else max_duplicates
    return coo_to_csr(reducer(merged), assume_sorted=True)


def laplacian(a: CsrMatrix, normalized: bool = False) -> CsrMatrix:
    """Graph Laplacian L = D - A (or normalized I - D^-1/2 A D^-1/2) as CSR.

    Reference: raft/spectral/matrix_wrappers.hpp (laplacian_matrix_t mv —
    computed implicitly there; materialized here since the TPU spmv is a
    gather/scatter composition either way).
    """
    from .op import sum_duplicates

    coo = csr_to_coo(a)
    d = jnp.zeros((a.shape[0],), a.data.dtype).at[coo.rows].add(coo.vals, mode="drop")
    if normalized:
        dinv = jnp.where(d > 0, 1.0 / jnp.sqrt(d), 0.0)
        r = jnp.minimum(coo.rows, a.shape[0] - 1)
        c = jnp.minimum(coo.cols, a.shape[1] - 1)
        off_vals = -coo.vals * dinv[r] * dinv[c]
        diag_vals = jnp.ones((a.shape[0],), a.data.dtype)
    else:
        off_vals = -coo.vals
        diag_vals = d
    n = a.shape[0]
    rows = jnp.concatenate([coo.rows, jnp.arange(n, dtype=jnp.int32)])
    cols = jnp.concatenate([coo.cols, jnp.arange(n, dtype=jnp.int32)])
    vals = jnp.concatenate([jnp.where(coo.valid_mask(), off_vals, 0), diag_vals])
    merged = sort_coo(CooMatrix(rows, cols, vals, coo.nnz + n, (n, n)))
    return coo_to_csr(sum_duplicates(merged), assume_sorted=True)
