"""raft_tpu.sparse — raft/sparse (S1-S7). Under construction."""
