"""raft_tpu.sparse — sparse containers, conversions, linalg, ops, distances,
neighbors (reference: raft/sparse — S1-S7 in SURVEY.md §2.4)."""

from .types import CooMatrix, CsrMatrix, make_coo, make_csr, from_scipy
from .convert import (
    coo_to_csr,
    csr_to_coo,
    dense_to_csr,
    dense_to_coo,
    csr_to_dense,
    coo_to_dense,
    adj_to_csr,
    sort_coo,
)
from .linalg import (
    spmv,
    spmm,
    add,
    degree,
    row_norm,
    normalize_rows,
    transpose,
    symmetrize,
    laplacian,
)
from .op import (
    sum_duplicates,
    max_duplicates,
    filter_entries,
    remove_zeros,
    slice_rows,
)
from .distance import pairwise_distance, csr_to_ell, SPARSE_SUPPORTED
from .neighbors import knn, knn_graph, connect_components

__all__ = [
    "CooMatrix",
    "CsrMatrix",
    "make_coo",
    "make_csr",
    "from_scipy",
    "coo_to_csr",
    "csr_to_coo",
    "dense_to_csr",
    "dense_to_coo",
    "csr_to_dense",
    "coo_to_dense",
    "adj_to_csr",
    "sort_coo",
    "spmv",
    "spmm",
    "add",
    "degree",
    "row_norm",
    "normalize_rows",
    "transpose",
    "symmetrize",
    "laplacian",
    "sum_duplicates",
    "max_duplicates",
    "filter_entries",
    "remove_zeros",
    "slice_rows",
    "pairwise_distance",
    "csr_to_ell",
    "SPARSE_SUPPORTED",
    "knn",
    "knn_graph",
    "connect_components",
]
