"""Sparse format conversions (reference: raft/sparse/convert/{coo,csr,dense}.cuh,
detail/adj_to_csr.cuh).

All conversions keep static capacities; sorting uses two stable argsorts
(col-major then row-major key) instead of 64-bit fused keys so everything
stays in int32 on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .types import CooMatrix, CsrMatrix

__all__ = [
    "coo_to_csr",
    "csr_to_coo",
    "dense_to_csr",
    "dense_to_coo",
    "csr_to_dense",
    "coo_to_dense",
    "adj_to_csr",
    "sort_coo",
]


def sort_coo(coo: CooMatrix) -> CooMatrix:
    """Sort COO entries by (row, col); padding (row==shape[0]) sorts last.

    Reference: raft/sparse/op/sort.cuh (coo_sort — thrust sort_by_key on a
    fused 64-bit key). TPU version: two stable argsorts.
    """
    order = jnp.argsort(coo.cols, stable=True)
    rows, cols, vals = coo.rows[order], coo.cols[order], coo.vals[order]
    order = jnp.argsort(rows, stable=True)
    return CooMatrix(rows[order], cols[order], vals[order], coo.nnz, coo.shape)


def coo_to_csr(coo: CooMatrix, assume_sorted: bool = False) -> CsrMatrix:
    """COO → CSR (reference: raft/sparse/convert/csr.cuh sorted_coo_to_csr)."""
    if not assume_sorted:
        coo = sort_coo(coo)
    n_rows = coo.shape[0]
    # indptr[r] = number of valid entries with row < r
    counts = jnp.zeros((n_rows + 1,), jnp.int32).at[coo.rows].add(
        coo.valid_mask().astype(jnp.int32), mode="drop"
    )
    indptr = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(counts[:-1])]).astype(
        jnp.int32
    )
    indptr = indptr.at[-1].set(coo.nnz)
    indices = jnp.where(coo.valid_mask(), coo.cols, coo.shape[1])
    data = jnp.where(coo.valid_mask(), coo.vals, 0)
    return CsrMatrix(indptr, indices, data, coo.shape)


def csr_to_coo(csr: CsrMatrix) -> CooMatrix:
    """CSR → COO (reference: raft/sparse/convert/coo.cuh csr_to_coo)."""
    return CooMatrix(csr.row_ids(), csr.indices, csr.data, csr.nnz, csr.shape)


def dense_to_coo(x: jax.Array, cap: int | None = None) -> CooMatrix:
    """Dense → COO keeping explicit zeros out; cap defaults to x.size.

    Reference: raft/sparse/convert/dense path (cusparse dense2csr).
    """
    n, m = x.shape
    cap = n * m if cap is None else cap
    mask = (x != 0).ravel()
    # a cap below the true nonzero count keeps the first `cap` entries in
    # row-major order; nnz is clamped so the container stays consistent
    nnz = jnp.minimum(jnp.sum(mask), cap).astype(jnp.int32)
    flat = jnp.arange(n * m, dtype=jnp.int32)
    # stable partition: valid entries first, in row-major order
    order = jnp.argsort(~mask, stable=True)[:cap]
    sel = flat[order]
    valid = mask[order]
    rows = jnp.where(valid, sel // m, n).astype(jnp.int32)
    cols = jnp.where(valid, sel % m, m).astype(jnp.int32)
    vals = jnp.where(valid, x.ravel()[order], 0)
    return CooMatrix(rows, cols, vals, nnz, (n, m))


def dense_to_csr(x: jax.Array, cap: int | None = None) -> CsrMatrix:
    """Dense → CSR (reference: raft/sparse/convert/csr.cuh)."""
    return coo_to_csr(dense_to_coo(x, cap), assume_sorted=True)


def csr_to_dense(csr: CsrMatrix) -> jax.Array:
    return csr.todense()


def coo_to_dense(coo: CooMatrix) -> jax.Array:
    return coo.todense()


def adj_to_csr(adj: jax.Array) -> CsrMatrix:
    """Boolean adjacency matrix → CSR with unit weights.

    Reference: raft/sparse/convert/detail/adj_to_csr.cuh (adj_to_csr kernel).
    """
    return dense_to_csr(adj.astype(jnp.float32))
