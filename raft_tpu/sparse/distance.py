"""Sparse pairwise distances.

Reference: raft/sparse/distance/distance.cuh (pairwiseDistance dispatch,
supported metric list :37-54) over the load-balanced COO SpMV
(sparse/distance/detail/coo_spmv.cuh:49-126) with dense-shared-mem vs
hash-table row strategies.

TPU re-think: the MXU wants dense tiles, so instead of a two-strategy SpMV
the rows are staged tile-by-tile from an ELL (fixed-width gather) layout into
dense VMEM blocks and scored with the *same* metric math as the dense layer
(distance/pairwise.py) — one code path for all 17 sparse-supported metrics,
identical numerics dense vs sparse. Peak memory is (tile·d) for the staged
block plus the (m·d) densified RHS; the row tile adapts to the workspace
budget exactly like the dense path's _choose_tile
(reference knn_brute_force.cuh:78 tile sizing).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..distance import pairwise as _pw
from ..distance.types import DistanceType, resolve_metric
from .types import CsrMatrix

__all__ = ["pairwise_distance", "csr_to_ell", "SPARSE_SUPPORTED"]

_f32 = jnp.float32

# reference: sparse/distance/distance.cuh:37-54 supported_distance list
SPARSE_SUPPORTED = frozenset(
    {
        DistanceType.L2Expanded,
        DistanceType.L2Unexpanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.L2SqrtUnexpanded,
        DistanceType.InnerProduct,
        DistanceType.L1,
        DistanceType.Canberra,
        DistanceType.Linf,
        DistanceType.LpUnexpanded,
        DistanceType.JaccardExpanded,
        DistanceType.CosineExpanded,
        DistanceType.HellingerExpanded,
        DistanceType.DiceExpanded,
        DistanceType.CorrelationExpanded,
        DistanceType.RusselRaoExpanded,
        DistanceType.HammingUnexpanded,
        DistanceType.JensenShannon,
        DistanceType.KLDivergence,
    }
)


def csr_to_ell(csr: CsrMatrix, width: int | None = None):
    """CSR → fixed-width ELL (idx (n, w) padded with shape[1], val (n, w)).

    The TPU-native sparse row layout: every row becomes a fixed-size gather,
    the analogue of the reference's max-row-nnz bucketing in the dense-smem
    SpMV strategy (coo_spmv_strategies/dense_smem_strategy.cuh).
    """
    n, m = csr.shape
    deg = csr.indptr[1:] - csr.indptr[:-1]
    w = int(width) if width is not None else int(jnp.max(deg)) if csr.cap else 0
    w = max(w, 1)
    pos = jnp.arange(csr.cap, dtype=jnp.int32)
    rows = csr.row_ids()
    within = pos - jnp.take(csr.indptr, jnp.minimum(rows, n))
    ok = (rows < n) & (within < w)
    flat = jnp.where(ok, rows * w + within, n * w)
    idx = jnp.full((n * w,), m, jnp.int32).at[flat].set(csr.indices, mode="drop")
    val = jnp.zeros((n * w,), csr.data.dtype).at[flat].set(csr.data, mode="drop")
    return idx.reshape(n, w), val.reshape(n, w)


def _densify(ell_idx, ell_val, d: int):
    """(t, w) ELL rows → (t, d) dense block; padding (idx==d) lands in a
    discard column."""
    t = ell_idx.shape[0]
    out = jnp.zeros((t, d + 1), _f32)
    out = out.at[jnp.arange(t)[:, None], ell_idx].add(ell_val.astype(_f32))
    return out[:, :d]


def _dense_block(metric: DistanceType, metric_arg: float, xd, yd):
    """Score a dense (t, d) block against dense (m, d) with the shared
    dense-metric math (distance/pairwise.py functions)."""
    if metric == DistanceType.L2Expanded:
        return _pw._l2_expanded(xd, yd, sqrt=False)
    if metric == DistanceType.L2SqrtExpanded:
        return _pw._l2_expanded(xd, yd, sqrt=True)
    if metric == DistanceType.CosineExpanded:
        return _pw._cosine(xd, yd)
    if metric == DistanceType.CorrelationExpanded:
        return _pw._correlation(xd, yd)
    if metric == DistanceType.InnerProduct:
        return _pw._inner_product(xd, yd)
    if metric == DistanceType.HellingerExpanded:
        return _pw._hellinger(xd, yd)
    if metric == DistanceType.RusselRaoExpanded:
        return _pw._russelrao(xd, yd)
    if metric == DistanceType.KLDivergence:
        return _pw._kl_divergence(xd, yd)
    if metric == DistanceType.JaccardExpanded:
        return _pw._jaccard(xd, yd)
    if metric == DistanceType.DiceExpanded:
        return _pw._dice(xd, yd)
    ew = {
        DistanceType.L1: _pw._ew_l1,
        DistanceType.L2Unexpanded: _pw._ew_l2(False),
        DistanceType.L2SqrtUnexpanded: _pw._ew_l2(True),
        DistanceType.Linf: _pw._ew_linf,
        DistanceType.Canberra: _pw._ew_canberra,
        DistanceType.LpUnexpanded: _pw._ew_lp(metric_arg),
        DistanceType.HammingUnexpanded: _pw._ew_hamming,
        DistanceType.JensenShannon: _pw._ew_jensenshannon,
    }[metric]
    return ew(xd[:, None, :], yd[None, :, :], None)


@functools.partial(jax.jit, static_argnames=("metric", "metric_arg", "tile", "d"))
def _sparse_pairwise(xi, xv, yd, metric: DistanceType, metric_arg: float, tile: int, d: int):
    n = xi.shape[0]
    num = -(-n // tile)
    pad = num * tile - n
    if pad:
        xi = jnp.pad(xi, ((0, pad), (0, 0)), constant_values=d)
        xv = jnp.pad(xv, ((0, pad), (0, 0)))
    xit = xi.reshape(num, tile, -1)
    xvt = xv.reshape(num, tile, -1)

    def per_tile(args):
        ti, tv = args
        xd = _densify(ti, tv, d)
        return _dense_block(metric, metric_arg, xd, yd)

    out = lax.map(per_tile, (xit, xvt))
    return out.reshape(num * tile, yd.shape[0])[:n]


def pairwise_distance(x: CsrMatrix, y: CsrMatrix | None = None, metric="euclidean",
                      metric_arg: float = 2.0, res: Resources | None = None):
    """All-pairs distances between CSR row sets (reference:
    raft::sparse::distance::pairwiseDistance, sparse/distance/distance.cuh:60).

    Returns an (n, m) float32 dense matrix, numerically identical to the dense
    ``raft_tpu.distance.pairwise_distance`` on densified inputs.
    """
    res = res or default_resources()
    mt = resolve_metric(metric)
    expects(mt in SPARSE_SUPPORTED, "metric %s unsupported for sparse inputs", mt.name)
    y = x if y is None else y
    expects(x.shape[1] == y.shape[1], "feature dims must match: %d vs %d", x.shape[1], y.shape[1])
    d = x.shape[1]
    xi, xv = csr_to_ell(x)
    yd = y.todense().astype(_f32)
    # elementwise metrics broadcast (tile, m, d); GEMM-shaped ones only (tile, m)
    ew = mt in (
        DistanceType.L1, DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded,
        DistanceType.Linf, DistanceType.Canberra, DistanceType.LpUnexpanded,
        DistanceType.HammingUnexpanded, DistanceType.JensenShannon,
    )
    tile = _pw._choose_tile(x.shape[0], y.shape[0], d if ew else 1, res.workspace_bytes)
    return _sparse_pairwise(xi, xv, yd, mt, float(metric_arg), tile, d)
