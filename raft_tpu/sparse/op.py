"""Structural sparse ops (reference: raft/sparse/op/{filter,reduce,row_op,
slice,sort}.cuh).

Duplicate reduction works on *sorted* COO: run-starts are detected by
comparing adjacent (row, col) pairs, then values are combined into the
run-start slot with a scatter — the TPU replacement for the reference's
hash/sort reduce (sparse/op/reduce.cuh max_duplicates).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .types import CooMatrix

__all__ = [
    "sum_duplicates",
    "max_duplicates",
    "filter_entries",
    "remove_zeros",
    "slice_rows",
]


def _runs(coo: CooMatrix):
    """For sorted COO: (segment id of each entry, is-run-start mask)."""
    valid = coo.valid_mask()
    prev_r = jnp.roll(coo.rows, 1)
    prev_c = jnp.roll(coo.cols, 1)
    is_start = (coo.rows != prev_r) | (coo.cols != prev_c)
    is_start = is_start.at[0].set(True) & valid
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1  # segment id per entry
    return seg, is_start, valid


def _dedupe(coo: CooMatrix, combine: str) -> CooMatrix:
    seg, is_start, valid = _runs(coo)
    n_seg = jnp.sum(is_start.astype(jnp.int32))
    cap = coo.cap
    drop = jnp.where(valid, seg, cap)  # invalid entries scatter out of range
    if combine == "sum":
        vals = jnp.zeros((cap,), coo.vals.dtype).at[drop].add(coo.vals, mode="drop")
    else:
        vals = jnp.full((cap,), -jnp.inf, coo.vals.dtype).at[drop].max(coo.vals, mode="drop")
        vals = jnp.where(jnp.arange(cap) < n_seg, vals, 0)
    # compact run-start coordinates into segment slots
    rows = jnp.full((cap,), coo.shape[0], jnp.int32).at[
        jnp.where(is_start, seg, cap)
    ].set(coo.rows, mode="drop")
    cols = jnp.full((cap,), coo.shape[1], jnp.int32).at[
        jnp.where(is_start, seg, cap)
    ].set(coo.cols, mode="drop")
    return CooMatrix(rows, cols, vals, n_seg.astype(jnp.int32), coo.shape)


def sum_duplicates(coo: CooMatrix) -> CooMatrix:
    """Combine duplicate (row, col) entries by sum. Input must be sorted."""
    return _dedupe(coo, "sum")


def max_duplicates(coo: CooMatrix) -> CooMatrix:
    """Combine duplicate (row, col) entries by max (reference:
    sparse/op/reduce.cuh max_duplicates). Input must be sorted."""
    return _dedupe(coo, "max")


def filter_entries(coo: CooMatrix, keep_mask: jax.Array) -> CooMatrix:
    """Keep entries where keep_mask is True, compacting to the front
    (reference: sparse/op/filter.cuh coo_remove_scalar)."""
    keep = keep_mask & coo.valid_mask()
    order = jnp.argsort(~keep, stable=True)
    nnz = jnp.sum(keep.astype(jnp.int32))
    kept = keep[order]
    rows = jnp.where(kept, coo.rows[order], coo.shape[0])
    cols = jnp.where(kept, coo.cols[order], coo.shape[1])
    vals = jnp.where(kept, coo.vals[order], 0)
    return CooMatrix(rows, cols, vals, nnz, coo.shape)


def remove_zeros(coo: CooMatrix) -> CooMatrix:
    """Drop explicit zeros (reference: sparse/op/filter.cuh coo_remove_zeros)."""
    return filter_entries(coo, coo.vals != 0)


def slice_rows(coo: CooMatrix, start: int, stop: int) -> CooMatrix:
    """Select rows in [start, stop), re-indexed to 0 (reference:
    sparse/op/slice.cuh csr_row_slice_indptr)."""
    keep = (coo.rows >= start) & (coo.rows < stop)
    sliced = filter_entries(coo, keep)
    new_shape = (stop - start, coo.shape[1])
    rows = jnp.where(sliced.valid_mask(), sliced.rows - start, new_shape[0])
    return CooMatrix(rows, sliced.cols, sliced.vals, sliced.nnz, new_shape)
