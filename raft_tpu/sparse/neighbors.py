"""Sparse brute-force kNN and kNN-graph construction.

Reference: raft/sparse/neighbors/knn.cuh (brute_force_knn — batched sparse
pairwise distances + select_k with cross-batch merge) and
raft/sparse/neighbors/knn_graph.cuh (knn_graph — kNN of a point set against
itself emitted as a COO adjacency).

TPU shape: the query side is processed in row tiles; each tile's distances
come from the shared sparse-pairwise staging (sparse/distance.py) and feed
directly into select_k — no cross-batch heap merge is needed because the full
candidate row fits in the (tile, n) block the budget planner sized.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..distance import pairwise as _pw
from ..distance.types import DistanceType, resolve_metric
from ..matrix.select_k import _select_k
from .distance import SPARSE_SUPPORTED, _dense_block, _densify, csr_to_ell
from .types import CooMatrix, CsrMatrix

__all__ = ["knn", "knn_graph", "connect_components"]

_f32 = jnp.float32


@functools.partial(jax.jit, static_argnames=("tile",))
def _cross_component_nn(x, colors, tile: int):
    """For every point, its nearest neighbor of a *different* component
    (squared L2), tiled over rows. Returns (dist (n,), idx (n,))."""
    n, d = x.shape
    xf = x.astype(_f32)
    norms = jnp.sum(xf * xf, axis=1)
    num = -(-n // tile)
    pad = num * tile - n
    xp = jnp.pad(xf, ((0, pad), (0, 0))) if pad else xf
    cp = jnp.pad(colors, (0, pad), constant_values=-1) if pad else colors
    np_ = jnp.pad(norms, (0, pad)) if pad else norms
    xt = xp.reshape(num, tile, d)
    ct = cp.reshape(num, tile)
    nt = np_.reshape(num, tile)

    def per_tile(args):
        xb, cb, nb = args
        d2 = nb[:, None] + norms[None, :] - 2.0 * (xb @ xf.T)
        d2 = jnp.where(cb[:, None] == colors[None, :], jnp.inf, jnp.maximum(d2, 0.0))
        j = jnp.argmin(d2, axis=1).astype(jnp.int32)
        return jnp.take_along_axis(d2, j[:, None], axis=1)[:, 0], j

    dv, di = lax.map(per_tile, (xt, ct, nt))
    return dv.reshape(-1)[:n], di.reshape(-1)[:n]


def connect_components(x, colors, res: Resources | None = None) -> CooMatrix:
    """Minimum cross-component connecting edges (one per component).

    Reference: raft::sparse::neighbors::connect_components
    (sparse/neighbors/detail/connect_components.cuh — fused L2 1-NN over
    points masked to other components, then per-component min edge). Used to
    repair disconnected kNN-graph MSTs in single-linkage (SURVEY.md K3).

    Returns a CooMatrix of (up to one-per-component) symmetric L2² edges.
    """
    res = res or default_resources()
    x = jnp.asarray(x)
    colors = jnp.asarray(colors, jnp.int32)
    n = x.shape[0]
    tile = _pw._choose_tile(n, n, 1, (res.workspace_bytes))
    dist, idx = _cross_component_nn(x, colors, tile)

    # per-component argmin via (dist, src) rank trick
    order = jnp.argsort(dist, stable=True)
    rank = jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    best = jnp.full((n,), 2**31 - 1, jnp.int32).at[colors].min(
        jnp.where(jnp.isfinite(dist), rank, 2**31 - 1), mode="drop"
    )
    winner = jnp.isfinite(dist) & (rank == best[colors])
    rows = jnp.where(winner, jnp.arange(n, dtype=jnp.int32), n)
    cols = jnp.where(winner, idx, n)
    vals = jnp.where(winner, dist, 0.0)
    # compact winners to the front
    corder = jnp.argsort(~winner, stable=True)
    return CooMatrix(
        rows[corder], cols[corder], vals[corder],
        jnp.sum(winner.astype(jnp.int32)), (n, n),
    )


@functools.partial(jax.jit, static_argnames=("metric", "metric_arg", "k", "tile", "d", "ascending"))
def _sparse_knn(qi, qv, yd, metric: DistanceType, metric_arg: float, k: int, tile: int,
                d: int, ascending: bool):
    m = qi.shape[0]
    num = -(-m // tile)
    pad = num * tile - m
    if pad:
        qi = jnp.pad(qi, ((0, pad), (0, 0)), constant_values=d)
        qv = jnp.pad(qv, ((0, pad), (0, 0)))
    qit = qi.reshape(num, tile, -1)
    qvt = qv.reshape(num, tile, -1)

    def per_tile(args):
        ti, tv = args
        dists = _dense_block(metric, metric_arg, _densify(ti, tv, d), yd)
        return _select_k(dists, None, k, ascending)

    dv, di = lax.map(per_tile, (qit, qvt))
    return (
        dv.reshape(num * tile, k)[:m],
        di.reshape(num * tile, k)[:m],
    )


def knn(dataset: CsrMatrix, queries: CsrMatrix, k: int, metric="euclidean",
        metric_arg: float = 2.0, res: Resources | None = None):
    """k nearest neighbors of sparse queries in a sparse dataset.

    Reference: raft::sparse::neighbors::brute_force_knn
    (sparse/neighbors/knn.cuh, detail/knn.cuh sparse_knn_t). Returns
    (distances (m, k), indices (m, k)).
    """
    res = res or default_resources()
    mt = resolve_metric(metric)
    expects(mt in SPARSE_SUPPORTED, "metric %s unsupported for sparse inputs", mt.name)
    expects(dataset.shape[1] == queries.shape[1], "feature dims must match")
    expects(k <= dataset.shape[0], "k > dataset size")
    d = dataset.shape[1]
    qi, qv = csr_to_ell(queries)
    yd = dataset.todense().astype(_f32)
    ascending = mt != DistanceType.InnerProduct
    ew = mt in (
        DistanceType.L1, DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded,
        DistanceType.Linf, DistanceType.Canberra, DistanceType.LpUnexpanded,
        DistanceType.HammingUnexpanded, DistanceType.JensenShannon,
    )
    tile = _pw._choose_tile(queries.shape[0], dataset.shape[0], d if ew else 1, res.workspace_bytes)
    return _sparse_knn(qi, qv, yd, mt, float(metric_arg), int(k), tile, d, ascending)


def knn_graph(dataset: CsrMatrix, k: int, metric="euclidean",
              res: Resources | None = None) -> CooMatrix:
    """kNN graph of a sparse point set as COO (self edges excluded).

    Reference: raft::sparse::neighbors::knn_graph
    (sparse/neighbors/knn_graph.cuh — k+1 search, self-edge drop, COO emit).
    """
    n = dataset.shape[0]
    expects(k + 1 <= n, "k + 1 > dataset size")
    dists, idx = knn(dataset, dataset, k + 1, metric=metric, res=res)
    # drop the self column: usually column 0, but ties may reorder — mask by id
    rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k + 1)
    cols = idx.reshape(-1).astype(jnp.int32)
    vals = dists.reshape(-1)
    self_edge = rows == cols
    # keep first k non-self edges per row via stable partition within rows
    order = jnp.argsort(self_edge.reshape(n, k + 1), axis=1, stable=True)
    cols2 = jnp.take_along_axis(cols.reshape(n, k + 1), order, axis=1)[:, :k]
    vals2 = jnp.take_along_axis(vals.reshape(n, k + 1), order, axis=1)[:, :k]
    rows2 = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
    return CooMatrix(
        rows2, cols2.reshape(-1), vals2.reshape(-1), jnp.int32(n * k), (n, n)
    )
