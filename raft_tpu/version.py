"""Version of raft_tpu.

Mirrors the reference's RAFT_VERSION 23.08 (cpp/CMakeLists.txt:14) but versions
independently: this is a from-scratch TPU-native framework, not a port.
"""

__version__ = "0.1.0"
