"""raft_tpu — TPU-native reusable ML/vector-search primitives.

A from-scratch, TPU-first framework with the capabilities of RAPIDS RAFT
(reference: /root/reference, dwwcqu/raft @ 23.08): pairwise distances, top-k
selection, random data generation, clustering, ANN indexes (brute-force,
IVF-Flat, IVF-PQ, CAGRA), sparse/graph solvers, statistics, and a multi-chip
communicator over ICI/DCN — built on JAX/XLA, ``shard_map`` and Pallas rather
than CUDA. See SURVEY.md for the layer map this implements.

Subpackages (lazily imported):
  core       resource handle, errors, logging, serialization   (ref: raft/core)
  comms      collectives veneer over shard_map                 (ref: raft/comms)
  distance   pairwise distances, fused 1-NN, gram kernels      (ref: raft/distance)
  linalg     dense BLAS/solvers/reductions                     (ref: raft/linalg)
  matrix     matrix ops + select_k                             (ref: raft/matrix)
  random     RNG + synthetic data generators                   (ref: raft/random)
  stats      moments + clustering/regression metrics           (ref: raft/stats)
  cluster    kmeans (+balanced), single-linkage                (ref: raft/cluster)
  neighbors  ANN indexes                                       (ref: raft/neighbors)
  sparse     sparse containers/linalg/distances                (ref: raft/sparse)
  solver     lanczos, MST, LAP                                 (ref: raft/solver, sparse/solver)
  spectral   spectral clustering/partitioning                  (ref: raft/spectral)
  label      label utilities                                   (ref: raft/label)
  spatial    legacy spatial::knn aliases + haversine           (ref: raft/spatial)
  config     global output-type conversion                     (ref: pylibraft.config)
  obs        metrics registry + compile attribution            (ref: nvtx/spdlog/bench harness)
  ops        Pallas TPU kernels backing the hot paths
  parallel   distributed (sharded) algorithm drivers           (ref: raft::comms consumers)
  serve      online serving: micro-batching, versioned hot-swap registry,
             admission control                                 (no ref counterpart — SURVEY §5
                                                                leaves scheduling to the user)
  stream     mutable index lifecycle: delta memtable, tombstone
             deletes, background compaction with warm hot-swap (no ref counterpart —
                                                                FreshDiskANN-style fresh/sealed split)
  tune       obs-driven autotuner: sweep engine, decision log,
             pinned operating points applied at serve.publish  (ref: the compiled-in
                                                                select_k heuristic table,
                                                                measured instead)
"""

import importlib

from .version import __version__
from .core import Resources, DeviceResources, default_resources

_SUBMODULES = {
    "core",
    "comms",
    "distance",
    "linalg",
    "matrix",
    "random",
    "stats",
    "cluster",
    "neighbors",
    "sparse",
    "solver",
    "spectral",
    "label",
    "obs",
    "ops",
    "parallel",
    "serve",
    "spatial",
    "stream",
    "tune",
    "config",
}


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    if name in ("warmup", "warm_buckets"):  # AOT cache warmup entry points
        # (docs/warm_builds.md; warm_buckets is the serving-bucket variant
        # the serve registry warms hot-swaps through — docs/serving.md)
        fn = getattr(importlib.import_module("._warmup", __name__), name)
        globals()[name] = fn
        return fn
    raise AttributeError(f"module 'raft_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | _SUBMODULES)
