"""Spectral graph partitioning and modularity clustering.

Reference: raft/spectral/partition.cuh (partition:49-59, analyzePartition:81),
raft/spectral/modularity_maximization.cuh (modularity_maximization:36,
analyzeModularity:73), detail impls in spectral/detail/{partition.hpp,
modularity_maximization.hpp,spectral_util.cuh}, operator wrappers in
spectral/matrix_wrappers.hpp.

TPU design: the Laplacian / modularity operators are matvec closures over the
padded-CSR spmv (gather + scatter-add); the eigensolver is the thick-restart
Lanczos in raft_tpu.solver.lanczos (dense GEMV inner loop on the MXU); the
cluster stage is the library k-means. The whitening transform
(spectral_util.cuh transform_eigen_matrix:122 — per-eigenvector mean-center +
divide by population std) and the modularity path's per-observation
normalization (scale_obs) are preserved exactly so partitions match the
reference's.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..cluster.kmeans import KMeansParams, fit as kmeans_fit
from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..solver.lanczos import eigsh
from ..sparse.linalg import laplacian, spmv
from ..sparse.types import CsrMatrix

__all__ = [
    "EigenSolverConfig",
    "ClusterSolverConfig",
    "SpectralOutput",
    "partition",
    "analyze_partition",
    "modularity_maximization",
    "analyze_modularity",
]


@dataclasses.dataclass(frozen=True)
class EigenSolverConfig:
    """Reference: raft::spectral::eigen_solver_config_t
    (spectral/eigen_solvers.cuh:30)."""

    n_eig_vecs: int = 2
    max_iter: int = 4000
    restart_iter: int | None = None
    tol: float = 1e-4
    seed: int = 1234567


@dataclasses.dataclass(frozen=True)
class ClusterSolverConfig:
    """Reference: raft::spectral::cluster_solver_config_t
    (spectral/cluster_solvers.cuh)."""

    max_iter: int = 100
    tol: float = 1e-4
    seed: int = 123456


@dataclasses.dataclass
class SpectralOutput:
    labels: jax.Array  # (n,) int32
    eigenvalues: jax.Array  # (n_eig_vecs,)
    eigenvectors: jax.Array  # (n, n_eig_vecs)
    n_eigen_restarts: int
    kmeans_inertia: jax.Array


def _whiten(vecs: jax.Array) -> jax.Array:
    """transform_eigen_matrix (spectral_util.cuh:122): per column, subtract the
    mean and divide by the population standard deviation."""
    mean = jnp.mean(vecs, axis=0, keepdims=True)
    centered = vecs - mean
    std = jnp.linalg.norm(centered, axis=0, keepdims=True) / jnp.sqrt(
        jnp.asarray(vecs.shape[0], vecs.dtype))
    return centered / jnp.maximum(std, 1e-30)


def _cluster(embedding, n_clusters, cfg: ClusterSolverConfig, res):
    params = KMeansParams(n_clusters=n_clusters, max_iter=cfg.max_iter,
                          tol=cfg.tol, seed=cfg.seed)
    out = kmeans_fit(params, embedding, res=res)
    return out.labels, out.inertia


def partition(a: CsrMatrix, n_clusters: int,
              eigen_cfg: EigenSolverConfig | None = None,
              cluster_cfg: ClusterSolverConfig | None = None,
              res: Resources | None = None) -> SpectralOutput:
    """Min-balanced-cut spectral partition (reference: spectral/partition.cuh:49,
    detail/partition.hpp partition): k smallest eigenpairs of the graph
    Laplacian -> whiten -> k-means on the embedding rows."""
    res = res or default_resources()
    expects(isinstance(a, CsrMatrix), "partition expects a CsrMatrix adjacency")
    expects(a.shape[0] == a.shape[1], "adjacency must be square")
    eigen_cfg = eigen_cfg or EigenSolverConfig(n_eig_vecs=n_clusters)
    cluster_cfg = cluster_cfg or ClusterSolverConfig()

    lap = laplacian(a)
    w, v, n_restarts = eigsh(lap, k=eigen_cfg.n_eig_vecs, which="SA",
                             ncv=eigen_cfg.restart_iter,
                             max_iter=eigen_cfg.max_iter, tol=eigen_cfg.tol,
                             seed=eigen_cfg.seed)
    emb = _whiten(v)
    labels, inertia = _cluster(emb, n_clusters, cluster_cfg, res)
    return SpectralOutput(labels, w, v, int(n_restarts), inertia)


def modularity_maximization(a: CsrMatrix, n_clusters: int,
                            eigen_cfg: EigenSolverConfig | None = None,
                            cluster_cfg: ClusterSolverConfig | None = None,
                            res: Resources | None = None) -> SpectralOutput:
    """Spectral modularity clustering (reference:
    spectral/modularity_maximization.cuh:36, detail impl): k largest
    eigenpairs of the modularity matrix B = A - d dᵀ / (2m) -> whiten ->
    row-normalize (scale_obs) -> k-means."""
    res = res or default_resources()
    expects(isinstance(a, CsrMatrix), "expects a CsrMatrix adjacency")
    expects(a.shape[0] == a.shape[1], "adjacency must be square")
    eigen_cfg = eigen_cfg or EigenSolverConfig(n_eig_vecs=n_clusters)
    cluster_cfg = cluster_cfg or ClusterSolverConfig()

    d = _degree_vector(a)
    two_m = jnp.sum(d)

    def b_matvec(x):
        # modularity_matrix_t::mv (spectral/matrix_wrappers.hpp): A x - d (d.x)/2m
        return spmv(a, x) - d * (jnp.dot(d, x) / jnp.maximum(two_m, 1e-30))

    w, v, n_restarts = eigsh(b_matvec, n=a.shape[0], k=eigen_cfg.n_eig_vecs,
                             which="LA", ncv=eigen_cfg.restart_iter,
                             max_iter=eigen_cfg.max_iter, tol=eigen_cfg.tol,
                             seed=eigen_cfg.seed)
    emb = _whiten(v)
    # scale_obs (spectral_util.cuh): normalize each observation to unit norm
    emb = emb / jnp.maximum(jnp.linalg.norm(emb, axis=1, keepdims=True), 1e-30)
    labels, inertia = _cluster(emb, n_clusters, cluster_cfg, res)
    return SpectralOutput(labels, w, v, int(n_restarts), inertia)


def _degree_vector(a: CsrMatrix) -> jax.Array:
    """Weighted degree = row sums of the adjacency."""
    rows = a.row_ids()
    return jnp.zeros((a.shape[0],), a.data.dtype).at[rows].add(a.data, mode="drop")


def _one_hot_labels(labels, n_clusters, dtype):
    return jax.nn.one_hot(labels, n_clusters, dtype=dtype)


def analyze_partition(a: CsrMatrix, n_clusters: int, labels) -> tuple:
    """(edge_cut, cost) of a partition (reference: spectral/partition.cuh:81
    analyzePartition; detail/partition.hpp:81 — per-cluster indicator vectors
    x_i with cut_i = x_iᵀ L x_i, cost = Σ cut_i/|cluster_i|, edge_cut = Σ cut_i/2).

    All clusters are evaluated in one batch: L @ X for the (n, k) one-hot
    indicator matrix is a single spmm, and the quadratic forms are one GEMM
    diagonal — no per-cluster loop.
    """
    labels = jnp.asarray(labels, jnp.int32)
    lap = laplacian(a)
    x = _one_hot_labels(labels, n_clusters, a.data.dtype)  # (n, k)
    from ..sparse.linalg import spmm

    lx = spmm(lap, x)  # (n, k)
    cuts = jnp.einsum("nk,nk->k", x, lx)  # x_iT L x_i
    sizes = jnp.sum(x, axis=0)
    nonempty = sizes > 0
    cost = jnp.sum(jnp.where(nonempty, cuts / jnp.maximum(sizes, 1.0), 0.0))
    edge_cut = jnp.sum(jnp.where(nonempty, cuts, 0.0)) / 2.0
    return edge_cut, cost


def analyze_modularity(a: CsrMatrix, n_clusters: int, labels) -> jax.Array:
    """Modularity of a clustering (reference:
    spectral/modularity_maximization.cuh:73 analyzeModularity — Σ_i x_iᵀ B x_i
    normalized by ‖d‖₁ = 2m)."""
    labels = jnp.asarray(labels, jnp.int32)
    d = _degree_vector(a)
    two_m = jnp.maximum(jnp.sum(d), 1e-30)
    x = _one_hot_labels(labels, n_clusters, a.data.dtype)  # (n, k)
    from ..sparse.linalg import spmm

    ax = spmm(a, x)
    quad_a = jnp.einsum("nk,nk->k", x, ax)
    dx = d @ x  # (k,) per-cluster degree mass
    quad = quad_a - dx * dx / two_m
    return jnp.sum(quad) / two_m
