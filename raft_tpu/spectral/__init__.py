"""raft_tpu.spectral — spectral partitioning / modularity clustering (K4).

Reference: raft/spectral/{partition,modularity_maximization,eigen_solvers,
cluster_solvers}.cuh + matrix_wrappers.hpp.
"""

from .partition import (
    ClusterSolverConfig,
    EigenSolverConfig,
    SpectralOutput,
    analyze_modularity,
    analyze_partition,
    modularity_maximization,
    partition,
)

__all__ = [
    "ClusterSolverConfig",
    "EigenSolverConfig",
    "SpectralOutput",
    "analyze_modularity",
    "analyze_partition",
    "modularity_maximization",
    "partition",
]
