"""raft_tpu.spectral — raft/spectral (K4). Under construction."""
