"""Write-ahead log for the delta memtable: crash-durable acknowledged writes.

The delta memtable is the one piece of serving state that lived only in
RAM: a killed process lost every upsert/delete since the last compaction.
FreshDiskANN (Singh et al. 2021 — the fresh/sealed split :mod:`raft_tpu
.stream` reproduces) pairs its in-memory delta with exactly this log so the
mutable tier is crash-durable by construction. The recovery contract:

    durable state = snapshot (``stream.save``, atomic)
                  + WAL records with ``seq`` > the snapshot's ``wal_seq``

- **Append-only, checksummed records.** One record per ``upsert``/``delete``
  call, written at admission BEFORE the rows land in the memtable
  (write-ahead: an acknowledged write is on disk first). Each record is
  ``[type u8 | seq u64 | payload_len u32 | crc32 u32 | payload]`` — a torn
  tail record (crash mid-write) fails its checksum and replay stops there,
  which is exactly right: a record that never finished was never
  acknowledged.
- **Batched fsync.** Every append flushes to the OS (a crashed *process*
  loses nothing); ``fsync_every`` bounds how many records a crashed
  *machine* can lose — the standard group-commit trade
  (``fsync_every=1`` = synchronous durability; the default 8 amortizes the
  fsync wall across a write burst).
- **Truncation rides snapshots.** ``stream.save()`` writes the FULL state
  (sealed + delta + tombstones) atomically, records the last applied
  ``wal_seq`` in the snapshot, and :meth:`WriteAheadLog.reset`\\ s the log —
  the snapshot now covers everything the log did. A compaction swap with a
  ``snapshot_path`` configured does the same after the fold, so the log is
  truncated at every compaction instead of growing without bound.
- **Replay** (:meth:`replay` / ``stream.load(wal=)``) applies records past
  the snapshot's ``wal_seq`` in order through the ordinary write path (WAL
  appends suppressed — the records are already in the log), then re-attaches
  the log for new writes. ``load + replay + warm()`` is the measured
  cold-start path (``bench.py --fault-smoke``, ``crash_recovery_100k``).

Fault points (:mod:`raft_tpu.testing.faults`): ``wal/append`` (per record,
before the write), ``wal/fsync`` (before each batched fsync).

Metrics (catalogue: docs/observability.md): ``raft_tpu_wal_*``.
"""

from __future__ import annotations

import functools
import os
import struct
import threading
import zlib

import numpy as np

from ..core import serialize
from ..core.errors import RaftError, expects
from ..obs import events as obs_events
from ..obs import metrics
from ..testing import faults

__all__ = ["WriteAheadLog", "WalCorruptError"]

# record header: type (u8), seq (u64), payload length (u32), crc32 (u32)
_HDR = struct.Struct("<BQII")
_T_UPSERT, _T_DELETE = 1, 2
_DTYPES = {"float32": 0, "int8": 1, "uint8": 2}
_DTYPES_INV = {v: np.dtype(k) for k, v in _DTYPES.items()}


class WalCorruptError(RaftError):
    """A WAL record failed its checksum somewhere other than the torn
    tail — the log itself is damaged (bit rot, concurrent writer), not
    merely interrupted. Raised by :meth:`WriteAheadLog.replay` with
    ``strict=True``; the default replay stops at the first bad record
    (everything before it was acknowledged and is recovered)."""


@functools.lru_cache(maxsize=None)
def _c_appends():
    return metrics.counter("raft_tpu_wal_appends_total",
                           "WAL records appended (one per upsert/delete "
                           "call, written before the memtable)")


@functools.lru_cache(maxsize=None)
def _c_bytes():
    return metrics.counter("raft_tpu_wal_bytes_total",
                           "WAL bytes appended", unit="bytes")


@functools.lru_cache(maxsize=None)
def _c_fsyncs():
    return metrics.counter("raft_tpu_wal_fsyncs_total",
                           "batched WAL fsyncs (appends/fsyncs is the "
                           "group-commit amortization)")


@functools.lru_cache(maxsize=None)
def _g_size():
    return metrics.gauge("raft_tpu_wal_size_bytes",
                         "current WAL file size (drops to ~0 at each "
                         "snapshot-coupled truncation)", unit="bytes")


@functools.lru_cache(maxsize=None)
def _c_truncations():
    return metrics.counter("raft_tpu_wal_truncations_total",
                           "WAL truncations (snapshot save / compaction "
                           "swap with a snapshot_path)")


@functools.lru_cache(maxsize=None)
def _c_replayed():
    return metrics.counter("raft_tpu_wal_replayed_total",
                           "WAL records applied by crash-recovery replay")


def _encode_upsert(seq: int, rows: np.ndarray, ids: np.ndarray) -> bytes:
    r, d = rows.shape
    payload = (struct.pack("<IIB", r, d, _DTYPES[str(rows.dtype)])
               + np.ascontiguousarray(ids, np.int64).tobytes()
               + np.ascontiguousarray(rows).tobytes())
    return _pack(_T_UPSERT, seq, payload)


def _encode_delete(seq: int, ids: np.ndarray) -> bytes:
    payload = (struct.pack("<I", len(ids))
               + np.ascontiguousarray(ids, np.int64).tobytes())
    return _pack(_T_DELETE, seq, payload)


def _pack(rtype: int, seq: int, payload: bytes) -> bytes:
    return _HDR.pack(rtype, seq, len(payload),
                     zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _decode(rtype: int, payload: bytes):
    if rtype == _T_UPSERT:
        r, d, dt = struct.unpack_from("<IIB", payload)
        off = struct.calcsize("<IIB")
        ids = np.frombuffer(payload, np.int64, count=r, offset=off)
        dtype = _DTYPES_INV[dt]
        rows = np.frombuffer(payload, dtype, count=r * d,
                             offset=off + 8 * r).reshape(r, d)
        return ("upsert", rows, ids)
    if rtype == _T_DELETE:
        (n,) = struct.unpack_from("<I", payload)
        ids = np.frombuffer(payload, np.int64, count=n, offset=4)
        return ("delete", None, ids)
    raise WalCorruptError(f"unknown WAL record type {rtype}")


class WriteAheadLog:
    """One shard's (or one unsharded index's) write-ahead log (see module
    doc). ``fsync_every`` batches fsyncs across that many appends
    (``flush()``/``reset()`` always sync); ``name`` labels the metrics.
    Opening an existing file scans it to recover the last sequence number,
    so appends continue a prior process's numbering — sequence numbers are
    the snapshot/replay coordination and must never restart."""

    def __init__(self, path, *, fsync_every: int = 8,
                 name: str = "default"):
        self.path = os.fspath(path)
        self.name = name
        self.fsync_every = int(fsync_every)
        expects(self.fsync_every >= 1, "fsync_every must be >= 1, got %d",
                self.fsync_every)
        self._lock = threading.Lock()
        self._pending = 0
        self._seq = 0
        self._size = 0
        for seq, _rtype, _payload in self._scan():
            self._seq = seq
        if self.last_scan["torn"]:
            # drop the torn tail BEFORE appending: new records written
            # after garbage bytes would be unreachable to replay (which
            # stops at the first bad record)
            with open(self.path, "r+b") as f:
                f.truncate(self.last_scan["good_bytes"])
        # a CORRUPT record (complete bytes, bad checksum) is evidence of
        # damage, not interruption — it is preserved, replay surfaces it
        # (strict=True raises), and APPENDS refuse: a record written past
        # it would be unreachable to replay, silently un-acknowledging it.
        # reset() (an explicit truncation) clears the condition.
        self._corrupt = self.last_scan["corrupt"]
        fresh = not os.path.exists(self.path)
        self._f = open(self.path, "ab")
        if fresh:
            # make the file's DIRECTORY entry crash-durable — fsyncing
            # record bytes into a file whose creation a machine crash can
            # drop would lose the whole log
            serialize.fsync_dir(os.path.dirname(os.path.abspath(self.path)))
        self._size = self._f.tell()
        self._set_size_gauge()

    # -- append side --------------------------------------------------------
    def append_upsert(self, rows, ids) -> int:
        """Log one upsert (rows + their global ids); returns the record's
        ``seq``. Called BEFORE the memtable insert — the write-ahead
        contract."""
        rows = np.asarray(rows)
        ids = np.asarray(ids, np.int64)
        with self._lock:
            seq = self._seq + 1
            self._append_locked(_encode_upsert(seq, rows, ids))
            self._seq = seq
        return seq

    def append_delete(self, ids) -> int:
        """Log one delete (global ids); returns the record's ``seq``."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._lock:
            seq = self._seq + 1
            self._append_locked(_encode_delete(seq, ids))
            self._seq = seq
        return seq

    def _append_locked(self, rec: bytes) -> None:
        if self._corrupt:
            raise WalCorruptError(
                f"WAL {self.path!r} holds a corrupt record — appending "
                "past it would make this write unreachable to replay; "
                "recover (stream.load(wal=)), snapshot, and reset() first")
        faults.fire("wal/append", name=self.name, seq=self._seq + 1)
        self._f.write(rec)
        # always reach the OS (a dead *process* loses nothing); fsync in
        # batches (a dead *machine* can lose at most fsync_every-1 records)
        self._f.flush()
        self._size += len(rec)
        self._pending += 1
        if self._pending >= self.fsync_every:
            self._fsync_locked()
        if metrics._enabled:
            _c_appends().inc(1, name=self.name)
            _c_bytes().inc(len(rec), name=self.name)
            self._set_size_gauge()

    def _fsync_locked(self) -> None:
        faults.fire("wal/fsync", name=self.name)
        os.fsync(self._f.fileno())
        self._pending = 0
        if metrics._enabled:
            _c_fsyncs().inc(1, name=self.name)

    def flush(self) -> None:
        """Force the batched fsync now (close of a write burst)."""
        with self._lock:
            self._f.flush()
            if self._pending:
                self._fsync_locked()

    def _set_size_gauge(self) -> None:
        if metrics._enabled:
            _g_size().set(self._size, name=self.name)

    @property
    def seq(self) -> int:
        """The last appended sequence number (0 = empty log)."""
        with self._lock:
            return self._seq

    @property
    def size_bytes(self) -> int:
        with self._lock:
            return self._size

    def rollback_last(self, seq: int, prev_size: int) -> None:
        """Remove the record just appended as ``seq`` — the write it
        logged failed on EVERY twin, so the caller is about to raise and
        replaying the record at recovery would resurrect a write the
        application was told did not land. Only valid immediately after
        the matching append with no append in between (the group write
        lock guarantees that)."""
        with self._lock:
            expects(self._seq == seq and prev_size <= self._size,
                    "rollback_last(%d) must immediately follow the "
                    "matching append (log at seq %d)", seq, self._seq)
            self._f.flush()
            self._f.truncate(prev_size)
            os.fsync(self._f.fileno())
            self._seq = seq - 1
            self._size = prev_size
            self._pending = 0  # nothing un-synced survives the truncate
            self._set_size_gauge()

    # -- truncation ---------------------------------------------------------
    def reset(self) -> None:
        """Truncate the log: everything it covered is now in a durable
        snapshot (``stream.save`` calls this AFTER its atomic rename — the
        crash-ordering that can lose nothing: crash before the rename keeps
        old snapshot + full log, crash between rename and reset keeps new
        snapshot + a log whose records are all <= its ``wal_seq`` and
        replay skips them). Sequence numbering continues — it coordinates
        with snapshots and must never restart."""
        with self._lock:
            self._f.close()
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            dropped = self._size
            self._f = open(self.path, "ab")
            self._pending = 0
            self._size = 0
            self._corrupt = False  # explicit truncation clears the damage
            if metrics._enabled:
                _c_truncations().inc(1, name=self.name)
                self._set_size_gauge()
        obs_events.emit("wal_truncated",
                        subject=("wal", self.name, None, None),
                        evidence={"dropped_bytes": dropped,
                                  "path": self.path})

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                if self._pending:
                    self._fsync_locked()
                self._f.close()

    # -- replay side --------------------------------------------------------
    def _scan(self):
        """Yield ``(seq, rtype, payload)`` for every intact record; stops
        at the first bad one. ``self.last_scan`` distinguishes a **torn**
        tail (incomplete bytes at EOF — a crash mid-append; tolerated,
        truncated at reopen) from a **corrupt** record (complete bytes
        failing their checksum — bit rot or a foreign writer; preserved
        as evidence, surfaced by ``replay(strict=True)``), and records the
        byte offset of the last intact record."""
        self.last_scan = {"records": 0, "torn": False, "corrupt": False,
                          "good_bytes": 0}
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(_HDR.size)
                if not hdr:
                    return
                if len(hdr) < _HDR.size:
                    self.last_scan["torn"] = True
                    return
                rtype, seq, plen, crc = _HDR.unpack(hdr)
                payload = f.read(plen)
                if len(payload) < plen:
                    self.last_scan["torn"] = True
                    return
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    self.last_scan["corrupt"] = True
                    return
                self.last_scan["records"] += 1
                self.last_scan["good_bytes"] = f.tell()
                yield seq, rtype, payload

    def replay(self, after_seq: int = 0, *, strict: bool = False):
        """Yield ``(seq, kind, rows, ids)`` for every intact record with
        ``seq > after_seq`` (the snapshot's ``wal_seq``), in append order.
        A torn tail (crash mid-append: the record was never acknowledged)
        is always tolerated; a CORRUPT record — complete bytes failing
        their checksum — stops replay there by default, and with
        ``strict=True`` raises :class:`WalCorruptError` instead, so
        operators can tell interruption from damage."""
        n = 0
        for seq, rtype, payload in self._scan():
            if seq <= after_seq:
                continue
            kind, rows, ids = _decode(rtype, payload)
            n += 1
            yield seq, kind, rows, ids
        if strict and self.last_scan["corrupt"]:
            raise WalCorruptError(
                f"WAL {self.path!r} has a corrupt record after "
                f"{self.last_scan['records']} intact ones")
        if n and metrics._enabled:
            _c_replayed().inc(n, name=self.name)
        if n:
            obs_events.emit(
                "wal_recovered",
                severity="warning" if self.last_scan["corrupt"] else "info",
                subject=("wal", self.name, None, None),
                evidence={"replayed": n,
                          "torn_tail": self.last_scan["torn"],
                          "corrupt": self.last_scan["corrupt"]})
