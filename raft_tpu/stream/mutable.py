"""MutableIndex: an LSM-style mutable lifecycle over any sealed ANN index.

Every index in :mod:`raft_tpu.neighbors` is immutable-at-best after build
(``extend`` appends; nothing deletes), yet a production corpus churns —
live traffic upserts and deletes rows continuously. The standard answer is
the fresh/sealed split of FreshDiskANN (Singh et al. 2021), which is the
memtable/compaction shape of LSM-trees (O'Neil et al. 1996) applied to ANN:

- **Delta memtable** — recent writes land in a fixed-capacity row buffer
  scanned by the exact fused-kNN at serve time. The buffer is exposed to
  the device at power-of-two *bucket* sizes (8, 16, ..., ``delta_capacity``
  — the same shape discipline as :mod:`raft_tpu.serve.batcher`'s query
  buckets), so delta growth never compiles on the hot path once
  :meth:`MutableIndex.warm` has touched the ladder.
- **Tombstone bitsets** — deletes flip per-slot alive bits: the sealed
  index is filtered through its module's ``sample_filter=`` epilogue (the
  reason every neighbors module grew one), the delta through the same mask
  applied to its exact scan. ``upsert`` = tombstone-the-old-slot +
  insert-new, so an id is live in exactly one physical slot at a time.
- **Unified search** — sealed(filtered) and delta scans merge through the
  existing ``select_k`` dispatch; slot-local ids translate to stable global
  ids through a device-resident id map. Results are indistinguishable from
  a fresh build over the live rows (bit-equal ids for exact sealed kinds,
  recall-parity for quantized ones — pinned by ``tests/test_stream.py``).
- **Compaction** (:mod:`raft_tpu.stream.compactor`) folds delta+tombstones
  into a new sealed index off the hot path — ``extend`` where the sealed
  kind supports it (IVF-Flat/IVF-PQ), full rebuild where it does not
  (CAGRA, brute-force) or when tombstones must actually be reclaimed — and
  swaps it in atomically. Writes that land during a fold are never lost:
  the fold consumes a snapshot *prefix* of the append-only delta, the
  remainder carries over, and the swap recomputes every alive bit from the
  live tombstone state.

Thread-safety: all mutations run under one lock; searches take a handle
snapshot and run lock-free (device arrays are immutable once published to a
state). :meth:`searcher` returns a serving hook pinned to the CURRENT state
object, which is exactly what :class:`raft_tpu.serve.IndexRegistry` leases:
after a compaction swap, in-flight flushes drain on the pinned (frozen)
pre-compaction state while new flushes pick up the published successor.
"""

from __future__ import annotations

import functools
import threading
import time
from dataclasses import dataclass
from typing import Callable

import numpy as np

from ..core.errors import RaftError, expects
from ..core.resources import default_resources
from ..distance.types import DistanceType, resolve_metric
from ..obs import dispatch as obs_dispatch
from ..obs import mem as obs_mem
from ..obs import metrics
from ..serve.errors import OverloadedError
from ..testing import faults
from .tiered import TieredStore, TierPolicy

__all__ = ["MutableIndex", "DeltaFullError", "DELTA_MIN_BUCKET",
           "delta_buckets", "save", "load"]

# NOTE on sharding: raft_tpu/stream/sharded.py composes S of these (one per
# mesh shard, device-pinned via ``device=`` with shard-owned global ids via
# ``ids=``) into a scatter-gather ShardedMutableIndex; the per-shard scan
# halves of :func:`_search_state` are exposed as :func:`_scan_state` so the
# sharded tier can merge ALL shards' sealed+delta candidates through ONE
# ``select_k`` dispatch instead of S per-shard merges.

# floor of the delta bucket ladder: an empty delta still scans one fully
# masked bucket of this size, so "delta empty" and "delta tiny" share a
# program instead of forking the hot path
DELTA_MIN_BUCKET = 8


class DeltaFullError(OverloadedError):
    """The delta memtable is at capacity — writes shed load exactly like the
    serve queue bound (same admission-control taxonomy: this IS an
    ``OverloadedError``). Compact, or attach a
    :class:`raft_tpu.stream.Compactor` whose delta-fill watermark folds the
    memtable before it fills."""


def delta_buckets(capacity: int) -> tuple[int, ...]:
    """The delta memtable's power-of-two device-shape ladder
    ``(8, 16, ..., capacity)``."""
    expects(capacity >= DELTA_MIN_BUCKET
            and (capacity & (capacity - 1)) == 0,
            "delta_capacity must be a power of two >= %d, got %d",
            DELTA_MIN_BUCKET, capacity)
    out, b = [], DELTA_MIN_BUCKET
    while b <= capacity:
        out.append(b)
        b *= 2
    return tuple(out)


def _bucket_for(n: int, capacity: int) -> int:
    b = DELTA_MIN_BUCKET
    while b < n:
        b *= 2
    return min(b, capacity)


def check_upsert_ids(ids, r: int) -> np.ndarray:
    """Validate caller-chosen upsert ids — the ONE id contract shared by
    the plain, sharded and replicated write paths (shape ``(r,)``,
    unique within the call, ``>= 0``, int32-representable for the device
    id maps). Returns the int64 array."""
    gids = np.asarray(ids, np.int64).reshape(-1)
    expects(gids.shape == (r,), "ids must match rows (%d)", r)
    expects(np.unique(gids).size == r,
            "upsert ids must be unique within one call")
    expects(int(gids.min()) >= 0, "ids must be >= 0")
    expects(int(gids.max()) < 2 ** 31 - 1,
            "ids must fit int32 (device id maps are int32)")
    return gids


# -- metrics (catalogue: docs/observability.md) ------------------------------

@functools.lru_cache(maxsize=None)
def _g_delta_fill():
    return metrics.gauge("raft_tpu_stream_delta_fill",
                         "delta memtable fill fraction (rows / capacity)")


@functools.lru_cache(maxsize=None)
def _g_delta_rows():
    return metrics.gauge("raft_tpu_stream_delta_rows",
                         "rows currently in the delta memtable")


@functools.lru_cache(maxsize=None)
def _g_tombstone():
    return metrics.gauge(
        "raft_tpu_stream_tombstone_ratio",
        "dead sealed slots / sealed slots (reclaimable by rebuild compaction)")


@functools.lru_cache(maxsize=None)
def _c_upserts():
    return metrics.counter("raft_tpu_stream_upserts_total",
                           "rows upserted into the delta memtable")


@functools.lru_cache(maxsize=None)
def _c_deletes():
    return metrics.counter("raft_tpu_stream_deletes_total",
                           "live rows tombstoned by delete/upsert")


@functools.lru_cache(maxsize=None)
def _c_delta_full():
    return metrics.counter("raft_tpu_stream_delta_full_total",
                           "writes refused because the delta memtable is full")


# -- per-kind dispatch -------------------------------------------------------

def _resolve_kind(sealed):
    from ..neighbors import brute_force, cagra, ivf_flat, ivf_pq

    for kind, mod, cls in (("brute_force", brute_force, brute_force.BruteForce),
                           ("ivf_flat", ivf_flat, ivf_flat.IvfFlatIndex),
                           ("ivf_pq", ivf_pq, ivf_pq.IvfPqIndex),
                           ("cagra", cagra, cagra.CagraIndex)):
        if isinstance(sealed, cls):
            return kind, mod
    raise RaftError(
        f"MutableIndex cannot wrap {type(sealed).__name__!r} (expected "
        "BruteForce, IvfFlatIndex, IvfPqIndex or CagraIndex)")


def _sealed_meta(kind, sealed):
    """(n_rows, dim, metric, metric_arg, data_kind) of a sealed index."""
    if kind == "brute_force":
        expects(sealed.dataset is not None, "sealed brute_force index is not built")
        n, d = sealed.dataset.shape
        dk = str(sealed.dataset.dtype)
        if dk not in ("int8", "uint8"):
            dk = "float32"
        return n, d, resolve_metric(sealed.metric), float(sealed.metric_arg), dk
    return (sealed.size, sealed.dim, sealed.metric, 2.0, sealed.data_kind)


def _store_rows(store) -> np.ndarray | None:
    """The raw rows of a retained store as a host array: a plain ``hbm``
    store IS the array, a :class:`~raft_tpu.stream.tiered.TieredStore`
    exposes its cold copy — compaction folds, drift sampling and
    serialization all read rows through this one seam."""
    if store is None:
        return None
    return store.host_view() if isinstance(store, TieredStore) else store


def _recover_store(kind, sealed, data_kind):
    """Raw live rows in the SERVING dtype, when the sealed kind stores them
    (brute-force/CAGRA keep the dataset; uint8 CAGRA holds it shifted into
    the s8 domain and is unshifted here). IVF kinds store lists/codes, not
    rows — their store must be supplied via ``dataset=``."""
    import jax

    if kind == "brute_force":
        return np.asarray(jax.device_get(sealed.dataset))
    if kind == "cagra":
        ds = np.asarray(jax.device_get(sealed.dataset))
        if data_kind == "uint8":
            return (ds.astype(np.int16) + 128).astype(np.uint8)
        return ds
    return None


def _sealed_search(cfg, sealed, queries, k, keep_mask, res=None):
    from ..neighbors import brute_force

    if cfg.kind == "brute_force":
        return brute_force.knn(sealed.dataset, queries, k, cfg.metric,
                               cfg.metric_arg, sample_filter=keep_mask,
                               res=res)
    return cfg.module.search(cfg.search_params, sealed, queries, k,
                             sample_filter=keep_mask, res=res)


# -- jitted merge pieces -----------------------------------------------------

@functools.cache
def _jnp():
    import jax.numpy as jnp

    return jnp


@functools.cache
def _jits():
    import jax
    import jax.numpy as jnp

    from ..matrix.select_k import _select_k

    @jax.jit
    def map_ids(ids, id_map):
        g = jnp.take(id_map, jnp.clip(ids, 0), axis=0)
        return jnp.where(ids >= 0, g, -1)

    @functools.partial(jax.jit, static_argnames=("k", "select_min"))
    def merge(sealed_d, sealed_i, delta_d, delta_i, k: int, select_min: bool):
        d = jnp.concatenate([sealed_d, delta_d], axis=1)
        i = jnp.concatenate([sealed_i, delta_i], axis=1)
        dv, iv = _select_k(d, i, k, select_min)
        # underfilled slots keep the shared sentinel: id -1 at ±inf
        return dv, jnp.where(jnp.isinf(dv), -1, iv)

    return map_ids, merge


def _map_ids(ids, id_map):
    """Translate slot-local ids to global ids; -1 sentinels pass through."""
    return _jits()[0](ids, id_map)


def _merge(sealed_d, sealed_i, delta_d, delta_i, k, select_min):
    obs_dispatch.note(1)
    return _jits()[1](sealed_d, sealed_i, delta_d, delta_i, int(k),
                      bool(select_min))


# -- state ------------------------------------------------------------------

@dataclass(frozen=True)
class _Config:
    """Immutable wrap-time configuration shared by every state epoch."""

    kind: str
    module: object
    search_params: object
    metric: DistanceType
    metric_arg: float
    select_min: bool
    dim: int
    data_kind: str
    query_dtype: str
    name: str
    # optional device pin (the sharded tier places each shard's arrays —
    # and therefore its compute — on its own mesh device); None = default
    device: object = None


def _dev_put(cfg: "_Config", x):
    """Upload a host array to the config's device: COMMITTED when a device
    pin is set (committed inputs make every downstream program run on the
    shard's device — jax's placement-follows-committed-args rule is the
    whole scatter mechanism), plain ``jnp.asarray`` otherwise (identical to
    the pre-sharding behavior, bit for bit)."""
    if cfg.device is None:
        return _jnp().asarray(x)
    import jax

    return jax.device_put(x, cfg.device)


class _StreamState:
    """One epoch of mutable-index state. The big arrays (sealed index,
    id map) are frozen per epoch — compaction builds a successor and swaps —
    while the tombstone/delta device handles are REPLACED (never mutated in
    place) on every write, so a search that snapshots the handles is always
    internally consistent without holding the write lock."""

    __slots__ = ("cfg", "sealed", "id_map", "sealed_alive", "sealed_dead_n",
                 "store", "delta", "delta_ids", "delta_alive", "delta_n",
                 "delta_oldest_at", "epoch", "id_map_dev", "sealed_keep_dev",
                 "delta_view", "store_dev", "mem", "__weakref__")

    def __init__(self, cfg: _Config):
        self.cfg = cfg
        self.delta_n = 0
        self.delta_oldest_at = None
        self.epoch = 0
        # incremental dead-sealed-slot count (== n - sealed_alive.sum(),
        # maintained at tombstone/swap time): stats() and the gauge updates
        # on the write path must not scan the whole bitset per write — the
        # sharded tier aggregates stats across S shards on EVERY routed
        # upsert/delete
        self.sealed_dead_n = 0
        # device copy of the retained row store, built lazily on the first
        # exact_search of an epoch (the recall canary's shadow oracle) —
        # never on the serving hot path
        self.store_dev = None
        # obs.mem ledger token for this epoch's stream-owned arrays (delta
        # view, masks, id map, store) — auto-releases when the state is
        # collected, which is the retirement-audit hook for pre-compaction
        # epochs
        self.mem = None


def _np_dtype(query_dtype: str):
    return {"float32": np.float32, "int8": np.int8,
            "uint8": np.uint8}[query_dtype]


def _refresh_sealed_keep(st: _StreamState) -> None:
    st.sealed_keep_dev = _dev_put(st.cfg, st.sealed_alive)


def _refresh_delta(st: _StreamState, capacity: int,
                   mask_only: bool = False) -> None:
    b = _bucket_for(st.delta_n, capacity)
    keep = st.delta_alive[:b] & (np.arange(b) < st.delta_n)
    # ONE attribute assignment: a lock-free reader snapshots rows, mask and
    # ids that always belong to the same bucket shape (per-field replacement
    # would let a grown rows array pair with a stale shorter mask).
    # Transfer economy: deletes (mask_only — rows/ids untouched, bucket
    # unchanged) reuse the published device arrays and re-upload just the
    # bool mask. Upserts re-upload the whole bucket: a device-side splice
    # of only the appended rows (lax.dynamic_update_slice) was considered
    # and REJECTED — its program is keyed on the caller's write batch size,
    # which warm() cannot enumerate, so it would put data-dependent
    # compiles on the write path and void the warmed-ladder zero-compile
    # guarantee the bucket discipline exists for. The memtable is small by
    # design (<= capacity rows), so the O(bucket) host upload is bounded,
    # value-independent, and compile-free.
    view = getattr(st, "delta_view", None)
    if mask_only and view is not None and view[3] == b:
        rows_dev, ids_dev = view[0], view[2]
    else:
        rows_dev = _dev_put(st.cfg, st.delta[:b])
        ids_dev = _dev_put(st.cfg, st.delta_ids[:b])
    st.delta_view = (rows_dev, _dev_put(st.cfg, keep), ids_dev, b)


def _build_loc(st: _StreamState) -> dict:
    """id → live-slot map, built from vectorized numpy passes (zip over
    materialized lists — ~10x a per-row Python loop with int() casts; at the
    bench's 100k scale this runs in single-digit ms, which matters because
    the compaction swap rebuilds it under the write lock)."""
    s_slots = np.nonzero(st.sealed_alive)[0]
    loc = dict(zip(st.id_map[s_slots].tolist(),
                   zip(("s",) * len(s_slots), s_slots.tolist())))
    d_slots = np.nonzero(st.delta_alive[:st.delta_n])[0]
    loc.update(zip(st.delta_ids[d_slots].tolist(),
                   zip(("d",) * len(d_slots), d_slots.tolist())))
    return loc


def _scan_state(st: _StreamState, queries, k: int, res=None,
                k_sealed: int | None = None):
    """The scatter half of a one-epoch search: sealed(filtered) scan +
    delta scan with slot-local ids mapped to the global space — everything
    BEFORE the select_k merge, so the sharded tier
    (:mod:`raft_tpu.stream.sharded`) can collect every shard's candidate
    sets and merge them through ONE dispatch (the knn_merge_parts contract
    generalized to mixed sealed+delta parts). All device handles are
    snapshotted up front, so a concurrent write (which replaces handles,
    never mutates them) cannot tear this call. Stage walls are recorded as
    ``stream/sealed`` / ``stream/delta`` request-log spans (host dispatch
    walls — jax is async; no-op unless a collector is open on this thread)
    plus the state epoch, so a traced flush attributes to a concrete index
    epoch and stream stage (the sharded tier prefixes them per shard).

    Returns ``(sealed_d (m, k), sealed_i, delta_d (m, kd), delta_i)`` with
    global ids and the shared ``-1 / ±inf`` sentinel in unfillable slots.
    ``k_sealed`` (sharded tier only) narrows the sealed candidate width —
    a shard with fewer sealed rows than k contributes what it has and the
    merge pads the rest; the single-device path keeps its k-≤-rows
    contract untouched.
    """
    from ..neighbors import brute_force
    from ..obs import requestlog

    jnp = _jnp()
    cfg = st.cfg
    requestlog.annotate("stream_epoch", st.epoch)
    # handle snapshot — one consistent view (delta_view is assigned as one
    # tuple, sealed/id_map are frozen per epoch, sealed_keep only changes
    # VALUES within an epoch, never shape). ORDER MATTERS: the delta view
    # is read BEFORE the sealed keep-mask, pairing with upsert's
    # kill-then-reveal publish order (sealed mask first, delta second) — a
    # reader that sees an upserted id's new delta copy is then guaranteed
    # to also see the old sealed copy's tombstone; the reverse read order
    # could surface BOTH copies of one id in a single result row. (The
    # benign anomaly — an id briefly absent — is the one the design
    # accepts, like any read racing a write.)
    delta, dkeep, dids, _ = st.delta_view
    sealed, skeep, imap = st.sealed, st.sealed_keep_dev, st.id_map_dev

    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == cfg.dim,
            "queries must be (rows, %d)", cfg.dim)
    if cfg.query_dtype == "float32":
        queries = queries.astype(jnp.float32)
    k = int(k)
    ks = k if k_sealed is None else int(k_sealed)
    t0 = time.perf_counter()
    sd, si = _sealed_search(cfg, sealed, queries, ks, skeep, res=res)
    si = _map_ids(si, imap)
    t1 = time.perf_counter()
    kd = min(k, delta.shape[0])
    dd, di = brute_force.knn(delta, queries, kd, cfg.metric, cfg.metric_arg,
                             sample_filter=dkeep, res=res)
    di = _map_ids(di, dids)
    t2 = time.perf_counter()
    # the dispatch meter (obs/dispatch.py): sealed search + delta scan +
    # the two id maps = 4 instrumented sites per epoch scan
    obs_dispatch.note(4)
    requestlog.add_span("stream/sealed", t1 - t0)
    requestlog.add_span("stream/delta", t2 - t1)
    return sd, si, dd, di


def _search_state(st: _StreamState, queries, k: int, res=None):
    """Unified search over one state epoch: the sealed+delta scan
    (:func:`_scan_state`) merged through select_k (``stream/merge`` span)."""
    from ..obs import requestlog

    sd, si, dd, di = _scan_state(st, queries, k, res=res)
    t0 = time.perf_counter()
    out = _merge(sd, si, dd, di, int(k), st.cfg.select_min)
    requestlog.add_span("stream/merge", time.perf_counter() - t0)
    return out


# -- the mutable index -------------------------------------------------------

class MutableIndex:
    """Mutable lifecycle wrapper over a sealed index (see module docstring).

    ``sealed`` must be a freshly built (or loaded) index whose stored ids
    are the dense row range ``0..n-1`` — exactly what ``build()`` produces.
    ``search_params`` are baked in at wrap time (the serving-hook
    discipline); ``index_params`` are required only for rebuild compaction
    of IVF kinds. ``delta_capacity`` (power of two) bounds the memtable;
    ``retain_vectors`` keeps a host-side raw row store (required for
    rebuild compaction — auto-recovered from brute-force/CAGRA sealed
    datasets, supplied via ``dataset=`` for IVF kinds, whose codes cannot
    reconstruct rows). ``builder`` (optional) replaces the default
    ``module.build(index_params, rows)`` in rebuild compaction: any
    ``fn(rows, res=None) -> sealed-index-of-the-same-kind`` — the hook that
    lets compactions rebuild SHARDED over a mesh
    (:func:`raft_tpu.parallel.cagra.merged_builder`), shrinking the rebuild
    wall that bounds sustainable write churn. Like ``search_params`` it is
    runtime configuration: never serialized, supplied fresh to ``load``.
    ``ids`` (optional, length-n unique non-negative ints) assigns the
    sealed rows' GLOBAL ids — by default the dense row range the sealed
    build produced. The sharded tier uses this as its global-id offset
    map: each shard's sealed index stays a dense local build while its
    results surface the caller's global id space, and fresh ids continue
    past ``max(ids)``. ``device`` (optional) pins every device array (and
    therefore every search program — jax placement follows committed
    inputs) to one device: the scatter mechanism of
    :class:`raft_tpu.stream.sharded.ShardedMutableIndex`, where shard ``s``
    lives on mesh device ``s`` and only candidate tuples ever leave it.
    ``shard`` (optional) is the shard ordinal for obs.mem ledger
    attribution — the sharded tier passes its index so ``/debug/mem``
    breaks bytes down per shard. ``storage`` picks where the retained
    raw rows live: ``"hbm"`` (default — the pre-tiering behavior, a
    host array with a lazy full device copy for the oracle) or
    ``"tiered"`` (:class:`~raft_tpu.stream.tiered.TieredStore`: rows in
    host RAM or an mmap'd file per ``tier`` — a
    :class:`~raft_tpu.stream.tiered.TierPolicy` — with refine/oracle
    batches crossing to the device through a double-buffered gather;
    see :meth:`search_refined` and docs/streaming.md "Tiered storage").
    ``clock`` is injected for deterministic tests (the age watermark's
    time base).
    """

    def __init__(self, sealed, *, search_params=None, index_params=None,
                 delta_capacity: int = 1024, retain_vectors: bool | None = None,
                 dataset=None, builder: Callable | None = None,
                 ids=None, device=None, name: str = "default",
                 shard: int | None = None, wal=None,
                 snapshot_path: str | None = None,
                 storage: str = "hbm", tier: TierPolicy | None = None,
                 tier_residency: str | None = None,
                 clock: Callable[[], float] = time.monotonic):
        kind, module = _resolve_kind(sealed)
        n, d, metric, metric_arg, data_kind = _sealed_meta(kind, sealed)
        expects(n > 0, "cannot wrap an empty sealed index")
        if device is not None:
            import jax

            if kind == "brute_force":
                # BruteForce is not a pytree — move its dataset in place
                # (the wrap takes ownership of the sealed index anyway)
                sealed.dataset = jax.device_put(sealed.dataset, device)
            else:
                sealed = jax.device_put(sealed, device)
        if kind in ("ivf_flat", "ivf_pq"):
            # the id-map contract: internal ids are the dense row range
            import jax.numpy as jnp

            expects(int(jnp.max(sealed.list_ids)) == n - 1,
                    "sealed %s ids must be the dense row range 0..n-1 "
                    "(a fresh build); wrap before extending with custom ids",
                    kind)
        query_dtype = data_kind if data_kind in ("int8", "uint8") else "float32"
        if search_params is None and kind != "brute_force":
            # default params at WRAP time, not an AttributeError at first
            # search (which could land on a serving thread)
            search_params = module.SearchParams()
        if (kind == "ivf_pq"
                and getattr(search_params, "funnel_widen", 1) > 1):
            # fail the funnel/tier mismatch at WRAP time, not on a serving
            # thread at first search (same rationale as the default above)
            expects(sealed.has_fast_scan,
                    "search_params pins funnel_widen=%d but the sealed "
                    "index carries no fast-scan tier — build with "
                    "IndexParams.fast_scan='1bit'|'4bit'",
                    int(search_params.funnel_widen))
        cfg = _Config(kind=kind, module=module, search_params=search_params,
                      metric=metric, metric_arg=metric_arg,
                      select_min=metric != DistanceType.InnerProduct,
                      dim=d, data_kind=data_kind, query_dtype=query_dtype,
                      name=name, device=device)
        self._cfg = cfg
        # shard ordinal for obs.mem ledger attribution (the sharded tier
        # passes its shard index; None = unsharded)
        self._shard = None if shard is None else int(shard)
        self._index_params = index_params
        expects(builder is None or callable(builder),
                "builder must be a callable fn(rows, res=None) -> sealed index")
        self._builder = builder
        self.delta_capacity = int(delta_capacity)
        self._buckets = delta_buckets(self.delta_capacity)
        self._clock = clock
        self._lock = threading.RLock()
        self._compact_lock = threading.Lock()
        # durability (docs/streaming.md "Durability & replication"): a WAL
        # logs every upsert/delete at admission, BEFORE the memtable sees
        # it; snapshot_path couples compaction swaps to an atomic snapshot
        # + WAL truncation. A fresh wrap refuses a log that already holds
        # records — those belong to an earlier life of this index and must
        # be recovered through stream.load(wal=), not silently shadowed.
        if wal is not None and not hasattr(wal, "append_upsert"):
            from .wal import WriteAheadLog

            wal = WriteAheadLog(wal, name=name)
        self._wal = wal
        self._wal_seq = 0
        self._snapshot_path = snapshot_path
        if wal is not None:
            expects(wal.seq == 0,
                    "WAL %r already holds records (seq=%d) — a fresh wrap "
                    "would shadow them; recover with stream.load(wal=) or "
                    "point at a fresh log", getattr(wal, "path", "?"),
                    wal.seq)
        if ids is None:
            id_map = np.arange(n, dtype=np.int64)
        else:
            id_map = np.asarray(ids, np.int64).reshape(-1)
            expects(id_map.shape == (n,),
                    "ids= must assign one global id per sealed row (%d), "
                    "got %d", n, id_map.shape[0])
            expects(np.unique(id_map).size == n, "ids= must be unique")
            expects(int(id_map.min()) >= 0, "ids= must be >= 0")
            expects(int(id_map.max()) < 2 ** 31 - 1,
                    "ids= must fit int32 (device id maps are int32)")
        self._next_id = int(id_map.max()) + 1
        self._loc: dict[int, tuple[str, int]] = {}

        store = None
        if dataset is not None:
            from ..core import chunked

            # a ChunkedReader dataset (the out-of-core build's corpus)
            # contributes its BACKING array — for an np.memmap that keeps
            # the retained rows disk-backed end to end (TieredStore
            # adopts the mmap; see stream/tiered.py), the corpus is
            # never copied into RAM
            store = (dataset.host_view() if chunked.is_reader(dataset)
                     else np.asarray(dataset))
            expects(store.shape == (n, d),
                    "dataset= must be the sealed rows (%d, %d), got %s",
                    n, d, tuple(store.shape))
            if query_dtype == "float32":
                if store.dtype != np.float32:
                    store = np.asarray(store, np.float32)
            else:
                expects(str(store.dtype) == query_dtype,
                        "dataset= dtype %s must match the serving dtype %s",
                        store.dtype, query_dtype)
        elif retain_vectors is not False:
            store = _recover_store(kind, sealed, data_kind)
        if retain_vectors is True:
            expects(store is not None,
                    "retain_vectors=True needs dataset= for %s (stored codes "
                    "cannot reconstruct raw rows)", kind)
        # the beyond-HBM storage policy (docs/streaming.md "Tiered
        # storage"): "tiered" keeps the full-precision rows cold (host
        # RAM / disk mmap) behind a TieredStore — the refine epilogue and
        # the exact oracle then cross to the device per batch instead of
        # pinning a second full-precision copy in HBM
        expects(storage in ("hbm", "tiered"),
                "storage must be 'hbm' or 'tiered', got %r", storage)
        expects(tier is None or storage == "tiered",
                "tier= (a TierPolicy) applies to storage='tiered' only")
        expects(tier_residency is None or storage == "tiered",
                "tier_residency= applies to storage='tiered' only")
        if storage == "tiered":
            expects(store is not None,
                    "storage='tiered' stores the raw refine rows cold — "
                    "pass dataset= (IVF kinds) or retain_vectors=True")
        self._storage = storage
        self._tier = tier

        st = _StreamState(cfg)
        st.sealed = sealed
        st.id_map = id_map
        st.sealed_alive = np.ones(n, bool)
        # tier_residency (load()'s layout-restore seam) skips the
        # placement decision entirely — re-deciding here and correcting
        # later would pay a full wasted H2D for a cold-saved store
        st.store = self._make_store(store, epoch=0,
                                    residency=tier_residency)
        dt = _np_dtype(query_dtype)
        st.delta = np.zeros((self.delta_capacity, d), dt)
        st.delta_ids = np.zeros(self.delta_capacity, np.int32)
        st.delta_alive = np.zeros(self.delta_capacity, bool)
        st.id_map_dev = _dev_put(cfg, st.id_map.astype(np.int32))
        _refresh_sealed_keep(st)
        _refresh_delta(st, self.delta_capacity)
        self._state = st
        self._loc = _build_loc(st)
        # ledger attribution: the sealed store re-attributes under the
        # serving name (idempotent per index object); the stream-owned
        # arrays get their own per-epoch entry
        self._sealed_mem = obs_mem.account_index(
            sealed, name=cfg.name, shard=self._shard, epoch=0)
        self._update_gauges(st)

    # -- introspection ------------------------------------------------------
    @property
    def kind(self) -> str:
        return self._cfg.kind

    @property
    def dim(self) -> int:
        return self._cfg.dim

    @property
    def name(self) -> str:
        return self._cfg.name

    @property
    def query_dtype(self) -> str:
        return self._cfg.query_dtype

    @property
    def can_rebuild(self) -> bool:
        """Whether rebuild compaction (the tombstone-reclaiming mode) is
        available: a raw row store, plus build params for IVF kinds."""
        st = self._state
        if st.store is None:
            return False
        return (self._cfg.kind in ("brute_force", "cagra")
                or self._index_params is not None
                or self._builder is not None)

    @property
    def size(self) -> int:
        """Live (searchable) rows."""
        with self._lock:
            st = self._state
            return int(len(st.sealed_alive) - st.sealed_dead_n
                       + st.delta_alive[:st.delta_n].sum())

    def _make_store(self, rows, epoch: int, residency: str | None = None):
        """Materialize the retained row store for one state epoch: the
        raw array under ``storage="hbm"``, a
        :class:`~raft_tpu.stream.tiered.TieredStore` under ``"tiered"``
        (per-epoch — a compaction successor's store re-places against the
        budget, carrying the predecessor's residency when asked, which is
        how tier residency migrates through the fold-and-swap)."""
        if rows is None or self._storage == "hbm":
            return rows
        # rows pass RAW: TieredStore adopts an np.memmap in place (zero
        # host bytes) — an asarray here would strip that provenance
        return TieredStore(
            rows, name=self._cfg.name, shard=self._shard,
            epoch=epoch, policy=self._tier, device=self._cfg.device,
            residency=residency, clock=self._clock)

    @property
    def storage(self) -> str:
        """The storage policy ("hbm" or "tiered")."""
        return self._storage

    @property
    def tiered_store(self) -> TieredStore | None:
        """The live epoch's :class:`TieredStore` (None under "hbm")."""
        st = self._state.store
        return st if isinstance(st, TieredStore) else None

    def _drift_store(self):
        """The retained raw-row store (or None) — what a
        :class:`~raft_tpu.stream.Compactor` feeds the corpus-side drift
        detector; the sharded tier overrides this with a cross-shard
        subsample."""
        return _store_rows(self._state.store)

    def stats(self) -> dict:
        with self._lock:
            st = self._state
            n_sealed = len(st.sealed_alive)
            dead = int(st.sealed_dead_n)
            return {
                "live": int(n_sealed - dead
                            + st.delta_alive[:st.delta_n].sum()),
                "sealed_rows": n_sealed,
                "sealed_dead": dead,
                "tombstone_ratio": dead / max(n_sealed, 1),
                "delta_rows": int(st.delta_n),
                "delta_fill": st.delta_n / self.delta_capacity,
                "delta_bucket": st.delta_view[3],
                "delta_oldest_at": st.delta_oldest_at,
                "epoch": st.epoch,
            }

    def _update_gauges(self, st: _StreamState) -> None:
        if not metrics._enabled:
            return
        name = self._cfg.name
        n_sealed = len(st.sealed_alive)
        dead = int(st.sealed_dead_n)
        _g_delta_fill().set(st.delta_n / self.delta_capacity, name=name)
        _g_delta_rows().set(st.delta_n, name=name)
        _g_tombstone().set(dead / max(n_sealed, 1), name=name)
        self._account_state(st)

    def _account_state(self, st: _StreamState) -> None:
        """(Re)account this epoch's stream-owned arrays in the obs.mem
        ledger: device = the published delta view + masks + id map (+ the
        lazy store copy), host = the preallocated memtable, bitsets and
        retained store. Keyed on the STATE object, so a compaction swap
        leaves the old epoch's entry to auto-release at drain — exactly
        what the retirement audit watches."""
        if not metrics._enabled:
            return
        dev = [st.id_map_dev, st.sealed_keep_dev, *st.delta_view[:3]]
        if st.store_dev is not None:
            dev.append(st.store_dev)
        host = [st.delta, st.delta_ids, st.delta_alive, st.sealed_alive,
                st.id_map]
        # a TieredStore carries its own "tier" ledger entry (rows + mirror
        # + gather slots) — ONE attribution, not a second copy here
        if st.store is not None and not isinstance(st.store, TieredStore):
            host.append(st.store)
        if st.mem is None:
            st.mem = obs_mem.account(
                "stream", name=self._cfg.name, shard=self._shard,
                epoch=st.epoch, device=dev, host=host, owner=st)
        else:
            obs_mem.reaccount(st.mem, device=dev, host=host)

    def _growth_bytes(self, r: int) -> int:
        """Device bytes a write of ``r`` rows would newly allocate — the
        uniform admission surface the sharded/replicated tiers price their
        hoisted whole-or-nothing gate with."""
        return self._delta_growth_bytes(self._state, r)

    def _delta_rows_now(self) -> int:
        """Current delta occupancy for hoisted admission checks (reads a
        snapshot without the lock: concurrent folds only SHRINK a delta,
        so a stale read can only over-refuse, never admit past capacity)."""
        return int(self._state.delta_n)

    def _delta_growth_bytes(self, st: _StreamState, r: int) -> int:
        """Device bytes a write of ``r`` rows would newly allocate: the
        delta bucket ladder only grows in power-of-two steps, and a grown
        bucket re-uploads rows+ids+mask (the old bucket's arrays free)."""
        b0 = st.delta_view[3]
        b1 = _bucket_for(st.delta_n + r, self.delta_capacity)
        if b1 <= b0:
            return 0
        return (b1 - b0) * (self._cfg.dim * st.delta.dtype.itemsize + 4 + 1)

    # -- writes -------------------------------------------------------------
    def _coerce_rows(self, rows):
        rows = np.asarray(rows)
        expects(rows.ndim == 2 and rows.shape[1] == self._cfg.dim,
                "rows must be (r, %d)", self._cfg.dim)
        if self._cfg.query_dtype == "float32":
            return np.asarray(rows, np.float32)
        expects(str(rows.dtype) == self._cfg.query_dtype,
                "byte index %r takes %s rows, got %s", self._cfg.name,
                self._cfg.query_dtype, rows.dtype)
        return rows

    def upsert(self, rows, ids=None, res=None):
        """Insert rows (fresh ids assigned and returned) or upsert under
        caller-chosen ids: the previous live occurrence of each id is
        tombstoned and the new row becomes visible to the very next search
        (read-your-writes — no compaction needed). Raises
        :class:`DeltaFullError` (an ``OverloadedError``) at capacity, and
        :class:`~raft_tpu.serve.errors.MemoryBudgetError` (also an
        ``OverloadedError``) when growing the delta's device bucket would
        exceed ``res.memory_budget_bytes`` — both BEFORE any row lands
        (whole-or-nothing)."""
        rows = self._coerce_rows(rows)
        r = rows.shape[0]
        expects(r >= 1, "upsert needs at least one row")
        with self._lock:
            st = self._state
            obs_mem.gate(res or default_resources(),
                         lambda: self._delta_growth_bytes(st, r),
                         site="upsert", detail=f"stream {self._cfg.name!r}")
            if st.delta_n + r > self.delta_capacity:
                if metrics._enabled:
                    _c_delta_full().inc(1, name=self._cfg.name)
                raise DeltaFullError(
                    f"delta memtable at {st.delta_n}/{self.delta_capacity} "
                    f"rows; upsert of {r} refused — compact() (or attach a "
                    "stream.Compactor) to fold the delta into the sealed "
                    "index")
            if ids is None:
                gids = np.arange(self._next_id, self._next_id + r,
                                 dtype=np.int64)
            else:
                gids = check_upsert_ids(ids, r)
            expects(int(gids.max()) < 2 ** 31 - 1,
                    "ids must fit int32 (device id maps are int32)")
            self._next_id = max(self._next_id, int(gids.max()) + 1)
            if self._wal is not None:
                # write-ahead: the record is durable BEFORE the memtable
                # mutates; a crash in the window below replays it at load
                self._wal_seq = self._wal.append_upsert(rows, gids)
                faults.fire("stream/post-wal", name=self._cfg.name,
                            op="upsert")
            sealed_dirty = self._tombstone_locked(st, gids.tolist())
            p = st.delta_n
            st.delta[p:p + r] = rows
            st.delta_ids[p:p + r] = gids.astype(np.int32)
            st.delta_alive[p:p + r] = True
            for j, g in enumerate(gids.tolist()):
                self._loc[g] = ("d", p + j)
            if st.delta_n == 0:
                st.delta_oldest_at = self._clock()
            st.delta_n += r
            # tombstone-before-reveal: the old copy's mask lands first so a
            # lock-free reader can never see both copies of an upserted id
            if sealed_dirty:
                _refresh_sealed_keep(st)
            _refresh_delta(st, self.delta_capacity)
            if metrics._enabled:
                _c_upserts().inc(r, name=self._cfg.name)
            self._update_gauges(st)
        return gids

    def _tombstone_locked(self, st, gids) -> bool:
        """Mark the live occurrence of each id dead; returns whether a
        SEALED slot changed (the caller refreshes that device mask)."""
        sealed_dirty = False
        killed = 0
        for g in gids:
            loc = self._loc.pop(int(g), None)
            if loc is None:
                continue
            killed += 1
            if loc[0] == "s":
                st.sealed_alive[loc[1]] = False
                st.sealed_dead_n += 1
                sealed_dirty = True
            else:
                st.delta_alive[loc[1]] = False
        if killed and metrics._enabled:
            _c_deletes().inc(killed, name=self._cfg.name)
        return sealed_dirty

    def delete(self, ids) -> int:
        """Tombstone ids; returns how many were live. Deletes are visible to
        the very next search (the masks flip before this returns); unknown
        or already-dead ids are a counted no-op, not an error."""
        arr = np.asarray(ids).reshape(-1)
        with self._lock:
            st = self._state
            if self._wal is not None and arr.size:
                self._wal_seq = self._wal.append_delete(arr)
                faults.fire("stream/post-wal", name=self._cfg.name,
                            op="delete")
            before = len(self._loc)
            sealed_dirty = self._tombstone_locked(st, arr.tolist())
            n = before - len(self._loc)
            if sealed_dirty:
                _refresh_sealed_keep(st)
            # delta tombstones ride the keep mask; rows/ids are untouched
            # by a delete, so only the mask re-uploads
            _refresh_delta(st, self.delta_capacity, mask_only=True)
            self._update_gauges(st)
        return n

    # -- reads --------------------------------------------------------------
    def search(self, queries, k: int, res=None):
        """Unified search over (sealed − tombstones) + delta; returns
        ``(distances (m, k), global ids (m, k))`` with the shared
        ``id -1 / ±inf`` sentinel in slots the live rows cannot fill."""
        return _search_state(self._state, queries, k, res=res)

    def exact_search(self, queries, k: int, res=None):
        """EXACT fused kNN over the live corpus — the recall canary's
        shadow oracle (:func:`raft_tpu.obs.quality.exact_oracle`). The
        sealed side scans the retained raw row store through the same
        tombstone keep-mask the serving path filters with; the delta side
        is the usual exact bucket scan; both merge through ``select_k``
        and map to global ids. Needs the retained store (``dataset=`` /
        ``retain_vectors=True`` — PQ codes cannot reconstruct rows).

        Off the hot path by design: the store's device copy uploads
        lazily once per compaction epoch, and the brute-force program is
        keyed on the epoch's sealed row count — warm it per epoch
        (``RecallCanary.warm``; the churn bench covers epochs by
        rehearsal). Handle-snapshot ordering matches :meth:`search`, so a
        concurrent write cannot tear the view."""
        sd, si, dd, di = self._exact_scan(queries, k, res=res)
        return _merge(sd, si, dd, di, int(k), self._cfg.select_min)

    def _exact_scan(self, queries, k: int, res=None):
        """The scatter half of :meth:`exact_search` — exact store scan +
        delta scan with global ids, BEFORE the merge — so the sharded tier
        composes shard-local exact scans through the same one-dispatch
        merge as :meth:`search` (the RecallCanary's oracle then covers a
        whole mesh unchanged). Returns ``(sd (m, ks), si, dd (m, kd),
        di)``; ``ks``/``kd`` are clamped to the store/bucket rows."""
        from ..neighbors import brute_force

        jnp = _jnp()
        st = self._state
        cfg = self._cfg
        # same snapshot discipline and ORDER as _search_state: delta view
        # before the sealed keep-mask (pairs with upsert's kill-then-reveal)
        delta, dkeep, dids, _ = st.delta_view
        skeep, imap = st.sealed_keep_dev, st.id_map_dev
        queries = jnp.asarray(queries)
        expects(queries.ndim == 2 and queries.shape[1] == cfg.dim,
                "queries must be (rows, %d)", cfg.dim)
        if cfg.query_dtype == "float32":
            queries = queries.astype(jnp.float32)
        k = int(k)
        ts = st.store if isinstance(st.store, TieredStore) else None
        # mirror SNAPSHOT: a concurrent pressure spill nulls ts.mirror
        # from a writer thread — one read decides the branch AND supplies
        # the array, so a spill mid-query degrades to the chunked path's
        # next call instead of failing this one
        mirror = ts.mirror if ts is not None else None
        if ts is not None and mirror is None:
            # cold tiered store: chunked scan through the double-buffered
            # slot ring — the oracle covers the full corpus with ZERO net
            # device row bytes (satellite: the canary's shadow-rerank must
            # not duplicate the store on device). The keep-mask is COPIED
            # once here — sealed_alive mutates in place under writes, and
            # a per-chunk live read could miss an id in BOTH parts (delta
            # snapshot too old, sealed bit already killed); one copy taken
            # AFTER the delta view preserves the kill-then-reveal pairing
            # exactly like the resident path's frozen device mask
            alive = st.sealed_alive.copy()
            sd, si = self._chunked_store_scan(st, ts, queries, k,
                                              alive=alive, res=res)
            si = _map_ids(si, imap)
        else:
            store_dev = (mirror if mirror is not None
                         else self._store_device(st))
            ks = min(k, store_dev.shape[0])
            sd, si = brute_force.knn(store_dev, queries, ks, cfg.metric,
                                     cfg.metric_arg, sample_filter=skeep,
                                     res=res)
            si = _map_ids(si, imap)
        kd = min(k, delta.shape[0])
        dd, di = brute_force.knn(delta, queries, kd, cfg.metric,
                                 cfg.metric_arg, sample_filter=dkeep, res=res)
        di = _map_ids(di, dids)
        obs_dispatch.note(4)  # store scan + delta scan + two id maps
        return sd, si, dd, di

    def _chunked_store_scan(self, st: _StreamState, ts: TieredStore,
                            queries, k: int, *, alive=None, res=None,
                            max_chunks: int | None = None):
        """Exact scan of a COLD tiered store: fixed-shape chunks stream
        through the store's replacement slot ring (chunk N+1's upload overlaps
        chunk N's distance compute under async dispatch) and fold into a
        running top-k through the same ``_merge`` program the serving path
        uses. Every chunk shares one program set — (chunk, k) knn + shift
        + merge — so store size never compiles on the oracle path after
        :meth:`warm`. Returns ``(sd, si)`` in STORE-SLOT id space (the
        caller maps to global ids); the tombstone keep-mask rides each
        chunk's ``sample_filter`` exactly like the resident scan.
        ``max_chunks`` bounds the walk (the warm path compiles the
        program set with two chunks instead of scanning everything)."""
        from ..neighbors import brute_force
        from . import tiered as _tiered

        cfg = st.cfg
        chunk = ts.oracle_chunk
        kc = min(int(k), chunk)
        n_chunks = ts.n_oracle_chunks()
        if max_chunks is not None:
            n_chunks = min(n_chunks, int(max_chunks))
        if alive is None:  # warm path; real scans pass the caller's copy
            alive = st.sealed_alive.copy()
        acc_d = acc_i = None
        for ci in range(n_chunks):
            rows_dev, base, valid = ts.oracle_chunk_dev(ci)
            keep = np.zeros(chunk, bool)
            keep[:valid] = alive[base:base + valid]
            cd, cidx = brute_force.knn(
                rows_dev, queries, kc, cfg.metric, cfg.metric_arg,
                sample_filter=_dev_put(cfg, keep), res=res)
            cidx = _tiered.shift_slots(cidx, base)
            if acc_d is None:
                acc_d, acc_i = cd, cidx
            else:
                acc_d, acc_i = _merge(acc_d, acc_i, cd, cidx, kc,
                                      cfg.select_min)
        return acc_d, acc_i

    def _store_device(self, st: _StreamState):
        """The epoch-frozen device copy of the retained row store (lazy;
        a benign publication race uploads at most twice — the store array
        itself is never mutated within an epoch)."""
        expects(st.store is not None,
                "exact_search needs the retained row store "
                "(retain_vectors=True / dataset= at wrap time)")
        expects(not isinstance(st.store, TieredStore),
                "tiered stores never materialize a second full device "
                "copy — use the mirror or the chunked scan")
        dev = st.store_dev
        if dev is None:
            dev = _dev_put(st.cfg, st.store)
            st.store_dev = dev
            # the lazy oracle copy joins the epoch's ledger entry (off the
            # serving hot path by construction)
            self._account_state(st)
        return dev

    # -- the refine epilogue (tiered storage's serving path) -----------------
    def search_refined(self, queries, k: int, refine_ratio: int = 4,
                       res=None):
        """IVF-PQ search with the exact-refine epilogue restructured as a
        store gather: the sealed scan widens to ``k * refine_ratio`` PQ
        candidates, their full-precision rows gather from the retained
        store — under ``storage="tiered"`` a double-buffered host→device
        hop (:meth:`TieredStore.fetch`; batch N+1's H2D overlaps batch
        N's distance compute), under ``"hbm"`` a device-side gather from
        the resident copy — and :func:`raft_tpu.neighbors.refine
        .refine_gathered` re-ranks exactly; the delta memtable (already
        exact) merges at serving width. Identical ids/distances across
        the two storage modes (bit-parity pinned by the ``tiering``
        suite): tiering moves WHERE the rows live, never what a query
        answers. Returns ``(distances (m, k), global ids (m, k))``."""
        return self._search_refined_state(self._state, queries, k,
                                          refine_ratio, res=res)

    def _search_refined_state(self, st: _StreamState, queries, k: int,
                              refine_ratio: int, res=None):
        from ..obs import requestlog

        rd, ri, dd, di = self._refined_scan(queries, k, refine_ratio,
                                            res=res, st=st)
        t0 = time.perf_counter()
        out = _merge(rd, ri, dd, di, int(k), self._cfg.select_min)
        requestlog.add_span("stream/merge", time.perf_counter() - t0)
        return out

    def _refined_scan(self, queries, k: int, refine_ratio: int, res=None,
                      st: _StreamState | None = None):
        """The scatter half of :meth:`search_refined` — refined sealed
        part + exact delta part, global ids, BEFORE the merge — so the
        sharded tier composes per-shard refined scans through its one
        ``select_k`` dispatch. Snapshot order matches :func:`_scan_state`
        (delta view before the sealed keep-mask). ``st`` pins an explicit
        state epoch (the :meth:`refined_searcher` hook's lease-drain
        contract); None reads the live state."""
        from ..neighbors import brute_force
        from ..neighbors.refine import refine_gathered
        from ..obs import requestlog
        from . import tiered as _tiered

        if st is None:
            st = self._state
        cfg = self._cfg
        expects(cfg.kind == "ivf_pq",
                "search_refined is the IVF-PQ refine epilogue (kind=%r "
                "scores candidates exactly already — use search())",
                cfg.kind)
        expects(st.store is not None,
                "search_refined needs the retained raw rows (dataset= / "
                "retain_vectors=True at wrap time)")
        r = int(refine_ratio)
        expects(r >= 1, "refine_ratio must be >= 1, got %d", r)
        jnp = _jnp()
        requestlog.annotate("stream_epoch", st.epoch)
        delta, dkeep, dids, _ = st.delta_view
        skeep, imap = st.sealed_keep_dev, st.id_map_dev
        queries = jnp.asarray(queries)
        expects(queries.ndim == 2 and queries.shape[1] == cfg.dim,
                "queries must be (rows, %d)", cfg.dim)
        if cfg.query_dtype == "float32":
            queries = queries.astype(jnp.float32)
        k = int(k)
        kr = min(k * r, st.id_map.shape[0])
        t0 = time.perf_counter()
        # PQ candidates at the widened width — approximate distances are
        # DISCARDED; only the slot ids feed the exact re-rank
        _, slots = cfg.module.search(cfg.search_params, st.sealed, queries,
                                     kr, sample_filter=skeep, res=res)
        t1 = time.perf_counter()
        ts = st.store if isinstance(st.store, TieredStore) else None
        if ts is not None:
            cand = ts.fetch(slots, res=res)
        else:
            cand = _tiered.mirror_gather(self._store_device(st), slots)
        ks = min(k, kr)
        rd, rslots = refine_gathered(cand, queries, slots, ks,
                                     metric=cfg.metric)
        ri = _map_ids(rslots, imap)
        t2 = time.perf_counter()
        kd = min(k, delta.shape[0])
        dd, di = brute_force.knn(delta, queries, kd, cfg.metric,
                                 cfg.metric_arg, sample_filter=dkeep,
                                 res=res)
        di = _map_ids(di, dids)
        obs_dispatch.note(5)
        requestlog.add_span("stream/sealed", t1 - t0)
        requestlog.add_span("tier/refine", t2 - t1)
        requestlog.add_span("stream/delta", time.perf_counter() - t2)
        return rd, ri, dd, di

    def refined_searcher(self, refine_ratio: int = 4):
        """Serving hook over :meth:`search_refined` (the
        ``batched_searcher`` contract) — what a tiered IVF-PQ index
        publishes: PQ scan + store-gather refine as ONE hook, pinned to
        the current state epoch exactly like :meth:`searcher` (a
        compaction swap freezes the leased hook's view; the republish
        picks up the successor — the registry lease-drain contract)."""
        from ..neighbors._hooks import make_hook

        st = self._state
        fn = make_hook(
            lambda queries, k: self._search_refined_state(st, queries, k,
                                                          refine_ratio),
            f"stream/{self._cfg.kind}+refine", self._cfg.dim,
            self._cfg.data_kind)
        fn.mutable = self
        return fn

    def warm_refined(self, buckets, ks=(10,), refine_ratio: int = 4,
                     sample=None) -> dict:
        """Rehearse the refined serving path per (query bucket, k): one
        real :meth:`search_refined` per shape compiles the widened PQ
        scan, the gather slots (filling the double-buffer ring), the
        refine program and the merge — after which the ``tiering`` suite's
        zero-cold-compile contract holds across refine double-buffer
        cycles. Under tiered storage the chunked-oracle program set warms
        too (two chunks — the knn/shift/merge triple compiles, the full
        walk stays off the warm path). Returns per-(k, bucket) compile
        attribution like :meth:`warm`."""
        import jax

        from .._warmup import _random_queries
        from ..obs import compile as obs_compile

        cfg = self._cfg
        out: dict = {}
        key = jax.random.key(0)
        for kk in sorted(set(int(x) for x in ks)):
            out[kk] = {}
            for b in sorted(set(int(x) for x in buckets)):
                key, kq = jax.random.split(key)
                q = _random_queries(kq, b, cfg.dim, cfg.query_dtype,
                                    sample=sample)
                t0 = time.perf_counter()
                with obs_compile.attribution() as rec:
                    jax.block_until_ready(
                        self.search_refined(q, kk, refine_ratio)[0])
                    ts = self.tiered_store
                    if ts is not None:
                        # warm the CHUNKED oracle programs regardless of
                        # current residency: a promoted store can be
                        # pressure-spilled later, and its first post-
                        # spill exact_search must not cold-compile the
                        # chunk knn/shift/merge set mid-serve
                        st = self._state
                        jnp_q = _jnp().asarray(q)
                        if cfg.query_dtype == "float32":
                            jnp_q = jnp_q.astype(_jnp().float32)
                        jax.block_until_ready(self._chunked_store_scan(
                            st, ts, jnp_q, kk, max_chunks=2)[0])
                out[kk][b] = {"wall_s": round(time.perf_counter() - t0, 3),
                              **rec.summary()}
        return out

    def searcher(self):
        """Serving hook pinned to the CURRENT state epoch (the
        ``batched_searcher`` contract: ``fn(queries, k)`` with
        ``kind``/``dim``/``query_dtype``). Deletes/upserts remain visible
        through a pinned hook until a compaction swap freezes its epoch —
        from then on it serves the pre-compaction view, which is exactly
        the lease-drain semantics ``serve.IndexRegistry`` wants."""
        from ..neighbors._hooks import make_hook

        st = self._state
        fn = make_hook(lambda queries, k: _search_state(st, queries, k),
                       f"stream/{st.cfg.kind}", st.cfg.dim,
                       st.cfg.data_kind)
        # marker for the serve write path: lets SearchService.publish tell a
        # mutable's own hook (keep/retarget the upsert handle) from any
        # other bare hook (close the write path)
        fn.mutable = self
        return fn

    # -- warmup -------------------------------------------------------------
    def warm(self, buckets, ks=(10,), sample=None) -> dict:
        """Compile the delta-ladder program set: the exact delta scan at
        EVERY memtable bucket × every serving (query-bucket, k), plus the
        id-map and merge programs. These shapes are sealed-independent, so
        one warm covers every future compaction epoch; the sealed-side
        programs are warmed per epoch by ``registry.publish`` (which runs
        the full hook). Returns per-(k, bucket) compile attribution like
        :func:`raft_tpu._warmup.warm_buckets`."""
        import jax

        from .._warmup import _random_queries
        from ..obs import compile as obs_compile

        cfg = self._cfg
        out: dict = {}
        key = jax.random.key(0)
        dt = _np_dtype(cfg.query_dtype)
        from ..neighbors import brute_force

        for kk in sorted(set(int(x) for x in ks)):
            out[kk] = {}
            for b in sorted(set(int(x) for x in buckets)):
                key, kq = jax.random.split(key)
                q = _random_queries(kq, b, cfg.dim, cfg.query_dtype,
                                    sample=sample)
                t0 = time.perf_counter()
                with obs_compile.attribution() as rec:
                    for db in self._buckets:
                        # dummies ride _dev_put so a device-pinned shard
                        # warms programs at the SAME committed placement
                        # its serving path dispatches (placement is part of
                        # the executable key — an off-device warm would
                        # leave the hot path cold)
                        dummy = _dev_put(cfg, np.zeros((db, cfg.dim), dt))
                        keep = _dev_put(cfg, np.zeros((db,), bool))
                        kd = min(kk, db)
                        dd, di = brute_force.knn(
                            dummy, q, kd, cfg.metric, cfg.metric_arg,
                            sample_filter=keep)
                        di = _map_ids(di, _dev_put(
                            cfg, np.zeros((db,), np.int32)))
                        sd = _dev_put(cfg, np.zeros((b, kk), np.float32))
                        si = _dev_put(cfg, np.full((b, kk), -1, np.int32))
                        jax.block_until_ready(
                            _merge(sd, si, dd, di, kk, cfg.select_min))
                out[kk][b] = {"wall_s": round(time.perf_counter() - t0, 3),
                              **rec.summary()}
        return out

    # -- compaction ---------------------------------------------------------
    def compact(self, mode: str = "auto", res=None, *,
                ooc_chunk_rows: int | None = None) -> dict:
        """Fold the delta memtable (and, in rebuild mode, the tombstones)
        into a new sealed index and swap it in atomically.

        ``mode``: "extend" appends the live delta rows to the sealed lists
        (IVF kinds only; tombstoned sealed slots stay masked), "rebuild"
        rebuilds the sealed index from the raw live rows (drops tombstones
        entirely; needs the retained row store), "auto" picks extend for
        IVF kinds and rebuild otherwise. The heavy fold runs OFF the write
        lock — searches keep serving the old state, and writes landing
        mid-fold carry over: the fold consumes a snapshot prefix of the
        delta, and every alive bit is re-read from the live tombstone state
        at swap time. Returns a report dict (mode, rows folded/reclaimed,
        wall seconds).

        ``ooc_chunk_rows`` (rebuild mode only) routes the fold through
        the out-of-core build path: the live rows feed the builder as a
        ``core.chunked.ChunkedReader`` instead of one device-resident
        array, so a rebuild's device peak stays at index + two staged
        chunks — what lets a tiered/beyond-HBM index compact without
        transiently re-materializing its corpus in HBM. Bit-equal to the
        in-core fold (the streamed-build parity contract).
        """
        expects(mode in ("auto", "extend", "rebuild"),
                "mode must be 'auto', 'extend' or 'rebuild', got %r", mode)
        import jax
        import jax.numpy as jnp

        cfg = self._cfg
        with self._compact_lock:
            if mode == "auto":
                mode = ("extend" if cfg.kind in ("ivf_flat", "ivf_pq")
                        else "rebuild")
            expects(mode == "rebuild" or cfg.kind in ("ivf_flat", "ivf_pq"),
                    "%s has no extend(); use mode='rebuild'", cfg.kind)
            expects(ooc_chunk_rows is None or mode == "rebuild",
                    "ooc_chunk_rows= streams the REBUILD fold; extend "
                    "folds only the (small) delta — pass mode='rebuild'")
            t0 = time.perf_counter()
            with self._lock:
                st = self._state
                snap_n = st.delta_n
                d_src = np.nonzero(st.delta_alive[:snap_n])[0]
                fold_rows = st.delta[d_src].copy()
                fold_gids = st.delta_ids[d_src].astype(np.int64)
                if mode == "rebuild":
                    expects(st.store is not None,
                            "rebuild compaction needs the retained row store "
                            "(retain_vectors=True / dataset=)")
                    s_src = np.nonzero(st.sealed_alive)[0]

            # ---- heavy fold, off the hot path ----------------------------
            if mode == "extend":
                n_old = len(st.id_map)
                if len(d_src):
                    new_sealed = cfg.module.extend(
                        st.sealed, fold_rows,
                        new_ids=jnp.arange(n_old, n_old + len(d_src),
                                           dtype=jnp.int32),
                        res=res)
                else:
                    new_sealed = st.sealed
                new_id_map = np.concatenate([st.id_map, fold_gids])
                new_store = (np.concatenate([_store_rows(st.store),
                                             fold_rows])
                             if st.store is not None else None)
                reclaimed = 0
            else:
                live_rows = np.concatenate([_store_rows(st.store)[s_src],
                                            fold_rows])
                expects(live_rows.shape[0] > 0,
                        "compaction would leave an empty index")
                new_id_map = np.concatenate([st.id_map[s_src], fold_gids])
                new_store = live_rows
                reclaimed = len(st.id_map) - len(s_src)
                if ooc_chunk_rows is not None:
                    # out-of-core fold: the builder streams the live rows
                    # chunk by chunk (all four kinds take readers) — no
                    # whole-corpus device copy; a device pin is restored
                    # on the sealed result below like any off-device build
                    from ..core import chunked

                    x = chunked.ChunkedReader(
                        live_rows, chunk_rows=int(ooc_chunk_rows))
                else:
                    # committed input: a device-pinned shard rebuilds ON
                    # its own device (off the hot path either way)
                    x = _dev_put(cfg, live_rows)
                if self._builder is not None:
                    new_sealed = self._builder(x, res=res)
                    got_kind, _ = _resolve_kind(new_sealed)
                    expects(got_kind == cfg.kind,
                            "builder returned a %s index for a %s mutable "
                            "index", got_kind, cfg.kind)
                elif cfg.kind == "brute_force":
                    from ..neighbors import brute_force

                    new_sealed = brute_force.BruteForce(
                        cfg.metric, cfg.metric_arg).build(x)
                else:
                    ip = self._index_params
                    if cfg.kind == "cagra" and ip is None:
                        ip = cfg.module.IndexParams()
                    expects(ip is not None,
                            "rebuild compaction of %s needs index_params "
                            "(build configuration)", cfg.kind)
                    new_sealed = cfg.module.build(ip, x, res=res)
                if cfg.device is not None:
                    # a builder may construct off-device (e.g. a mesh-
                    # sharded build); the successor must land back on the
                    # shard's pin or the next search would mix committed
                    # devices in one program
                    if cfg.kind == "brute_force":
                        new_sealed.dataset = jax.device_put(
                            new_sealed.dataset, cfg.device)
                    else:
                        new_sealed = jax.device_put(new_sealed, cfg.device)
            # materialize before the swap (BruteForce is not a pytree —
            # block on its dataset directly)
            if cfg.kind == "brute_force":
                jax.block_until_ready(new_sealed.dataset)
            else:
                jax.block_until_ready(jax.tree_util.tree_leaves(new_sealed))
            id_map_dev = _dev_put(cfg, new_id_map.astype(np.int32))

            # ---- atomic swap ---------------------------------------------
            with self._lock:
                st = self._state
                nd = _StreamState(cfg)
                nd.sealed = new_sealed
                nd.id_map = new_id_map
                # tier residency MIGRATES through the fold-and-swap: the
                # successor epoch's store re-places with the predecessor's
                # residency (its promote still honors the budget — a
                # squeezed successor degrades to cold instead of failing
                # the compaction)
                nd.store = self._make_store(
                    new_store, epoch=st.epoch + 1,
                    residency=(st.store.residency
                               if isinstance(st.store, TieredStore)
                               else None))
                # alive bits re-read from the LIVE state: deletes that
                # landed mid-fold are preserved across the swap
                if mode == "extend":
                    nd.sealed_alive = np.concatenate(
                        [st.sealed_alive, st.delta_alive[d_src]])
                else:
                    nd.sealed_alive = np.concatenate(
                        [st.sealed_alive[s_src], st.delta_alive[d_src]])
                # re-based from the concatenated bitset (O(n) once per
                # fold, never per write)
                nd.sealed_dead_n = int(len(nd.sealed_alive)
                                       - nd.sealed_alive.sum())
                dt = _np_dtype(cfg.query_dtype)
                nd.delta = np.zeros((self.delta_capacity, cfg.dim), dt)
                nd.delta_ids = np.zeros(self.delta_capacity, np.int32)
                nd.delta_alive = np.zeros(self.delta_capacity, bool)
                rem = st.delta_n - snap_n
                if rem:
                    nd.delta[:rem] = st.delta[snap_n:st.delta_n]
                    nd.delta_ids[:rem] = st.delta_ids[snap_n:st.delta_n]
                    nd.delta_alive[:rem] = st.delta_alive[snap_n:st.delta_n]
                nd.delta_n = rem
                nd.delta_oldest_at = self._clock() if rem else None
                nd.epoch = st.epoch + 1
                nd.id_map_dev = id_map_dev
                _refresh_sealed_keep(nd)
                _refresh_delta(nd, self.delta_capacity)
                # location map: every live id points at its new slot
                self._loc = _build_loc(nd)
                old_state, self._state = st, nd
                # retirement audit: the pre-compaction epoch (and, when the
                # fold produced a successor index, the old sealed store)
                # SHOULD free once draining leases release it — a retired
                # entry still accounted is the leak obs.mem.audit() reports
                obs_mem.retire(old_state.mem)
                if isinstance(old_state.store, TieredStore):
                    # the pre-fold epoch's tier entry should free at drain
                    # like every other retired epoch allocation
                    old_state.store.retire()
                if nd.sealed is not old_state.sealed:
                    old_sealed_mem = self._sealed_mem
                    self._sealed_mem = obs_mem.account_index(
                        nd.sealed, name=cfg.name, shard=self._shard,
                        epoch=nd.epoch)
                    obs_mem.retire(old_sealed_mem)
                self._update_gauges(nd)
            report = {"mode": mode, "epoch": nd.epoch,
                      "folded": int(len(d_src)), "reclaimed": int(reclaimed),
                      "sealed_rows": int(len(nd.id_map)),
                      "delta_remaining": int(rem),
                      "wall_s": round(time.perf_counter() - t0, 3)}
            if self._wal is not None and self._snapshot_path is not None:
                # WAL truncation rides the compaction swap: the post-fold
                # state lands atomically at snapshot_path (save() also
                # resets the log once the rename is durable), so the WAL
                # never outgrows one epoch's writes
                save(self, self._snapshot_path)
                report["snapshot"] = self._snapshot_path
            return report


# -- serialization (raft_tpu/8 "stream" section) -----------------------------

def save(mutable: MutableIndex, path: str) -> None:
    """Serialize the FULL mutable state — sealed index, delta memtable,
    tombstone bitsets, id map — as one ``stream`` section (raft_tpu/10;
    /8 layout plus the WAL coordination seq). The sealed index rides
    embedded through its own module serializer (``write_index``), so its
    layout/back-compat rules are unchanged.

    ATOMIC: the bytes land in a same-directory temp file and replace
    ``path`` in one ``os.replace`` — a crash mid-save leaves the previous
    snapshot readable (:func:`raft_tpu.core.serialize.atomic_write`; the
    fault-injection suite pins it). When the index carries a WAL, the log
    is truncated AFTER the rename is durable: crash before the rename
    keeps old snapshot + full log, crash between rename and truncate keeps
    the new snapshot + a log whose records are all ≤ its ``wal_seq`` (and
    replay skips them) — no ordering loses an acknowledged write."""
    from ..core import serialize
    from ..core.serialize import (atomic_write, serialize_header,
                                  serialize_mdspan, serialize_scalar)

    with mutable._lock:
        st = mutable._state
        cfg = mutable._cfg
        with atomic_write(path) as f:
            serialize_header(f, "stream")
            serialize_scalar(f, cfg.kind)
            serialize_scalar(f, cfg.name)
            serialize_scalar(f, mutable.delta_capacity)
            serialize_scalar(f, int(mutable._next_id))
            if serialize.version_number(serialize.SERIALIZATION_VERSION) >= 10:
                serialize_scalar(f, int(mutable._wal_seq))
            serialize_scalar(f, int(st.delta_n))
            serialize_scalar(f, st.store is not None)
            if serialize.version_number(serialize.SERIALIZATION_VERSION) >= 12:
                # the decided tier layout (raft_tpu/12): storage policy +
                # the store's residency at save time, so load() restores
                # placement without re-deciding (TierPolicy itself is
                # runtime configuration, supplied fresh like search_params)
                serialize_scalar(f, mutable._storage)
                serialize_scalar(f, (st.store.residency
                                     if isinstance(st.store, TieredStore)
                                     else "device"))
            serialize_mdspan(f, st.id_map)
            serialize_mdspan(f, st.sealed_alive)
            serialize_mdspan(f, st.delta[:st.delta_n])
            serialize_mdspan(f, st.delta_ids[:st.delta_n])
            serialize_mdspan(f, st.delta_alive[:st.delta_n])
            if st.store is not None:
                serialize_mdspan(f, _store_rows(st.store))
            cfg.module.write_index(f, st.sealed)
        if mutable._wal is not None:
            mutable._wal.reset()


def load(path: str, *, search_params=None, index_params=None,
         builder: Callable | None = None, name: str | None = None,
         device=None, wal=None, snapshot_path: str | None = None,
         shard: int | None = None, tier: TierPolicy | None = None,
         clock: Callable[[], float] = time.monotonic) -> MutableIndex:
    """Load a :func:`save`d mutable index. ``search_params``/
    ``index_params``/``builder``/``device`` are runtime configuration (like
    every other index loader) and are supplied fresh here.

    ``wal`` (a path or :class:`~raft_tpu.stream.wal.WriteAheadLog`) is the
    crash-recovery entry: every intact record with ``seq`` past the
    snapshot's ``wal_seq`` replays through the ordinary write path (WAL
    appends suppressed — the records are already in the log), then the log
    re-attaches for new writes. ``m.last_recovery`` reports
    ``{replayed, skipped, torn}``; follow with ``warm()`` + a registry
    publish for the zero-cold-compile cold-start path (docs/streaming.md
    "Durability & replication"). ``snapshot_path`` re-arms the
    compaction-coupled snapshot+truncation (defaults to ``path`` whenever
    a WAL is given — recovering WITHOUT re-arming snapshots would let the
    log grow past what the next crash can afford to replay)."""
    from ..core.serialize import (check_header, deserialize_mdspan,
                                  deserialize_scalar, version_number)
    from ..neighbors import brute_force, cagra, ivf_flat, ivf_pq

    mods = {"brute_force": brute_force, "ivf_flat": ivf_flat,
            "ivf_pq": ivf_pq, "cagra": cagra}
    with open(path, "rb") as f:
        ver = check_header(f, "stream")
        kind = deserialize_scalar(f)
        saved_name = deserialize_scalar(f)
        capacity = int(deserialize_scalar(f))
        next_id = int(deserialize_scalar(f))
        wal_seq = (int(deserialize_scalar(f))
                   if version_number(ver) >= 10 else 0)
        delta_n = int(deserialize_scalar(f))
        has_store = bool(deserialize_scalar(f))
        storage, residency = "hbm", None
        if version_number(ver) >= 12:
            storage = deserialize_scalar(f)
            residency = deserialize_scalar(f)
        id_map = np.asarray(deserialize_mdspan(f))
        sealed_alive = np.asarray(deserialize_mdspan(f)).astype(bool)
        delta = np.asarray(deserialize_mdspan(f))
        delta_ids = np.asarray(deserialize_mdspan(f))
        delta_alive = np.asarray(deserialize_mdspan(f)).astype(bool)
        store = np.asarray(deserialize_mdspan(f)) if has_store else None
        sealed = mods[kind].read_index(f)

    if snapshot_path is None and wal is not None:
        snapshot_path = path
    # the SAVED placement threads into construction instead of being
    # re-decided: the layout is part of the snapshot (raft_tpu/12), so
    # load + WAL replay + warm() comes back exactly as placed — no
    # re-decision, no wasted upload-then-spill; a saved device residency
    # that no longer fits the budget degrades to cold (promote() never
    # raises), which the tier events make visible
    m = MutableIndex(sealed, search_params=search_params,
                     index_params=index_params, delta_capacity=capacity,
                     retain_vectors=has_store, dataset=store, builder=builder,
                     device=device, snapshot_path=snapshot_path, shard=shard,
                     storage=storage, tier=tier,
                     tier_residency=residency if storage == "tiered" else None,
                     name=saved_name if name is None else name, clock=clock)
    with m._lock:
        st = m._state
        st.id_map = id_map.astype(np.int64)
        st.sealed_alive = sealed_alive
        st.sealed_dead_n = int(sealed_alive.size - sealed_alive.sum())
        st.delta[:delta_n] = delta
        st.delta_ids[:delta_n] = delta_ids
        st.delta_alive[:delta_n] = delta_alive
        st.delta_n = delta_n
        # the restored delta's true write times are gone — age it from load
        # time (conservative: the Compactor's max_age_s watermark stays
        # armed for a restored non-empty delta instead of silently never
        # firing)
        st.delta_oldest_at = clock() if delta_n else None
        m._next_id = next_id
        st.id_map_dev = _dev_put(st.cfg, st.id_map.astype(np.int32))
        _refresh_sealed_keep(st)
        _refresh_delta(st, capacity)
        m._loc = _build_loc(st)
        m._update_gauges(st)
        m._wal_seq = wal_seq
    if wal is not None:
        if not hasattr(wal, "replay"):
            from .wal import WriteAheadLog

            wal = WriteAheadLog(wal, name=m.name)
        # replay through the ORDINARY write path (m._wal is still None, so
        # nothing re-appends): every acknowledged write past the snapshot
        # comes back with read-your-writes semantics intact
        replayed, last = 0, wal_seq
        for seq, op, rows, ids in wal.replay(after_seq=wal_seq):
            if op == "upsert":
                m.upsert(rows, ids=ids)
            else:
                m.delete(ids)
            replayed, last = replayed + 1, seq
        m.last_recovery = {
            "replayed": replayed,
            "skipped": wal.last_scan["records"] - replayed,
            "torn": wal.last_scan["torn"], "wal_seq": last}
        with m._lock:
            m._wal = wal
            m._wal_seq = last
    return m
