"""TieredStore: beyond-HBM storage for full-precision refine rows.

Every byte the stack served before this module had to fit in device
memory, capping corpus size at shards × HBM — even though the refine
epilogue is the only consumer of full-precision rows. This is the
DiskANN/FreshDiskANN storage split (Subramanya et al. 2019, Singh et al.
2021; ROADMAP item 1) applied to the TPU serving stack: **PQ codes and
coarse structures stay resident in HBM** (they are the per-query scan
operands), while **raw rows live in host RAM** — optionally an mmap'd
on-disk file for the cold majority — and cross to the device only as
per-batch candidate gathers for the exact-refine epilogue.

Three moving parts:

- **The row store** (:class:`TieredStore`). One (n, d) row array resident
  on exactly one cold tier (``host`` RAM, or ``disk`` via ``np.memmap``
  when :attr:`TierPolicy.disk_path` is set), plus an optional **device
  mirror** — the promoted state, byte-identical to the pre-tiering
  all-HBM store. Residency is *decided, not hardcoded*:
  :func:`decide_placement` prices the mirror against
  ``Resources.memory_budget_bytes`` through the obs.mem ledger (no
  budget = stay cold; tiering exists to spend less HBM, not more), and
  residency moves at runtime — **budget-pressure spill** (the ledger's
  gate consults :func:`raft_tpu.obs.mem.register_pressure_handler`\\ ed
  stores before refusing an admission, so a mirror is dropped to make
  room for an upsert/publish instead of shedding the write) and
  **hit-rate-driven promote** (``promote_min_hits`` host fetches with
  budget headroom lift the mirror back). Every move is a counted event
  (``raft_tpu_tier_spill_total`` / ``raft_tpu_tier_promote_total``),
  visible at ``/debug/mem`` under the ``tiers`` section.

- **The double-buffered fetch** (:meth:`TieredStore.fetch`) — the refine
  hop. Candidate slots gather on the host (``np.take`` over RAM or mmap
  pages) into a per-shape **ring of device slots** (the
  :mod:`raft_tpu.serve.staging` shape discipline): under jax's async
  dispatch, batch N+1's H2D overlaps batch N's distance compute, and
  ring REPLACEMENT keeps steady-state accounted bytes CONSTANT — the
  ledger entry for the store proves it (slot bytes are accounted once
  per shape, never per fetch; displaced uploads free by reference drop
  once their batch completes — staging's donation program is
  deliberately NOT used here, because searches are lock-free and a
  concurrent caller may still hold a returned slot, see
  ``_slot_upload``). The same ring backs :meth:`oracle_chunk_dev`, the
  chunked exact scan that lets ``exact_search``/the recall canary score
  the full corpus with **zero net device row bytes** (the pre-tiering
  oracle uploaded a whole second copy of the store).

- **Placement observability.** Per-tier bytes publish as
  ``raft_tpu_tier_bytes{tier=,name=}``; fetches, transfer bytes and the
  device-hit ratio ride ``raft_tpu_tier_fetch_total`` /
  ``raft_tpu_tier_h2d_bytes_total`` / ``raft_tpu_tier_hit_ratio``; the
  ``tiers`` section of ``/debug/mem`` lists every live store's
  residency, per-tier bytes and recent spill/promote events. The host
  side gates against the new optional ``Resources.host_budget_bytes``
  exactly like device bytes gate against ``memory_budget_bytes``.

:class:`raft_tpu.stream.MutableIndex` composes this behind its
``storage="tiered"`` policy (IVF-PQ sealed side): the retained raw-row
store becomes a TieredStore, ``search_refined`` restructures the refine
epilogue as the double-buffered gather, compaction folds migrate tier
residency through the ordinary fold-and-swap, and ``save()``/``load()``
persist the tier layout (raft_tpu/12) so a recovered index restores its
placement without re-deciding. Sizing rules and when-to-tier guidance:
docs/streaming.md "Tiered storage".
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import os
import threading
import time
import weakref
from typing import Callable

import numpy as np

from ..core.errors import expects
from ..core.resources import default_resources
from ..obs import dispatch as obs_dispatch
from ..obs import events as obs_events
from ..obs import mem as obs_mem
from ..obs import metrics
from ..testing import faults

__all__ = ["TierPolicy", "TieredStore", "TIERS", "decide_placement",
           "tier_totals", "debug_tiers", "spillable_bytes"]

# residency tiers, hottest first — the vocabulary shared by the metrics,
# /debug/mem, obs.mem.plan(storage="tiered") and the serialized layout
TIERS = ("device", "host", "disk")


@dataclasses.dataclass(frozen=True)
class TierPolicy:
    """Runtime configuration of a :class:`TieredStore` (supplied fresh at
    ``load`` like ``search_params`` — only the decided LAYOUT is
    serialized, see ``MutableIndex.save``).

    ``disk_path``: path PREFIX for the cold mmap file (``<prefix>.e<N>``
    per store epoch, so a compaction successor never clobbers the live
    epoch's pages while draining leases still read them); ``None`` keeps
    rows in host RAM. ``oracle_chunk``: device shape (power of two) of
    the chunked exact scan — the one program size every oracle pass
    reuses. ``fetch_slots``: depth of the per-shape device slot ring the
    double-buffered gathers rotate through (2 = classic double
    buffering). ``promote_min_hits``: cold fetches before a store
    promotes its mirror — fires only under an ARMED
    ``memory_budget_bytes`` with headroom; with no budget there is no
    safe ceiling, so the store stays cold (``auto_promote=False`` pins
    residency to explicit :meth:`TieredStore.promote`/``spill`` calls).
    """

    disk_path: str | None = None
    oracle_chunk: int = 8192
    fetch_slots: int = 2
    promote_min_hits: int = 3
    auto_promote: bool = True

    def __post_init__(self):
        expects(self.oracle_chunk >= 8
                and (self.oracle_chunk & (self.oracle_chunk - 1)) == 0,
                "oracle_chunk must be a power of two >= 8, got %d",
                self.oracle_chunk)
        expects(self.fetch_slots >= 2,
                "fetch_slots must be >= 2 (double buffering), got %d",
                self.fetch_slots)


# -- metrics (catalogue: docs/observability.md) ------------------------------

@functools.lru_cache(maxsize=None)
def _g_tier_bytes():
    return metrics.gauge(
        "raft_tpu_tier_bytes",
        "live bytes per storage tier (device mirror + gather slots / host "
        "RAM rows / disk mmap rows) per tiered store", unit="bytes")


@functools.lru_cache(maxsize=None)
def _c_fetches():
    return metrics.counter(
        "raft_tpu_tier_fetch_total",
        "refine/oracle gathers served by a tiered store, by source tier")


@functools.lru_cache(maxsize=None)
def _c_h2d():
    return metrics.counter(
        "raft_tpu_tier_h2d_bytes_total",
        "host->device bytes transferred by cold-tier gathers (the refine "
        "hop's transfer cost; 0 while the mirror is resident)",
        unit="bytes")


@functools.lru_cache(maxsize=None)
def _c_spills():
    return metrics.counter(
        "raft_tpu_tier_spill_total",
        "device mirrors dropped, by reason (pressure = the obs.mem budget "
        "gate reclaimed HBM for an admission; explicit = spill() called)")


@functools.lru_cache(maxsize=None)
def _c_promotes():
    return metrics.counter(
        "raft_tpu_tier_promote_total",
        "device-mirror promotions (construction placement, hit-rate "
        "auto-promote, explicit promote(), load() layout restore)")


@functools.lru_cache(maxsize=None)
def _g_hit_ratio():
    return metrics.gauge(
        "raft_tpu_tier_hit_ratio",
        "fraction of fetched rows served device-resident (mirror hits / "
        "all fetched rows) since the store was created")


# -- jitted pieces -----------------------------------------------------------

@functools.cache
def _tier_jits():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def gather(rows, slots):
        # device-mirror gather: negative (sentinel) slots read row 0; the
        # refine epilogue masks them by candidate id, so the value never
        # surfaces
        return jnp.take(rows, jnp.clip(slots, 0), axis=0)

    @jax.jit
    def shift(ids, base):
        # chunk-local ids -> store-slot ids; -1 sentinels pass through.
        # base rides as a TRACED scalar so every chunk of one shape shares
        # one program
        return jnp.where(ids >= 0, ids + base, ids)

    return gather, shift


def mirror_gather(rows_dev, slots):
    """Device-side candidate gather (the promoted / all-HBM refine path):
    ``rows_dev[(clip(slots, 0))]`` with sentinel slots left to the refine
    mask. One jitted program per (slots-shape, store-shape)."""
    obs_dispatch.note(1)
    return _tier_jits()[0](rows_dev, slots)


def shift_slots(ids, base: int):
    """Shift chunk-local candidate ids into store-slot space (``-1``
    passes through); ``base`` is traced, so all chunks share a program."""
    obs_dispatch.note(1)
    return _tier_jits()[1](ids, np.int32(base))


# -- placement ---------------------------------------------------------------

def decide_placement(n_bytes: int, res=None) -> str:
    """Initial mirror placement of ``n_bytes`` of raw rows: ``"device"``
    only when a device budget is armed AND the ledger-accounted device
    bytes plus the mirror still fit it — an unbudgeted tiered store stays
    cold (the point of tiering is to spend less HBM, and the hit-rate
    promote path lifts genuinely hot stores later). Pure decision — no
    allocation, no metrics."""
    res = res or default_resources()
    budget = getattr(res, "memory_budget_bytes", None)
    if budget is None or not metrics._enabled:
        return "host"
    used = obs_mem.totals()["device_bytes"]
    return "device" if used + int(n_bytes) <= int(budget) else "host"


# -- live-store registry (/debug/mem "tiers", pressure spills) ---------------

_stores: "weakref.WeakSet[TieredStore]" = weakref.WeakSet()
_registered = False


def _ensure_registered() -> None:
    """Install the module's obs.mem hooks once, lazily at first store
    construction (imports of the stream package must not mutate the
    ledger's hook tables)."""
    global _registered
    if _registered:
        return
    _registered = True
    obs_mem.register_pressure_handler(_relieve_pressure)
    obs_mem.register_debug_section("tiers", debug_tiers)


def _relieve_pressure(need_bytes: int) -> int:
    """Budget-pressure spill: drop device mirrors (largest first) until
    ``need_bytes`` of HBM are reclaimed or no mirror remains. Called by
    :func:`raft_tpu.obs.mem.gate` BEFORE it refuses an admission — a
    resident mirror is a cache, and shedding a cache beats shedding a
    write. Returns the bytes actually freed."""
    freed = 0
    stores = sorted((s for s in list(_stores) if s.mirror_resident),
                    key=lambda s: -s.row_bytes)
    for s in stores:
        if freed >= need_bytes:
            break
        freed += s.spill(reason="pressure")
    return freed


def spillable_bytes() -> int:
    """HBM bytes a budget-pressure spill could reclaim right now: the sum
    of every live store's RESIDENT device mirror (exactly what
    :func:`_relieve_pressure` drops, in the same accounting). The control
    plane's reshard admission adds this to the budget headroom — a
    migration's double-buffer may displace caches, never live state. 0
    when no tiered store is live."""
    return sum(s.row_bytes for s in list(_stores) if s.mirror_resident)


def tier_totals() -> dict:
    """Per-tier byte totals over every live store (empty dict when no
    tiered store is live)."""
    out: dict[str, int] = {}
    for s in list(_stores):
        for tier, b in s.tier_bytes().items():
            if b:
                out[tier] = out.get(tier, 0) + b
    return out


# per-tier high-water marks since the last reset — what the bench's
# per-row ``mem.tiers`` field reads: a row's TieredStore is usually a
# frame local freed before the row-guard attaches attribution, so the
# LIVE totals would read {} there; the watermark survives the scope
# (same reset-per-row discipline as obs.mem.reset_peak)
_tier_peak: dict = {}


def _note_tier_peak() -> None:
    for tier, b in tier_totals().items():
        if b > _tier_peak.get(tier, 0):
            _tier_peak[tier] = b


def reset_tier_peak() -> None:
    """Re-base the per-tier watermarks (the bench calls this at each
    row-scope start, mirroring ``obs.mem.reset_peak``)."""
    _tier_peak.clear()
    _note_tier_peak()


def tier_peak() -> dict:
    """Per-tier high-water bytes since the last :func:`reset_tier_peak`
    (non-empty iff a tiered store lived in the window)."""
    return dict(_tier_peak)


def debug_tiers() -> dict:
    """The ``tiers`` section of ``/debug/mem``: every live store's
    residency, per-tier bytes, fetch/hit counters and recent
    spill/promote events (bounded — a debug scrape stays cheap)."""
    stores = [s.stats() for s in list(_stores)]
    stores.sort(key=lambda r: (r["name"], r["shard"] or 0))
    return {"stores": stores, "totals": tier_totals()}


# -- the store ---------------------------------------------------------------

class TieredStore:
    """Tiered raw-row store (see module docstring).

    ``rows`` (n, d) land on the cold tier chosen by ``policy`` (host RAM,
    or a ``<disk_path>.e<epoch>`` mmap when ``disk_path`` is set) and the
    device mirror is placed by :func:`decide_placement` against ``res``
    (or restored explicitly via ``residency=`` — the ``load()`` path,
    which must NOT re-decide). ``device`` pins uploads (the sharded
    tier's committed-placement contract); ``name``/``shard``/``epoch``
    key the ledger entry and the metric series."""

    def __init__(self, rows, *, name: str = "default",
                 shard: int | None = None, epoch: int = 0,
                 policy: TierPolicy | None = None, device=None, res=None,
                 residency: str | None = None,
                 clock: Callable[[], float] = time.monotonic):
        # mmap detection must see the RAW argument: np.asarray strips the
        # memmap subclass (same memory, but the disk-backed provenance —
        # what prices the rows at zero host bytes — would be lost)
        raw = rows
        rows = np.asarray(rows)
        expects(rows.ndim == 2 and rows.shape[0] > 0,
                "TieredStore rows must be (n>0, d)")
        self._policy = policy or TierPolicy()
        self._name = name
        self._shard = None if shard is None else int(shard)
        self._epoch = int(epoch)
        self._device = device
        self._clock = clock
        self._lock = threading.Lock()
        # serializes the slot-ring turn bookkeeping (see _slot_upload);
        # distinct from _lock so stats() never blocks behind a dispatch
        self._ring_lock = threading.Lock()
        self._mirror = None
        self._promoting = False  # promote-transition reservation flag
        self._cold_fetches = 0  # host/disk gathers since last promote
        self._rows_fetched = 0
        self._rows_hit = 0  # rows served from the resident mirror
        self._h2d_bytes = 0
        self._fetch_wall_s = 0.0  # host gather + upload dispatch walls
        self._spills = 0
        self._promotes = 0
        self._events: collections.deque = collections.deque(maxlen=16)
        # per-shape device slot rings (the double buffer): key -> [arrays]
        self._slots: dict[tuple, list] = {}
        self._turn: dict[tuple, int] = {}
        self._slot_bytes = 0

        res = res or default_resources()
        self._mmap_adopted = False
        if self._policy.disk_path is None and isinstance(raw, np.memmap):
            # ADOPT the caller's mmap as the cold tier in place (the
            # out-of-core build path: a ChunkedReader's backing memmap
            # becomes the refine-row store without ever materializing a
            # RAM copy). Pages are disk-backed, so the rows price zero
            # host bytes — same rule as a disk_path store.
            self._disk_file = None
            self._mmap_adopted = True
            self._rows = raw
            host_gate = 0
        elif self._policy.disk_path is not None:
            # the cold majority on disk: rows stream once into an mmap
            # whose pages the OS caches — the name+epoch suffix keeps a
            # compaction successor (or a shard/replica twin sharing the
            # policy's path prefix) from clobbering pages a draining
            # lease still reads
            self._disk_file = (f"{self._policy.disk_path}"
                               f".{name.replace('/', '_')}.e{self._epoch}")
            # unlink any existing file FIRST: open_memmap("w+") truncates
            # in place, so a same-(path, name, epoch) collision — two
            # loads of one snapshot, or a stale file from a crashed
            # process — would destroy pages a LIVE older store still
            # maps. Unlink keeps the old inode alive for its mapping and
            # gives this store a fresh one.
            _unlink_quiet(self._disk_file)
            mm = np.lib.format.open_memmap(
                self._disk_file, mode="w+", dtype=rows.dtype,
                shape=rows.shape)
            mm[:] = rows
            mm.flush()
            self._rows = mm
            # the epoch file dies with the store: a compaction successor
            # writes its own `.e<N+1>` file, and without this a
            # periodically-compacting disk-tiered index would grow disk
            # by store_bytes per fold forever (POSIX unlink-while-mapped
            # is safe — draining leases keep reading their pages). The
            # finalizer is inode-checked: if a LATER store reused this
            # path (same name/epoch — it unlinked our entry and created
            # a fresh inode), our death must not delete ITS live file
            stat = os.stat(self._disk_file)
            weakref.finalize(self, _unlink_if_same_inode, self._disk_file,
                             (stat.st_dev, stat.st_ino))
            host_gate = 0
        else:
            self._disk_file = None
            self._rows = np.ascontiguousarray(rows)
            host_gate = self._rows.nbytes
        # host-budget admission (whole-or-nothing, BEFORE the ledger entry
        # lands): a RAM-resident store prices its rows against the new
        # Resources.host_budget_bytes; an mmap'd store prices nothing (its
        # pages are disk-backed). HOST-only: constructing a store adds
        # zero device bytes, and the device budget's cumulative check
        # must not fail e.g. a compaction successor while the
        # double-buffered predecessor epoch is still accounted
        obs_mem.gate_host(res, host_gate, site="tier",
                          detail=f"tiered store {name!r}")
        self._mem = obs_mem.account(
            "tier", name=name, shard=self._shard, epoch=self._epoch,
            host=([] if self._on_disk else [self._rows]),
            owner=self)
        _ensure_registered()
        _stores.add(self)
        if residency is None:
            residency = decide_placement(self._rows.nbytes, res)
        expects(residency in ("device", "host", "disk"),
                "residency must be one of %s, got %r", TIERS, residency)
        if residency == "device":
            self.promote(res=res, reason="placement")
        self._publish_gauges()

    # -- introspection -------------------------------------------------------
    @property
    def shape(self) -> tuple:
        return self._rows.shape

    @property
    def dtype(self):
        return self._rows.dtype

    @property
    def row_bytes(self) -> int:
        """Bytes of one full row-set copy (what a mirror costs in HBM)."""
        return int(self._rows.nbytes)

    @property
    def policy(self) -> TierPolicy:
        return self._policy

    @property
    def mirror_resident(self) -> bool:
        return self._mirror is not None

    @property
    def _on_disk(self) -> bool:
        """Cold rows are disk-backed (own epoch file OR an adopted
        mmap) — they price zero host bytes either way."""
        return self._disk_file is not None or self._mmap_adopted

    @property
    def mirror(self):
        """The promoted device copy (None while cold)."""
        return self._mirror

    @property
    def residency(self) -> str:
        """The COLD-COPY tier plus promotion state: ``device`` while the
        mirror is resident, else ``disk``/``host`` per the backing array —
        the one scalar ``save()`` persists as the decided layout."""
        if self._mirror is not None:
            return "device"
        return "disk" if self._on_disk else "host"

    def host_view(self) -> np.ndarray:
        """The cold row array (ndarray or memmap) — compaction folds,
        drift sampling and serialization read rows through this (never a
        device hop)."""
        return self._rows

    def tier_bytes(self) -> dict:
        """Live bytes per tier. Device = mirror + gather slots (the
        constant double-buffer rings); exactly one of host/disk carries
        the row bytes."""
        dev = self._slot_bytes + (self.row_bytes if self._mirror is not None
                                  else 0)
        return {
            "device": int(dev),
            "host": 0 if self._on_disk else self.row_bytes,
            "disk": self.row_bytes if self._on_disk else 0,
        }

    def stats(self) -> dict:
        tb = self.tier_bytes()
        return {
            "name": self._name, "shard": self._shard, "epoch": self._epoch,
            "rows": int(self._rows.shape[0]),
            "dim": int(self._rows.shape[1]),
            "dtype": str(self._rows.dtype),
            "residency": self.residency,
            "tier_bytes": tb,
            "rows_fetched": self._rows_fetched,
            "hit_ratio": (self._rows_hit / self._rows_fetched
                          if self._rows_fetched else 0.0),
            "h2d_bytes": self._h2d_bytes,
            "fetch_wall_s": round(self._fetch_wall_s, 6),
            "spills": self._spills, "promotes": self._promotes,
            "events": list(self._events),
        }

    # -- accounting ----------------------------------------------------------
    def _reaccount(self) -> None:
        dev = [] if self._mirror is None else [self._mirror]
        with self._ring_lock:
            for ring in self._slots.values():
                dev.extend(ring)
        obs_mem.reaccount(
            self._mem, device=dev,
            host=([] if self._on_disk else [self._rows]))

    def _publish_gauges(self) -> None:
        """Publish the per-tier byte gauges + the global peak watermark.
        Called ONLY when tier bytes can actually change (construction,
        promote/spill, ring growth) — never per fetch: the watermark
        rescans every live store, which would be O(shards) per batch on
        a tiered mesh's hot path."""
        _note_tier_peak()
        if not metrics._enabled:
            return
        for tier, b in self.tier_bytes().items():
            _g_tier_bytes().set(b, tier=tier, name=self._name)
        self._publish_hit_ratio()

    def _publish_hit_ratio(self) -> None:
        if metrics._enabled and self._rows_fetched:
            _g_hit_ratio().set(self._rows_hit / self._rows_fetched,
                               name=self._name)

    # -- residency moves -----------------------------------------------------
    def promote(self, res=None, *, force: bool = False,
                reason: str = "explicit") -> bool:
        """Lift the device mirror (idempotent). Unless ``force``, the
        mirror is priced against ``res.memory_budget_bytes`` headroom
        first — a store that does not fit stays cold and returns False
        (never raises: a failed promote is a skipped optimization, not an
        error). Counted + event-logged either way it lands.

        The residency transition is RESERVED under the lock before the
        upload: two search threads crossing ``promote_min_hits``
        together would otherwise both pass the cold check and both
        upload the full row set — transiently 2x the store in HBM on
        exactly the budget-squeezed hosts tiering targets."""
        with self._lock:
            if self._mirror is not None:
                return True
            if self._promoting:
                return False  # a concurrent promote owns the transition
            self._promoting = True
        try:
            if not force and not self._headroom(res):
                return False
            import jax

            rows = np.ascontiguousarray(self._rows)
            mirror = (jax.device_put(rows, self._device)
                      if self._device is not None
                      else jax.device_put(rows))
            with self._lock:
                self._mirror = mirror
            self._promotes += 1
            self._events.append({"event": "promote", "reason": reason,
                                 "at": round(self._clock(), 3)})
            obs_events.emit(
                "tier_promote", subject=("tier", self._name, None, None),
                evidence={"reason": reason, "bytes": self.row_bytes},
                counter=_c_promotes, counter_labels={"name": self._name})
        finally:
            with self._lock:
                self._promoting = False
        self._reaccount()
        self._publish_gauges()
        return True

    def _headroom(self, res) -> bool:
        res = res or default_resources()
        budget = getattr(res, "memory_budget_bytes", None)
        if budget is None:
            # no armed budget: an auto/hit-rate promote may lift the
            # mirror (there is nothing to protect), construction placement
            # already chose cold via decide_placement
            return True
        if not metrics._enabled:
            return False
        used = obs_mem.totals()["device_bytes"]
        return used + self.row_bytes <= int(budget)

    def spill(self, reason: str = "explicit") -> int:
        """Drop the device mirror (idempotent; returns the bytes freed).
        The cold copy is authoritative, so a spill loses nothing — the
        next fetch pays the host hop again (in-flight queries keep their
        mirror snapshot). ``reason="pressure"`` is the obs.mem gate's
        reclaim path."""
        with self._lock:
            if self._mirror is None:
                return 0
            self._mirror = None
        freed = self.row_bytes
        self._cold_fetches = 0
        self._spills += 1
        self._events.append({"event": "spill", "reason": reason,
                             "at": round(self._clock(), 3)})
        obs_events.emit(
            "tier_spill",
            # a pressure spill is the budget gate reclaiming HBM —
            # operator-visible; an explicit/idle spill is routine
            severity="warning" if reason == "pressure" else "info",
            subject=("tier", self._name, None, None),
            evidence={"reason": reason, "freed_bytes": freed},
            counter=_c_spills,
            counter_labels={"name": self._name, "reason": reason})
        self._reaccount()
        self._publish_gauges()
        return freed

    def retire(self) -> None:
        """Mark this store's ledger entry expected-to-free (a compaction
        swap retiring the pre-fold epoch's store — the retirement audit
        then proves draining leases actually release it)."""
        obs_mem.retire(self._mem)

    # -- the double-buffered device hop --------------------------------------
    def _slot_upload(self, key: tuple, host_arr: np.ndarray):
        """Upload ``host_arr`` through the shape-keyed slot ring: the
        ring REPLACES its ``turn`` entry per upload, so the ledger's
        accounted slot bytes per shape are constant in steady state; a
        ring only allocates (and reaccounts) once per NEW shape. The
        displaced upload frees by reference drop once the batch that
        consumed it completes — the same flat-bytes contract
        ``serve/staging`` documents for its unpinned mode.

        Deliberately NOT the staging buffers' ``donate_argnums``
        program: donation invalidates the stale buffer at dispatch, and
        searches here are lock-free by design — a concurrent same-shape
        fetch may still HOLD a previously returned slot it has not yet
        dispatched, so donating it would fail that query ("array has
        been deleted"). Staging's donation is safe only under its
        single-flush-worker discipline, which this path cannot assume.
        The ring lock keeps the turn bookkeeping and ring growth
        consistent; the host gather stays concurrent."""
        import jax

        dev = (jax.device_put(host_arr, self._device)
               if self._device is not None else jax.device_put(host_arr))
        grew = 0
        with self._ring_lock:
            ring = self._slots.get(key)
            if ring is None:
                ring = self._slots[key] = []
                self._turn[key] = 0
            if len(ring) < self._policy.fetch_slots:
                ring.append(dev)
                grew = int(host_arr.nbytes)
            else:
                turn = self._turn[key]
                ring[turn] = dev
                self._turn[key] = (turn + 1) % len(ring)
        if grew:
            with self._lock:
                self._slot_bytes += grew
            self._reaccount()
            self._publish_gauges()
        return dev

    def fetch(self, slots, res=None):
        """Gather candidate rows by store SLOT for the refine epilogue:
        ``slots`` (m, k0) int (device or host; ``-1`` = padding — reads
        row 0, masked downstream by candidate id) → device rows
        (m, k0, d). Mirror-resident stores gather on device (a tier
        *hit*, zero transfer); cold stores gather on the host and upload
        through the replacement slot ring — under async dispatch batch N+1's
        H2D overlaps batch N's compute, which is the whole refine-hop
        cost model. Hit-rate promote rides here: ``promote_min_hits``
        cold fetches with budget headroom lift the mirror."""
        faults.fire("tier/fetch", name=self._name, residency=self.residency)
        # mirror SNAPSHOT: a pressure spill can null self._mirror from a
        # writer thread between a check and a use — the local reference
        # keeps this query on the (still-live) promoted copy; "a spill
        # loses nothing" includes queries in flight
        mirror = self._mirror
        if mirror is not None:
            out = mirror_gather(mirror, slots)
            n_rows = int(np.prod(out.shape[:-1]))
            self._rows_fetched += n_rows
            self._rows_hit += n_rows
            if metrics._enabled:
                _c_fetches().inc(1, name=self._name, src="device")
            self._publish_hit_ratio()
            return out
        t0 = time.perf_counter()
        ids = np.asarray(slots)
        gathered = np.take(self._rows, np.clip(ids, 0, None), axis=0)
        dev = self._slot_upload(("fetch",) + gathered.shape, gathered)
        self._fetch_wall_s += time.perf_counter() - t0
        self._rows_fetched += int(ids.size)
        self._cold_fetches += 1
        self._h2d_bytes += int(gathered.nbytes)
        src = "disk" if self._on_disk else "host"
        if metrics._enabled:
            _c_fetches().inc(1, name=self._name, src=src)
            _c_h2d().inc(int(gathered.nbytes), name=self._name)
        obs_dispatch.note(1)
        if (self._policy.auto_promote
                and self._cold_fetches >= self._policy.promote_min_hits):
            self._cold_fetches = 0
            # hit-rate promote fires ONLY under an ARMED budget with
            # headroom: without a budget there is no safe ceiling to
            # promote against, and uploading a beyond-HBM store because
            # it was queried 3 times is exactly the OOM tiering exists
            # to avoid (explicit promote()/load-layout restore remain
            # available without a budget)
            res_eff = res or default_resources()
            if getattr(res_eff, "memory_budget_bytes", None) is not None:
                self.promote(res=res_eff, reason="hit-rate")
        self._publish_hit_ratio()
        return dev

    # -- the chunked oracle scan ---------------------------------------------
    @property
    def oracle_chunk(self) -> int:
        """Device shape of one oracle chunk (every pass reuses it, so the
        exact scan is one program regardless of store size)."""
        return min(self._policy.oracle_chunk,
                   _pow2_at_least(self._rows.shape[0]))

    def n_oracle_chunks(self) -> int:
        c = self.oracle_chunk
        return -(-self._rows.shape[0] // c)

    def oracle_chunk_dev(self, ci: int):
        """``(rows_dev (chunk, d), base, valid)`` — chunk ``ci`` of the
        cold rows uploaded through the slot ring (zero NET device bytes
        across a scan; the last chunk zero-pads and reports ``valid`` <
        chunk so the caller can mask). Mirror-resident stores never call
        this — they scan the mirror directly."""
        c = self.oracle_chunk
        base = ci * c
        n = self._rows.shape[0]
        expects(0 <= base < n, "oracle chunk %d out of range", ci)
        t0 = time.perf_counter()
        valid = min(c, n - base)
        block = self._rows[base:base + valid]
        if valid < c:
            pad = np.zeros((c, self._rows.shape[1]), self._rows.dtype)
            pad[:valid] = block
            block = pad
        else:
            block = np.ascontiguousarray(block)
        dev = self._slot_upload(("oracle", c), block)
        self._fetch_wall_s += time.perf_counter() - t0
        self._h2d_bytes += int(block.nbytes)
        src = "disk" if self._on_disk else "host"
        if metrics._enabled:
            _c_fetches().inc(1, name=self._name, src=src)
            _c_h2d().inc(int(block.nbytes), name=self._name)
        return dev, base, valid

    # NOTE on warmup: there is deliberately no store-level warm helper —
    # the one rehearsal path is ``MutableIndex.warm_refined``, which runs
    # the REAL search_refined / chunked-scan programs (filling these same
    # rings as a side effect), so the warmed set can never drift from
    # what the serving path actually dispatches.


def _pow2_at_least(n: int) -> int:
    b = 8
    while b < n:
        b *= 2
    return b


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _unlink_if_same_inode(path: str, devino: tuple) -> None:
    """Unlink ``path`` only if it still names the inode the owning store
    created — a later store may have reused the path with a fresh inode
    (same name/epoch collision), and the older store's death must not
    delete the live file."""
    try:
        stat = os.stat(path)
        if (stat.st_dev, stat.st_ino) == devino:
            os.unlink(path)
    except OSError:
        pass
