"""ReplicatedShard: R device-pinned MutableIndex twins behind one surface.

PR 9's sharded tier spread the corpus across devices, but each shard stayed
a single point of failure: one wedged or crashed device failed every query
routed to it. This module is the availability half of ROADMAP item 3 —
replica groups with read failover — built from pieces that already exist:
the twins are ordinary :class:`~raft_tpu.stream.MutableIndex` objects
(device-pinned via ``device=``, per-replica mem-ledger attribution under
``name/r<j>``), writes reuse the hoisted whole-or-nothing admission pattern
of the sharded upsert, and the scatter-gather composes a group exactly
where it composed a single shard. Semantics:

- **Writes apply to all live replicas.** Deterministic refusals
  (:class:`~raft_tpu.stream.DeltaFullError`,
  :class:`~raft_tpu.serve.errors.MemoryBudgetError`) are hoisted BEFORE
  any replica writes — nothing lands anywhere, the same whole-or-nothing
  contract as a cross-shard upsert. A replica whose write RAISES past
  admission (device fault) is marked **stale** and fenced from reads — it
  missed an acknowledged write, and serving from it would un-acknowledge
  it; the write succeeds as long as one twin (plus the WAL, when armed)
  holds it. Stale is permanent until the replica is rebuilt: a re-probe
  can heal a slow replica, not a diverged one.
- **Reads fan to ONE replica**, picked by health + recent latency: fenced
  and stale replicas are excluded, and among the healthy the lowest
  scan-wall EWMA wins (the per-replica SLO-burn proxy — a replica burning
  latency budget stops being picked before it trips the breaker). A
  failed or deadline-blown scan strikes the replica's circuit breaker
  (``FencingPolicy.max_consecutive`` consecutive strikes → fenced for
  ``backoff_s``, doubling per re-fence up to ``backoff_max_s``) and the
  SAME flush retries the surviving twin — one dead replica means degraded
  capacity, never a failed query. After the backoff, the next pick
  half-opens the breaker as a probe: success closes it, failure re-fences
  with doubled backoff. Only when every replica is fenced/stale/failed
  does the query raise
  :class:`~raft_tpu.serve.errors.ReplicaUnavailableError`.
- **Durability is group-level.** ``wal=`` logs the group's serialized
  write stream once (the twins are in-memory redundancy; the log is the
  on-disk copy), ``save()`` snapshots the primary twin atomically with
  the group's WAL seq and truncates the log, and recovery is
  ``stream.load(path, wal=)`` — a degraded-to-one restore that recovers
  every acknowledged write; re-replication is a rebuild (document-level
  contract: replication protects availability, the WAL protects data).

Fault points (:mod:`raft_tpu.testing.faults`): ``replica/search`` (per
scan attempt; a callback that advances the injected clock simulates a
WEDGED replica — the scan "takes" past ``deadline_s`` and strikes the
breaker with no wall sleep), ``replica/upsert`` (per replica write).

Metrics: ``raft_tpu_replica_*`` (catalogue: docs/observability.md);
health detail for ``/healthz`` via :meth:`ReplicatedShard.health`.
Failover semantics: docs/serving.md; write/read rules:
docs/streaming.md "Durability & replication".
"""

from __future__ import annotations

import copy
import dataclasses
import functools
import threading
import time
from typing import Callable, Sequence

import numpy as np

from ..core.errors import RaftError, expects
from ..core.resources import default_resources
from ..obs import events as obs_events
from ..obs import mem as obs_mem
from ..obs import metrics
from ..serve.errors import ReplicaUnavailableError
from ..testing import faults
from . import mutable as _mut
from .mutable import MutableIndex

__all__ = ["ReplicatedShard", "FencingPolicy"]


# -- metrics (catalogue: docs/observability.md) ------------------------------

@functools.lru_cache(maxsize=None)
def _g_healthy():
    return metrics.gauge(
        "raft_tpu_replica_healthy",
        "replicas currently pickable for reads (not fenced, not stale)")


@functools.lru_cache(maxsize=None)
def _g_stale():
    return metrics.gauge(
        "raft_tpu_replica_stale",
        "replicas that missed an acknowledged write (fenced from reads "
        "until rebuilt — re-probing cannot heal divergence)")


@functools.lru_cache(maxsize=None)
def _c_fenced():
    return metrics.counter(
        "raft_tpu_replica_fenced_total",
        "replica fencings by reason (error/slow strikes tripping the "
        "breaker, write = missed write marked stale)")


@functools.lru_cache(maxsize=None)
def _c_failovers():
    return metrics.counter(
        "raft_tpu_replica_failovers_total",
        "reads retried on a surviving twin within the SAME flush after "
        "the picked replica failed")


@functools.lru_cache(maxsize=None)
def _c_probes():
    return metrics.counter(
        "raft_tpu_replica_probes_total",
        "half-open breaker probes by outcome (ok closes the breaker, "
        "fail re-fences with doubled backoff)")


@functools.lru_cache(maxsize=None)
def _c_reads():
    return metrics.counter(
        "raft_tpu_replica_reads_total",
        "scans served per replica (the read fan-out's pick distribution)")


@dataclasses.dataclass(frozen=True)
class FencingPolicy:
    """When a replica stops being trusted (see module doc).

    ``deadline_s`` — a completed scan slower than this counts as a SLOW
    strike (None disables deadline fencing); the result is still returned
    (it is valid), but the replica stops being picked once the breaker
    opens. ``max_consecutive`` — error/slow strikes in a row before the
    breaker opens. ``backoff_s``/``backoff_max_s`` — fence duration,
    doubling on each re-fence (the re-probe schedule). ``ewma_alpha`` —
    smoothing of the per-replica scan-wall EWMA the read pick minimizes.
    """

    deadline_s: float | None = None
    max_consecutive: int = 2
    backoff_s: float = 1.0
    backoff_max_s: float = 60.0
    ewma_alpha: float = 0.2


class _Health:
    """One replica's breaker + latency state (mutated under the group
    lock only)."""

    __slots__ = ("consecutive", "fenced_until", "backoff", "stale", "ewma",
                 "strikes", "last_error")

    def __init__(self, backoff: float):
        self.consecutive = 0
        self.fenced_until = None  # None = breaker closed
        self.backoff = backoff
        self.stale = False
        self.ewma = None
        self.strikes = 0
        self.last_error = None


class _PinnedGroup:
    """A serving hook's frozen view of one replica group: each replica's
    state epoch pinned at hook-creation time (the registry lease-drain
    contract), with the failover logic live — health/fencing decisions
    always read the CURRENT breaker state, so a hook issued before a
    fence still avoids the fenced twin."""

    __slots__ = ("group", "states")

    def __init__(self, group: "ReplicatedShard", states: tuple):
        self.group = group
        self.states = states

    def scan_serving(self, queries, k, res=None, k_sealed_clamp=True):
        def scan(st, q, kk, res=None):
            ks = (min(int(kk), st.id_map.shape[0]) if k_sealed_clamp
                  else None)
            return _mut._scan_state(st, q, kk, res=res, k_sealed=ks)

        return self.group._failover(self.states, queries, k, scan, res=res)

    def search(self, queries, k, res=None):
        return self.group._failover(
            self.states, queries, k,
            lambda st, q, kk, res=None: _mut._search_state(st, q, kk,
                                                           res=res),
            res=res)


class ReplicatedShard:
    """R MutableIndex twins behind the MutableIndex surface (see module
    doc). ``sealed`` is built ONCE and device-put per replica (twins are
    bit-identical by construction — the crash-recovery bench's parity
    contract); ``devices`` pins replica ``j`` to ``devices[j]`` (the
    anti-affinity that makes a replica group survive a device, not just a
    thread). ``wal``/``snapshot_path`` arm group-level durability;
    ``policy`` is the :class:`FencingPolicy`. Everything else forwards to
    each replica's :class:`MutableIndex` (``ids=`` carries global ids for
    the sharded composition; ``shard=`` the mem-ledger ordinal; replicas
    attribute under ``name/r<j>``)."""

    def __init__(self, sealed, *, n_replicas: int = 2,
                 devices: Sequence | None = None, ids=None,
                 search_params=None, index_params=None,
                 builder: Callable | None = None,
                 delta_capacity: int = 1024,
                 retain_vectors: bool | None = None, dataset=None,
                 wal=None, snapshot_path: str | None = None,
                 policy: FencingPolicy = FencingPolicy(),
                 name: str = "default", shard: int | None = None,
                 storage: str = "hbm", tier=None,
                 clock: Callable[[], float] = time.monotonic):
        n_replicas = int(n_replicas)
        expects(n_replicas >= 1, "n_replicas must be >= 1, got %d",
                n_replicas)
        if devices is not None:
            devices = list(devices)
            expects(len(devices) >= n_replicas,
                    "%d replicas need %d devices, got %d", n_replicas,
                    n_replicas, len(devices))
        self._name = name
        self._clock = clock
        self.policy = policy
        self._lock = threading.RLock()
        # health/breaker state gets its OWN mutex: the read path's
        # pick/strike/observe must never wait out a group write's WAL
        # fsync + R device uploads (held under _lock) — replication is the
        # availability axis; it must not regress read tail latency
        self._hlock = threading.Lock()
        self._rr = 0  # round-robin tie-break cursor
        kind, _ = _mut._resolve_kind(sealed)
        self._replicas: list[MutableIndex] = []
        for j in range(n_replicas):
            # BruteForce is mutated in place by the wrap (dataset moved to
            # the pin) — each replica needs its own shell; pytree kinds are
            # copied by device_put inside MutableIndex anyway
            sealed_j = copy.copy(sealed) if kind == "brute_force" else sealed
            self._replicas.append(MutableIndex(
                sealed_j, search_params=search_params,
                index_params=index_params, delta_capacity=delta_capacity,
                retain_vectors=retain_vectors, dataset=dataset,
                builder=builder, ids=ids,
                device=devices[j] if devices is not None else None,
                name=f"{name}/r{j}", shard=shard, storage=storage,
                tier=tier, clock=clock))
        self._health = [_Health(policy.backoff_s) for _ in range(n_replicas)]
        # group-level durability: ONE log for the group's serialized write
        # stream (the twins are in-memory redundancy; the log is the disk
        # copy) — see save()/stream.load for the recovery contract
        if wal is not None and not hasattr(wal, "append_upsert"):
            from .wal import WriteAheadLog

            wal = WriteAheadLog(wal, name=name)
        if wal is not None:
            expects(wal.seq == 0,
                    "WAL %r already holds records (seq=%d) — recover with "
                    "stream.load(wal=) before re-replicating",
                    getattr(wal, "path", "?"), wal.seq)
        self._wal = wal
        self._wal_seq = 0
        self._snapshot_path = snapshot_path
        self._update_health_gauges()

    # -- introspection (the MutableIndex surface) ---------------------------
    @property
    def kind(self) -> str:
        return self._replicas[0].kind

    @property
    def dim(self) -> int:
        return self._replicas[0].dim

    @property
    def name(self) -> str:
        return self._name

    @property
    def query_dtype(self) -> str:
        return self._replicas[0].query_dtype

    @property
    def delta_capacity(self) -> int:
        return self._replicas[0].delta_capacity

    @property
    def can_rebuild(self) -> bool:
        return all(r.can_rebuild for r in self._replicas)

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    @property
    def replicas(self) -> tuple:
        """The per-replica :class:`MutableIndex` twins (read-only tuple —
        write through the group surface so the twins stay in lockstep)."""
        return tuple(self._replicas)

    @property
    def _cfg(self):
        return self._replicas[0]._cfg

    @property
    def _buckets(self):
        return self._replicas[0]._buckets

    def _coerce_rows(self, rows):
        return self._replicas[0]._coerce_rows(rows)

    @property
    def size(self) -> int:
        return self._primary().size

    def _primary(self) -> MutableIndex:
        """The first non-stale replica — the stats/oracle/snapshot twin
        (live replicas are in lockstep, so any of them speaks for the
        group's data)."""
        for j, h in enumerate(self._health):
            if not h.stale:
                return self._replicas[j]
        return self._replicas[0]

    def _drift_store(self):
        return self._primary()._drift_store()

    def stats(self) -> dict:
        """The primary twin's watermarks (lockstep — the Compactor reads
        them unchanged) plus the group's replica/health detail."""
        st = self._primary().stats()
        with self._hlock:
            now = self._clock()
            healthy = sum(1 for h in self._health
                          if not h.stale and (h.fenced_until is None
                                              or now >= h.fenced_until))
            st["replicas"] = len(self._replicas)
            st["healthy"] = healthy
            st["stale"] = sum(1 for h in self._health if h.stale)
        return st

    def health(self) -> dict:
        """Per-replica breaker state for ``/healthz``
        (``obs.start_http_exporter(replicas=...)``)."""
        with self._hlock:
            now = self._clock()
            reps = []
            for j, h in enumerate(self._health):
                fenced = (h.stale or (h.fenced_until is not None
                                      and now < h.fenced_until))
                reps.append({
                    "replica": self._replicas[j].name,
                    "fenced": bool(fenced), "stale": bool(h.stale),
                    "consecutive_strikes": h.consecutive,
                    "strikes_total": h.strikes,
                    "ewma_ms": (round(h.ewma * 1e3, 3)
                                if h.ewma is not None else None),
                    "fenced_until": h.fenced_until,
                    "last_error": (f"{type(h.last_error).__name__}: "
                                   f"{str(h.last_error)[:120]}"
                                   if h.last_error is not None else None),
                })
            return {"name": self._name, "replicas": reps,
                    "healthy": sum(1 for r in reps if not r["fenced"])}

    def _update_health_gauges(self) -> None:
        if not metrics._enabled:
            return
        now = self._clock()
        healthy = sum(1 for h in self._health
                      if not h.stale and (h.fenced_until is None
                                          or now >= h.fenced_until))
        _g_healthy().set(healthy, name=self._name)
        _g_stale().set(sum(1 for h in self._health if h.stale),
                       name=self._name)

    # -- read pick + breaker -------------------------------------------------
    def _pick(self, exclude: set) -> int | None:
        """The read replica for one attempt: a probe-due fenced replica
        (fence expired, earliest first) half-opens FIRST — without a
        background prober, the next pick is the only chance a fenced twin
        gets to heal, and a failed probe re-fences with same-call failover
        covering the query; otherwise the healthy (breaker-closed) replica
        with the lowest scan-wall EWMA, round-robin among ties; None when
        nothing is pickable."""
        with self._hlock:
            now = self._clock()
            closed, probes = [], []
            for j, h in enumerate(self._health):
                if j in exclude or h.stale:
                    continue
                if h.fenced_until is None:
                    closed.append(j)
                elif now >= h.fenced_until:
                    probes.append((h.fenced_until, j))
            if probes:
                return min(probes)[1]
            if closed:
                self._rr += 1
                rr = self._rr
                return min(closed,
                           key=lambda j: (self._health[j].ewma or 0.0,
                                          (j - rr) % len(self._health)))
            return None

    def _strike(self, j: int, reason: str, exc=None) -> None:
        fenced = was_probe = False
        with self._hlock:
            h = self._health[j]
            h.consecutive += 1
            h.strikes += 1
            if exc is not None:
                h.last_error = exc
            was_probe = h.fenced_until is not None
            if was_probe or h.consecutive >= self.policy.max_consecutive:
                fenced = True
                h.fenced_until = self._clock() + h.backoff
                backoff = h.backoff
                h.backoff = min(h.backoff * 2, self.policy.backoff_max_s)
                if metrics._enabled:
                    _c_fenced().inc(1, name=self._name, reason=reason)
                    if was_probe:
                        _c_probes().inc(1, name=self._name, outcome="fail")
            self._update_health_gauges()
        # journal OUTSIDE the health lock: a subscriber tap must never
        # run (or block) under the breaker's lock
        if fenced:
            if was_probe:
                obs_events.emit(
                    "replica_probe", severity="warning",
                    subject=("replica", self._name, j, None),
                    evidence={"outcome": "fail", "reason": reason,
                              "backoff_s": backoff})
            obs_events.emit(
                "replica_fenced",
                subject=("replica", self._name, j, None),
                evidence={"reason": reason, "backoff_s": backoff,
                          "error": None if exc is None else repr(exc)})

    def _observe_ok(self, j: int, wall: float) -> bool:
        """Record a completed scan; returns True if it counted as a SLOW
        strike (the caller still returns the valid result)."""
        p = self.policy
        slow = p.deadline_s is not None and wall > p.deadline_s
        unfenced = False
        with self._hlock:
            h = self._health[j]
            h.ewma = (wall if h.ewma is None
                      else (1 - p.ewma_alpha) * h.ewma + p.ewma_alpha * wall)
            if slow:
                pass  # strike accounting below, outside the success path
            else:
                if h.fenced_until is not None:
                    unfenced = True
                    if metrics._enabled:
                        _c_probes().inc(1, name=self._name, outcome="ok")
                h.consecutive = 0
                h.fenced_until = None  # a successful probe closes the breaker
                h.backoff = self.policy.backoff_s
            self._update_health_gauges()
        if unfenced:
            # probe ok + breaker close journal as one causal pair, outside
            # the health lock
            obs_events.emit("replica_probe",
                            subject=("replica", self._name, j, None),
                            evidence={"outcome": "ok",
                                      "wall_s": round(wall, 6)})
            obs_events.emit("replica_unfenced",
                            subject=("replica", self._name, j, None),
                            evidence={"wall_s": round(wall, 6)})
        if slow:
            self._strike(j, "slow")
        return slow

    def _failover(self, states, queries, k, scan, res=None):
        """Run ``scan`` on one replica, failing over to the surviving
        twins IN THE SAME CALL on error; deadline-slow completions return
        their (valid) result but strike the breaker for future picks."""
        from ..obs import requestlog

        tried: set = set()
        last_exc = None
        while True:
            j = self._pick(tried)
            if j is None:
                with self._hlock:
                    fenced = sum(
                        1 for h in self._health
                        if h.stale or h.fenced_until is not None)
                raise ReplicaUnavailableError(
                    f"replica group {self._name!r}: no replica can serve "
                    f"({fenced}/{len(self._replicas)} fenced or stale, "
                    f"{len(tried)} failed this call)",
                    name=self._name, replicas=len(self._replicas),
                    fenced=fenced) from last_exc
            tried.add(j)
            t0 = self._clock()
            try:
                with requestlog.prefix(f"r{j}/"):
                    faults.fire("replica/search",
                                replica=self._replicas[j].name, attempt=j)
                    out = scan(states[j], queries, k, res=res)
            except ReplicaUnavailableError:
                raise
            except faults.FaultError as e:
                # injected faults simulate device failures — they strike
                last_exc = e
                self._strike(j, "error", exc=e)
                continue
            except RaftError:
                # deterministic validation (expects-style: bad query
                # shape/dim/k) — every twin would refuse identically, so
                # striking the breaker would let a caller-side bug fence
                # the whole group and fail subsequent VALID queries
                raise
            except Exception as e:
                last_exc = e
                self._strike(j, "error", exc=e)
                continue
            self._observe_ok(j, self._clock() - t0)
            if metrics._enabled:
                if len(tried) > 1:
                    # counted at SUCCESS, not per failed attempt: the
                    # metric's contract is "retried on a SURVIVING twin" —
                    # an all-dead call raises and must not count
                    _c_failovers().inc(len(tried) - 1, name=self._name)
                _c_reads().inc(1, name=self._name, replica=f"r{j}")
            if len(tried) > 1:
                obs_events.emit(
                    "replica_failover",
                    subject=("replica", self._name, j, None),
                    evidence={"retried": len(tried) - 1,
                              "error": repr(last_exc)})
            requestlog.annotate("replica", j)
            return out

    # -- reads ---------------------------------------------------------------
    def pin_group(self) -> _PinnedGroup:
        """Freeze every replica's current state epoch behind the live
        failover logic — what a serving hook (and the sharded scatter)
        holds across compaction swaps."""
        return _PinnedGroup(self, tuple(r._state for r in self._replicas))

    def search(self, queries, k: int, res=None):
        """One replica's full merged search (twins are equivalent), with
        same-call failover — the :meth:`MutableIndex.search` contract."""
        return self.pin_group().search(queries, k, res=res)

    def _exact_scan(self, queries, k: int, res=None):
        """Failover composition of the exact-oracle scan half (the sharded
        ``exact_search`` calls this per shard)."""
        return self._failover(
            tuple(range(len(self._replicas))), queries, k,
            lambda j, q, kk, res=None: self._replicas[j]._exact_scan(
                q, kk, res=res),
            res=res)

    def exact_search(self, queries, k: int, res=None):
        """EXACT fused kNN over the live corpus via any live twin (the
        RecallCanary's oracle surface)."""
        sd, si, dd, di = self._exact_scan(queries, k, res=res)
        return _mut._merge(sd, si, dd, di, int(k),
                           self._cfg.select_min)

    def searcher(self):
        """Serving hook pinned to the group's current epochs (the
        ``batched_searcher`` contract), failover inside."""
        from ..neighbors._hooks import make_hook

        pin = self.pin_group()
        cfg = self._cfg
        fn = make_hook(lambda queries, k: pin.search(queries, k),
                       f"stream/replicated/{cfg.kind}", cfg.dim,
                       cfg.data_kind)
        fn.mutable = self
        return fn

    # -- writes --------------------------------------------------------------
    def _delta_rows_now(self) -> int:
        return max(r._delta_rows_now() for r in self._live())

    def _growth_bytes(self, r: int) -> int:
        return sum(rep._growth_bytes(r) for rep in self._live())

    def _live(self) -> list[MutableIndex]:
        return [rep for rep, h in zip(self._replicas, self._health)
                if not h.stale] or [self._replicas[0]]

    def upsert(self, rows, ids=None, res=None):
        """Insert/upsert on every live replica. Deterministic admission
        (capacity, memory budget) is hoisted across the group BEFORE the
        WAL append and before any replica writes — whole-or-nothing; a
        replica that fails PAST admission is marked stale and fenced (it
        missed an acknowledged write), and the write succeeds as long as
        one twin applied it."""
        rows = self._coerce_rows(rows)
        r = rows.shape[0]
        expects(r >= 1, "upsert needs at least one row")
        with self._lock:
            live = [(j, self._replicas[j]) for j in range(len(self._replicas))
                    if not self._health[j].stale]
            if not live:
                raise ReplicaUnavailableError(
                    f"replica group {self._name!r}: every replica is "
                    "stale — refusing the write (acknowledging it with "
                    "no twin to hold it would lose it); rebuild the "
                    "group", name=self._name,
                    replicas=len(self._replicas),
                    fenced=len(self._replicas))
            gids = self._assign_ids(r, ids)
            # hoisted admission: every live twin must have room (lockstep
            # makes these equal, but a refusal after a sibling accepted
            # would break whole-or-nothing, so check them all)
            for j, rep in live:
                if rep._delta_rows_now() + r > rep.delta_capacity:
                    if metrics._enabled:
                        _mut._c_delta_full().inc(1, name=self._name)
                    raise _mut.DeltaFullError(
                        f"replica {rep.name} delta at "
                        f"{rep._delta_rows_now()}/{rep.delta_capacity} "
                        f"rows; upsert of {r} refused — compact() to fold")
            obs_mem.gate(res or default_resources(),
                         lambda: self._growth_bytes(r),
                         site="upsert",
                         detail=f"stream/replicated {self._name!r}")
            wal_prev = (self._wal.size_bytes
                        if self._wal is not None else None)
            if self._wal is not None:
                self._wal_seq = self._wal.append_upsert(rows, gids)
                faults.fire("stream/post-wal", name=self._name, op="upsert")
            inner = res or default_resources()
            if getattr(inner, "memory_budget_bytes", None) is not None:
                inner = dataclasses.replace(inner, memory_budget_bytes=None)
            self._apply(live, "upsert",
                        lambda rep: rep.upsert(rows, ids=gids, res=inner),
                        wal_prev=wal_prev)
        return gids

    def delete(self, ids) -> int:
        """Tombstone ids on every live replica; returns how many were
        live (the primary twin's count — lockstep)."""
        arr = np.asarray(ids, np.int64).reshape(-1)
        if arr.size == 0:
            return 0
        with self._lock:
            live = [(j, self._replicas[j]) for j in range(len(self._replicas))
                    if not self._health[j].stale]
            if not live:
                raise ReplicaUnavailableError(
                    f"replica group {self._name!r}: every replica is "
                    "stale — refusing the write (acknowledging it with "
                    "no twin to hold it would lose it); rebuild the "
                    "group", name=self._name,
                    replicas=len(self._replicas),
                    fenced=len(self._replicas))
            wal_prev = (self._wal.size_bytes
                        if self._wal is not None else None)
            if self._wal is not None:
                self._wal_seq = self._wal.append_delete(arr)
                faults.fire("stream/post-wal", name=self._name, op="delete")
            box: dict = {}

            def do(rep, _box=box):
                n = rep.delete(arr)
                _box.setdefault("n", n)

            self._apply(live, "delete", do, wal_prev=wal_prev)
        return int(box.get("n", 0))

    def _assign_ids(self, r: int, ids):
        if ids is None:
            base = max(rep._next_id for rep in self._replicas)
            return np.arange(base, base + r, dtype=np.int64)
        return _mut.check_upsert_ids(ids, r)

    def _apply(self, live, op: str, fn, wal_prev=None) -> None:
        """Forward one admitted write to every live twin; a raising twin
        goes STALE (fenced from reads — it missed the write). If EVERY
        twin failed, the write itself failed: its WAL record (appended
        write-ahead under the same lock) is rolled back so recovery
        cannot resurrect a write the caller was told did not land, and
        the last error re-raises."""
        ok = 0
        last = None
        for j, rep in live:
            try:
                faults.fire(f"replica/{op}", replica=rep.name)
                fn(rep)
                ok += 1
            except Exception as e:
                last = e
                with self._hlock:
                    h = self._health[j]
                    h.stale = True
                    h.last_error = e
                if metrics._enabled:
                    _c_fenced().inc(1, name=self._name, reason="write")
                obs_events.emit(
                    "replica_stale",
                    subject=("replica", self._name, j, None),
                    evidence={"op": op, "error": repr(e)})
        with self._hlock:
            self._update_health_gauges()
        if ok == 0 and last is not None:
            if self._wal is not None and wal_prev is not None:
                self._wal.rollback_last(self._wal_seq, wal_prev)
                self._wal_seq -= 1
            raise last

    # -- compaction / warm / durability --------------------------------------
    def compact(self, mode: str = "auto", res=None,
                trigger: str | None = None,
                ooc_chunk_rows: int | None = None) -> dict:
        """Fold every live twin (each through its ordinary off-lock
        fold+swap — readers keep serving whichever twin is not mid-swap,
        and the swap itself is atomic per twin). Report = the primary
        fold's report + per-replica walls; with group durability armed,
        the post-fold snapshot + WAL truncation ride here exactly like the
        single-index path."""
        reports = []
        for rep in self._live():
            reports.append(rep.compact(mode=mode, res=res,
                                       ooc_chunk_rows=ooc_chunk_rows))
        report = dict(reports[0])
        report["replica_wall_s"] = [rp["wall_s"] for rp in reports]
        if self._wal is not None and self._snapshot_path is not None:
            self.save(self._snapshot_path)
            report["snapshot"] = self._snapshot_path
        return report

    def warm(self, buckets, ks=(10,), sample=None) -> dict:
        """Warm EVERY replica's delta-ladder program set (failover must
        never cold-compile — a twin that was never picked still has to be
        hot the moment its sibling is fenced)."""
        return {f"r{j}": rep.warm(buckets, ks=ks, sample=sample)
                for j, rep in enumerate(self._replicas)}

    def save(self, path: str) -> None:
        """Atomic group snapshot: the primary twin's full state stamped
        with the GROUP's WAL seq, then the group log truncates (same
        crash-ordering argument as :func:`raft_tpu.stream.mutable.save`).
        Recovery: ``stream.load(path, wal=...)`` — a degraded-to-one
        restore of every acknowledged write; re-replicate by rebuilding
        the group around the recovered corpus."""
        with self._lock:
            primary = self._primary()
            with primary._lock:
                primary._wal_seq = self._wal_seq
                _mut.save(primary, path)
            if self._wal is not None:
                self._wal.reset()
