"""raft_tpu.stream — mutable index lifecycle (delta memtable, tombstones,
background compaction with warm hot-swap).

The serve layer (PR 3) batches and hot-swaps immutable indexes; this layer
makes the indexes themselves mutable under live traffic — the LSM-style
fresh/sealed split of FreshDiskANN (Singh et al. 2021; PAPERS.md):

- :class:`MutableIndex` — wraps any sealed index (brute-force / IVF-Flat /
  IVF-PQ / CAGRA, float and byte dtypes): upserts land in a fixed-capacity
  **delta memtable** scanned by the exact fused-kNN at power-of-two bucket
  shapes; deletes flip **tombstone bitsets** applied through
  ``sample_filter=`` on the sealed side and the scan mask on the delta
  side; ``search()`` merges both through the existing ``select_k``
  dispatch. Read-your-writes: a write is visible to the next search.
- :class:`Compactor` — watermark-triggered (delta fill / tombstone ratio /
  age) background folds: ``extend`` for IVF kinds, full rebuild to reclaim
  tombstones, atomically swapped and republished through
  :class:`raft_tpu.serve.IndexRegistry` so the serving hot path never sees
  a cold program and in-flight leases drain on the old epoch.
- :func:`save`/:func:`load` — the full mutable state (sealed + delta +
  tombstones + id map) as one ``stream`` file section (raft_tpu/10),
  written ATOMICALLY (temp file + rename — a crash mid-save keeps the
  previous snapshot) and stamped with the WAL sequence it covers;
  ``load(wal=)`` replays acknowledged writes past the snapshot — the
  crash-recovery path.
- :class:`~raft_tpu.stream.wal.WriteAheadLog` — append-only checksummed
  log of every upsert/delete, written at admission BEFORE the memtable
  (``MutableIndex(wal=)``), fsync-batched, truncated at each snapshot.
  A killed process loses no acknowledged write.
- :class:`ReplicatedShard` — R device-pinned MutableIndex twins behind
  one surface: writes apply to all live twins (whole-or-nothing
  admission), reads fan to ONE picked by health + latency EWMA with
  same-call failover, and a failed/slow twin is fenced by a
  consecutive-strike circuit breaker with doubling-backoff re-probes
  (:class:`FencingPolicy`). One dead replica = degraded capacity, never
  a failed query (:class:`~raft_tpu.serve.errors.ReplicaUnavailableError`
  only when EVERY twin is out).
- :class:`TieredStore` / :class:`TierPolicy` — beyond-HBM storage
  (``MutableIndex(storage="tiered")``): PQ codes + coarse structures stay
  in HBM while full-precision refine rows live in host RAM (or an mmap'd
  on-disk file), crossing to the device as double-buffered per-batch
  gathers for ``search_refined``'s exact-refine epilogue and the chunked
  exact oracle. Placement is decided against
  ``Resources.memory_budget_bytes`` (budget-pressure spill, hit-rate
  promote), visible at ``/debug/mem`` + ``raft_tpu_tier_*``.
- :class:`ShardedMutableIndex` — the same lifecycle scatter-gathered
  across a mesh: S device-pinned shards with hash-routed writes
  (:func:`shard_of`), one ``select_k`` merge over every shard's
  sealed+delta candidates, and STAGGERED per-shard compaction (one shard
  folded per Compactor cycle — no global stop-the-world). Serve, canary
  and request tracing resolve it duck-typed; ``replicas=R`` makes every
  shard a :class:`ReplicatedShard` with device anti-affinity;
  ``wal_dir=`` arms MESH-WIDE durability (one WAL per shard group +
  atomic per-shard snapshots + a topology manifest, recovered whole by
  ``ShardedMutableIndex.load``); ``reshard(n)`` splits/merges the
  topology ONLINE by power-of-two steps through the same fold-and-swap
  machinery compaction uses — warm-before-flip, leases draining on the
  old topology, mid-migration writes carried over at the atomic swap,
  the manifest rename as the durable commit point.

Worked example + consistency model: docs/streaming.md (durability &
replication rules under "Durability & replication"). Metrics
(``raft_tpu_stream_*``, ``raft_tpu_wal_*``, ``raft_tpu_replica_*``):
docs/observability.md. The serve write path
(`SearchService.upsert/delete`) routes here: docs/serving.md. Fault
points for the failover/replay suites: :mod:`raft_tpu.testing.faults`.
"""

from . import compactor, mutable, replicated, sharded, tiered, wal
from .compactor import CompactionPolicy, Compactor
from .mutable import (DELTA_MIN_BUCKET, DeltaFullError, MutableIndex,
                      delta_buckets, load, save)
from .replicated import FencingPolicy, ReplicatedShard
from .sharded import ShardedMutableIndex, shard_of
from .tiered import TieredStore, TierPolicy
from .wal import WalCorruptError, WriteAheadLog

__all__ = [
    "mutable", "compactor", "sharded", "replicated", "tiered", "wal",
    "MutableIndex", "DeltaFullError", "DELTA_MIN_BUCKET", "delta_buckets",
    "ShardedMutableIndex", "shard_of",
    "ReplicatedShard", "FencingPolicy",
    "TieredStore", "TierPolicy",
    "WriteAheadLog", "WalCorruptError",
    "Compactor", "CompactionPolicy",
    "save", "load",
]
