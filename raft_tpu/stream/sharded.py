"""ShardedMutableIndex: the mutable serve+stream lifecycle across a mesh.

Everything :class:`~raft_tpu.stream.MutableIndex` proved on one device —
delta memtable, tombstone bitsets, warm compaction swaps — composed S ways
into the production serving topology the distributed pieces already
justify: ``parallel/knn`` reproduces the reference's knn_merge_parts
contract (all_gather + select_k over per-shard candidates,
detail/knn_merge_parts.cuh), PR 3/6 measured shard-local graphs at zero
recall cost, and the FreshDiskANN lineage's fresh/sealed split shards
cleanly when compaction is staggered per shard. Three moving parts:

- **Hash-routed writes.** Every global id owns exactly one home shard
  (:func:`shard_of`, a stable SplitMix-style mix — independent of shard
  history, so a restart routes identically). Each shard is a full
  :class:`MutableIndex`: its own delta memtable, tombstone bitset, id map
  (``ids=`` carries the global ids, so shard-local sealed builds stay
  dense while results surface global ids) and — when a mesh is given —
  its own pinned device, which is what makes the scatter real: jax runs
  every per-shard program on the device its committed arrays live on.
- **Scatter-gather search.** A query batch fans to all shards (the
  per-shard scans dispatch WITHOUT materializing — jax's async dispatch
  overlaps them across devices), each shard contributes its sealed(k) and
  delta(≤k) candidate sets with global ids, and ALL ``2S`` parts merge
  through ONE ``select_k`` dispatch — the ``parallel/knn`` merge
  generalized to mixed sealed+delta parts. Candidates ride the
  interconnect; raw rows never do. Delta parts are padded to width k
  with the shared ``-1 / ±inf`` sentinel so the merge program is keyed on
  ``(m, 2S·k)`` alone — per-shard delta growth can never mint a new merge
  shape, which is what keeps the warmed ladder finite.
- **Staggered compaction.** :meth:`compact` folds ONE shard per call —
  the most-due one — through that shard's ordinary fold+swap; the other
  S−1 shards keep serving their current epochs untouched. A
  :class:`~raft_tpu.stream.Compactor` drives it unchanged (``stats()``
  reports the BINDING shard's watermarks: max fill, max tombstone ratio,
  oldest delta), so one ``run_once`` = one shard folded + one warm
  republish through the serve registry — there is never a global
  stop-the-world, and the publish warm covers the successor epoch's
  program set exactly like the single-device churn rows.

Serve integration is duck-typed end to end: ``serve.publish`` /
``make_searcher`` resolve this class exactly like a ``MutableIndex``
(``upsert``/``searcher`` attributes open the write path),
:meth:`exact_search` composes the shard-local exact scans through the same
one-dispatch merge so ``obs.quality.exact_oracle`` — and therefore the
RecallCanary and SLOTracker — work unchanged over the mesh, and
``obs.requestlog`` spans are prefixed ``stream/shard<i>/`` so a traced
flush attributes tail latency to the straggler shard.

Consistency: per-shard reads/writes keep MutableIndex's guarantees
(read-your-writes, kill-then-reveal upserts); a cross-shard search
snapshots each shard's state independently, so a multi-row write that
spans shards may be half-visible to one racing read — the same anomaly
class as any read racing a write, documented in docs/streaming.md
("Sharded lifecycle").

Two late additions complete the availability axis (docs/streaming.md
"Elastic resharding" / "Durability & replication"):

- **Elastic resharding** (:meth:`ShardedMutableIndex.reshard`): online
  power-of-two split/merge. Because :func:`shard_of` routes by ``h % S``,
  doubling to ``2S`` sends every id homed on shard ``s`` to exactly ``s``
  or ``s + S`` — a split is a LOCAL fold of one donor shard into two
  successors (a merge the inverse), replayed shard-at-a-time through the
  same fold machinery compaction uses: donors keep serving (and accepting
  writes) while successors build off-lock, the new topology's whole
  program set warms BEFORE the flip (through the registry's pre-flip
  ``publish(warm_hook=)`` seam when a publisher drives it), writes that
  landed mid-migration carry over at the atomic id→shard-map swap exactly
  like compaction's mid-fold writes, and in-flight flushes finish on the
  topology they leased (retire-after-drain generalizes to whole donor
  shards).
- **Mesh-wide durability** (``wal_dir=``): one
  :class:`~raft_tpu.stream.wal.WriteAheadLog` per shard group, a
  per-shard atomic snapshot, and a topology MANIFEST (shard count,
  topology epoch, per-shard wal_seq) written through
  ``core.serialize.atomic_write`` — the manifest's rename is the durable
  commit point of both :meth:`ShardedMutableIndex.save` and a reshard, so
  recovery (:meth:`ShardedMutableIndex.load`) replays each shard's log
  against whichever topology the manifest committed. A crash between a
  successor swap and the manifest write recovers to the OLD topology with
  zero acknowledged-write loss (fault points ``reshard/split``,
  ``reshard/flip``, ``reshard/manifest``).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
import time
from typing import Callable, Sequence

import numpy as np

from ..core.errors import expects
from ..core.resources import default_resources
from ..obs import dispatch as obs_dispatch
from ..obs import events as obs_events
from ..obs import mem as obs_mem
from ..obs import metrics
from ..testing import faults
from . import mutable as _mut
from .mutable import DeltaFullError, MutableIndex
from .replicated import FencingPolicy, ReplicatedShard, _PinnedGroup

__all__ = ["ShardedMutableIndex", "shard_of"]

# the topology manifest's file name inside a mesh's wal_dir/save dir
_MANIFEST = "manifest"


# -- the one-dispatch merge --------------------------------------------------

@functools.cache
def _shard_jits():
    import jax
    import jax.numpy as jnp

    from ..matrix.select_k import _select_k

    @functools.partial(jax.jit, static_argnames=("k", "select_min"))
    def pad(d, i, k: int, select_min: bool):
        # widen a (m, kd<k) candidate set to width k with the shared
        # underfill sentinel (id -1 at ±inf): appended AFTER the real
        # candidates, so a stable select keeps the unpadded ordering —
        # the 1-shard bit-parity with MutableIndex's own merge rides on it
        m, kd = d.shape
        fill = jnp.inf if select_min else -jnp.inf
        return (jnp.concatenate([d, jnp.full((m, k - kd), fill, d.dtype)], 1),
                jnp.concatenate([i, jnp.full((m, k - kd), -1, i.dtype)], 1))

    @functools.partial(jax.jit, static_argnames=("k", "select_min"))
    def merge(ds: tuple, is_: tuple, k: int, select_min: bool):
        # the knn_merge_parts contract over 2S mixed sealed+delta parts,
        # every part pre-padded to width k so this program is keyed on
        # (m, 2S·k) alone — ONE _select_k dispatch per (bucket, k)
        d = jnp.concatenate(ds, axis=1)
        i = jnp.concatenate(is_, axis=1)
        dv, iv = _select_k(d, i, k, select_min)
        return dv, jnp.where(jnp.isinf(dv), -1, iv)

    return pad, merge


def _pad_part(d, i, k: int, select_min: bool):
    obs_dispatch.note(1)
    return _shard_jits()[0](d, i, int(k), bool(select_min))


def _serving_scan(st, queries, k, res=None):
    """Per-shard serving scan: sealed width clamps to the shard's sealed
    rows (small shards contribute what they have; the merge pads)."""
    return _mut._scan_state(st, queries, k, res=res,
                            k_sealed=min(int(k), st.id_map.shape[0]))


def _view_scan(view, queries, k, res=None):
    """Per-shard scan over a pinned view: a plain shard's state runs the
    single-replica scan; a replica group's pinned view routes through its
    health-picked twin with same-flush failover."""
    if isinstance(view, _PinnedGroup):
        return view.scan_serving(queries, k, res=res)
    return _serving_scan(view, queries, k, res=res)


def _merge_parts(ds, is_, k: int, select_min: bool):
    obs_dispatch.note(1)
    return _shard_jits()[1](tuple(ds), tuple(is_), int(k), bool(select_min))


def _resident_on(x, device) -> bool:
    """Whether a candidate part already lives (committed) on ``device`` —
    the skip test of the fused gather. Anything that cannot prove
    residency moves (moving is always correct; skipping is the
    optimization)."""
    try:
        devs = x.devices()
        return len(devs) == 1 and next(iter(devs)) == device
    except Exception:  # non-jax arrays (host numpy parts) always move
        return False


def _gather_parts(parts_d, parts_i, device):
    """The one merge-device gather, shared by the serving scatter-gather
    and the warm ladder: move candidate parts onto ``device`` for the
    single cross-shard ``_select_k`` merge, SKIPPING parts already
    resident there (shard 0's candidates live on the merge device — the
    old per-call ``device_put`` of every part re-dispatched 4S transfers
    per flush, S of them no-ops) and batching the movers into ONE
    ``device_put`` call. Returns ``(parts_d, parts_i, moved)`` where
    ``moved`` counts the arrays that actually crossed devices."""
    if device is None:
        return parts_d, parts_i, 0
    import jax

    arrays = list(parts_d) + list(parts_i)
    move = [j for j, a in enumerate(arrays) if not _resident_on(a, device)]
    if move:
        placed = jax.device_put(tuple(arrays[j] for j in move), device)
        for j, a in zip(move, placed):
            arrays[j] = a
        obs_dispatch.note(len(move))
    s = len(parts_d)
    return arrays[:s], arrays[s:], len(move)


@functools.lru_cache(maxsize=None)
def _g_shards():
    return metrics.gauge(
        "raft_tpu_stream_shards",
        "shard count of a sharded mutable index (per-shard series report "
        "under name/shard<i>)")


@functools.lru_cache(maxsize=None)
def _c_migrations():
    return metrics.counter(
        "raft_tpu_reshard_migrations_total",
        "reshard migrations by action (split/merge) and phase "
        "(started/completed) — started without completed is an aborted "
        "or crashed migration, which recovery resolves to the old "
        "topology")


@functools.lru_cache(maxsize=None)
def _c_rows_moved():
    return metrics.counter(
        "raft_tpu_reshard_rows_moved_total",
        "live rows folded from donor shards into reshard successors",
        unit="rows")


@functools.lru_cache(maxsize=None)
def _h_reshard():
    return metrics.histogram(
        "raft_tpu_reshard_seconds",
        "one reshard step's wall seconds (fold + warm + carry-over + "
        "flip + manifest, off the serving hot path)", unit="seconds")


def shard_of(ids, n_shards: int):
    """Stable home shard of each global id: a SplitMix64-style avalanche
    mix mod the shard count — independent of insertion order or shard
    state, so routing is reproducible across processes and restarts
    (the contract a router in front of a real fleet would share)."""
    h = np.asarray(ids, np.uint64)
    h = (h + np.uint64(0x9E3779B97F4A7C15))
    h ^= h >> np.uint64(30)
    h *= np.uint64(0xBF58476D1CE4E5B9)
    h ^= h >> np.uint64(27)
    h *= np.uint64(0x94D049BB133111EB)
    h ^= h >> np.uint64(31)
    return (h % np.uint64(n_shards)).astype(np.int64)


class ShardedMutableIndex:
    """Mesh-wide mutable index (see module docstring).

    ``dataset`` (n, d) rows are routed to ``n_shards`` home shards by
    :func:`shard_of` over their global ids (``ids=``, default the dense
    row range) and each shard's sealed index is built by ``build`` — any
    ``fn(rows) -> sealed index`` (size per-shard knobs like ``n_lists`` /
    ``n_probes`` / ``itopk`` for rows/S shards, see docs/using_comms.md
    "Serving-tier sizing"). Every shard must own at least one row.

    ``devices`` pins shard ``s`` to ``devices[s]`` (pass ``comms=`` to take
    the mesh's devices) — candidates then gather onto ``devices[0]`` for
    the merge; without a pin everything stays on the default device and
    only the search-composition semantics remain (the 1-shard twin of a
    plain MutableIndex, bit-equal by the parity suite).

    ``search_params`` / ``index_params`` / ``builder`` / ``delta_capacity``
    (per shard) / ``retain_vectors`` / ``clock`` forward to every shard's
    :class:`MutableIndex`. The retained row store defaults ON (the
    constructor holds each shard's rows anyway), so rebuild compaction,
    :meth:`exact_search` AND :meth:`reshard` work out of the box; pass
    ``retain_vectors=False`` to drop it.

    ``wal_dir`` arms mesh-wide durability: one write-ahead log per shard
    group (``<wal_dir>/shard<i>.e<epoch>.wal``, logging every acknowledged
    write at admission), per-shard atomic snapshots, and the topology
    manifest — written at construction, so the mesh is recoverable
    (:meth:`load`) from its very first acknowledged write. Per-shard WAL
    truncation saw-tooths with each shard's compaction fold (the shards'
    ``snapshot_path`` is armed automatically), and a :meth:`reshard`
    commits durably through the manifest. The directory must be fresh or
    belong to this mesh's previous life recovered via :meth:`load` — a
    directory holding unrecovered records is refused (shadowing them
    would lose acknowledged writes).
    """

    def __init__(self, dataset, *, n_shards: int, build: Callable,
                 ids=None, search_params=None, index_params=None,
                 builder: Callable | None = None,
                 delta_capacity: int = 1024,
                 retain_vectors: bool | None = None,
                 devices: Sequence | None = None, comms=None,
                 replicas: int = 1,
                 fencing: FencingPolicy | None = None,
                 wal_dir: str | None = None,
                 name: str = "default",
                 storage: str = "hbm", tier=None,
                 clock: Callable[[], float] = time.monotonic):
        from ..core import chunked

        # a ChunkedReader corpus (the out-of-core build path) shards
        # WITHOUT a whole-corpus RAM copy: each shard gathers only its
        # own rows off the reader (memmap pages fault per shard)
        stream = chunked.is_reader(dataset)
        if not stream:
            dataset = np.asarray(dataset)
        expects(dataset.ndim == 2, "dataset must be (rows, d)")
        n = int(dataset.shape[0])
        n_shards = int(n_shards)
        expects(n_shards >= 1, "n_shards must be >= 1, got %d", n_shards)
        if ids is None:
            gids = np.arange(n, dtype=np.int64)
        else:
            gids = np.asarray(ids, np.int64).reshape(-1)
            expects(gids.shape == (n,), "ids= must match dataset rows (%d)", n)
        if comms is not None:
            expects(devices is None, "pass devices= or comms=, not both")
            devices = list(comms.mesh.devices.flat)
        if devices is not None:
            devices = list(devices)
            expects(len(devices) >= n_shards,
                    "%d shards need %d devices, got %d", n_shards, n_shards,
                    len(devices))
        owner = shard_of(gids, n_shards)
        self._name = name
        self._clock = clock  # Compactor inherits it (one age time base)
        self._lock = threading.RLock()
        self._compact_lock = threading.Lock()
        R = int(replicas)
        expects(R >= 1, "replicas must be >= 1, got %d", R)
        if R > 1 and devices is not None:
            # twins of one shard land on devices[(s*R + j) % D]: j1 and j2
            # collide iff D divides j1-j2, i.e. iff D < R — and co-located
            # twins silently void the device anti-affinity the replica
            # groups promise (pass devices=None for unpinned twins)
            expects(len(devices) >= R,
                    "replica anti-affinity needs >= %d devices so twins "
                    "of one shard land on different devices, got %d",
                    R, len(devices))
        # the shard build recipe, retained whole: reshard successors are
        # built with EXACTLY what the originals were
        self._build_fn = build
        self._search_params = search_params
        self._index_params = index_params
        self._builder = builder
        self._delta_capacity = int(delta_capacity)
        self._retain_vectors = retain_vectors
        # the beyond-HBM policy, per shard: every shard's MutableIndex gets
        # its own TieredStore, so mesh capacity = shards x (HBM + host)
        self._storage = storage
        self._tier = tier
        self._devices = devices
        self._replicas_n = R
        self._fencing = fencing
        self._topology_epoch = 0
        self._migration: dict | None = None
        self._wal_dir = os.fspath(wal_dir) if wal_dir is not None else None
        if self._wal_dir is not None:
            os.makedirs(self._wal_dir, exist_ok=True)
            # a directory with a committed manifest belongs to an earlier
            # life of a mesh — possibly at a DIFFERENT topology epoch, so
            # the per-shard WAL probe below would miss its files entirely
            # and the construction-time save() would orphan every
            # acknowledged write behind a fresh epoch-0 manifest
            expects(not os.path.exists(
                os.path.join(self._wal_dir, _MANIFEST)),
                "wal_dir %r already holds a mesh manifest — recover that "
                "mesh with ShardedMutableIndex.load() (a fresh mesh here "
                "would shadow its acknowledged writes) or point at a "
                "fresh directory", self._wal_dir)
        self._shards: list = []
        for s in range(n_shards):
            rows_idx = np.nonzero(owner == s)[0]
            expects(len(rows_idx) > 0,
                    "shard %d of %d owns no rows (n=%d) — use fewer shards",
                    s, n_shards, n)
            wal_path = snap_path = None
            if self._wal_dir is not None:
                snap_path, wal_path = self._shard_files(s)
            rows_s = (dataset.take(rows_idx) if stream
                      else dataset[rows_idx])
            self._shards.append(self._make_shard(
                rows_s, gids[rows_idx], s, n_shards,
                wal=wal_path, snapshot_path=snap_path))
        self._next_id = int(gids.max()) + 1 if n else 0
        self._finish_init()
        if self._wal_dir is not None:
            # durable by construction: the baseline snapshots + manifest
            # land before the first write can be acknowledged, so load()
            # works from the very first WAL record
            self.save()

    @staticmethod
    def _shard_names(s: int, e: int) -> tuple:
        """(snapshot, wal) FILE NAMES of shard ``s`` at topology epoch
        ``e`` — the one place the naming scheme lives: construction,
        save(), the manifest and the reshard commit all derive from here,
        so the manifest can never desynchronize from the files on disk."""
        return f"shard{s}.e{e}.idx", f"shard{s}.e{e}.wal"

    def _shard_files(self, s: int, epoch: int | None = None,
                     dir: str | None = None) -> tuple:
        """(snapshot, wal) paths of shard ``s`` at a topology epoch —
        epoch-keyed so a mid-reshard crash can never confuse the old
        topology's files with a half-written successor set."""
        e = self._topology_epoch if epoch is None else int(epoch)
        sn, wn = self._shard_names(s, e)
        d = self._wal_dir if dir is None else dir
        return os.path.join(d, sn), os.path.join(d, wn)

    def _make_shard(self, rows_s, gids_s, s: int, total: int, *,
                    wal=None, snapshot_path=None):
        """Build one home shard at ordinal ``s`` of a ``total``-shard
        topology — the ONE recipe shared by construction and resharding.
        Past the construction-time device floor, ordinals pin modulo the
        device list (a split beyond the mesh size co-locates successors,
        trading isolation for capacity — documented in streaming.md)."""
        sealed = self._build_fn(rows_s)
        devices = self._devices
        if self._replicas_n == 1:
            return MutableIndex(
                sealed, search_params=self._search_params,
                index_params=self._index_params,
                delta_capacity=self._delta_capacity,
                # the constructor holds the shard's raw rows either way,
                # so retention costs no extra recover pass; False opts out
                retain_vectors=self._retain_vectors,
                dataset=(None if self._retain_vectors is False else rows_s),
                builder=self._builder, ids=gids_s,
                device=(devices[s % len(devices)] if devices is not None
                        else None),
                wal=wal, snapshot_path=snapshot_path,
                storage=self._storage, tier=self._tier,
                name=f"{self._name}/shard{s}", shard=s, clock=self._clock)
        # replica j of shard s lands on devices[s*R + j] (mod the mesh):
        # twins of one shard live on DIFFERENT devices — the anti-affinity
        # that makes a group survive a device
        R = self._replicas_n
        return ReplicatedShard(
            sealed, n_replicas=R,
            devices=([devices[(s * R + j) % len(devices)]
                      for j in range(R)] if devices is not None else None),
            search_params=self._search_params,
            index_params=self._index_params,
            delta_capacity=self._delta_capacity,
            retain_vectors=self._retain_vectors,
            dataset=(None if self._retain_vectors is False else rows_s),
            builder=self._builder, ids=gids_s,
            policy=self._fencing or FencingPolicy(),
            wal=wal, snapshot_path=snapshot_path,
            storage=self._storage, tier=self._tier,
            name=f"{self._name}/shard{s}", shard=s, clock=self._clock)

    def _finish_init(self) -> None:
        """Shared tail of ``__init__`` and :meth:`load`: cross-shard
        config consistency, merge-device pin, gauge baseline."""
        cfg0 = self._shards[0]._cfg
        for s, sh in enumerate(self._shards[1:], 1):
            expects(sh._cfg.kind == cfg0.kind and sh._cfg.dim == cfg0.dim
                    and sh._cfg.query_dtype == cfg0.query_dtype,
                    "shard %d built a (%s, %d, %s) index but shard 0 is "
                    "(%s, %d, %s) — build must be deterministic in kind",
                    s, sh._cfg.kind, sh._cfg.dim, sh._cfg.query_dtype,
                    cfg0.kind, cfg0.dim, cfg0.query_dtype)
        self._select_min = cfg0.select_min
        self._merge_device = (self._devices[0]
                              if self._devices is not None else None)
        self._update_gauges()

    # -- introspection ------------------------------------------------------
    @property
    def kind(self) -> str:
        return self._shards[0].kind

    @property
    def dim(self) -> int:
        return self._shards[0].dim

    @property
    def name(self) -> str:
        return self._name

    @property
    def query_dtype(self) -> str:
        return self._shards[0].query_dtype

    @property
    def n_shards(self) -> int:
        return len(self._shards)

    @property
    def shards(self) -> tuple:
        """The per-shard :class:`MutableIndex` objects (read-only tuple —
        write through the sharded surface so routing stays consistent)."""
        return tuple(self._shards)

    @property
    def can_rebuild(self) -> bool:
        return all(sh.can_rebuild for sh in self._shards)

    @property
    def size(self) -> int:
        return sum(sh.size for sh in self._shards)

    def stats(self) -> dict:
        """Aggregated view + ``per_shard`` detail. The scalar watermarks a
        :class:`~raft_tpu.stream.Compactor` reads are the BINDING shard's:
        ``delta_fill`` / ``tombstone_ratio`` are maxima (the shard that
        will hit the wall first) and ``delta_oldest_at`` the minimum (the
        stalest write) — so an aggregate watermark trips exactly when some
        shard needs a fold, and :meth:`compact` folds that shard."""
        per = [sh.stats() for sh in self._shards]
        oldest = [p["delta_oldest_at"] for p in per
                  if p["delta_oldest_at"] is not None]
        return {
            "live": sum(p["live"] for p in per),
            "sealed_rows": sum(p["sealed_rows"] for p in per),
            "sealed_dead": sum(p["sealed_dead"] for p in per),
            "tombstone_ratio": max(p["tombstone_ratio"] for p in per),
            "delta_rows": sum(p["delta_rows"] for p in per),
            "delta_fill": max(p["delta_fill"] for p in per),
            "delta_oldest_at": min(oldest) if oldest else None,
            "epoch": sum(p["epoch"] for p in per),
            "shards": len(per),
            "per_shard": per,
            # replica-group detail (replicas=1: every shard is its own
            # single healthy "replica"): healthy is the WORST shard's
            # pickable-twin count — the availability binding constraint
            **({"replicas": sum(p.get("replicas", 1) for p in per),
                "healthy": min(p.get("healthy", 1) for p in per),
                "stale": sum(p.get("stale", 0) for p in per)}
               if any("replicas" in p for p in per) else {}),
        }

    def health(self) -> dict:
        """Per-shard replica-group health for ``/healthz``
        (``obs.start_http_exporter(replicas=...)``): each group's breaker
        detail plus the mesh verdict — a shard with ZERO pickable twins
        means queries to it fail, which is an outage, not degradation."""
        shards = [sh.health() if isinstance(sh, ReplicatedShard)
                  else {"name": sh.name, "replicas": [], "healthy": 1}
                  for sh in self._shards]
        with self._lock:
            migration = (dict(self._migration)
                         if self._migration is not None else None)
        return {"name": self._name, "shards": shards,
                "healthy_min": min(s["healthy"] for s in shards),
                # live topology-migration state (None outside a reshard):
                # folds into /healthz via obs.start_http_exporter(replicas=)
                "reshard": migration}

    def _update_gauges(self, st: dict | None = None) -> None:
        if not metrics._enabled:
            return
        st = self.stats() if st is None else st
        name = self._name
        _g_shards().set(st["shards"], name=name)
        # the aggregate rides the same stream gauges under the parent name
        # (per-shard series report under name/shard<i> already)
        _mut._g_delta_fill().set(st["delta_fill"], name=name)
        _mut._g_delta_rows().set(st["delta_rows"], name=name)
        _mut._g_tombstone().set(st["tombstone_ratio"], name=name)

    def _drift_store(self):
        """Cross-shard corpus sample for the drift detector: an interleave
        of every shard's retained rows (bounded — the classifier subsamples
        downstream anyway); None when any shard dropped its store."""
        stores = [sh._drift_store() for sh in self._shards]
        if any(s is None for s in stores):
            return None
        cap = max(4096 // len(stores), 256)
        return np.concatenate([s[:cap] for s in stores])

    # -- writes -------------------------------------------------------------
    def upsert(self, rows, ids=None, res=None):
        """Insert/upsert rows, each routed to its global id's home shard.
        Admission is checked across ALL touched shards BEFORE any row
        lands (writes go through this serialized surface, so the check is
        exact): one full home shard refuses the whole call with
        :class:`~raft_tpu.stream.DeltaFullError`, and the summed device
        growth of every touched shard's delta bucket is checked against
        ``res.memory_budget_bytes`` in the same hoisted pass
        (:class:`~raft_tpu.serve.errors.MemoryBudgetError`) — either way
        nothing is written, the same whole-or-nothing contract as a single
        shard's upsert."""
        # validate ONCE up front (dim + dtype through shard 0's rules): a
        # per-shard refusal after a sibling already accepted its group
        # would break the whole-or-nothing contract
        rows = self._shards[0]._coerce_rows(rows)
        r = rows.shape[0]
        expects(r >= 1, "upsert needs at least one row")
        with self._lock:
            if ids is None:
                gids = np.arange(self._next_id, self._next_id + r,
                                 dtype=np.int64)
            else:
                gids = _mut.check_upsert_ids(ids, r)
            self._next_id = max(self._next_id, int(gids.max()) + 1)
            owner = shard_of(gids, len(self._shards))
            groups = [np.nonzero(owner == s)[0]
                      for s in range(len(self._shards))]
            for s, idx in enumerate(groups):
                sh = self._shards[s]
                # concurrent folds only SHRINK a delta, so a stale read
                # here can only over-refuse, never admit past capacity
                if len(idx) and (sh._delta_rows_now() + len(idx)
                                 > sh.delta_capacity):
                    if metrics._enabled:
                        _mut._c_delta_full().inc(1, name=self._name)
                    raise DeltaFullError(
                        f"shard {s} delta at {sh._delta_rows_now()}"
                        f"/{sh.delta_capacity} rows; upsert routing "
                        f"{len(idx)} there refused — compact() (or attach "
                        "a stream.Compactor) to fold it")
            # memory-budget admission, hoisted like the capacity check: the
            # SUMMED bucket growth across home shards (and, for replica
            # groups, across every live twin) gates before any shard
            # writes (cross-shard whole-or-nothing)
            obs_mem.gate(
                res or default_resources(),
                lambda: sum(
                    self._shards[s]._growth_bytes(len(idx))
                    for s, idx in enumerate(groups) if len(idx)),
                site="upsert", detail=f"stream/sharded {self._name!r}")
            # the hoisted pass IS the admission decision: the per-shard
            # upserts get a budget-free res so their gates cannot refuse
            # mid-write — a stricter ambient default, or concurrent ledger
            # growth between the hoisted admit and shard s's write (another
            # name's publish, an off-lock fold's double-buffer), would
            # otherwise land a partial cross-shard write
            inner = res or default_resources()
            if getattr(inner, "memory_budget_bytes", None) is not None:
                inner = dataclasses.replace(inner, memory_budget_bytes=None)
            for s, idx in enumerate(groups):
                if len(idx):
                    self._shards[s].upsert(rows[idx], ids=gids[idx],
                                           res=inner)
            self._update_gauges()
        return gids

    def delete(self, ids) -> int:
        """Tombstone ids on their home shards; returns how many were live.
        Unknown or already-dead ids are a counted no-op, not an error."""
        arr = np.asarray(ids, np.int64).reshape(-1)
        if arr.size == 0:
            return 0
        with self._lock:
            owner = shard_of(arr, len(self._shards))
            killed = 0
            for s in range(len(self._shards)):
                idx = np.nonzero(owner == s)[0]
                if len(idx):
                    killed += self._shards[s].delete(arr[idx])
            self._update_gauges()
        return killed

    # -- reads --------------------------------------------------------------
    def _scatter_gather(self, states, queries, k: int, scan, res=None):
        """Fan ``queries`` to every shard state (async dispatch — jax
        overlaps the per-shard programs across their pinned devices),
        collect each shard's sealed + delta candidate sets, and merge all
        ``2S`` parts through ONE ``select_k`` dispatch. ``scan`` is the
        per-state scan half (serving: :func:`mutable._scan_state`; oracle:
        the bound ``_exact_scan``). The gather moves ONLY the parts not
        already resident on the merge device, in one ``device_put``
        (:func:`_gather_parts`), and the flush's dispatch count rides the
        obs dispatch meter + the ``stream_moved_parts`` trace note so the
        fusion win is attributable per flush."""
        from ..obs import requestlog

        k = int(k)
        parts_d, parts_i = [], []
        for s, st in enumerate(states):
            with requestlog.prefix(f"stream/shard{s}/"):
                sd, si, dd, di = scan(st, queries, k, res=res)
            for d, i in ((sd, si), (dd, di)):
                if d.shape[1] < k:  # delta buckets (and tiny oracle
                    # stores) can be narrower than k — pad on the shard's
                    # device so the merge shape below is invariant
                    d, i = _pad_part(d, i, k, self._select_min)
                parts_d.append(d)
                parts_i.append(i)
        t0 = time.perf_counter()
        # the gather: ONLY the (m, k) candidate tuples cross devices, and
        # only the non-resident ones move
        parts_d, parts_i, moved = _gather_parts(parts_d, parts_i,
                                                self._merge_device)
        out = _merge_parts(parts_d, parts_i, k, self._select_min)
        requestlog.add_span("stream/merge", time.perf_counter() - t0)
        requestlog.annotate("stream_shards", len(states))
        requestlog.annotate("stream_moved_parts", moved)
        return out

    def search(self, queries, k: int, res=None):
        """Scatter-gather search over every shard's (sealed − tombstones)
        + delta; returns ``(distances (m, k), global ids (m, k))`` with the
        shared ``id -1 / ±inf`` sentinel in slots the live rows cannot
        fill. Identical result contract to :meth:`MutableIndex.search` —
        the 1-shard composition is bit-equal to a plain MutableIndex
        (pinned by the parity suite). A shard smaller than k contributes
        every sealed row it has (``k_sealed`` clamp) and the merge pads.
        With ``replicas > 1`` each shard's scan routes through its replica
        group's health-picked twin, failing over within this same call —
        one fenced replica degrades capacity, never the query."""
        return self._scatter_gather(self._views(), queries, k,
                                    _view_scan, res=res)

    def _views(self) -> tuple:
        """Per-shard read views: a plain shard pins its current state
        epoch; a replica group pins EVERY twin's epoch behind the live
        failover pick (:meth:`ReplicatedShard.pin_group`)."""
        return tuple(sh.pin_group() if isinstance(sh, ReplicatedShard)
                     else sh._state for sh in self._shards)

    def exact_search(self, queries, k: int, res=None):
        """EXACT fused kNN over the whole mesh's live corpus — shard-local
        exact store+delta scans composed through the same one-dispatch
        merge as :meth:`search`, so the RecallCanary's shadow oracle
        (``obs.quality.exact_oracle``) covers the sharded tier unchanged.
        Needs every shard's retained store."""
        shards = tuple(self._shards)

        def scan(sh, q, kk, res=None):
            return sh._exact_scan(q, kk, res=res)

        return self._scatter_gather(shards, queries, k, scan, res=res)

    def search_refined(self, queries, k: int, refine_ratio: int = 4,
                       res=None):
        """Scatter-gather :meth:`MutableIndex.search_refined` over the
        mesh: each shard widens its PQ scan to ``k * refine_ratio``,
        refines against its OWN tiered store (the per-shard host hop —
        mesh refine capacity is shards × host bandwidth), and the
        per-shard refined + delta parts merge through the same one
        ``select_k`` dispatch as :meth:`search`. The 1-shard composition
        is bit-equal to the plain index's ``search_refined`` (parity
        suite)."""
        shards = tuple(self._shards)
        expects(all(not isinstance(sh, ReplicatedShard) for sh in shards),
                "search_refined does not route replica groups yet — "
                "serve replicas=1 shards tiered, or use search()")

        def scan(sh, q, kk, res=None):
            return sh._refined_scan(q, kk, refine_ratio, res=res)

        return self._scatter_gather(shards, queries, k, scan, res=res)

    def refined_searcher(self, refine_ratio: int = 4):
        """Serving hook over :meth:`search_refined` (the
        ``batched_searcher`` contract) — the sharded twin of
        :meth:`MutableIndex.refined_searcher`: every shard's CURRENT
        state epoch is pinned at hook creation (the same lease-drain
        semantics as :meth:`searcher` — a staggered compaction or a
        reshard flip freezes the leased hook's view; republish picks up
        the successor)."""
        from ..neighbors._hooks import make_hook

        shards = tuple(self._shards)
        expects(all(not isinstance(sh, ReplicatedShard) for sh in shards),
                "refined_searcher does not route replica groups yet — "
                "serve replicas=1 shards tiered, or use searcher()")
        pinned = tuple((sh, sh._state) for sh in shards)
        cfg0 = shards[0]._cfg

        def scan(pin, q, kk, res=None):
            sh, st = pin
            return sh._refined_scan(q, kk, refine_ratio, res=res, st=st)

        fn = make_hook(
            lambda queries, k: self._scatter_gather(pinned, queries, k,
                                                    scan),
            f"stream/sharded/{cfg0.kind}+refine", cfg0.dim, cfg0.data_kind)
        fn.mutable = self
        return fn

    def searcher(self):
        """Serving hook pinned to every shard's CURRENT state epoch (the
        ``batched_searcher`` contract). A staggered compaction freezes only
        the folded shard's epoch inside an already-issued hook; republish
        (what the Compactor does per fold) picks up the successor — the
        same lease-drain semantics as the single-device flow, per shard.
        A reshard generalizes this to whole shards: hooks issued on the
        old topology keep serving the donor shards' frozen views until
        their leases drain."""
        return self._searcher_for(tuple(self._shards))

    def _searcher_for(self, shards):
        """The serving hook over an explicit shard list — what
        :meth:`reshard` publishes for the successor topology BEFORE the
        flip, so the registry's bucket warm compiles the new program set
        while the old topology still serves."""
        from ..neighbors._hooks import make_hook

        states = tuple(sh.pin_group() if isinstance(sh, ReplicatedShard)
                       else sh._state for sh in shards)
        cfg0 = shards[0]._cfg
        fn = make_hook(
            lambda queries, k: self._scatter_gather(
                states, queries, k, _view_scan),
            f"stream/sharded/{cfg0.kind}", cfg0.dim, cfg0.data_kind)
        # marker for the serve write path (SearchService.publish follows it
        # across compaction republishes, exactly like MutableIndex's hook)
        fn.mutable = self
        return fn

    # -- warmup -------------------------------------------------------------
    def warm(self, buckets, ks=(10,), sample=None) -> dict:
        """Compile the sharded delta-ladder program set: every shard's
        exact delta scan at every memtable bucket × (query bucket, k) —
        each ON its pinned device (placement is part of the program key) —
        plus the pad programs and the ONE cross-shard merge at its fixed
        ``(m, 2S·k)`` shape. Sealed-side programs are warmed per epoch by
        ``registry.publish`` (which runs the full hook), exactly like the
        single-device flow. Returns per-(k, bucket) compile attribution."""
        return self._warm_impl(tuple(self._shards), buckets, ks=ks,
                               sample=sample)

    def _warm_impl(self, shards, buckets, ks=(10,), sample=None) -> dict:
        """:meth:`warm` over an explicit shard list — :meth:`reshard`
        warms its successors' ladder (and the successor-count merge)
        through this BEFORE the topology flip."""
        import jax

        from .._warmup import _random_queries
        from ..obs import compile as obs_compile
        from ..neighbors import brute_force

        out: dict = {}
        key = jax.random.key(0)
        for kk in sorted(set(int(x) for x in ks)):
            out[kk] = {}
            for b in sorted(set(int(x) for x in buckets)):
                key, kq = jax.random.split(key)
                q = _random_queries(kq, b, self.dim, self.query_dtype,
                                    sample=sample)
                t0 = time.perf_counter()
                with obs_compile.attribution() as rec:
                    parts_d, parts_i = [], []
                    for sh in shards:
                        # a replica group warms EVERY twin's ladder on its
                        # own pinned device (placement is part of the
                        # program key): failover must never cold-compile —
                        # a twin that was never picked has to be hot the
                        # moment its sibling is fenced. Any twin's parts
                        # feed the merge (the gather re-places them).
                        units = (sh.replicas
                                 if isinstance(sh, ReplicatedShard)
                                 else (sh,))
                        for u in units:
                            cfg = u._cfg
                            dt = _mut._np_dtype(cfg.query_dtype)
                            sd = _mut._dev_put(
                                cfg, np.zeros((b, kk), np.float32))
                            si = _mut._dev_put(
                                cfg, np.full((b, kk), -1, np.int32))
                            dd = di = None
                            for db in u._buckets:
                                dummy = _mut._dev_put(
                                    cfg, np.zeros((db, cfg.dim), dt))
                                keep = _mut._dev_put(
                                    cfg, np.zeros((db,), bool))
                                dd, di = brute_force.knn(
                                    dummy, q, min(kk, db), cfg.metric,
                                    cfg.metric_arg, sample_filter=keep)
                                di = _mut._map_ids(di, _mut._dev_put(
                                    cfg, np.zeros((db,), np.int32)))
                                if dd.shape[1] < kk:  # same pad rule as
                                    # _scatter_gather — per (width, device)
                                    dd, di = _pad_part(dd, di, kk,
                                                       self._select_min)
                                jax.block_until_ready((dd, di))
                        parts_d += [sd, dd]
                        parts_i += [si, di]
                    parts_d, parts_i, _ = _gather_parts(
                        parts_d, parts_i, self._merge_device)
                    jax.block_until_ready(_merge_parts(
                        parts_d, parts_i, kk, self._select_min))
                out[kk][b] = {"wall_s": round(time.perf_counter() - t0, 3),
                              **rec.summary()}
        return out

    # -- compaction ---------------------------------------------------------
    def _pick_shard(self, mode: str, trigger: str | None = None) -> int:
        """The most-due shard for one staggered fold: rebuilds (and
        tombstone trips) chase the highest tombstone ratio, an AGE trip
        chases the stalest non-empty delta — picking the fullest there
        would starve a quiet shard forever while its age watermark stays
        tripped — and everything else chases the fullest delta; ties break
        low."""
        per = [sh.stats() for sh in self._shards]
        if mode == "rebuild" or trigger == "tombstone_ratio":
            ratios = [p["tombstone_ratio"] for p in per]
            if max(ratios) > 0:
                return int(np.argmax(ratios))
        if trigger == "age":
            ages = [(p["delta_oldest_at"], s) for s, p in enumerate(per)
                    if p["delta_oldest_at"] is not None]
            if ages:
                return min(ages)[1]
        return int(np.argmax([p["delta_rows"] for p in per]))

    def compact(self, mode: str = "auto", shard: int | None = None,
                res=None, trigger: str | None = None,
                ooc_chunk_rows: int | None = None) -> dict:
        """Fold ONE shard (the most-due, or an explicit ``shard=``) through
        its ordinary fold+swap — the staggered step: the other shards keep
        serving their epochs untouched, and a Compactor loop folds shard
        after shard while its watermark stays tripped, republishing between
        folds (the Compactor forwards its tripped ``trigger`` so the pick
        chases the right shard). ``ooc_chunk_rows`` forwards to the shard's
        :meth:`MutableIndex.compact` — a rebuild fold then streams the
        shard's live rows through the out-of-core build path instead of
        one device-resident array. Returns the shard's compaction report
        plus ``shard`` and the aggregate ``epoch``."""
        with self._compact_lock:
            if shard is None:
                shard = self._pick_shard(mode, trigger)
            shard = int(shard)
            expects(0 <= shard < len(self._shards),
                    "shard %d out of range (%d shards)", shard,
                    len(self._shards))
            report = self._shards[shard].compact(
                mode=mode, res=res, ooc_chunk_rows=ooc_chunk_rows)
            report["shard"] = shard
            report["shard_epoch"] = report["epoch"]
            agg = self.stats()
            report["epoch"] = agg["epoch"]  # aggregate fold count
            self._update_gauges(agg)
            return report

    # -- elastic resharding --------------------------------------------------
    def reshard(self, n_shards: int, *, publisher=None,
                name: str | None = None, ks=(10,), warm_buckets=None,
                warm_data=None, res=None,
                cause: dict | None = None) -> dict:
        """Online power-of-two split/merge to ``n_shards`` — the topology
        change as a sequence of LOCAL folds, never a stop-the-world.

        Because :func:`shard_of` routes by ``h % S``, doubling sends every
        id homed on shard ``s`` to exactly ``s`` or ``s + S``: each
        doubling (halving) step folds one donor shard (donor pair) at a
        time into its successor(s) — donors keep serving reads AND
        accepting writes throughout — then warms the new topology's whole
        program set, applies the writes that landed mid-migration
        (carry-over, exactly like compaction's mid-fold writes) and flips
        the id→shard map atomically under the write lock. A larger jump
        (e.g. 2 → 8) runs as successive doublings, each individually
        committed.

        ``publisher`` (+ ``name``/``ks``/``warm_data``) threads the flip
        through the registry's pre-flip ``publish(warm_hook=)`` seam: the
        registry warms the successor searcher at every bucket, the commit
        runs as the LAST pre-flip hook, and only then does the registry
        pointer move — serving traffic never sees a cold program or a
        half-migrated mesh, and in-flight flushes finish on the topology
        they leased (publish with the same ``ks`` the name already
        serves). Without a publisher, ``warm_buckets`` drives the
        library-mode warm (successor delta ladders + sealed scans + the
        new merge) before the flip.

        With ``wal_dir`` durability armed, each successor gets an atomic
        baseline snapshot + fresh WAL BEFORE the flip, carry-over writes
        land in the successor logs, and the topology manifest's atomic
        rename is the durable commit point: a crash at any fault point
        (``reshard/split``/``reshard/flip``/``reshard/manifest``)
        recovers via :meth:`load` to the OLD topology with zero
        acknowledged-write loss — no write resurrected either, since
        uncommitted successor files are ignored and removed.

        Returns ``{from, to, steps, rows_moved, epoch, wall_s}``. Raises
        (mesh untouched, donors still serving) on a non-power-of-two
        ratio, a successor that would own zero rows, or a shard without
        its retained row store.

        ``cause`` (a small dict — e.g. the controller's trigger/decision
        journal seqs) rides the ``reshard_started`` / ``reshard_committed``
        / ``reshard_aborted`` evidence verbatim, so an automated topology
        change stays causally chained to the sensor event that advised
        it."""
        target = int(n_shards)
        S = len(self._shards)
        expects(target >= 1, "n_shards must be >= 1, got %d", target)
        expects(target != S, "mesh is already at %d shards", S)
        big, small = max(target, S), min(target, S)
        ratio = big // small
        expects(big % small == 0 and (ratio & (ratio - 1)) == 0,
                "reshard moves between power-of-two-related shard counts "
                "(%d -> %d is not): shard_of routes by h %% S, so only a "
                "doubling/halving keeps every id's migration local to one "
                "donor group", S, target)
        expects(self._build_fn is not None,
                "reshard needs the shard build recipe — construct with "
                "build=, or pass build= to load()")
        expects(publisher is None or hasattr(publisher, "publish"),
                "publisher must expose publish() (SearchService or "
                "IndexRegistry)")
        expects(publisher is None or name is not None,
                "a publisher needs the published name")
        kks = (ks,) if isinstance(ks, int) else tuple(int(x) for x in ks)
        t0 = time.perf_counter()
        steps = []
        while len(self._shards) != target:
            nxt = (len(self._shards) * 2 if target > len(self._shards)
                   else len(self._shards) // 2)
            steps.append(self._reshard_step(
                nxt, publisher=publisher, name=name, ks=kks,
                warm_buckets=warm_buckets, warm_data=warm_data, res=res,
                cause=cause))
        return {"from": S, "to": target, "steps": steps,
                "rows_moved": sum(st["rows_moved"] for st in steps),
                "epoch": self._topology_epoch,
                "wall_s": round(time.perf_counter() - t0, 3)}

    def _reshard_step(self, target: int, *, publisher, name, ks,
                      warm_buckets, warm_data, res, cause=None) -> dict:
        """One doubling/halving: fold donors shard-at-a-time, warm, then
        commit (carry-over + flip + manifest). Holds the compaction lock
        for the whole step — a staggered fold and a migration must not
        interleave (both rebuild shard state); writes and reads are only
        ever blocked for the brief snapshot/commit critical sections."""
        with self._compact_lock:
            S = len(self._shards)
            action = "split" if target > S else "merge"
            if metrics._enabled:
                _c_migrations().inc(1, name=self._name, action=action,
                                    phase="started")
            obs_events.emit(
                "reshard_started",
                subject=("reshard", self._name, None,
                         self._topology_epoch),
                evidence={"action": action, "from": S, "to": target,
                          **({"cause": dict(cause)} if cause else {})})
            t0 = time.perf_counter()
            with self._lock:
                self._migration = {"action": action, "from": S,
                                   "to": target, "folded_donors": 0,
                                   "rows_moved": 0}
            try:
                # split: donor s feeds successors (s, s+S); merge: donors
                # (t, t+T) feed successor t — h % S and h % target agree
                # exactly on these groups (the power-of-two locality rule)
                donor_groups = ([((s,), (s, s + S)) for s in range(S)]
                                if action == "split"
                                else [((t, t + target), (t,))
                                      for t in range(target)])
                successors: list = [None] * target
                snaps: list = []
                rows_moved = 0
                for donors_idx, succ_idx in donor_groups:
                    faults.fire("reshard/split", name=self._name,
                                donors=donors_idx, action=action)
                    rows_parts, gid_parts = [], []
                    for di in donors_idx:
                        donor = self._shards[di]
                        prim = (donor._primary()
                                if isinstance(donor, ReplicatedShard)
                                else donor)
                        with self._lock:
                            # brief freeze: snapshot the donor's live rows
                            # (sealed survivors + live delta prefix) — the
                            # fold input; everything after this point
                            # carries over at the commit
                            st = prim._state
                            expects(st.store is not None,
                                    "reshard folds raw rows into successor "
                                    "builds — shard %d has no retained row "
                                    "store (retain_vectors=False)", di)
                            snap_n = int(st.delta_n)
                            s_live = np.nonzero(st.sealed_alive)[0]
                            d_live = np.nonzero(
                                st.delta_alive[:snap_n])[0]
                            rows = np.concatenate(
                                [_mut._store_rows(st.store)[s_live],
                                 st.delta[d_live]])
                            gids = np.concatenate(
                                [st.id_map[s_live],
                                 st.delta_ids[d_live].astype(np.int64)])
                            # tombstone watermarks at the snapshot: a
                            # delete (or replacing upsert) of a snapshot-
                            # live id must flip one of these, so the
                            # commit can SKIP its dead-id scan whenever
                            # they are unchanged — the common case
                            dead0 = (int(st.sealed_dead_n),
                                     snap_n - len(d_live))
                        rows_parts.append(rows)
                        gid_parts.append(gids)
                        # the DONOR rides to the commit (not the twin the
                        # fold read): a replicated donor's primary can go
                        # stale mid-migration, and the commit must read
                        # carry-over state from a twin that received
                        # every acknowledged write
                        snaps.append((donor, snap_n, gids, dead0))
                    rows = (np.concatenate(rows_parts)
                            if len(rows_parts) > 1 else rows_parts[0])
                    gids = (np.concatenate(gid_parts)
                            if len(gid_parts) > 1 else gid_parts[0])
                    owner = shard_of(gids, target)
                    for t in succ_idx:
                        mask = owner == t
                        expects(int(mask.sum()) > 0,
                                "successor shard %d of %d would own no "
                                "live rows — the corpus is too small for "
                                "this split", t, target)
                        # the heavy build runs OFF every lock: donors keep
                        # serving and accepting writes
                        successors[t] = self._make_shard(
                            rows[mask], gids[mask], t, target)
                    rows_moved += int(len(gids))
                    with self._lock:
                        self._migration["folded_donors"] += len(donors_idx)
                        self._migration["rows_moved"] = rows_moved
                succ = tuple(successors)
                # warm BEFORE any flip: successor delta ladders + pads +
                # the one (bucket, 2·target·k) merge, each on its device
                if warm_buckets:
                    self._warm_impl(succ, warm_buckets, ks=ks,
                                    sample=warm_data)
                step: dict = {"action": action, "from": S, "to": target,
                              "rows_moved": rows_moved}

                if publisher is not None:
                    # the registry's pre-flip seam: its bucket warm runs
                    # the NEW topology's full hook (sealed scans on their
                    # pinned devices + the successor-count merge), then
                    # the commit runs as the last pre-flip hook, and only
                    # then does the registry pointer flip — in-flight
                    # flushes drain on the topology they leased
                    def commit_hook(_searcher, _ks, _step=step):
                        out = self._commit_reshard(succ, snaps, target,
                                                   action, cause=cause)
                        _step.update(out)
                        return out

                    step["publish"] = publisher.publish(
                        name, self._searcher_for(succ), k=ks,
                        warm_data=warm_data, res=res,
                        warm_hook=commit_hook, cause=cause)
                else:
                    if warm_buckets:
                        self._rehearse(succ, warm_buckets, ks, warm_data)
                    step.update(self._commit_reshard(succ, snaps, target,
                                                     action, cause=cause))
                if metrics._enabled:
                    _c_migrations().inc(1, name=self._name, action=action,
                                        phase="completed")
                    _c_rows_moved().inc(rows_moved, name=self._name)
                    _h_reshard().observe(time.perf_counter() - t0,
                                         name=self._name, action=action)
                obs_events.emit(
                    "reshard_committed",
                    subject=("reshard", self._name, None,
                             step.get("epoch")),
                    evidence={"action": action, "rows_moved": rows_moved,
                              "carried_over": step.get("carried_over"),
                              **({"cause": dict(cause)} if cause else {})})
                step["wall_s"] = round(time.perf_counter() - t0, 3)
                return step
            finally:
                with self._lock:
                    self._migration = None

    def _commit_reshard(self, successors, snaps, target: int,
                        action: str, cause: dict | None = None) -> dict:
        """The atomic flip. Pre-lock: each successor gets its baseline
        atomic snapshot + a fresh WAL (durability armed). Under the mesh
        write lock: carry over every write that landed on a donor after
        its fold snapshot (deletes first, then the delta tail — the
        alive-bit re-read discipline of a compaction swap), swap the
        shard list, and commit the manifest (its ``os.replace`` is the
        durable commit point — a crash before it recovers to the old
        topology, whose donors logged every mid-migration write; no write
        is admitted between the swap and the manifest because the lock is
        held). Post-lock: donor ledger entries retire (the audit proves
        the migration's double-buffer frees once leases drain) and the
        old epoch's files are removed."""
        new_epoch = self._topology_epoch + 1
        if self._wal_dir is not None:
            from .wal import WriteAheadLog

            for t, sh in enumerate(successors):
                snap, wal_path = self._shard_files(t, epoch=new_epoch)
                # stale files of an earlier ABORTED migration at this
                # epoch (the manifest never committed them) must not be
                # mistaken for live state
                if os.path.exists(wal_path):
                    os.remove(wal_path)
                if isinstance(sh, ReplicatedShard):
                    sh.save(snap)
                else:
                    _mut.save(sh, snap)
                sh._wal = WriteAheadLog(wal_path, name=sh.name)
                sh._snapshot_path = snap
        carried = 0
        with self._lock:
            for donor, snap_n, snap_gids, dead0 in snaps:
                # re-pick the carry-over twin NOW: the fold's primary may
                # have gone stale mid-migration — a stale twin stops
                # receiving (still-acknowledged) group writes, so reading
                # its tail would silently drop them; any currently
                # non-stale twin received every group write (lockstep),
                # at the same delta offsets and tombstone counts, so the
                # fold's snap_n and watermarks transfer
                prim = (donor._primary()
                        if isinstance(donor, ReplicatedShard) else donor)
                st = prim._state
                dead_now = (int(st.sealed_dead_n),
                            snap_n
                            - int(np.count_nonzero(st.delta_alive[:snap_n])))
                if dead_now == dead0:
                    # no snapshot-live id died mid-migration (the common
                    # case): skip the O(live-rows) membership scan — this
                    # runs under the mesh write lock, stalling every write
                    dead = np.empty(0, np.int64)
                elif len(prim._loc):
                    live_now = np.fromiter(prim._loc.keys(), np.int64,
                                           count=len(prim._loc))
                    dead = np.sort(snap_gids[
                        np.isin(snap_gids, live_now, invert=True)])
                else:
                    dead = np.sort(snap_gids)
                tail = (np.nonzero(st.delta_alive[snap_n:st.delta_n])[0]
                        + snap_n)
                tail_ids = st.delta_ids[tail].astype(np.int64)
                tail_rows = st.delta[tail].copy()
                if dead.size:
                    owner = shard_of(dead, target)
                    for t in np.unique(owner):
                        successors[int(t)].delete(dead[owner == t])
                    carried += int(dead.size)
                if tail_ids.size:
                    owner = shard_of(tail_ids, target)
                    for t in np.unique(owner):
                        m2 = owner == t
                        # an id upserted mid-migration tombstones its
                        # snapshot copy in the successor here (and lands
                        # in the successor WAL — durable before the flip)
                        successors[int(t)].upsert(tail_rows[m2],
                                                  ids=tail_ids[m2])
                    carried += int(tail_ids.size)
            old_shards = self._shards
            self._shards = list(successors)
            self._topology_epoch = new_epoch
            try:
                faults.fire("reshard/flip", name=self._name,
                            epoch=new_epoch)
                if self._wal_dir is not None:
                    faults.fire("reshard/manifest", name=self._name,
                                epoch=new_epoch)
                    self._write_manifest(self._wal_dir)
            except BaseException:
                # a manifest that failed to LAND (ENOSPC, EIO — a raise,
                # not a crash) must not leave the mesh flipped in memory
                # while the durable manifest still names the old topology:
                # later acknowledged writes would land only in successor
                # WALs recovery never reads. Roll the swap back — donors
                # are untouched (carry-over only read them) and keep
                # logging, so the abort loses nothing and reshard() keeps
                # its mesh-untouched-on-raise contract.
                self._shards = old_shards
                self._topology_epoch = new_epoch - 1
                if self._wal_dir is not None:
                    for sh in successors:
                        if sh._wal is not None:
                            sh._wal.close()
                            sh._wal = None
                obs_events.emit(
                    "reshard_aborted", severity="error",
                    subject=("reshard", self._name, None, new_epoch - 1),
                    evidence={"action": action, "rolled_back_to":
                              new_epoch - 1,
                              **({"cause": dict(cause)} if cause else {})})
                raise
            obs_events.emit(
                "reshard_flip",
                subject=("reshard", self._name, None, new_epoch),
                evidence={"action": action, "shards": target,
                          "carried_over": carried})
            self._update_gauges()
        # off the write lock: donor retirement and the old epoch's files —
        # the manifest is durable, nothing references them anymore
        for sh in old_shards:
            self._retire_shard(sh)
        if self._wal_dir is not None:
            for j in range(len(old_shards)):
                for path in self._shard_files(j, epoch=new_epoch - 1):
                    try:
                        os.remove(path)
                    except OSError:
                        pass
        return {"epoch": new_epoch, "carried_over": carried}

    def _retire_shard(self, sh) -> None:
        """Donor-shard retirement: obs.mem entries retire — a retired
        entry still accounted after draining leases release the old
        topology is exactly the leak the audit reports — and WAL handles
        close (their files are gone; the successor logs own durability
        now)."""
        reps = (sh.replicas if isinstance(sh, ReplicatedShard) else (sh,))
        for rep in reps:
            obs_mem.retire(rep._state.mem)
            obs_mem.retire(rep._sealed_mem)
            if rep._wal is not None:
                rep._wal.close()
                rep._wal = None
        if isinstance(sh, ReplicatedShard) and sh._wal is not None:
            sh._wal.close()
            sh._wal = None

    def _rehearse(self, shards, buckets, ks, sample) -> None:
        """Library-mode pre-flip warm of the successors' SEALED programs:
        run the real new-topology scatter-gather at every (bucket, k),
        once per replica ordinal so every twin's per-device executables
        compile before failover can pick them. (The publisher path gets
        this from the registry's bucket warm instead.)"""
        import jax

        from .._warmup import _random_queries

        R = max((sh.n_replicas if isinstance(sh, ReplicatedShard) else 1)
                for sh in shards)
        key = jax.random.key(7)
        for r in range(R):
            states = tuple(
                (sh.replicas[min(r, sh.n_replicas - 1)]._state
                 if isinstance(sh, ReplicatedShard) else sh._state)
                for sh in shards)
            for kk in ks:
                for b in sorted(set(int(x) for x in buckets)):
                    key, kq = jax.random.split(key)
                    q = _random_queries(kq, b, self.dim, self.query_dtype,
                                        sample=sample)
                    jax.block_until_ready(self._scatter_gather(
                        states, q, int(kk), _view_scan))

    # -- mesh durability -----------------------------------------------------
    def save(self, dir: str | None = None) -> None:
        """Atomic mesh snapshot: every shard's full mutable state
        (:func:`raft_tpu.stream.save` — per-shard atomic with
        parent-directory fsync, WAL-truncating when durability is armed)
        plus the topology MANIFEST written LAST through
        ``core.serialize.atomic_write``. A crash anywhere mid-save leaves
        a loadable set: each shard pair (snapshot + WAL) is independently
        consistent — the snapshot stamps the ``wal_seq`` it covers and
        truncates only after its own rename is durable — and the manifest
        only ever references complete pairs. ``dir`` defaults to (and,
        when durability is armed, must be) the construction-time
        ``wal_dir``."""
        if dir is None:
            dir = self._wal_dir
        expects(dir is not None,
                "save() needs a directory (pass dir= or construct with "
                "wal_dir=)")
        dir = os.fspath(dir)
        if self._wal_dir is not None:
            expects(os.path.abspath(dir) == os.path.abspath(self._wal_dir),
                    "a durable mesh snapshots into its wal_dir (%r) — the "
                    "per-shard WALs truncate against exactly these files; "
                    "got %r", self._wal_dir, dir)
        os.makedirs(dir, exist_ok=True)
        # serialize with topology changes (and staggered folds): a reshard
        # committing mid-save would close donor WALs under our per-shard
        # saves and flip _shards/_topology_epoch between the snapshot loop
        # and the manifest — the lock makes a save see one topology whole
        with self._compact_lock:
            for s, sh in enumerate(self._shards):
                snap, _ = self._shard_files(s, dir=dir)
                if isinstance(sh, ReplicatedShard):
                    sh.save(snap)
                else:
                    _mut.save(sh, snap)
            self._write_manifest(dir)

    def _write_manifest(self, dir: str) -> None:
        from ..core.serialize import (atomic_write, serialize_header,
                                      serialize_scalar)

        e = self._topology_epoch
        with atomic_write(os.path.join(dir, _MANIFEST)) as f:
            serialize_header(f, "mesh")
            serialize_scalar(f, self._name)
            serialize_scalar(f, len(self._shards))
            serialize_scalar(f, int(e))
            serialize_scalar(f, int(self._replicas_n))
            serialize_scalar(f, int(self._next_id))
            for s, sh in enumerate(self._shards):
                sn, wn = self._shard_names(s, e)
                serialize_scalar(f, sn)
                serialize_scalar(f, wn if self._wal_dir is not None else "")
                serialize_scalar(f, int(sh._wal_seq))

    @classmethod
    def load(cls, dir, *, build: Callable | None = None,
             search_params=None, index_params=None,
             builder: Callable | None = None,
             devices: Sequence | None = None, comms=None,
             fencing: FencingPolicy | None = None,
             name: str | None = None, tier=None,
             clock: Callable[[], float] = time.monotonic
             ) -> "ShardedMutableIndex":
        """Recover a mesh from :meth:`save`'s manifest + per-shard
        snapshots (+ per-shard WAL replay when durability was armed).
        The manifest decides the topology: a crash mid-reshard — before
        the manifest's atomic rename — recovers to the OLD topology, each
        shard's log replayed past its snapshot's stamp through the
        ordinary write path, so no acknowledged write is lost and no
        unacknowledged write resurrected. Runtime configuration
        (``build`` — needed only to reshard again —
        ``search_params``/``index_params``/``builder``/``devices``/
        ``comms``/``fencing``) is supplied fresh, like every loader.

        A replicated mesh recovers DEGRADED-TO-ONE: the group snapshot is
        the primary twin's state (twins are in-memory redundancy; the log
        is the on-disk copy), so every acknowledged write comes back on a
        ``replicas=1`` surface — re-replicate by rebuilding the mesh
        around the recovered corpus. ``mesh.last_recovery`` aggregates
        the per-shard replay reports (``replayed``, ``topology_epoch``,
        ``degraded_from_replicas``)."""
        from ..core.serialize import check_header, deserialize_scalar

        dir = os.fspath(dir)
        if comms is not None:
            expects(devices is None, "pass devices= or comms=, not both")
            devices = list(comms.mesh.devices.flat)
        if devices is not None:
            devices = list(devices)
        with open(os.path.join(dir, _MANIFEST), "rb") as f:
            check_header(f, "mesh")
            saved_name = deserialize_scalar(f)
            n_shards = int(deserialize_scalar(f))
            epoch = int(deserialize_scalar(f))
            saved_replicas = int(deserialize_scalar(f))
            next_id = int(deserialize_scalar(f))
            entries = [(deserialize_scalar(f), deserialize_scalar(f),
                        int(deserialize_scalar(f)))
                       for _ in range(n_shards)]
        obj = cls.__new__(cls)
        obj._name = saved_name if name is None else name
        obj._clock = clock
        obj._lock = threading.RLock()
        obj._compact_lock = threading.Lock()
        obj._build_fn = build
        obj._search_params = search_params
        obj._index_params = index_params
        obj._builder = builder
        obj._retain_vectors = None
        obj._devices = devices
        obj._replicas_n = 1  # degraded-to-one restore (see docstring)
        obj._fencing = fencing
        obj._topology_epoch = epoch
        obj._migration = None
        has_wal = any(wname for _, wname, _ in entries)
        obj._wal_dir = dir if has_wal else None
        shards = []
        for j, (sname, wname, _seq) in enumerate(entries):
            shards.append(_mut.load(
                os.path.join(dir, sname),
                wal=os.path.join(dir, wname) if wname else None,
                search_params=search_params, index_params=index_params,
                builder=builder, shard=j, tier=tier,
                device=(devices[j % len(devices)] if devices else None),
                clock=clock))
        obj._shards = shards
        # per-shard stream sections carry the tier layout (raft_tpu/12) —
        # the mesh inherits whatever the shards restored
        obj._storage = shards[0]._storage
        obj._tier = tier
        obj._delta_capacity = shards[0].delta_capacity
        obj._next_id = max([next_id] + [sh._next_id for sh in shards])
        obj._finish_init()
        per = [getattr(sh, "last_recovery", None) for sh in shards]
        obj.last_recovery = {
            "n_shards": n_shards, "topology_epoch": epoch,
            "replayed": sum(p["replayed"] for p in per if p),
            "torn": any(p["torn"] for p in per if p),
            "degraded_from_replicas": saved_replicas,
            "per_shard": per,
        }
        return obj
