"""ShardedMutableIndex: the mutable serve+stream lifecycle across a mesh.

Everything :class:`~raft_tpu.stream.MutableIndex` proved on one device —
delta memtable, tombstone bitsets, warm compaction swaps — composed S ways
into the production serving topology the distributed pieces already
justify: ``parallel/knn`` reproduces the reference's knn_merge_parts
contract (all_gather + select_k over per-shard candidates,
detail/knn_merge_parts.cuh), PR 3/6 measured shard-local graphs at zero
recall cost, and the FreshDiskANN lineage's fresh/sealed split shards
cleanly when compaction is staggered per shard. Three moving parts:

- **Hash-routed writes.** Every global id owns exactly one home shard
  (:func:`shard_of`, a stable SplitMix-style mix — independent of shard
  history, so a restart routes identically). Each shard is a full
  :class:`MutableIndex`: its own delta memtable, tombstone bitset, id map
  (``ids=`` carries the global ids, so shard-local sealed builds stay
  dense while results surface global ids) and — when a mesh is given —
  its own pinned device, which is what makes the scatter real: jax runs
  every per-shard program on the device its committed arrays live on.
- **Scatter-gather search.** A query batch fans to all shards (the
  per-shard scans dispatch WITHOUT materializing — jax's async dispatch
  overlaps them across devices), each shard contributes its sealed(k) and
  delta(≤k) candidate sets with global ids, and ALL ``2S`` parts merge
  through ONE ``select_k`` dispatch — the ``parallel/knn`` merge
  generalized to mixed sealed+delta parts. Candidates ride the
  interconnect; raw rows never do. Delta parts are padded to width k
  with the shared ``-1 / ±inf`` sentinel so the merge program is keyed on
  ``(m, 2S·k)`` alone — per-shard delta growth can never mint a new merge
  shape, which is what keeps the warmed ladder finite.
- **Staggered compaction.** :meth:`compact` folds ONE shard per call —
  the most-due one — through that shard's ordinary fold+swap; the other
  S−1 shards keep serving their current epochs untouched. A
  :class:`~raft_tpu.stream.Compactor` drives it unchanged (``stats()``
  reports the BINDING shard's watermarks: max fill, max tombstone ratio,
  oldest delta), so one ``run_once`` = one shard folded + one warm
  republish through the serve registry — there is never a global
  stop-the-world, and the publish warm covers the successor epoch's
  program set exactly like the single-device churn rows.

Serve integration is duck-typed end to end: ``serve.publish`` /
``make_searcher`` resolve this class exactly like a ``MutableIndex``
(``upsert``/``searcher`` attributes open the write path),
:meth:`exact_search` composes the shard-local exact scans through the same
one-dispatch merge so ``obs.quality.exact_oracle`` — and therefore the
RecallCanary and SLOTracker — work unchanged over the mesh, and
``obs.requestlog`` spans are prefixed ``stream/shard<i>/`` so a traced
flush attributes tail latency to the straggler shard.

Consistency: per-shard reads/writes keep MutableIndex's guarantees
(read-your-writes, kill-then-reveal upserts); a cross-shard search
snapshots each shard's state independently, so a multi-row write that
spans shards may be half-visible to one racing read — the same anomaly
class as any read racing a write, documented in docs/streaming.md
("Sharded lifecycle").
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Callable, Sequence

import numpy as np

from ..core.errors import expects
from ..core.resources import default_resources
from ..obs import dispatch as obs_dispatch
from ..obs import mem as obs_mem
from ..obs import metrics
from . import mutable as _mut
from .mutable import DeltaFullError, MutableIndex
from .replicated import FencingPolicy, ReplicatedShard, _PinnedGroup

__all__ = ["ShardedMutableIndex", "shard_of"]


# -- the one-dispatch merge --------------------------------------------------

@functools.cache
def _shard_jits():
    import jax
    import jax.numpy as jnp

    from ..matrix.select_k import _select_k

    @functools.partial(jax.jit, static_argnames=("k", "select_min"))
    def pad(d, i, k: int, select_min: bool):
        # widen a (m, kd<k) candidate set to width k with the shared
        # underfill sentinel (id -1 at ±inf): appended AFTER the real
        # candidates, so a stable select keeps the unpadded ordering —
        # the 1-shard bit-parity with MutableIndex's own merge rides on it
        m, kd = d.shape
        fill = jnp.inf if select_min else -jnp.inf
        return (jnp.concatenate([d, jnp.full((m, k - kd), fill, d.dtype)], 1),
                jnp.concatenate([i, jnp.full((m, k - kd), -1, i.dtype)], 1))

    @functools.partial(jax.jit, static_argnames=("k", "select_min"))
    def merge(ds: tuple, is_: tuple, k: int, select_min: bool):
        # the knn_merge_parts contract over 2S mixed sealed+delta parts,
        # every part pre-padded to width k so this program is keyed on
        # (m, 2S·k) alone — ONE _select_k dispatch per (bucket, k)
        d = jnp.concatenate(ds, axis=1)
        i = jnp.concatenate(is_, axis=1)
        dv, iv = _select_k(d, i, k, select_min)
        return dv, jnp.where(jnp.isinf(dv), -1, iv)

    return pad, merge


def _pad_part(d, i, k: int, select_min: bool):
    obs_dispatch.note(1)
    return _shard_jits()[0](d, i, int(k), bool(select_min))


def _serving_scan(st, queries, k, res=None):
    """Per-shard serving scan: sealed width clamps to the shard's sealed
    rows (small shards contribute what they have; the merge pads)."""
    return _mut._scan_state(st, queries, k, res=res,
                            k_sealed=min(int(k), st.id_map.shape[0]))


def _view_scan(view, queries, k, res=None):
    """Per-shard scan over a pinned view: a plain shard's state runs the
    single-replica scan; a replica group's pinned view routes through its
    health-picked twin with same-flush failover."""
    if isinstance(view, _PinnedGroup):
        return view.scan_serving(queries, k, res=res)
    return _serving_scan(view, queries, k, res=res)


def _merge_parts(ds, is_, k: int, select_min: bool):
    obs_dispatch.note(1)
    return _shard_jits()[1](tuple(ds), tuple(is_), int(k), bool(select_min))


def _resident_on(x, device) -> bool:
    """Whether a candidate part already lives (committed) on ``device`` —
    the skip test of the fused gather. Anything that cannot prove
    residency moves (moving is always correct; skipping is the
    optimization)."""
    try:
        devs = x.devices()
        return len(devs) == 1 and next(iter(devs)) == device
    except Exception:  # non-jax arrays (host numpy parts) always move
        return False


def _gather_parts(parts_d, parts_i, device):
    """The one merge-device gather, shared by the serving scatter-gather
    and the warm ladder: move candidate parts onto ``device`` for the
    single cross-shard ``_select_k`` merge, SKIPPING parts already
    resident there (shard 0's candidates live on the merge device — the
    old per-call ``device_put`` of every part re-dispatched 4S transfers
    per flush, S of them no-ops) and batching the movers into ONE
    ``device_put`` call. Returns ``(parts_d, parts_i, moved)`` where
    ``moved`` counts the arrays that actually crossed devices."""
    if device is None:
        return parts_d, parts_i, 0
    import jax

    arrays = list(parts_d) + list(parts_i)
    move = [j for j, a in enumerate(arrays) if not _resident_on(a, device)]
    if move:
        placed = jax.device_put(tuple(arrays[j] for j in move), device)
        for j, a in zip(move, placed):
            arrays[j] = a
        obs_dispatch.note(len(move))
    s = len(parts_d)
    return arrays[:s], arrays[s:], len(move)


@functools.lru_cache(maxsize=None)
def _g_shards():
    return metrics.gauge(
        "raft_tpu_stream_shards",
        "shard count of a sharded mutable index (per-shard series report "
        "under name/shard<i>)")


def shard_of(ids, n_shards: int):
    """Stable home shard of each global id: a SplitMix64-style avalanche
    mix mod the shard count — independent of insertion order or shard
    state, so routing is reproducible across processes and restarts
    (the contract a router in front of a real fleet would share)."""
    h = np.asarray(ids, np.uint64)
    h = (h + np.uint64(0x9E3779B97F4A7C15))
    h ^= h >> np.uint64(30)
    h *= np.uint64(0xBF58476D1CE4E5B9)
    h ^= h >> np.uint64(27)
    h *= np.uint64(0x94D049BB133111EB)
    h ^= h >> np.uint64(31)
    return (h % np.uint64(n_shards)).astype(np.int64)


class ShardedMutableIndex:
    """Mesh-wide mutable index (see module docstring).

    ``dataset`` (n, d) rows are routed to ``n_shards`` home shards by
    :func:`shard_of` over their global ids (``ids=``, default the dense
    row range) and each shard's sealed index is built by ``build`` — any
    ``fn(rows) -> sealed index`` (size per-shard knobs like ``n_lists`` /
    ``n_probes`` / ``itopk`` for rows/S shards, see docs/using_comms.md
    "Serving-tier sizing"). Every shard must own at least one row.

    ``devices`` pins shard ``s`` to ``devices[s]`` (pass ``comms=`` to take
    the mesh's devices) — candidates then gather onto ``devices[0]`` for
    the merge; without a pin everything stays on the default device and
    only the search-composition semantics remain (the 1-shard twin of a
    plain MutableIndex, bit-equal by the parity suite).

    ``search_params`` / ``index_params`` / ``builder`` / ``delta_capacity``
    (per shard) / ``retain_vectors`` / ``clock`` forward to every shard's
    :class:`MutableIndex`. The retained row store defaults ON (the
    constructor holds each shard's rows anyway), so rebuild compaction and
    :meth:`exact_search` work out of the box; pass
    ``retain_vectors=False`` to drop it.
    """

    def __init__(self, dataset, *, n_shards: int, build: Callable,
                 ids=None, search_params=None, index_params=None,
                 builder: Callable | None = None,
                 delta_capacity: int = 1024,
                 retain_vectors: bool | None = None,
                 devices: Sequence | None = None, comms=None,
                 replicas: int = 1,
                 fencing: FencingPolicy | None = None,
                 name: str = "default",
                 clock: Callable[[], float] = time.monotonic):
        dataset = np.asarray(dataset)
        expects(dataset.ndim == 2, "dataset must be (rows, d)")
        n = dataset.shape[0]
        n_shards = int(n_shards)
        expects(n_shards >= 1, "n_shards must be >= 1, got %d", n_shards)
        if ids is None:
            gids = np.arange(n, dtype=np.int64)
        else:
            gids = np.asarray(ids, np.int64).reshape(-1)
            expects(gids.shape == (n,), "ids= must match dataset rows (%d)", n)
        if comms is not None:
            expects(devices is None, "pass devices= or comms=, not both")
            devices = list(comms.mesh.devices.flat)
        if devices is not None:
            devices = list(devices)
            expects(len(devices) >= n_shards,
                    "%d shards need %d devices, got %d", n_shards, n_shards,
                    len(devices))
        owner = shard_of(gids, n_shards)
        self._name = name
        self._clock = clock  # Compactor inherits it (one age time base)
        self._lock = threading.RLock()
        self._compact_lock = threading.Lock()
        R = int(replicas)
        expects(R >= 1, "replicas must be >= 1, got %d", R)
        if R > 1 and devices is not None:
            # twins of one shard land on devices[(s*R + j) % D]: j1 and j2
            # collide iff D divides j1-j2, i.e. iff D < R — and co-located
            # twins silently void the device anti-affinity the replica
            # groups promise (pass devices=None for unpinned twins)
            expects(len(devices) >= R,
                    "replica anti-affinity needs >= %d devices so twins "
                    "of one shard land on different devices, got %d",
                    R, len(devices))
        self._shards: list = []
        for s in range(n_shards):
            rows_idx = np.nonzero(owner == s)[0]
            expects(len(rows_idx) > 0,
                    "shard %d of %d owns no rows (n=%d) — use fewer shards",
                    s, n_shards, n)
            rows_s = dataset[rows_idx]
            sealed = build(rows_s)
            if R == 1:
                self._shards.append(MutableIndex(
                    sealed, search_params=search_params,
                    index_params=index_params,
                    delta_capacity=delta_capacity,
                    # the constructor holds the shard's raw rows either
                    # way, so retention costs no extra recover pass; False
                    # opts out
                    retain_vectors=retain_vectors,
                    dataset=None if retain_vectors is False else rows_s,
                    builder=builder, ids=gids[rows_idx],
                    device=devices[s] if devices is not None else None,
                    name=f"{name}/shard{s}", shard=s, clock=clock))
            else:
                # replica j of shard s lands on devices[s*R + j] (mod the
                # mesh): twins of one shard live on DIFFERENT devices —
                # the anti-affinity that makes a group survive a device
                self._shards.append(ReplicatedShard(
                    sealed, n_replicas=R,
                    devices=([devices[(s * R + j) % len(devices)]
                              for j in range(R)]
                             if devices is not None else None),
                    search_params=search_params,
                    index_params=index_params,
                    delta_capacity=delta_capacity,
                    retain_vectors=retain_vectors,
                    dataset=None if retain_vectors is False else rows_s,
                    builder=builder, ids=gids[rows_idx],
                    policy=fencing or FencingPolicy(),
                    name=f"{name}/shard{s}", shard=s, clock=clock))
        cfg0 = self._shards[0]._cfg
        for s, sh in enumerate(self._shards[1:], 1):
            expects(sh._cfg.kind == cfg0.kind and sh._cfg.dim == cfg0.dim
                    and sh._cfg.query_dtype == cfg0.query_dtype,
                    "shard %d built a (%s, %d, %s) index but shard 0 is "
                    "(%s, %d, %s) — build must be deterministic in kind",
                    s, sh._cfg.kind, sh._cfg.dim, sh._cfg.query_dtype,
                    cfg0.kind, cfg0.dim, cfg0.query_dtype)
        self._select_min = cfg0.select_min
        self._merge_device = devices[0] if devices is not None else None
        self._next_id = int(gids.max()) + 1 if n else 0
        self._update_gauges()

    # -- introspection ------------------------------------------------------
    @property
    def kind(self) -> str:
        return self._shards[0].kind

    @property
    def dim(self) -> int:
        return self._shards[0].dim

    @property
    def name(self) -> str:
        return self._name

    @property
    def query_dtype(self) -> str:
        return self._shards[0].query_dtype

    @property
    def n_shards(self) -> int:
        return len(self._shards)

    @property
    def shards(self) -> tuple:
        """The per-shard :class:`MutableIndex` objects (read-only tuple —
        write through the sharded surface so routing stays consistent)."""
        return tuple(self._shards)

    @property
    def can_rebuild(self) -> bool:
        return all(sh.can_rebuild for sh in self._shards)

    @property
    def size(self) -> int:
        return sum(sh.size for sh in self._shards)

    def stats(self) -> dict:
        """Aggregated view + ``per_shard`` detail. The scalar watermarks a
        :class:`~raft_tpu.stream.Compactor` reads are the BINDING shard's:
        ``delta_fill`` / ``tombstone_ratio`` are maxima (the shard that
        will hit the wall first) and ``delta_oldest_at`` the minimum (the
        stalest write) — so an aggregate watermark trips exactly when some
        shard needs a fold, and :meth:`compact` folds that shard."""
        per = [sh.stats() for sh in self._shards]
        oldest = [p["delta_oldest_at"] for p in per
                  if p["delta_oldest_at"] is not None]
        return {
            "live": sum(p["live"] for p in per),
            "sealed_rows": sum(p["sealed_rows"] for p in per),
            "sealed_dead": sum(p["sealed_dead"] for p in per),
            "tombstone_ratio": max(p["tombstone_ratio"] for p in per),
            "delta_rows": sum(p["delta_rows"] for p in per),
            "delta_fill": max(p["delta_fill"] for p in per),
            "delta_oldest_at": min(oldest) if oldest else None,
            "epoch": sum(p["epoch"] for p in per),
            "shards": len(per),
            "per_shard": per,
            # replica-group detail (replicas=1: every shard is its own
            # single healthy "replica"): healthy is the WORST shard's
            # pickable-twin count — the availability binding constraint
            **({"replicas": sum(p.get("replicas", 1) for p in per),
                "healthy": min(p.get("healthy", 1) for p in per),
                "stale": sum(p.get("stale", 0) for p in per)}
               if any("replicas" in p for p in per) else {}),
        }

    def health(self) -> dict:
        """Per-shard replica-group health for ``/healthz``
        (``obs.start_http_exporter(replicas=...)``): each group's breaker
        detail plus the mesh verdict — a shard with ZERO pickable twins
        means queries to it fail, which is an outage, not degradation."""
        shards = [sh.health() if isinstance(sh, ReplicatedShard)
                  else {"name": sh.name, "replicas": [], "healthy": 1}
                  for sh in self._shards]
        return {"name": self._name, "shards": shards,
                "healthy_min": min(s["healthy"] for s in shards)}

    def _update_gauges(self, st: dict | None = None) -> None:
        if not metrics._enabled:
            return
        st = self.stats() if st is None else st
        name = self._name
        _g_shards().set(st["shards"], name=name)
        # the aggregate rides the same stream gauges under the parent name
        # (per-shard series report under name/shard<i> already)
        _mut._g_delta_fill().set(st["delta_fill"], name=name)
        _mut._g_delta_rows().set(st["delta_rows"], name=name)
        _mut._g_tombstone().set(st["tombstone_ratio"], name=name)

    def _drift_store(self):
        """Cross-shard corpus sample for the drift detector: an interleave
        of every shard's retained rows (bounded — the classifier subsamples
        downstream anyway); None when any shard dropped its store."""
        stores = [sh._drift_store() for sh in self._shards]
        if any(s is None for s in stores):
            return None
        cap = max(4096 // len(stores), 256)
        return np.concatenate([s[:cap] for s in stores])

    # -- writes -------------------------------------------------------------
    def upsert(self, rows, ids=None, res=None):
        """Insert/upsert rows, each routed to its global id's home shard.
        Admission is checked across ALL touched shards BEFORE any row
        lands (writes go through this serialized surface, so the check is
        exact): one full home shard refuses the whole call with
        :class:`~raft_tpu.stream.DeltaFullError`, and the summed device
        growth of every touched shard's delta bucket is checked against
        ``res.memory_budget_bytes`` in the same hoisted pass
        (:class:`~raft_tpu.serve.errors.MemoryBudgetError`) — either way
        nothing is written, the same whole-or-nothing contract as a single
        shard's upsert."""
        # validate ONCE up front (dim + dtype through shard 0's rules): a
        # per-shard refusal after a sibling already accepted its group
        # would break the whole-or-nothing contract
        rows = self._shards[0]._coerce_rows(rows)
        r = rows.shape[0]
        expects(r >= 1, "upsert needs at least one row")
        with self._lock:
            if ids is None:
                gids = np.arange(self._next_id, self._next_id + r,
                                 dtype=np.int64)
            else:
                gids = _mut.check_upsert_ids(ids, r)
            self._next_id = max(self._next_id, int(gids.max()) + 1)
            owner = shard_of(gids, len(self._shards))
            groups = [np.nonzero(owner == s)[0]
                      for s in range(len(self._shards))]
            for s, idx in enumerate(groups):
                sh = self._shards[s]
                # concurrent folds only SHRINK a delta, so a stale read
                # here can only over-refuse, never admit past capacity
                if len(idx) and (sh._delta_rows_now() + len(idx)
                                 > sh.delta_capacity):
                    if metrics._enabled:
                        _mut._c_delta_full().inc(1, name=self._name)
                    raise DeltaFullError(
                        f"shard {s} delta at {sh._delta_rows_now()}"
                        f"/{sh.delta_capacity} rows; upsert routing "
                        f"{len(idx)} there refused — compact() (or attach "
                        "a stream.Compactor) to fold it")
            # memory-budget admission, hoisted like the capacity check: the
            # SUMMED bucket growth across home shards (and, for replica
            # groups, across every live twin) gates before any shard
            # writes (cross-shard whole-or-nothing)
            obs_mem.gate(
                res or default_resources(),
                lambda: sum(
                    self._shards[s]._growth_bytes(len(idx))
                    for s, idx in enumerate(groups) if len(idx)),
                site="upsert", detail=f"stream/sharded {self._name!r}")
            # the hoisted pass IS the admission decision: the per-shard
            # upserts get a budget-free res so their gates cannot refuse
            # mid-write — a stricter ambient default, or concurrent ledger
            # growth between the hoisted admit and shard s's write (another
            # name's publish, an off-lock fold's double-buffer), would
            # otherwise land a partial cross-shard write
            inner = res or default_resources()
            if getattr(inner, "memory_budget_bytes", None) is not None:
                inner = dataclasses.replace(inner, memory_budget_bytes=None)
            for s, idx in enumerate(groups):
                if len(idx):
                    self._shards[s].upsert(rows[idx], ids=gids[idx],
                                           res=inner)
            self._update_gauges()
        return gids

    def delete(self, ids) -> int:
        """Tombstone ids on their home shards; returns how many were live.
        Unknown or already-dead ids are a counted no-op, not an error."""
        arr = np.asarray(ids, np.int64).reshape(-1)
        if arr.size == 0:
            return 0
        with self._lock:
            owner = shard_of(arr, len(self._shards))
            killed = 0
            for s in range(len(self._shards)):
                idx = np.nonzero(owner == s)[0]
                if len(idx):
                    killed += self._shards[s].delete(arr[idx])
            self._update_gauges()
        return killed

    # -- reads --------------------------------------------------------------
    def _scatter_gather(self, states, queries, k: int, scan, res=None):
        """Fan ``queries`` to every shard state (async dispatch — jax
        overlaps the per-shard programs across their pinned devices),
        collect each shard's sealed + delta candidate sets, and merge all
        ``2S`` parts through ONE ``select_k`` dispatch. ``scan`` is the
        per-state scan half (serving: :func:`mutable._scan_state`; oracle:
        the bound ``_exact_scan``). The gather moves ONLY the parts not
        already resident on the merge device, in one ``device_put``
        (:func:`_gather_parts`), and the flush's dispatch count rides the
        obs dispatch meter + the ``stream_moved_parts`` trace note so the
        fusion win is attributable per flush."""
        from ..obs import requestlog

        k = int(k)
        parts_d, parts_i = [], []
        for s, st in enumerate(states):
            with requestlog.prefix(f"stream/shard{s}/"):
                sd, si, dd, di = scan(st, queries, k, res=res)
            for d, i in ((sd, si), (dd, di)):
                if d.shape[1] < k:  # delta buckets (and tiny oracle
                    # stores) can be narrower than k — pad on the shard's
                    # device so the merge shape below is invariant
                    d, i = _pad_part(d, i, k, self._select_min)
                parts_d.append(d)
                parts_i.append(i)
        t0 = time.perf_counter()
        # the gather: ONLY the (m, k) candidate tuples cross devices, and
        # only the non-resident ones move
        parts_d, parts_i, moved = _gather_parts(parts_d, parts_i,
                                                self._merge_device)
        out = _merge_parts(parts_d, parts_i, k, self._select_min)
        requestlog.add_span("stream/merge", time.perf_counter() - t0)
        requestlog.annotate("stream_shards", len(states))
        requestlog.annotate("stream_moved_parts", moved)
        return out

    def search(self, queries, k: int, res=None):
        """Scatter-gather search over every shard's (sealed − tombstones)
        + delta; returns ``(distances (m, k), global ids (m, k))`` with the
        shared ``id -1 / ±inf`` sentinel in slots the live rows cannot
        fill. Identical result contract to :meth:`MutableIndex.search` —
        the 1-shard composition is bit-equal to a plain MutableIndex
        (pinned by the parity suite). A shard smaller than k contributes
        every sealed row it has (``k_sealed`` clamp) and the merge pads.
        With ``replicas > 1`` each shard's scan routes through its replica
        group's health-picked twin, failing over within this same call —
        one fenced replica degrades capacity, never the query."""
        return self._scatter_gather(self._views(), queries, k,
                                    _view_scan, res=res)

    def _views(self) -> tuple:
        """Per-shard read views: a plain shard pins its current state
        epoch; a replica group pins EVERY twin's epoch behind the live
        failover pick (:meth:`ReplicatedShard.pin_group`)."""
        return tuple(sh.pin_group() if isinstance(sh, ReplicatedShard)
                     else sh._state for sh in self._shards)

    def exact_search(self, queries, k: int, res=None):
        """EXACT fused kNN over the whole mesh's live corpus — shard-local
        exact store+delta scans composed through the same one-dispatch
        merge as :meth:`search`, so the RecallCanary's shadow oracle
        (``obs.quality.exact_oracle``) covers the sharded tier unchanged.
        Needs every shard's retained store."""
        shards = tuple(self._shards)

        def scan(sh, q, kk, res=None):
            return sh._exact_scan(q, kk, res=res)

        return self._scatter_gather(shards, queries, k, scan, res=res)

    def searcher(self):
        """Serving hook pinned to every shard's CURRENT state epoch (the
        ``batched_searcher`` contract). A staggered compaction freezes only
        the folded shard's epoch inside an already-issued hook; republish
        (what the Compactor does per fold) picks up the successor — the
        same lease-drain semantics as the single-device flow, per shard."""
        from ..neighbors._hooks import make_hook

        states = self._views()
        cfg0 = self._shards[0]._cfg
        fn = make_hook(
            lambda queries, k: self._scatter_gather(
                states, queries, k, _view_scan),
            f"stream/sharded/{cfg0.kind}", cfg0.dim, cfg0.data_kind)
        # marker for the serve write path (SearchService.publish follows it
        # across compaction republishes, exactly like MutableIndex's hook)
        fn.mutable = self
        return fn

    # -- warmup -------------------------------------------------------------
    def warm(self, buckets, ks=(10,), sample=None) -> dict:
        """Compile the sharded delta-ladder program set: every shard's
        exact delta scan at every memtable bucket × (query bucket, k) —
        each ON its pinned device (placement is part of the program key) —
        plus the pad programs and the ONE cross-shard merge at its fixed
        ``(m, 2S·k)`` shape. Sealed-side programs are warmed per epoch by
        ``registry.publish`` (which runs the full hook), exactly like the
        single-device flow. Returns per-(k, bucket) compile attribution."""
        import jax

        from .._warmup import _random_queries
        from ..obs import compile as obs_compile
        from ..neighbors import brute_force

        out: dict = {}
        key = jax.random.key(0)
        S = len(self._shards)
        for kk in sorted(set(int(x) for x in ks)):
            out[kk] = {}
            for b in sorted(set(int(x) for x in buckets)):
                key, kq = jax.random.split(key)
                q = _random_queries(kq, b, self.dim, self.query_dtype,
                                    sample=sample)
                t0 = time.perf_counter()
                with obs_compile.attribution() as rec:
                    parts_d, parts_i = [], []
                    for sh in self._shards:
                        # a replica group warms EVERY twin's ladder on its
                        # own pinned device (placement is part of the
                        # program key): failover must never cold-compile —
                        # a twin that was never picked has to be hot the
                        # moment its sibling is fenced. Any twin's parts
                        # feed the merge (the gather re-places them).
                        units = (sh.replicas
                                 if isinstance(sh, ReplicatedShard)
                                 else (sh,))
                        for u in units:
                            cfg = u._cfg
                            dt = _mut._np_dtype(cfg.query_dtype)
                            sd = _mut._dev_put(
                                cfg, np.zeros((b, kk), np.float32))
                            si = _mut._dev_put(
                                cfg, np.full((b, kk), -1, np.int32))
                            dd = di = None
                            for db in u._buckets:
                                dummy = _mut._dev_put(
                                    cfg, np.zeros((db, cfg.dim), dt))
                                keep = _mut._dev_put(
                                    cfg, np.zeros((db,), bool))
                                dd, di = brute_force.knn(
                                    dummy, q, min(kk, db), cfg.metric,
                                    cfg.metric_arg, sample_filter=keep)
                                di = _mut._map_ids(di, _mut._dev_put(
                                    cfg, np.zeros((db,), np.int32)))
                                if dd.shape[1] < kk:  # same pad rule as
                                    # _scatter_gather — per (width, device)
                                    dd, di = _pad_part(dd, di, kk,
                                                       self._select_min)
                                jax.block_until_ready((dd, di))
                        parts_d += [sd, dd]
                        parts_i += [si, di]
                    parts_d, parts_i, _ = _gather_parts(
                        parts_d, parts_i, self._merge_device)
                    jax.block_until_ready(_merge_parts(
                        parts_d, parts_i, kk, self._select_min))
                out[kk][b] = {"wall_s": round(time.perf_counter() - t0, 3),
                              **rec.summary()}
        return out

    # -- compaction ---------------------------------------------------------
    def _pick_shard(self, mode: str, trigger: str | None = None) -> int:
        """The most-due shard for one staggered fold: rebuilds (and
        tombstone trips) chase the highest tombstone ratio, an AGE trip
        chases the stalest non-empty delta — picking the fullest there
        would starve a quiet shard forever while its age watermark stays
        tripped — and everything else chases the fullest delta; ties break
        low."""
        per = [sh.stats() for sh in self._shards]
        if mode == "rebuild" or trigger == "tombstone_ratio":
            ratios = [p["tombstone_ratio"] for p in per]
            if max(ratios) > 0:
                return int(np.argmax(ratios))
        if trigger == "age":
            ages = [(p["delta_oldest_at"], s) for s, p in enumerate(per)
                    if p["delta_oldest_at"] is not None]
            if ages:
                return min(ages)[1]
        return int(np.argmax([p["delta_rows"] for p in per]))

    def compact(self, mode: str = "auto", shard: int | None = None,
                res=None, trigger: str | None = None) -> dict:
        """Fold ONE shard (the most-due, or an explicit ``shard=``) through
        its ordinary fold+swap — the staggered step: the other shards keep
        serving their epochs untouched, and a Compactor loop folds shard
        after shard while its watermark stays tripped, republishing between
        folds (the Compactor forwards its tripped ``trigger`` so the pick
        chases the right shard). Returns the shard's compaction report plus
        ``shard`` and the aggregate ``epoch``."""
        with self._compact_lock:
            if shard is None:
                shard = self._pick_shard(mode, trigger)
            shard = int(shard)
            expects(0 <= shard < len(self._shards),
                    "shard %d out of range (%d shards)", shard,
                    len(self._shards))
            report = self._shards[shard].compact(mode=mode, res=res)
            report["shard"] = shard
            report["shard_epoch"] = report["epoch"]
            agg = self.stats()
            report["epoch"] = agg["epoch"]  # aggregate fold count
            self._update_gauges(agg)
            return report
