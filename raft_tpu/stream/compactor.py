"""Background compaction: watermark-triggered delta folds with warm hot-swap.

The compaction half of the LSM lifecycle (FreshDiskANN's background
merge/StreamingMerger; an LSM-tree's compaction thread): a
:class:`Compactor` watches one :class:`~raft_tpu.stream.MutableIndex` and,
when a watermark trips, folds the delta memtable into a new sealed index
OFF the hot path, then republishes through a
:class:`~raft_tpu.serve.IndexRegistry` / :class:`SearchService` so the swap
is warm-before-visible and in-flight leases drain on the old epoch — the
exact hot-swap machinery PR 3 built, now driven by data churn instead of an
operator.

Watermarks (:class:`CompactionPolicy`):

- ``delta_fill`` — the memtable is nearly full: fold before writers hit the
  :class:`~raft_tpu.stream.DeltaFullError` back-pressure wall. Uses
  extend-compaction for IVF kinds (cheap: encode + re-pack, no retraining).
- ``tombstone_ratio`` — dead sealed slots waste scan work and recall head-
  room: RECLAIM them with a rebuild compaction (the only mode that actually
  drops tombstoned rows). Only armed when the index ``can_rebuild``.
- ``max_age_s`` — freshness bound: a trickle of writes that never fills the
  memtable still gets folded within this horizon (clock-based; the clock is
  injected so tests drive it without sleeping).

The worker thread is a thin poll loop around :meth:`run_once`, which is the
deterministic entry tests (and the churn bench, which needs shape-
deterministic folds) drive directly.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from dataclasses import dataclass
from typing import Callable

from ..core.errors import expects
from ..obs import events as obs_events
from ..obs import metrics
from .mutable import MutableIndex

__all__ = ["CompactionPolicy", "Compactor"]

# per-Compactor journal transition keys (see last_advice)
_compactor_ids = itertools.count()


@functools.lru_cache(maxsize=None)
def _c_compactions():
    return metrics.counter(
        "raft_tpu_stream_compactions_total",
        "compactions by trigger watermark and fold mode")


@functools.lru_cache(maxsize=None)
def _h_wall():
    return metrics.histogram(
        "raft_tpu_stream_compaction_seconds",
        "compaction wall seconds (fold + warm + publish, off the hot path)",
        unit="seconds")


@functools.lru_cache(maxsize=None)
def _c_compile():
    return metrics.counter(
        "raft_tpu_stream_compaction_compile_seconds_total",
        "backend-compile seconds spent inside compactions (publish warms "
        "new sealed shapes here, never on the search hot path)",
        unit="seconds")


@functools.lru_cache(maxsize=None)
def _c_swaps():
    return metrics.counter(
        "raft_tpu_stream_swap_total",
        "compaction hot-swaps published through the serve registry")


@functools.lru_cache(maxsize=None)
def _c_failures():
    return metrics.counter(
        "raft_tpu_stream_compaction_failures_total",
        "compaction attempts that raised (see last_error and the WARNING "
        "log line)")


@functools.lru_cache(maxsize=None)
def _c_reshard_advised():
    return metrics.counter(
        "raft_tpu_reshard_advised_total",
        "reshard advisories emitted by the Compactor's per-shard row "
        "watermarks (once per transition; auto_apply is always False — an "
        "operator or controller calls ShardedMutableIndex.reshard)")


@functools.lru_cache(maxsize=None)
def _c_deferred():
    return metrics.counter(
        "raft_tpu_stream_compaction_deferred_total",
        "due compactions deferred by the external pacing hint (a "
        "controller's SLO-burn signal — compaction waits out a latency "
        "burn instead of competing with the serve path)")


@dataclass(frozen=True)
class CompactionPolicy:
    """Watermarks that arm :meth:`Compactor.run_once` (see module doc).
    ``None`` disables a watermark; see docs/streaming.md for tuning.

    ``reshard_rows_per_shard`` / ``reshard_min_rows_per_shard`` are the
    ADVISORY topology watermarks for a sharded mesh: when the mean live
    rows per shard cross the high (low) mark, the Compactor emits a
    once-per-transition ``reshard_advised`` event recommending a
    power-of-two split (merge) — compaction alone cannot relieve a mesh
    that outgrew its shard count. Advice only (``auto_apply: False``, the
    ``retune_advised`` discipline): the fold machinery stays in
    :meth:`raft_tpu.stream.ShardedMutableIndex.reshard`, driven by an
    operator or a controller reading ``Compactor.last_advice``."""

    delta_fill: float | None = 0.75
    tombstone_ratio: float | None = 0.25
    max_age_s: float | None = None
    reshard_rows_per_shard: int | None = None
    reshard_min_rows_per_shard: int | None = None


class Compactor:
    """Watermark-driven compaction for one mutable index (see module doc).

    Also drives a :class:`raft_tpu.stream.ShardedMutableIndex` unchanged —
    its ``stats()`` reports the BINDING shard's watermarks and its
    ``compact()`` folds one shard per call, so each ``run_once`` here is
    one STAGGERED shard fold + warm republish (no global stop-the-world);
    while a watermark stays tripped, successive polls walk shard after
    shard (docs/streaming.md "Sharded lifecycle").

    ``publisher`` is optional: a :class:`~raft_tpu.serve.SearchService` or
    :class:`~raft_tpu.serve.IndexRegistry` (anything with ``publish``) plus
    ``name``/``ks`` — each compaction then republishes the post-swap
    searcher, warming the new sealed shapes BEFORE the flip (the zero-cold-
    compile swap). Without one, the swap still happens atomically and
    direct ``MutableIndex.search`` callers pay their own first-touch
    compiles (library mode).

    ``drift`` (an :class:`raft_tpu.obs.quality.DriftDetector`) re-runs the
    tune family classifier on compaction-time corpus stats: each fold that
    leaves a retained row store feeds a corpus subsample plus the live row
    count into :meth:`DriftDetector.check` — the corpus-side half of the
    drift → retune loop (docs/tuning.md; the query-side half rides the
    recall canary).

    ``clock`` is injected for the age watermark and the tests; the
    background worker (``start()``) polls ``run_once`` on the real wall
    clock and exists for deployments — tests drive :meth:`run_once`
    directly, with no sleeps.
    """

    def __init__(self, mutable: MutableIndex, *, publisher=None,
                 name: str | None = None, ks=(10,),
                 policy: CompactionPolicy = CompactionPolicy(),
                 warm_data=None, drift=None, pacing=None,
                 clock: Callable[[], float] | None = None,
                 poll_interval_s: float = 0.05):
        expects(publisher is None or hasattr(publisher, "publish"),
                "publisher must expose publish() (SearchService or "
                "IndexRegistry)")
        expects(publisher is None or name is not None,
                "a publisher needs the published name")
        self._mutable = mutable
        # a sharded index picks WHICH shard to fold from the tripped
        # watermark (an age trip must chase the stalest shard, not the
        # fullest — starvation otherwise); plain MutableIndex.compact has
        # no such choice and takes no trigger
        import inspect

        self._compact_takes_trigger = (
            "trigger" in inspect.signature(mutable.compact).parameters)
        self._publisher = publisher
        self._pub_name = name
        self._ks = (ks,) if isinstance(ks, int) else tuple(ks)
        self.policy = policy
        self._warm_data = warm_data
        expects(drift is None or hasattr(drift, "check"),
                "drift must be an obs.quality.DriftDetector (check())")
        self._drift = drift
        # external pacing hint (zero-arg callable -> truthy = defer):
        # wired by a controller feeding its SLO-burn signal so a due fold
        # waits out a latency burn (run_once; force= overrides). Default
        # None = scheduling behavior unchanged.
        expects(pacing is None or callable(pacing),
                "pacing must be a zero-arg callable returning truthy to "
                "defer (e.g. control.Controller wires one)")
        self._pacing = pacing
        self.last_deferred: str | None = None
        # default to the MUTABLE's clock: the age watermark subtracts this
        # clock's now from delta_oldest_at stamps taken with the mutable's —
        # two different time bases would silently disable (or constantly
        # trip) max_age_s
        self._clock = mutable._clock if clock is None else clock
        self._poll_s = float(poll_interval_s)
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        self.last_report: dict | None = None
        self.last_error: BaseException | None = None
        # the standing reshard advisory lives in the event journal's
        # transition store (keyed per instance); last_advice below is a
        # thin view over it — the counter/WARNING emit once per
        # transition, dedup owned by the journal
        self._advice_tkey = ("compactor/reshard_advice",
                             next(_compactor_ids))

    # -- pacing --------------------------------------------------------------
    def set_pacing(self, fn) -> None:
        """(Re)wire the external pacing hint after construction — what
        :meth:`raft_tpu.control.Controller.attach_compactor` calls.
        ``None`` unwires it (default scheduling restored)."""
        expects(fn is None or callable(fn),
                "pacing must be a zero-arg callable or None")
        self._pacing = fn

    def _defer(self) -> bool:
        if self._pacing is None:
            return False
        try:
            return bool(self._pacing())
        except Exception:  # a broken hint must never stall compaction
            return False

    # -- watermarks ---------------------------------------------------------
    def due(self) -> str | None:
        """The tripped watermark name, or None. Priority order: reclaim
        (rebuild) beats fold (extend) beats freshness — a rebuild subsumes
        the other two anyway."""
        p = self.policy
        st = self._mutable.stats()
        if (p.tombstone_ratio is not None
                and st["tombstone_ratio"] >= p.tombstone_ratio
                and self._mutable.can_rebuild):
            return "tombstone_ratio"
        if (p.delta_fill is not None and st["delta_fill"] >= p.delta_fill):
            return "delta_fill"
        if (p.max_age_s is not None and st["delta_oldest_at"] is not None
                and self._clock() - st["delta_oldest_at"] >= p.max_age_s):
            return "age"
        return None

    @property
    def last_advice(self) -> dict | None:
        """The STANDING reshard advisory — a dict while a topology
        watermark stays crossed, None once it clears. A thin view over
        the event journal's transition store
        (:meth:`raft_tpu.obs.events.EventJournal.transition_payload`),
        so it survives ring eviction and stays consistent with the
        ``reshard_advised`` / ``reshard_advice_cleared`` events."""
        return obs_events.transition_payload(self._advice_tkey)

    def _check_reshard(self) -> dict | None:
        """Evaluate the advisory topology watermarks (see
        :class:`CompactionPolicy`): updates the journal-backed
        :attr:`last_advice` — a STANDING advisory while a mark stays
        crossed, None once it clears — emitting the ``reshard_advised``
        event (journal entry + counter + WARNING, atomically) exactly
        once per transition; the dedup is the journal's. Only meaningful
        for an index that can actually reshard (a sharded mesh);
        silently None otherwise."""
        p = self.policy
        if (p.reshard_rows_per_shard is None
                and p.reshard_min_rows_per_shard is None):
            return None
        if not hasattr(self._mutable, "reshard"):
            return None
        st = self._mutable.stats()
        shards = st.get("shards")
        if not shards:
            return None
        per = st["live"] / shards
        advice = None
        if (p.reshard_rows_per_shard is not None
                and per >= p.reshard_rows_per_shard):
            advice = {"action": "split", "target": 2 * shards,
                      "watermark": "reshard_rows_per_shard",
                      "threshold": p.reshard_rows_per_shard}
        elif (p.reshard_min_rows_per_shard is not None and shards > 1
                and shards % 2 == 0  # reshard() only halves even counts —
                # advising an unreachable target would send a controller
                # into a refusal loop
                and per <= p.reshard_min_rows_per_shard):
            advice = {"action": "merge", "target": shards // 2,
                      "watermark": "reshard_min_rows_per_shard",
                      "threshold": p.reshard_min_rows_per_shard}
        key = ((advice["action"], advice["target"])
               if advice is not None else None)
        # the payload carries the full measured watermark evidence inline
        # (live rows, shard count, per-shard mean AND the crossed
        # threshold): a controller decides — and a postmortem replays —
        # from the journal alone, re-probing nothing
        payload = None if advice is None else dict(
            advice, name=self._mutable.name, shards=shards,
            live=int(st["live"]),
            rows_per_shard=round(per, 1), auto_apply=False)
        if not obs_events.transition(self._advice_tkey, key, payload):
            return self.last_advice
        if advice is None:
            obs_events.emit(
                "reshard_advice_cleared",
                subject=("compactor", self._mutable.name, None, None),
                evidence={"shards": shards,
                          "rows_per_shard": round(per, 1)})
            return None
        obs_events.emit(
            "reshard_advised",
            subject=("compactor", self._mutable.name, None, None),
            evidence=payload,
            counter=_c_reshard_advised,
            counter_labels={"name": self._mutable.name,
                            "action": advice["action"]},
            message=(
                "reshard advised for %r: %s to %d shards (%.0f live "
                "rows/shard crossed %s=%d); advisory only — call "
                "reshard(%d) to apply"),
            log_args=(self._mutable.name, advice["action"],
                      advice["target"], per, advice["watermark"],
                      advice["threshold"], advice["target"]))
        return self.last_advice

    # -- one compaction cycle ----------------------------------------------
    def run_once(self, *, force: bool = False, mode: str | None = None,
                 res=None) -> dict | None:
        """Check watermarks and run one fold+swap(+publish) if due; returns
        the compaction report (with ``trigger`` and, when publishing, the
        publish report under ``publish``) or None when nothing was due.
        ``force=True`` compacts regardless; ``mode`` overrides the
        trigger's fold mode."""
        trigger = self.due()
        # topology advisory rides every poll, due or not: a mesh that
        # outgrew its shard count keeps folding without relief — the
        # advice must not wait for a compaction watermark to also trip
        advice = self._check_reshard()
        if trigger is None:
            if not force:
                return None
            trigger = "forced"
        elif not force and self._defer():
            # a due fold waits out the pacing signal (a controller's SLO
            # latency burn); the tripped watermark stays tripped and the
            # next poll retries — reclaim is deferred, never lost
            self.last_deferred = trigger
            if metrics._enabled:
                _c_deferred().inc(1, name=self._mutable.name,
                                  trigger=trigger)
            return None
        if mode is None:
            mode = "rebuild" if trigger == "tombstone_ratio" else "auto"
        from ..obs import compile as obs_compile

        name = self._mutable.name
        obs_events.emit("compaction_started",
                        subject=("compactor", name, None, None),
                        evidence={"trigger": trigger, "mode": mode})
        t0 = time.perf_counter()
        with obs_compile.attribution() as rec:
            kw = {"trigger": trigger} if self._compact_takes_trigger else {}
            report = self._mutable.compact(mode=mode, res=res, **kw)
            report["trigger"] = trigger
            if self._publisher is not None:
                # publish AFTER the swap: the registry warms the new epoch's
                # searcher at every bucket BEFORE flipping its pointer, so
                # the serving hot path never sees a cold program; in-flight
                # leases drain on the pre-compaction epoch's hook
                report["publish"] = self._publisher.publish(
                    self._pub_name, self._mutable.searcher(),
                    k=self._ks, warm_data=self._warm_data)
                if metrics._enabled:
                    _c_swaps().inc(1, name=name)
        wall = time.perf_counter() - t0
        report["wall_s"] = round(wall, 3)
        report["compile_s"] = round(rec.compile_s, 3)
        if advice is not None:
            report["reshard_advised"] = advice
        if self._drift is not None:
            # compaction-time corpus stats: the retained store is the live
            # corpus' raw rows (the classifier subsamples internally; a few
            # not-yet-reclaimed tombstoned rows are noise at the CV's
            # decision margins; a sharded index hands back a cross-shard
            # interleave). No store → the corpus side cannot classify; the
            # query-side canary feed still covers the pin.
            store = self._mutable._drift_store()
            if store is not None:
                report["drift"] = self._drift.check(
                    rows=store, n_rows=max(self._mutable.size, 1),
                    dim=self._mutable.dim, source="compaction")
        if metrics._enabled:
            _c_compactions().inc(1, name=name, trigger=trigger,
                                 mode=report["mode"])
            _h_wall().observe(wall, name=name)
            if rec.compile_s:
                _c_compile().inc(rec.compile_s, name=name)
        obs_events.emit(
            "compaction_completed",
            subject=("compactor", name, None, None),
            evidence={"trigger": trigger, "mode": report["mode"],
                      "wall_s": report["wall_s"],
                      "compile_s": report["compile_s"],
                      "published": "publish" in report})
        self.last_report = report
        return report

    # -- background worker --------------------------------------------------
    def start(self) -> "Compactor":
        """Start the background poll loop (idempotent). A worker that a
        timed-out close() left draining is reaped here once it exits; while
        it is still alive, clearing the stop flag resumes it instead of
        spawning a second concurrent poller."""
        if self._worker is not None and not self._worker.is_alive():
            self._worker = None
        self._stop.clear()  # resumes a still-draining worker too
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._run, name=f"raft-compactor-{self._mutable.name}",
                daemon=True)
            self._worker.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            try:
                self.run_once()
                self.last_error = None
            except Exception as e:  # keep the loop alive, but NEVER
                # silently: a misconfigured fold (e.g. a tombstone trigger
                # without rebuild inputs) would otherwise retry every poll
                # forever while writers march toward DeltaFullError
                first = not isinstance(self.last_error, type(e))
                self.last_error = e
                if metrics._enabled:
                    _c_failures().inc(1, name=self._mutable.name)
                if first:  # emit once per failure kind, not per poll tick
                    obs_events.emit(
                        "compaction_failed",
                        subject=("compactor", self._mutable.name,
                                 None, None),
                        evidence={"error": repr(e),
                                  "poll_s": self._poll_s},
                        message=(
                            "compaction of %r failed (will keep retrying "
                            "every %.2fs; see Compactor.last_error): %s"),
                        log_args=(self._mutable.name, self._poll_s, e))

    def close(self, timeout_s: float = 30.0) -> None:
        """Stop the worker (a fold in flight finishes first). Idempotent.
        If the join times out (a fold longer than ``timeout_s``), the worker
        handle is KEPT so a later ``start()`` cannot spawn a second
        concurrent poller next to the still-draining one — call close()
        again (or with a larger timeout) to finish the drain."""
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout_s)
            if not self._worker.is_alive():
                self._worker = None
