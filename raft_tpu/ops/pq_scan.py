"""Fused Pallas LUT-scan kernel for IVF-PQ (the reference's hottest kernel).

The reference's `compute_similarity` keeps the per-(query, probe) LUT in smem
and each thread gathers LUT[s, code_s] at full shared-memory throughput
(cpp/include/raft/neighbors/detail/ivf_pq_compute_similarity-inl.cuh, launched
from ivf_pq_search.cuh:419-557). A TPU has no smem gather, so rounds 1-3
re-expressed the gather as a one-hot MXU contraction — correct, but it
synthesizes a (T, pc, cap, pq_dim*K) one-hot operand through HBM. An
XLA-level compare+select chain (`ivf_pq._select_scores`) measured 2x SLOWER
still (24.6k vs 46.4k QPS at 1M): XLA materializes each pass of the 16-step
chain instead of keeping it register-resident.

This kernel hand-schedules that sweep as the TPU analogue of ScaNN's SIMD
LUT16 shuffle:

- codes stream as int8 planes (32-64 bytes per candidate instead of the
  one-hot's 1-2 KB) and are PACKED so the lane dimension is full 128-wide:
  for pq_dim=64, two candidates share one lane row ((cap, 64) viewed as
  (cap/2, 128) — a free reshape in HBM; a 64-lane array would waste half of
  every VPU op in 128-lane vregs);
- the LUT block (lane-tiled to the packed width) stays resident in VMEM;
- the gather itself is ONE hardware op per (16, lanes) tile:
  ``tpu.dynamic_gather`` (Mosaic's lowering of a same-shape 2D
  take_along_axis) — the literal TPU LUT16 shuffle. Two earlier variants
  measured and rejected: a 16-pass compare+select chain (~48 whole-array VPU
  passes — Mosaic executes op-at-a-time, so the chain streams the
  accumulator through VMEM) and the XLA one-hot contraction (HBM-streamed
  operand);
- per-candidate-half partial sums come from masked lane reductions, emitted
  as a (pack, bt, capb) output the XLA caller de-interleaves (cheap).

Scores are raw Σ_s LUT[s, code_s]; bias/consts/±inf masking stay in the XLA
epilogue (cheap: (T, pc, cap) elementwise, ~40 KB/query).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._compat import CompilerParams as _CompilerParams

__all__ = ["pq_lut_scan", "pq_scan_backend_ok"]


def pq_scan_backend_ok():
    """(may_run, interpret): Mosaic on TPU, or interpret mode opted into for
    tests via RAFT_TPU_PQ_SCAN_INTERPRET=1 (same contract as fused_knn)."""
    import os

    on_tpu = jax.default_backend() == "tpu"
    interpret_ok = os.environ.get(
        "RAFT_TPU_PQ_SCAN_INTERPRET", "").lower() in ("1", "true", "yes")
    return on_tpu or interpret_ok, not on_tpu


def _make_kernel(split: bool, bt: int, capb: int, lanes: int, s: int,
                 pack: int):
    """capb = packed candidate rows per block; lanes = s*pack."""

    def kernel(*refs):
        if split:
            hi_ref, lo_ref, lut_ref, out_ref, g_ref = refs
        else:
            code_ref, lut_ref, out_ref, g_ref = refs
        # selector for the per-half lane sums: sel[h, l] = 1 iff lane l
        # belongs to candidate-half h; M padded to >= 8 sublanes for the MXU
        mrows = max(8, pack)
        lane = jax.lax.broadcasted_iota(jnp.int32, (mrows, lanes), 1)
        half = jax.lax.broadcasted_iota(jnp.int32, (mrows, lanes), 0)
        sel = ((lane // s) == half).astype(jnp.float32)

        def lut16(idx_ref, b, rows, t_lo, t_hi):
            # the hardware LUT16 in two halves: tpu.dynamic_gather (Mosaic's
            # lowering of a same-shape 2D take_along_axis) shuffles one
            # source vreg, i.e. 8 f32 sublanes — so the 16-entry table is
            # split into two (8, lanes) halves, gathered with the masked
            # index, and recombined on bit 3. ~7 ops per (8, lanes) tile vs
            # ~48 whole-array passes for a compare+select chain (measured
            # slower than even the one-hot MXU path).
            idx = idx_ref[b, rows, :].astype(jnp.int32)
            lo_bits = idx & 7
            g_lo = jnp.take_along_axis(t_lo, lo_bits, axis=0,
                                       mode="promise_in_bounds")
            g_hi = jnp.take_along_axis(t_hi, lo_bits, axis=0,
                                       mode="promise_in_bounds")
            return jnp.where(idx < 8, g_lo, g_hi)

        for b in range(bt):
            lut = lut_ref[b].astype(jnp.float32)  # (K, lanes), VMEM-resident
            tables = [(lut[0:8], lut[8:16])]
            if split:
                tables.append((lut[16:24], lut[24:32]))
            for j in range(capb // 8):
                rows = slice(j * 8, (j + 1) * 8)
                g = lut16(hi_ref if split else code_ref, b, rows, *tables[0])
                if split:
                    g = g + lut16(lo_ref, b, rows, *tables[1])
                g_ref[rows, :] = g
            # both half-sums in ONE MXU contraction over the lane dim —
            # masked lane reductions per tile measured ~2x the gather cost
            mm = jax.lax.dot_general(
                sel, g_ref[...], (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,  # bf16 would round g
                preferred_element_type=jnp.float32)  # (8, capb)
            out_ref[:, b, :] = mm[:pack]

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("bt", "capb", "pack", "interpret"))
def _pq_scan_impl(codes_hi, codes_lo, lut, bt: int, capb: int,
                  pack: int, interpret: bool):
    """codes_*: (B, capP, lanes) int8 packed planes; lut: (B, K, lanes)
    lane-tiled. Returns (pack, B, capP) f32 partial scores (pack = lanes//S
    candidate interleave)."""
    B, capP, lanes = codes_hi.shape
    K = lut.shape[1]
    split = codes_lo is not None
    Bp = -(-B // bt) * bt
    capp = -(-capP // capb) * capb
    pad3 = ((0, Bp - B), (0, capp - capP), (0, 0))
    ch = jnp.pad(codes_hi, pad3)
    cl = jnp.pad(codes_lo, pad3) if split else None
    lp = jnp.pad(lut, ((0, Bp - B), (0, 0), (0, 0)))
    grid = (Bp // bt, capp // capb)
    code_spec = pl.BlockSpec((bt, capb, lanes), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _make_kernel(split, bt, capb, lanes, lanes // pack, pack),
        grid=grid,
        in_specs=[code_spec] + ([code_spec] if split else []) + [
            pl.BlockSpec((bt, K, lanes), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((pack, bt, capb), lambda i, j: (0, i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((pack, Bp, capp), jnp.float32),
        scratch_shapes=[pltpu.VMEM((capb, lanes), jnp.float32)],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*([ch, cl, lp] if split else [ch, lp]))
    return out[:, :B, :capP]


def pq_lut_scan(codes, lut, codes_lo=None, *, bt: int = 32,
                capb: int | None = None, interpret: bool = False):
    """Σ_s LUT[s, code_s] for every (batch row, candidate).

    ``codes``: (B, cap, S) int8 values in [0, 16) — the stage-1 (or only)
    code plane. ``codes_lo``: optional stage-2 plane (nibble-split pq8).
    ``lut``: (B, K, S) float (K = 16 single-stage, 32 split; any float dtype
    — cast to f32 in-kernel). Returns (B, cap) f32.
    """
    from ..core.errors import expects

    B, cap, S = codes.shape
    expects(lut.shape[0] == B and lut.shape[2] == S,
            "lut must be (B, K, S) matching codes (B, cap, S)")
    expects(lut.shape[1] == (32 if codes_lo is not None else 16),
            "lut K must be 16 (single-stage) or 32 (split with codes_lo)")
    # Mosaic requires the lane (last) dim be 128-aligned: pad S up to the
    # next divisor of 128 (S < 128) or multiple of 128 (S > 128) with
    # zero-valued LUT columns — pad lanes gather lut[0, pad] == 0 and add
    # nothing to the sum, so scores are exact. (A raw S like 96 or 24,
    # reachable via pq_bits=4 builds, would otherwise hit an opaque Mosaic
    # lowering failure that interpret-mode tests cannot catch.)
    if 128 % S != 0:
        Sp = 1 << (S - 1).bit_length() if S < 128 else -(-S // 128) * 128
        zpad = ((0, 0), (0, 0), (0, Sp - S))
        codes = jnp.pad(codes, zpad)
        if codes_lo is not None:
            codes_lo = jnp.pad(codes_lo, zpad)
        lut = jnp.pad(lut, zpad)
        S = Sp
    pack = 128 // S if 128 % S == 0 else 1
    capP = -(-cap // pack)
    lanes = S * pack

    def packit(c):
        if pack == 1:
            return c
        padded = jnp.pad(c, ((0, 0), (0, capP * pack - cap), (0, 0)))
        return padded.reshape(B, capP, lanes)  # free: contiguous in HBM

    ch = packit(codes)
    cl = packit(codes_lo) if codes_lo is not None else None
    lt = jnp.tile(lut, (1, 1, pack))  # lane-tiled LUT
    if capb is None:
        capb = -(-capP // 16) * 16 if capP <= 1024 else 512
    capb = max(16, min(capb, -(-capP // 16) * 16))
    capb = -(-capb // 8) * 8  # whole (8, lanes) gather tiles
    out = _pq_scan_impl(ch, cl, lt, bt, int(capb), pack, interpret)
    # de-interleave: candidate pack*row + h lives at out[h, :, row]
    scores = jnp.moveaxis(out, 0, 2).reshape(B, capP * pack)
    return scores[:, :cap]
