"""Fused CAGRA hop kernel: the whole per-hop beam update in ONE Pallas pass.

The r04 hop study (BASELINE.md "Round-4 CAGRA hop study" + addendum)
decomposed the 1M batch-synchronous search into ~0.27 us/query/hop of
expansion scoring (the vector gather — which XLA's gather engine serves at
~60 GB/s effective on overlapping beam frontiers, 15x the isolated per-row
DMA rate, so an in-kernel `make_async_copy` gather CANNOT win) and
~0.46 us/query of "everything else": ~20 op-at-a-time XLA passes over the
(m, itopk+deg) beam-state arrays per hop, none individually hot — dispatch
and small-op latency, not bandwidth. This kernel attacks exactly that term,
the way the reference's persistent SINGLE_CTA kernel keeps its itopk queue
in registers/smem (detail/cagra/search_single_cta.cuh): the two gathers
(graph row, vectors) stay in XLA where they are fastest, and EVERYTHING
between them — candidate scoring, dedup against the beam, the
beam-merge selection, visited bookkeeping, and the next hop's pick —
runs in one kernel launch with all beam state resident in VMEM.

Per hop the XLA level does exactly three ops: graph-row gather, vector
gather, this kernel. Beam state crosses HBM once per hop instead of ~20
times, and 20 op dispatches collapse into 1.

Layout: beam arrays are (m, 128)-padded (lanes >= itopk carry the empty
sentinel) so every in-kernel op is full-lane-width; the merge pool packs
[beam | candidates | pad] into the same 128 lanes with static slice writes.
Selection is ascending iterative extraction with lowest-id tie-breaks
(matching the XLA path's two-sort dedup semantics); candidate ids already
present in the beam are masked before the merge (the beam's copy of a node
carries the identical exact distance, so keeping it is equivalent).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["cagra_hop", "hop_backend_ok", "hop_shapes_eligible"]

_POOL = 128               # merge pool lanes: itopk + deg must fit
_BIG = 2 ** 30
_INF = jnp.inf


def hop_backend_ok():
    """(may_run, interpret): Mosaic on TPU, or interpret mode opted into for
    tests via RAFT_TPU_CAGRA_HOP_INTERPRET=1 (same contract as fused_knn)."""
    import os

    on_tpu = jax.default_backend() == "tpu"
    interpret_ok = os.environ.get(
        "RAFT_TPU_CAGRA_HOP_INTERPRET", "").lower() in ("1", "true", "yes")
    return on_tpu or interpret_ok, not on_tpu


def hop_shapes_eligible(itopk: int, deg: int, width: int, d: int) -> bool:
    """The fused hop supports the single-pick beam (search_width=1 — the
    default and the only width the r04 profile measured) with the merge pool
    inside one 128-lane register row."""
    return width == 1 and itopk + deg <= _POOL and itopk >= 1 and d <= 4096


def _make_hop_kernel(itopk: int, deg: int, qt: int, dp: int,
                     profile: str = "full"):
    """``profile`` carves phases out for the in-kernel profile
    (bench/cagra_hop_profile.py): "full", "noscore" (skip the distance
    computation), "nodedup" (skip the beam-membership masks), "nomerge"
    (skip dedup+extraction — beam passes through, pick still computed)."""
    def kernel(q_ref, bd_ref, bi_ref, bv_ref, nbr_ref, vec_ref, valid_ref,
               nbd_ref, nbi_ref, nbv_ref, pick_ref, nocand_ref,
               pd_ref, pi_ref, pv_ref):
        lane = jax.lax.broadcasted_iota(jnp.int32, (qt, _POOL), 1)

        # ---- candidate scoring: ||v - q||^2, (qt, deg) ----
        nbr = nbr_ref[...]                   # (qt, deg) int32
        if profile == "noscore":
            nd = jnp.abs(nbr).astype(jnp.float32)  # fake but well-formed
        else:
            q = q_ref[...]                   # (qt, dp)
            vecs = vec_ref[...]              # (qt, deg, dp)
            diff = vecs - q[:, None, :]
            nd = jnp.sum(diff * diff, axis=-1)   # (qt, deg)
        ok = (nbr >= 0) & (valid_ref[...] > 0)          # (qt, deg) & (qt, 1)
        nd = jnp.where(ok, nd, _INF)

        # ---- dedup vs the beam: a candidate already in the beam carries
        # the identical exact distance there — drop the new copy ----
        bi = bi_ref[...]                     # (qt, _POOL)
        if profile == "nomerge":
            nbd_ref[...] = bd_ref[...]
            nbi_ref[...] = bi
            nbv_ref[...] = bv_ref[...]
            _emit_pick(itopk, qt, lane, nbd_ref, nbi_ref, nbv_ref,
                       pick_ref, nocand_ref)
            return
        if profile != "nodedup":
            for b in range(itopk):
                nd = jnp.where(nbr == bi[:, b:b + 1], _INF, nd)

        # ---- merge pool: [beam | candidates | +inf pad], one row ----
        pd_ref[...] = bd_ref[...]
        pi_ref[...] = bi
        pv_ref[...] = bv_ref[...]
        pd_ref[:, itopk:itopk + deg] = nd
        pi_ref[:, itopk:itopk + deg] = nbr
        pv_ref[:, itopk:itopk + deg] = jnp.zeros((qt, deg), jnp.int32)
        pd_ref[:, itopk + deg:] = jnp.full((qt, _POOL - itopk - deg), _INF,
                                           jnp.float32)
        pi_ref[:, itopk + deg:] = jnp.full((qt, _POOL - itopk - deg), -1,
                                           jnp.int32)
        pv_ref[:, itopk + deg:] = jnp.ones((qt, _POOL - itopk - deg),
                                           jnp.int32)

        # ---- ascending extraction with lowest-id ties: the in-VMEM form of
        # the XLA path's lexsort+sort dedup merge ----
        nbd_ref[...] = jnp.full((qt, _POOL), _INF, jnp.float32)
        nbi_ref[...] = jnp.full((qt, _POOL), -1, jnp.int32)
        nbv_ref[...] = jnp.ones((qt, _POOL), jnp.int32)
        for t in range(itopk):
            pdv = pd_ref[...]
            mn = jnp.min(pdv, axis=1, keepdims=True)
            sel = pdv <= mn                          # winners incl. ties
            amid = jnp.min(jnp.where(sel, pi_ref[...], _BIG), axis=1,
                           keepdims=True)
            hit = (pi_ref[...] == amid) & sel
            wv = jnp.min(jnp.where(hit, pv_ref[...], _BIG), axis=1,
                         keepdims=True)
            nbd_ref[:, t] = mn[:, 0]
            nbi_ref[:, t] = jnp.where(mn[:, 0] < _INF, amid[:, 0], -1)
            nbv_ref[:, t] = jnp.minimum(wv[:, 0], 1)
            # mask every copy of the chosen id (kills in-row duplicates too)
            pd_ref[...] = jnp.where(pi_ref[...] == amid, _INF, pdv)

        _emit_pick(itopk, qt, lane, nbd_ref, nbi_ref, nbv_ref,
                   pick_ref, nocand_ref)

    return kernel


def _emit_pick(itopk, qt, lane, nbd_ref, nbi_ref, nbv_ref, pick_ref,
               nocand_ref):
    """Next pick: best unvisited in the itopk window; mark it visited."""
    nbd = nbd_ref[...]
    nbv = nbv_ref[...]
    cd = jnp.where((nbv > 0) | (lane >= itopk), _INF, nbd)
    mn = jnp.min(cd, axis=1, keepdims=True)
    nocand = (mn >= _INF).astype(jnp.int32)
    sel = cd <= mn
    pick_id = jnp.min(jnp.where(sel, nbi_ref[...], _BIG), axis=1,
                      keepdims=True)
    nbv_ref[...] = jnp.where(
        (nbi_ref[...] == pick_id) & (nocand == 0), 1, nbv)
    pick_ref[...] = jnp.clip(pick_id, 0, _BIG)
    nocand_ref[...] = nocand


@functools.partial(jax.jit, static_argnames=("itopk", "deg", "qt", "interpret",
                                             "profile"))
def cagra_hop(queries, beam_d, beam_i, beam_v, nbrs, vecs, valid,
              itopk: int, deg: int, qt: int = 128, interpret: bool = False,
              profile: str = "full"):
    """One fused CAGRA hop over the whole query batch.

    ``queries`` (m, d) f32; ``beam_d/beam_i/beam_v`` (m, 128) padded beam
    state (distances f32 ascending, ids i32, visited i32; lanes >= itopk are
    +inf/-1/1); ``nbrs`` (m, deg) i32 candidate ids (-1 = none); ``vecs``
    (m, deg, d) their vectors; ``valid`` (m, 1) i32 — 0 masks this hop's
    candidates (used to prime the loop and after convergence).

    Returns (beam_d, beam_i, beam_v, pick (m, 1) i32 clipped >= 0,
    no_cand (m, 1) i32).
    """
    m, d = queries.shape
    dp = -(-d // 128) * 128
    mp = -(-m // qt) * qt
    pad_rows = mp - m

    def prow(x, fill=0):
        return jnp.pad(x, ((0, pad_rows),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill) if pad_rows else x

    qp = prow(jnp.pad(queries, ((0, 0), (0, dp - d))) if dp > d else queries)
    vp = prow(jnp.pad(vecs, ((0, 0), (0, 0), (0, dp - d)))
              if dp > d else vecs)
    args = (qp, prow(beam_d, _INF), prow(beam_i, -1), prow(beam_v, 1),
            prow(nbrs, -1), vp, prow(valid))
    grid = (mp // qt,)
    spec2 = lambda w: pl.BlockSpec((qt, w), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        _make_hop_kernel(itopk, deg, qt, dp, profile),
        grid=grid,
        in_specs=[spec2(dp), spec2(_POOL), spec2(_POOL), spec2(_POOL),
                  spec2(deg),
                  pl.BlockSpec((qt, deg, dp), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
                  spec2(1)],
        out_specs=[spec2(_POOL), spec2(_POOL), spec2(_POOL), spec2(1),
                   spec2(1)],
        out_shape=[
            jax.ShapeDtypeStruct((mp, _POOL), jnp.float32),
            jax.ShapeDtypeStruct((mp, _POOL), jnp.int32),
            jax.ShapeDtypeStruct((mp, _POOL), jnp.int32),
            jax.ShapeDtypeStruct((mp, 1), jnp.int32),
            jax.ShapeDtypeStruct((mp, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((qt, _POOL), jnp.float32),   # merge pool distances
            pltpu.VMEM((qt, _POOL), jnp.int32),     # merge pool ids
            pltpu.VMEM((qt, _POOL), jnp.int32),     # merge pool visited
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*args)
    return tuple(o[:m] for o in outs)
