"""Fused CAGRA hop kernel: the whole per-hop beam update in ONE Pallas pass.

The r04 hop study (BASELINE.md "Round-4 CAGRA hop study" + addendum)
decomposed the 1M batch-synchronous search into ~0.27 us/query/hop of
expansion scoring (the vector gather — which XLA's gather engine serves at
~60 GB/s effective on overlapping beam frontiers, 15x the isolated per-row
DMA rate, so an in-kernel `make_async_copy` gather CANNOT win) and
~0.46 us/query of "everything else": ~20 op-at-a-time XLA passes over the
(m, itopk+deg) beam-state arrays per hop, none individually hot — dispatch
and small-op latency, not bandwidth. This kernel attacks exactly that term,
the way the reference's persistent SINGLE_CTA kernel keeps its itopk queue
in registers/smem (detail/cagra/search_single_cta.cuh): the two gathers
(graph row, vectors) stay in XLA where they are fastest, and EVERYTHING
between them — candidate scoring, dedup against the beam, the
beam-merge selection, visited bookkeeping, and the next hop's pick —
runs in one kernel launch with all beam state resident in VMEM.

Per hop the XLA level does exactly three ops: graph-row gather, vector
gather, this kernel. Beam state crosses HBM once per hop instead of ~20
times, and 20 op dispatches collapse into 1.

Layout: beam arrays are (m, 128)-padded (lanes >= itopk carry the empty
sentinel) so every in-kernel op is full-lane-width; the merge pool packs
[beam | candidates | pad] into the same 128 lanes with static slice writes.
Selection is ascending iterative extraction with lowest-id tie-breaks
(matching the XLA path's two-sort dedup semantics); candidate ids already
present in the beam are masked before the merge (the beam's copy of a node
carries the identical exact distance, so keeping it is equivalent).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._compat import CompilerParams as _CompilerParams

__all__ = ["cagra_hop", "hop_backend_ok", "hop_shapes_eligible"]

_POOL = 128               # merge pool lanes: itopk + deg must fit
_BIG = 2 ** 30
_INF = jnp.inf
_NEG = -3.0e38            # finite sentinel for masked maxima


def hop_backend_ok():
    """(may_run, interpret): Mosaic on TPU, or interpret mode opted into for
    tests via RAFT_TPU_CAGRA_HOP_INTERPRET=1 (same contract as fused_knn)."""
    import os

    on_tpu = jax.default_backend() == "tpu"
    interpret_ok = os.environ.get(
        "RAFT_TPU_CAGRA_HOP_INTERPRET", "").lower() in ("1", "true", "yes")
    return on_tpu or interpret_ok, not on_tpu


# VMEM budget for the staged candidate-vector block (of the kernel's 100MB
# vmem_limit_bytes, leaving headroom for the beam-state blocks and scratch)
_HOP_VMEM_BUDGET = 80 * 1024 * 1024


def hop_shapes_eligible(itopk: int, deg: int, width: int, d: int,
                        itemsize: int = 4) -> bool:
    """The fused hop supports any search_width whose merge pool
    (itopk + width*degree candidates) fits one 128-lane register row AND
    whose staged d-scaled blocks fit the VMEM budget: the kernel stages a
    (qt=128, width*deg, d_pad) candidate block of the dataset's dtype
    (``itemsize`` bytes/element — 1 for byte datasets, which are upcast
    in-kernel) plus a (qt, d_pad) f32 query tile, both double-buffered by
    the Pallas pipeline. Bounding by
    estimated bytes instead of a flat ``d <= 4096`` cap means
    ``hop_impl='auto'`` falls back to the XLA loop for large-d configs
    (e.g. itopk=32, deg=32, d=4096 f32: ~67MB/block, >100MB double-buffered)
    instead of failing at compile (ADVICE r5)."""
    if not (width >= 1 and itopk + width * deg <= _POOL and itopk >= 1
            and d >= 1):
        return False
    d_pad = -(-d // 128) * 128
    vec_bytes = 128 * width * deg * d_pad * itemsize
    q_bytes = 128 * d_pad * 4  # f32 query tile, also double-buffered
    return 2 * (vec_bytes + q_bytes) <= _HOP_VMEM_BUDGET


def _make_hop_kernel(itopk: int, cw: int, width: int, qt: int, dp: int,
                     profile: str = "full", merge: str = "extract"):
    """``profile`` carves phases out for the in-kernel profile
    (bench/cagra_hop_profile.py): "full", "noscore" (skip the distance
    computation), "nodedup" (skip the beam-membership masks), "nomerge"
    (skip dedup+extraction — beam passes through, pick still computed),
    "nogate" (arena merges only: run the insertion loop UNGATED — the
    full-vs-nogate delta is the threshold gate's measured worth).
    ``merge``: "extract" (itopk ascending-extraction passes; beam stays
    sorted), "arena" (threshold-gated insertion into an unsorted arena —
    the caller sorts once after the loop; r06 form, gate carried in a
    register and candidate scores carried as loop values), or "arena_smem"
    (the r05 arena: gate handshake through SMEM, candidate pool stashed in
    VMEM scratch and re-read per candidate — kept verbatim as the A/B
    control for the r06 iteration)."""
    def kernel(q_ref, bd_ref, bi_ref, bv_ref, nbr_ref, vec_ref, valid_ref,
               nbd_ref, nbi_ref, nbv_ref, pick_ref, nocand_ref,
               pd_ref, pi_ref, pv_ref, go_ref):
        lane = jax.lax.broadcasted_iota(jnp.int32, (qt, _POOL), 1)

        # ---- candidate scoring: direct ||v - q||^2, (qt, cw). The
        # expanded ||v||^2 - 2 q.v form was tried and measured WORSE (r05):
        # gathering ||v||^2 per candidate doubles the hop's random-gather
        # count (4 B norm gathers are as latency-bound as the 512 B rows)
        # and costs far more than the one VPU pass it saves (arena
        # 38k -> 28.5k QPS at 1M).
        nbr = nbr_ref[...]                   # (qt, cw) int32
        if profile == "noscore":
            nd = jnp.abs(nbr).astype(jnp.float32)  # fake but well-formed
        else:
            q = q_ref[...]                   # (qt, dp)
            # byte datasets arrive as int8 (a quarter of the f32 DMA bytes
            # — the hop's vector traffic) and upcast HERE, at the tile
            # level; 8-bit integers are exact in f32, so the s8 path's
            # distances match the f32 path's bitwise
            vecs = vec_ref[...].astype(jnp.float32)  # (qt, cw, dp)
            diff = vecs - q[:, None, :]
            nd = jnp.sum(diff * diff, axis=-1)   # (qt, cw)
        # valid is per-candidate (the XLA side expands the per-pick flags
        # over each pick's deg candidates)
        ok = (nbr >= 0) & (valid_ref[...] > 0)          # (qt, cw)
        nd = jnp.where(ok, nd, _INF)

        # ---- dedup vs the beam: a candidate already in the beam carries
        # the identical exact distance there — drop the new copy ----
        bi = bi_ref[...]                     # (qt, _POOL)
        if profile == "nomerge":
            nbd_ref[...] = bd_ref[...]
            nbi_ref[...] = bi
            nbv_ref[...] = bv_ref[...]
            _emit_pick(itopk, width, qt, lane, nbd_ref, nbi_ref, nbv_ref,
                       pick_ref, nocand_ref)
            return
        arena = merge in ("arena", "arena_smem") and profile in ("full",
                                                                 "nogate")
        if profile != "nodedup" and not arena:
            for b in range(itopk):
                nd = jnp.where(nbr == bi[:, b:b + 1], _INF, nd)

        if arena:
            # ---- threshold-gated arena merge: the beam is an UNSORTED
            # arena of itopk slots (sorted once in XLA after the loop); a
            # candidate is inserted — replacing the arena's current worst —
            # only while the best remaining candidate beats that worst.
            # Late hops insert ~0-2 candidates, so the whole merge gates
            # off after a couple of iterations (the fused_knn per-tile-gate
            # insight applied to the beam), vs itopk unconditional
            # extraction passes. Candidate count bounds the iterations.
            nbd_ref[...] = bd_ref[...]
            nbi_ref[...] = bi
            nbv_ref[...] = bv_ref[...]
            if merge == "arena_smem":
                # r05 form (the A/B control): gate handshake through an
                # SMEM scalar, candidate pool stashed in scratch and
                # re-read per candidate — the ~5 us/query residual the r05
                # profile named ("gated-loop scalar checks and pool I/O")
                pd_ref[:, :cw] = nd
                pi_ref[:, :cw] = nbr
                go_ref[0] = 1
                for t in range(cw):
                    def _insert(t=t):
                        ad = nbd_ref[...]
                        admask = jnp.where(lane < itopk, ad, _NEG)
                        worst = jnp.max(admask, axis=1, keepdims=True)
                        cd = pd_ref[:, :cw]
                        best = jnp.min(cd, axis=1, keepdims=True)
                        improve = best < worst              # (qt, 1)
                        go_ref[0] = jnp.any(improve).astype(jnp.int32)

                        @pl.when(jnp.any(improve))
                        def _apply():
                            cdv = pd_ref[:, :cw]
                            civ = pi_ref[:, :cw]
                            bid = jnp.min(jnp.where(cdv <= best, civ, _BIG),
                                          axis=1, keepdims=True)
                            # dedup HERE instead of a 32-pass pre-mask: a
                            # candidate already in the arena carries the
                            # same exact score — consume it, don't insert
                            ai = nbi_ref[...]
                            dup = jnp.any((ai == bid) & (lane < itopk),
                                          axis=1, keepdims=True)
                            ins = improve & jnp.logical_not(dup)
                            # arena slot to evict: the worst entry, highest
                            # lane on ties (any one copy)
                            wsel = (admask >= worst)
                            wlane = jnp.max(jnp.where(wsel, lane, -1),
                                            axis=1, keepdims=True)
                            at = ins & (lane == wlane)
                            nbd_ref[...] = jnp.where(at, best, ad)
                            nbi_ref[...] = jnp.where(at, bid, ai)
                            nbv_ref[...] = jnp.where(at, 0, nbv_ref[...])
                            # consume the candidate (all copies of its id)
                            pd_ref[:, :cw] = jnp.where(
                                improve & (civ == bid), _INF, cdv)

                    if profile == "nogate":
                        _insert()
                    else:
                        pl.when(go_ref[0] == 1)(_insert)
            else:
                # r06 form: the gate lives in a REGISTER (lax.cond carries
                # it across iterations as a loop value — no SMEM write+read
                # handshake serializing the VPU per candidate), candidate
                # scores ride the fori_loop carry (vregs, no pool-scratch
                # round trips), candidate ids are the already-loaded nbr
                # (never mutated), and the one any(improve) reduction both
                # closes the gate and masks the writes — the r05 loop paid
                # it twice plus two scratch re-reads per candidate. The
                # insertion math (tie-breaks, dedup-on-insert, eviction
                # lane) is unchanged from arena_smem.
                itmask = lane < itopk

                def _insert_step(_, carry):
                    go, cd = carry

                    def _live():
                        ad = nbd_ref[...]
                        admask = jnp.where(itmask, ad, _NEG)
                        worst = jnp.max(admask, axis=1, keepdims=True)
                        best = jnp.min(cd, axis=1, keepdims=True)
                        improve = best < worst              # (qt, 1)
                        bid = jnp.min(jnp.where(cd <= best, nbr, _BIG),
                                      axis=1, keepdims=True)
                        ai = nbi_ref[...]
                        dup = jnp.any((ai == bid) & itmask, axis=1,
                                      keepdims=True)
                        ins = improve & jnp.logical_not(dup)
                        wsel = (admask >= worst)
                        wlane = jnp.max(jnp.where(wsel, lane, -1), axis=1,
                                        keepdims=True)
                        at = ins & (lane == wlane)
                        # masked writes: rows whose improve is false keep
                        # their arena untouched, so no inner when-branch
                        nbd_ref[...] = jnp.where(at, best, ad)
                        nbi_ref[...] = jnp.where(at, bid, ai)
                        nbv_ref[...] = jnp.where(at, 0, nbv_ref[...])
                        cd2 = jnp.where(improve & (nbr == bid), _INF, cd)
                        return jnp.any(improve).astype(jnp.int32), cd2

                    if profile == "nogate":
                        return _live()
                    return jax.lax.cond(go == 1, _live,
                                        lambda: (jnp.int32(0), cd))

                jax.lax.fori_loop(0, cw, _insert_step, (jnp.int32(1), nd))
        else:
            # ---- merge pool: [beam | candidates | +inf pad], one row ----
            pd_ref[...] = bd_ref[...]
            pi_ref[...] = bi
            pv_ref[...] = bv_ref[...]
            pd_ref[:, itopk:itopk + cw] = nd
            pi_ref[:, itopk:itopk + cw] = nbr
            pv_ref[:, itopk:itopk + cw] = jnp.zeros((qt, cw), jnp.int32)
            pd_ref[:, itopk + cw:] = jnp.full((qt, _POOL - itopk - cw), _INF,
                                              jnp.float32)
            pi_ref[:, itopk + cw:] = jnp.full((qt, _POOL - itopk - cw), -1,
                                              jnp.int32)
            pv_ref[:, itopk + cw:] = jnp.ones((qt, _POOL - itopk - cw),
                                              jnp.int32)
            # ---- ascending extraction with lowest-id ties: the in-VMEM
            # form of the XLA path's lexsort+sort dedup merge ----
            nbd_ref[...] = jnp.full((qt, _POOL), _INF, jnp.float32)
            nbi_ref[...] = jnp.full((qt, _POOL), -1, jnp.int32)
            nbv_ref[...] = jnp.ones((qt, _POOL), jnp.int32)
            for t in range(itopk):
                pdv = pd_ref[...]
                mn = jnp.min(pdv, axis=1, keepdims=True)
                sel = pdv <= mn                          # winners incl. ties
                amid = jnp.min(jnp.where(sel, pi_ref[...], _BIG), axis=1,
                               keepdims=True)
                hit = (pi_ref[...] == amid) & sel
                wv = jnp.min(jnp.where(hit, pv_ref[...], _BIG), axis=1,
                             keepdims=True)
                nbd_ref[:, t] = mn[:, 0]
                nbi_ref[:, t] = jnp.where(mn[:, 0] < _INF, amid[:, 0], -1)
                nbv_ref[:, t] = jnp.minimum(wv[:, 0], 1)
                # mask every copy of the chosen id (kills in-row dups too)
                pd_ref[...] = jnp.where(pi_ref[...] == amid, _INF, pdv)

        _emit_pick(itopk, width, qt, lane, nbd_ref, nbi_ref, nbv_ref,
                   pick_ref, nocand_ref)

    return kernel


def _emit_pick(itopk, width, qt, lane, nbd_ref, nbi_ref, nbv_ref, pick_ref,
               nocand_ref):
    """Next picks: the ``width`` best unvisited entries in the itopk window,
    each marked visited as it is taken (matching the XLA loop's argsort
    top-width pick)."""
    nbd = nbd_ref[...]
    for w in range(width):
        nbv = nbv_ref[...]
        cd = jnp.where((nbv > 0) | (lane >= itopk), _INF, nbd)
        mn = jnp.min(cd, axis=1, keepdims=True)
        nocand = (mn >= _INF).astype(jnp.int32)
        sel = cd <= mn
        pick_id = jnp.min(jnp.where(sel, nbi_ref[...], _BIG), axis=1,
                          keepdims=True)
        nbv_ref[...] = jnp.where(
            (nbi_ref[...] == pick_id) & (nocand == 0), 1, nbv)
        pick_ref[:, w] = jnp.clip(pick_id[:, 0], 0, _BIG)
        nocand_ref[:, w] = nocand[:, 0]


@functools.partial(jax.jit, static_argnames=("itopk", "width", "qt",
                                             "interpret", "profile", "merge"))
def cagra_hop(queries, beam_d, beam_i, beam_v, nbrs, vecs, valid,
              itopk: int, width: int = 1, qt: int = 128,
              interpret: bool = False, profile: str = "full",
              merge: str = "extract"):
    """One fused CAGRA hop over the whole query batch.

    ``queries`` (m, d) f32; ``beam_d/beam_i/beam_v`` (m, 128) padded beam
    state (distances f32 ascending, ids i32, visited i32; lanes >= itopk are
    +inf/-1/1); ``nbrs`` (m, cw) i32 candidate ids for cw = width*degree
    (-1 = none); ``vecs`` (m, cw, d) their vectors — f32, or int8 for byte
    datasets (upcast in-kernel at the tile level: quarter the DMA bytes,
    bitwise-identical distances); ``valid`` (m, cw) i32 —
    0 masks a candidate (the caller expands each pick's validity over its
    deg candidates; all-zero primes the loop).

    Returns (beam_d, beam_i, beam_v, pick (m, width) i32 clipped >= 0,
    no_cand (m, width) i32). Beam distances are full ||v - q||^2.
    """
    if merge not in ("extract", "arena", "arena_smem"):
        raise ValueError(f"merge must be 'extract', 'arena' or 'arena_smem', "
                         f"got {merge!r}")
    if profile not in ("full", "noscore", "nodedup", "nomerge", "nogate"):
        raise ValueError(f"unknown profile {profile!r}")
    m, d = queries.shape
    cw = nbrs.shape[1]
    dp = -(-d // 128) * 128
    mp = -(-m // qt) * qt
    pad_rows = mp - m

    def prow(x, fill=0):
        return jnp.pad(x, ((0, pad_rows),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill) if pad_rows else x

    qp = prow(jnp.pad(queries, ((0, 0), (0, dp - d))) if dp > d else queries)
    vp = prow(jnp.pad(vecs, ((0, 0), (0, 0), (0, dp - d)))
              if dp > d else vecs)
    args = (qp, prow(beam_d, _INF), prow(beam_i, -1), prow(beam_v, 1),
            prow(nbrs, -1), vp, prow(valid))
    grid = (mp // qt,)
    spec2 = lambda w: pl.BlockSpec((qt, w), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        _make_hop_kernel(itopk, cw, width, qt, dp, profile, merge),
        grid=grid,
        in_specs=[spec2(dp), spec2(_POOL), spec2(_POOL), spec2(_POOL),
                  spec2(cw),
                  pl.BlockSpec((qt, cw, dp), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
                  spec2(cw)],
        out_specs=[spec2(_POOL), spec2(_POOL), spec2(_POOL), spec2(width),
                   spec2(width)],
        out_shape=[
            jax.ShapeDtypeStruct((mp, _POOL), jnp.float32),
            jax.ShapeDtypeStruct((mp, _POOL), jnp.int32),
            jax.ShapeDtypeStruct((mp, _POOL), jnp.int32),
            jax.ShapeDtypeStruct((mp, width), jnp.int32),
            jax.ShapeDtypeStruct((mp, width), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((qt, _POOL), jnp.float32),   # merge pool distances
            pltpu.VMEM((qt, _POOL), jnp.int32),     # merge pool ids
            pltpu.VMEM((qt, _POOL), jnp.int32),     # merge pool visited
            pltpu.SMEM((1,), jnp.int32),            # arena insertion gate
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*args)
    return tuple(o[:m] for o in outs)
