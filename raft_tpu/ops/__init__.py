"""raft_tpu.ops — Pallas TPU kernels backing hot paths (select_k variants,
IVF scan fusions). Population grows as profiling identifies XLA-composition
bottlenecks; modules land here with benchmarks."""

from .fused_knn import FUSED_KNN_MAX_K, fused_knn
from .topk import TOPK_MAX_K, topk_pallas

__all__ = ["topk_pallas", "TOPK_MAX_K", "fused_knn", "FUSED_KNN_MAX_K"]
