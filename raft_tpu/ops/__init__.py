"""raft_tpu.ops — Pallas TPU kernels backing hot paths. Under construction."""
