"""raft_tpu.ops — Pallas TPU kernels backing hot paths (select_k variants,
IVF scan fusions). Population grows as profiling identifies XLA-composition
bottlenecks; modules land here with benchmarks."""

from .topk import TOPK_MAX_K, topk_pallas

__all__ = ["topk_pallas", "TOPK_MAX_K"]
