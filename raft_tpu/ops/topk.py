"""Pallas streaming top-k with index payloads.

The critical selection kernel called out in SURVEY.md §2.3 (P8): the
reference implements two CUDA selectors (11-bit radix filter,
matrix/detail/select_radix.cuh, and warp bitonic queues,
detail/select_warpsort.cuh) because a full sort is wasteful for k ≪ n. XLA's
TopK on TPU is sort-based; for the ANN stack's k ≤ ~64 a streaming selector
wins: score columns arrive in VMEM blocks (Pallas pipelines the HBM reads),
and a running sorted top-k per row lives in VMEM scratch. Each block is
merged by k iterations of (min, argmin, mask) on the VPU — O(k·(k+B)) per
block instead of a sort network over n.

Exact (bit-identical values to lax.top_k for select_min; ties may resolve to
a different but equally-minimal index).

Measured on TPU v5 lite (100k cols, k=10): this kernel does NOT beat XLA —
the k-iteration argmax/mask loop re-reads each block ~4k times on the VPU
(66-138 ms/batch vs 56 ms for lax.top_k and 24 ms for lax.approx_min_k), so
the library's hot paths keep lax.top_k (exact) / approx_min_k (fast). The
kernel stays as the starting point for a future single-pass threshold-filter
variant and as the reference Pallas selector for k > XLA's TopK sweet spot.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["topk_pallas", "TOPK_MAX_K"]

TOPK_MAX_K = 128
_NEG = -jnp.inf


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _topk_kernel(x_ref, out_v_ref, out_i_ref, run_v, run_i, *, k: int, blk: int, n: int):
    """Grid dim 0 walks column blocks; scratch carries the running top-k."""
    j = pl.program_id(0)
    nblk = pl.num_programs(0)
    t = x_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        run_v[:] = jnp.full((t, k), _NEG, jnp.float32)
        run_i[:] = jnp.full((t, k), -1, jnp.int32)

    block = x_ref[:].astype(jnp.float32)  # (T, BLK)
    # mask out-of-range padding columns of the final block
    col = jax.lax.broadcasted_iota(jnp.int32, (t, blk), 1) + j * blk
    block = jnp.where(col < n, block, _NEG)

    vals = jnp.concatenate([run_v[:], block], axis=1)  # (T, k+BLK)
    idxs = jnp.concatenate([run_i[:], col], axis=1)

    kcol = jax.lax.broadcasted_iota(jnp.int32, (t, k), 1)

    def extract(i, carry):
        vals, idxs, top_v, top_i = carry
        am = jnp.argmax(vals, axis=1)  # (T,)
        onehot = (
            jax.lax.broadcasted_iota(jnp.int32, vals.shape, 1) == am[:, None]
        )
        v = jnp.max(vals, axis=1)
        gi = jnp.max(jnp.where(onehot, idxs, -1), axis=1)
        # masked write of column i (dynamic_update_slice is not lowered on TPU)
        top_v = jnp.where(kcol == i, v[:, None], top_v)
        top_i = jnp.where(kcol == i, gi[:, None], top_i)
        vals = jnp.where(onehot, _NEG, vals)
        return vals, idxs, top_v, top_i

    init = (
        vals,
        idxs,
        jnp.full((t, k), _NEG, jnp.float32),
        jnp.full((t, k), -1, jnp.int32),
    )
    _, _, top_v, top_i = jax.lax.fori_loop(0, k, extract, init)
    run_v[:] = top_v
    run_i[:] = top_i

    @pl.when(j == nblk - 1)
    def _emit():
        out_v_ref[:] = run_v[:]
        out_i_ref[:] = run_i[:]


@functools.partial(jax.jit, static_argnames=("k", "select_min", "blk", "interpret"))
def topk_pallas(x, k: int, select_min: bool = True, blk: int = 2048,
                interpret: bool | None = None):
    """Top-k of each row of ``x`` (2-D) with source-column payloads.

    Returns (values (m, k), indices (m, k) int32), values sorted best-first.
    Exact; `select_min=True` mirrors lax.top_k on -x. ``interpret`` defaults
    to True off-TPU (Pallas interpreter) so the kernel is testable on the CPU
    mesh.
    """
    m, n = x.shape
    if k > min(TOPK_MAX_K, n):
        raise ValueError(f"k={k} must be <= min({TOPK_MAX_K}, n={n})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    xw = -x if select_min else x
    blk = min(blk, _round_up(n, 128))
    npad = _round_up(n, blk)
    if npad != n:
        xw = jnp.pad(xw, ((0, 0), (0, npad - n)), constant_values=_NEG)

    grid = (npad // blk,)
    out_v, out_i = pl.pallas_call(
        functools.partial(_topk_kernel, k=k, blk=blk, n=n),
        out_shape=(
            jax.ShapeDtypeStruct((m, k), jnp.float32),
            jax.ShapeDtypeStruct((m, k), jnp.int32),
        ),
        grid=grid,
        in_specs=[pl.BlockSpec((m, blk), lambda j: (0, j), memory_space=pltpu.VMEM)],
        out_specs=(
            pl.BlockSpec((m, k), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, k), lambda j: (0, 0), memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((m, k), jnp.float32),
            pltpu.VMEM((m, k), jnp.int32),
        ],
        interpret=interpret,
    )(xw)
    return (-out_v if select_min else out_v), out_i
