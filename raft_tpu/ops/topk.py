"""Pallas streaming top-k with index payloads.

The selection kernel called out in SURVEY.md §2.3 (P8): the reference
implements two CUDA selectors (11-bit radix filter,
matrix/detail/select_radix.cuh, and warp bitonic queues,
detail/select_warpsort.cuh) because a full sort is wasteful for k ≪ n; XLA's
TopK custom call on TPU is sort-based and costs ~3 HBM passes over the
matrix.

This kernel streams the matrix once: column blocks arrive in VMEM (Pallas
pipelines the HBM reads) and a running top-k per row lives in VMEM scratch.
Selection is *threshold-gated* iterative extraction — a block is scanned only
while its row-maximum still beats the running k-th best (``tau``), so most
blocks beyond the first few cost one max-pass over VMEM. The same structure
fused with the distance GEMM is ops/fused_knn.py; this variant is the
standalone selector for matrices that already exist in HBM, dispatched from
matrix/select_k.py for wide rows on TPU.

An earlier ungated VPU design (k-iteration argmax/mask run unconditionally
per block) measured 66-138 ms vs 56 ms for lax.top_k on (10k, 100k); the
gated form beats lax.top_k at wide shapes (see matrix/select_k.py dispatch
notes for measurements).

Exact values; ties resolve to the lowest column index, matching lax.top_k.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._compat import CompilerParams as _CompilerParams

__all__ = ["topk_pallas", "TOPK_MAX_K"]

# k <= 64: merge buffer is one 128-lane register (measured path).
# 64 < k <= 256: the running buffer is kept SORTED and merged with the
# sorted block candidates by a bitonic merge network (VERDICT r4 #5) —
# since r06 at HALF the lane width: the first stage of the 2k-wide network
# is an elementwise compare of the two k-wide halves (the discarded loser
# half is never formed), so every merge intermediate is <= kh lanes wide.
# log2(k) kh-lane compare-exchange stages instead of k extraction
# iterations (8 stages at kh lanes vs 256 iterations at k=256).
TOPK_MAX_K = 256
_NEG = -3.0e38
_BIG = 2**30


def _extract_topk_ids(v, ids, k):
    """k iterations of (max, argmin-id, mask-by-id) over a small array."""
    vals, idxs = [], []
    for _ in range(k):
        m = jnp.max(v, axis=1, keepdims=True)
        am = jnp.min(jnp.where(v >= m, ids, _BIG), axis=1, keepdims=True)
        vals.append(m)
        idxs.append(am)
        v = jnp.where(ids == am, _NEG, v)
    return jnp.concatenate(vals, axis=1), jnp.concatenate(idxs, axis=1)


def _bitonic_merge_desc(v, ids, s0):
    """Sort a (qt, w) bitonic sequence into descending order with stages
    s0, s0/2, ..., 1, ids riding along; ties resolve to the lower id,
    matching lax.top_k. All ops stay full (qt, w)-lane-width — rolls
    instead of narrow reshapes (the r03 lesson: narrow-lane intermediates
    cost a vreg relayout each). With s0 == w/2 this is the full bitonic
    merge network for a w-length bitonic sequence."""
    lane = jax.lax.broadcasted_iota(jnp.int32, v.shape, 1)
    s = s0
    while s >= 1:
        vf, idf = jnp.roll(v, -s, axis=1), jnp.roll(ids, -s, axis=1)
        vb, idb = jnp.roll(v, s, axis=1), jnp.roll(ids, s, axis=1)
        up = (lane % (2 * s)) < s
        # descending compare-exchange: winner (greater value, lower id on
        # ties) moves to the window's first half
        fwd_win = (v > vf) | ((v == vf) & (ids < idf))
        bwd_win = (vb > v) | ((vb == v) & (idb < ids))
        v_new = jnp.where(up, jnp.where(fwd_win, v, vf),
                          jnp.where(bwd_win, v, vb))
        i_new = jnp.where(up, jnp.where(fwd_win, ids, idf),
                          jnp.where(bwd_win, ids, idb))
        v, ids = v_new, i_new
        s //= 2
    return v, ids


def _select_kernel(x_ref, out_i_ref, run_v, run_i, s_ref,
                   cand_v, cand_i, go_ref, *, k, kh, blk, n, qt, select_min,
                   wide_merge):
    j = pl.program_id(1)
    nb = pl.num_programs(1)
    wide = kh > 64
    w = kh if wide else 128

    @pl.when(j == 0)
    def _init():
        run_v[:] = jnp.full((qt, w), _NEG, jnp.float32)
        run_i[:] = jnp.full((qt, w), _BIG, jnp.int32)

    s = x_ref[:].astype(jnp.float32)
    if select_min:
        s = -s
    # clamp into the sentinel-safe range so +/-inf inputs still rank above the
    # padding sentinel (exact values are restored by a final gather from x)
    s = jnp.clip(s, -2.9e38, 2.9e38)
    cols = jax.lax.broadcasted_iota(jnp.int32, (qt, blk), 1) + j * blk
    s = jnp.where(cols < n, s, _NEG)
    s_ref[:] = s

    tau = run_v[:, k - 1:k]
    go_ref[0] = 1
    go_ref[1] = 0
    cand_v[:] = jnp.full((qt, w), _NEG, jnp.float32)
    cand_i[:] = jnp.full((qt, w), _BIG, jnp.int32)

    for t in range(k):                           # static unroll, flag-gated
        # wide path: write best-first extractions into REVERSED lanes so the
        # candidate buffer is born ascending — Mosaic has no `rev` lowering,
        # so the bitonic concat below must not need a flip
        tpos = (kh - 1 - t) if wide else t

        @pl.when(go_ref[0] == 1)
        def _step(t=t, tpos=tpos):
            sv = s_ref[:]
            m = jnp.max(sv, axis=1, keepdims=True)
            any_improve = jnp.any(m > tau)
            go_ref[0] = any_improve.astype(jnp.int32)

            @pl.when(any_improve)
            def _extract():
                am = jnp.min(jnp.where(sv >= m, cols, _BIG), axis=1,
                             keepdims=True)
                cand_v[:, tpos] = m[:, 0]
                cand_i[:, tpos] = am[:, 0]
                s_ref[:] = jnp.where(cols == am, _NEG, sv)
                go_ref[1] = 1

    if not wide:
        # measured k<=64 path, unchanged: 2k-wide buffer, k-step extraction
        mv = jnp.concatenate([run_v[:, :k], cand_v[:, :k]], axis=1)
        mi = jnp.concatenate([run_i[:, :k], cand_i[:, :k]], axis=1)
        nv, ni = _extract_topk_ids(mv, mi, k)
        run_v[:, :k] = nv
        run_i[:, :k] = ni
    else:
        # wide path: merge only when this block extracted anything (most
        # blocks beyond the first few are gated off entirely once tau
        # tightens — an unconditional full-width merge would dominate)
        @pl.when(go_ref[1] == 1)
        def _merge():
            # run is sorted desc; candidates were written reversed (see
            # tpos above) so cand is already ascending — run ++ cand is
            # bitonic with no flip
            rv, riv = run_v[:, :kh], run_i[:, :kh]
            cv, civ = cand_v[:, :kh], cand_i[:, :kh]
            if wide_merge == "half":
                # half-width form (r06): the 2kh-wide network's first
                # stage (stride kh) only ever routes the winner of
                # (run[i], cand[i]) into the kept half — computed as one
                # elementwise compare-exchange of the two kh-wide halves,
                # whose output is itself bitonic. The remaining stages
                # (kh/2 .. 1) never cross the half boundary, so NOTHING
                # in the merge exceeds kh lanes: the kh=256 instance uses
                # exactly the lane widths of the chaining-proven kh=128
                # path (the workaround for the two-instance Mosaic
                # failure, see topk_pallas docstring), and one full-width
                # stage is saved outright.
                win = (rv > cv) | ((rv == cv) & (riv < civ))
                nv = jnp.where(win, rv, cv)
                ni = jnp.where(win, riv, civ)
                nv, ni = _bitonic_merge_desc(nv, ni, kh // 2)
                run_v[:, :kh] = nv
                run_i[:, :kh] = ni
            else:  # "concat": the r05 formulation (2kh-lane concat +
                # full network) — kept verbatim for the on-hardware
                # chaining repro/bisect (bench/topk_chain_repro.py)
                mv = jnp.concatenate([rv, cv], axis=1)
                mi = jnp.concatenate([riv, civ], axis=1)
                nv, ni = _bitonic_merge_desc(mv, mi, kh)
                run_v[:, :kh] = nv[:, :kh]
                run_i[:, :kh] = ni[:, :kh]

    @pl.when(j == nb - 1)
    def _emit():
        out_i_ref[:] = run_i[:, :k]


@functools.partial(jax.jit,
                   static_argnames=("k", "select_min", "blk", "qt", "interpret",
                                    "wide_merge"))
def topk_pallas(x, k: int, select_min: bool = True, blk: int = 4096,
                qt: int = 256, interpret: bool | None = None,
                wide_merge: str = "half"):
    """Top-k of each row of ``x`` (2-D) with source-column payloads.

    Returns (values (m, k), indices (m, k) int32), values sorted best-first.
    Exact; ``select_min=True`` mirrors lax.top_k on -x. ``interpret``
    defaults to True off-TPU (Pallas interpreter) so the kernel is testable
    on the CPU mesh. k <= TOPK_MAX_K; larger k belongs to lax.top_k (the
    matrix/select_k.py dispatch handles that split).

    Magnitude limit: ranking happens after a clamp to +/-2.9e38 (so +/-inf
    inputs still beat the padding sentinel), which collapses finite f32
    magnitudes in (2.9e38, 3.4e38] with each other and with +/-inf — among
    such values the selected *index* can differ from lax.top_k (returned
    values are exact either way, restored by the final gather). Pre-scale
    inputs if distinctions above 2.9e38 matter; distance pipelines never get
    near this range.

    kh=256 chaining history (r05 -> r06): embedding two kh=256 kernel
    instances (two k > 128 calls) inside one XLA program used to hit a
    TPU-internal Mosaic error, which capped the matrix/select_k.py dispatch
    at k <= 128. The r05 merge built 2*kh-lane intermediates — 512 lanes at
    kh=256, the ONLY lane width the chaining-proven kh=128 path never uses —
    so ``wide_merge="half"`` (default) now computes the first network stage
    as an elementwise compare of the two kh-wide halves (the discarded loser
    half is never formed) and keeps every merge intermediate <= kh lanes;
    the dispatch cap is lifted to k <= 256. ``wide_merge="concat"`` keeps
    the r05 formulation verbatim so ``bench/topk_chain_repro.py`` can
    reproduce and bisect the original failure on hardware; if a future
    toolchain still rejects chained kh=256 "half" instances, re-cap the
    dispatch with ``RAFT_TPU_WIDE_SELECT_CAP=128`` (see select_k) and run
    the repro. The two-instance composition at the CAGRA build-chunk shapes
    is pinned by ``tests/test_ops.py::test_topk_pallas_two_wide_instances``.
    """
    m, n = x.shape
    if k > min(TOPK_MAX_K, n):
        raise ValueError(f"k={k} must be <= min({TOPK_MAX_K}, n={n})")
    if wide_merge not in ("half", "concat"):
        raise ValueError(f"wide_merge must be 'half' or 'concat', got {wide_merge!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    blk = max(128, min(blk, -(-n // 128) * 128))
    # kh: running-buffer width — 64 keeps the measured narrow path; wider k
    # rounds to a power of two for the bitonic merge network
    kh = 64 if k <= 64 else 1 << (k - 1).bit_length()
    w = 128 if kh == 64 else kh
    # no host-side jnp.pad (it would copy the whole matrix through HBM):
    # Pallas pads boundary blocks itself and the kernel masks cols >= n;
    # boundary-row garbage is sliced away below
    n_blocks = -(-n // blk)
    m_blocks = -(-m // qt)
    grid = (m_blocks, n_blocks)
    kern = functools.partial(_select_kernel, k=k, kh=kh, blk=blk, n=n, qt=qt,
                             select_min=bool(select_min),
                             wide_merge=wide_merge)
    out_i = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((qt, blk), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((qt, k), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_blocks * qt, k), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((qt, w), jnp.float32),       # running top-k values
            pltpu.VMEM((qt, w), jnp.int32),         # running top-k ids
            pltpu.VMEM((qt, blk), jnp.float32),     # block scratch
            pltpu.VMEM((qt, w), jnp.float32),       # block candidates
            pltpu.VMEM((qt, w), jnp.int32),
            pltpu.SMEM((2,), jnp.int32),            # extraction + merge gates
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(x)
    pos = jnp.minimum(out_i[:m], n - 1)        # _BIG only when a row is degenerate
    vals = jnp.take_along_axis(x, pos, axis=1)  # exact values, infs included
    return vals, pos
