"""jax-version compatibility aliases shared by the Pallas kernels."""

from jax.experimental.pallas import tpu as pltpu

# pltpu.CompilerParams was named TPUCompilerParams before jax 0.5; the
# kernels only pass vmem_limit_bytes, which both spellings accept.
CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
