"""jax-version compatibility aliases shared by the Pallas kernels and obs."""

from jax.experimental.pallas import tpu as pltpu

# pltpu.CompilerParams was named TPUCompilerParams before jax 0.5; the
# kernels only pass vmem_limit_bytes, which both spellings accept.
CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def jax_monitoring():
    """The ``jax.monitoring`` event bus when this jax ships one with listener
    registration (0.4.x+), else None. obs/compile.py keys its compile
    attribution on this; callers without it fall back to wall-time deltas
    (the cold-vs-warm timing fallback in ``_warmup``)."""
    try:
        from jax import monitoring
    except ImportError:  # pragma: no cover - ancient jax
        return None
    if not (hasattr(monitoring, "register_event_duration_secs_listener")
            and hasattr(monitoring, "register_event_listener")):
        return None  # pragma: no cover - pre-listener jax
    return monitoring
