"""Fused distance + top-k selection Pallas kernel for brute-force kNN.

This is the TPU resolution of SURVEY.md hard part #2 ("competitive batched
select_k").  The reference GPU stack computes a tiled distance GEMM, writes the
score tile to global memory, and runs a separate selection kernel over it
(cpp/include/raft/neighbors/detail/knn_brute_force.cuh:232-273 tile+select
loop; cpp/include/raft/matrix/detail/select_radix.cuh and
detail/select_warpsort.cuh selection kernels).  On TPU the measured bottleneck
of that structure is HBM traffic: the (m, n) score matrix costs one write plus
~3 sort passes of reads, and XLA's TopK custom call cannot fuse its producer.

This kernel never materializes scores to HBM.  Grid = (query_tiles,
dataset_blocks), dataset-block minor.  Each step computes a (QT, NBLK) score
block in VMEM with one MXU contraction (scores are oriented so *larger is
better*: ``2 q·y - |y|^2`` for L2, ``q·y`` for inner product), then runs a
threshold-gated iterative extraction: the block is scanned for candidates
only while its row-maximum still beats the running k-th best (``tau``),
which skips most extraction work once the running top-k tightens after the
first few blocks.  Running top-k state lives in VMEM scratch that persists
across the dataset-block walk; only the final (QT, k) values and indices
leave the chip.  Bounds padding and sample-filter masks are folded into the
norms operand (one fused subtract) instead of iota/compare/select passes,
and bf16-mode operands are cast OUTSIDE the kernel (half the DMA bytes, no
per-block VPU cast).

Profiling notes (v5e, 100k x 128, k=10, 10k-query batches; details and
QPS-with-controls in BASELINE.md "Round-3 fused-kernel engineering notes"):
- the kernel is VPU-extraction-bound, not MXU-bound: k=1 runs 3.3x faster
  than k=10, while a 6x MXU-cost swing (f32 HIGHEST vs bf16) moves QPS ~20%;
- three redesigns measured and REJECTED, kept here as negative results:
  (a) two-pass with XLA top_k tau between (2nd contraction sweep costs more
  than the extraction it skips), (b) segmented extraction over per-128-lane
  maxima (every (QT, NSEG) narrow-lane intermediate costs a vreg relayout;
  5x slower — keep Pallas hot-loop ops full-lane-width), (c) slice-maxima
  tau pass seeding the running k-th slot (flat: the running tau is already
  tight after ~2 blocks; the per-TILE any-row gate, not tau quality, sets
  the iteration count);
- the same per-tile-gate insight made qt=128 the default: fewer rows share
  one extraction loop, so it gates off sooner (+32% f32 / +11% bf16 over
  qt=256 in the same session).

Modes:
  "f32"   — f32 inputs, Precision.HIGHEST contraction. Exact: neighbor sets
            match the XLA f32 pipeline; within-1-ULP distance ties may order
            differently (score accumulation order differs between kernels).
  "f32x3" — compensated bf16x3 contraction (hi/lo split, three MXU passes),
            f32-class accuracy at roughly half the MXU cost. Neighbor
            sets match f32 except where two distances differ by < ~1e-6 rel.
  "bf16"  — single-pass bf16 contraction. Fastest; set recall ~0.98 on
            worst-case (uniform) data, higher on clustered data.
  "s8"    — int8 operands, s8 x s8 -> s32 MXU contraction (~2x bf16 peak,
            1-byte operand DMAs). For int8/uint8 datasets (the reference's
            ivf_flat/brute-force int8_t/uint8_t instantiations,
            cpp/src/neighbors/*_int8_t_*.cu): callers pass SHIFTED signed
            values (uint8 - 128 — L2 is shift-invariant; inner-product
            callers fold the 128-sum correction into the yn operand).
            EXACT distances when 3*128^2*d < 2^24 (d <= ~340): every
            intermediate is an integer below f32's exact range.

Ties: equal scores resolve to the lowest dataset index, matching lax.top_k.

Magnitude limit: scores are ranked against a -3e38 sentinel and masked
entries ride at ~-3e38, so inputs whose scores approach float32 max (|q·y|
beyond ~1e37 — feature scales ~1e17+) are out of contract.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._compat import CompilerParams as _CompilerParams

__all__ = ["fused_knn", "FUSED_KNN_MAX_K"]

FUSED_KNN_MAX_K = 64          # merge buffer is one 128-lane register: 2k <= 128


def fused_backend_ok():
    """True when the fused kernel may run: Mosaic on TPU, or interpret mode
    explicitly opted into for tests (RAFT_TPU_FUSED_KNN_INTERPRET=1)."""
    import os

    on_tpu = jax.default_backend() == "tpu"
    interpret_ok = os.environ.get(
        "RAFT_TPU_FUSED_KNN_INTERPRET", "").lower() in ("1", "true", "yes")
    return on_tpu or interpret_ok, not on_tpu


def shapes_eligible(n: int, d: int, k: int) -> bool:
    """Shared shape gate for fused-kernel dispatch: big-enough candidate set
    (below ~4096 rows XLA is fine and kernel padding overhead dominates),
    feature dim within the VMEM budget, and d not dominated by lane padding
    (inputs are zero-padded to 128 lanes; d << 64 would mostly multiply
    zeros and pay a padded dataset copy per call)."""
    return 0 < k <= FUSED_KNN_MAX_K and n >= 4096 and 64 <= d <= 4096
_NEG = -3.0e38                # finite sentinel: 0 * _NEG must stay finite
_BIG = 2**30                  # "no index" sentinel
_MASK_PENALTY = 3.0e38        # added to |y|^2 for padded / filtered-out rows


def _extract_topk_ids(v, ids, k):
    """k iterations of (max, argmin-id, mask-by-id) over a small (QT, W) array.

    Ties resolve to the smallest payload id; masking is by id, so a value
    merged twice under the same id (e.g. a running entry re-offered by a
    later candidate set) is consumed in one step, never duplicated.
    """
    vals, idxs = [], []
    for _ in range(k):
        m = jnp.max(v, axis=1, keepdims=True)
        am = jnp.min(jnp.where(v >= m, ids, _BIG), axis=1, keepdims=True)
        vals.append(m)
        idxs.append(am)
        v = jnp.where(ids == am, _NEG, v)
    return jnp.concatenate(vals, axis=1), jnp.concatenate(idxs, axis=1)


def _scores(q, y, mode):
    """MXU contraction q @ y.T in the requested precision mode."""
    dn = (((1,), (1,)), ((), ()))
    if mode == "s8":
        # int8 MXU path: s8 x s8 -> s32 (double bf16 peak), f32 at the end
        # for the extraction machinery's sentinel arithmetic
        return jax.lax.dot_general(
            q, y, dn, preferred_element_type=jnp.int32).astype(jnp.float32)
    if mode == "bf16":
        return jax.lax.dot_general(
            q.astype(jnp.bfloat16), y.astype(jnp.bfloat16), dn,
            preferred_element_type=jnp.float32)
    if mode == "f32x3":
        # compensated bf16x3: x·y ~ hi·hi + hi·lo + lo·hi (Mosaic has no
        # Precision.HIGH lowering, so the split is spelled out)
        qh = q.astype(jnp.bfloat16)
        ql = (q - qh.astype(jnp.float32)).astype(jnp.bfloat16)
        yh = y.astype(jnp.bfloat16)
        yl = (y - yh.astype(jnp.float32)).astype(jnp.bfloat16)
        return (jax.lax.dot_general(qh, yh, dn, preferred_element_type=jnp.float32)
                + jax.lax.dot_general(qh, yl, dn, preferred_element_type=jnp.float32)
                + jax.lax.dot_general(ql, yh, dn, preferred_element_type=jnp.float32))
    return jax.lax.dot_general(q, y, dn, precision=lax.Precision.HIGHEST,
                               preferred_element_type=jnp.float32)


def _make_kernel(k, nblk, qt, mode, l2):
    def kernel(q_ref, y_ref, yn_ref, out_v_ref, out_i_ref,
               run_v, run_i, s_ref, cand_v, cand_i, go_ref):
        j = pl.program_id(1)
        nb = pl.num_programs(1)

        @pl.when(j == 0)
        def _init():
            run_v[:] = jnp.full((qt, 128), _NEG, jnp.float32)
            run_i[:] = jnp.full((qt, 128), _BIG, jnp.int32)

        dots = _scores(q_ref[:], y_ref[:], mode)
        # yn carries |y|^2 (L2), the bounds padding penalty AND the sample
        # filter penalty — one fused subtract instead of iota/compare/select
        # masking passes. (A segmented-extraction variant that reduced the
        # block to per-128-lane maxima measured 5x SLOWER: every (qt, nseg)
        # narrow-lane intermediate costs a vreg relayout on TPU; all hot ops
        # here deliberately stay (qt, nblk)-wide.)
        s = (2.0 * dots if l2 else dots) - yn_ref[:]
        s_ref[:] = s
        cols = jax.lax.broadcasted_iota(jnp.int32, (qt, nblk), 1) + j * nblk

        tau = run_v[:, k - 1:k]
        go_ref[0] = 1
        cand_v[:] = jnp.full((qt, 128), _NEG, jnp.float32)
        cand_i[:] = jnp.full((qt, 128), _BIG, jnp.int32)

        for t in range(k):                      # static unroll, flag-gated
            @pl.when(go_ref[0] == 1)
            def _step(t=t):
                sv = s_ref[:]
                m = jnp.max(sv, axis=1, keepdims=True)
                any_improve = jnp.any(m > tau)
                go_ref[0] = any_improve.astype(jnp.int32)

                @pl.when(any_improve)
                def _extract():
                    am = jnp.min(jnp.where(sv >= m, cols, _BIG), axis=1,
                                 keepdims=True)
                    cand_v[:, t] = m[:, 0]
                    cand_i[:, t] = am[:, 0]
                    s_ref[:] = jnp.where(cols == am, _NEG, sv)

        mv = jnp.concatenate([run_v[:, :k], cand_v[:, :k]], axis=1)
        mi = jnp.concatenate([run_i[:, :k], cand_i[:, :k]], axis=1)
        nv, ni = _extract_topk_ids(mv, mi, k)
        run_v[:, :k] = nv
        run_i[:, :k] = ni

        @pl.when(j == nb - 1)
        def _emit():
            out_v_ref[:] = run_v[:, :k]
            out_i_ref[:] = run_i[:, :k]

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("k", "l2", "mode", "qt", "nblk", "interpret"))
def _fused_knn_impl(dataset, queries, yn, keep, k, l2, mode, qt, nblk,
                    interpret):
    n, d = dataset.shape
    m = queries.shape[0]
    n_pad = -(-n // nblk) * nblk
    m_pad = -(-m // qt) * qt
    d_pad = -(-d // 128) * 128
    # bf16 mode: cast once here, outside the kernel — the per-block VPU cast
    # inside the kernel was costing more than the narrower MXU pass saved
    # (measured bf16 SLOWER than f32 with in-kernel casts), and bf16 operands
    # also halve the per-step DMA bytes
    io_t = {"bf16": jnp.bfloat16, "s8": jnp.int8}.get(mode, jnp.float32)
    ds = jnp.pad(dataset.astype(io_t), ((0, n_pad - n), (0, d_pad - d)))
    qs = jnp.pad(queries.astype(io_t), ((0, m_pad - m), (0, d_pad - d)))
    base = yn if yn is not None else jnp.zeros((n,), jnp.float32)
    if keep is not None:
        # clamp: |y|^2 + penalty would overflow f32 to +inf for rows with
        # |y|^2 beyond ~4e37, and an inf norm turns the kernel's masked
        # arithmetic into NaN — pin filtered rows at the finite sentinel so
        # masking stays magnitude-independent
        base = jnp.minimum(base + jnp.where(keep, 0.0, _MASK_PENALTY),
                           _MASK_PENALTY)
    ynp = jnp.pad(base, (0, n_pad - n),
                  constant_values=_MASK_PENALTY).reshape(1, n_pad)
    grid = (m_pad // qt, n_pad // nblk)

    kern = _make_kernel(k, nblk, qt, mode, l2)
    out_v, out_i = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((qt, d_pad), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((nblk, d_pad), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nblk), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((qt, k), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((qt, k), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((m_pad, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((qt, 128), jnp.float32),     # running top-k values
            pltpu.VMEM((qt, 128), jnp.int32),       # running top-k ids
            pltpu.VMEM((qt, nblk), jnp.float32),    # staged score block
            pltpu.VMEM((qt, 128), jnp.float32),     # block candidates (values)
            pltpu.VMEM((qt, 128), jnp.int32),       # block candidates (ids)
            pltpu.SMEM((1,), jnp.int32),            # extraction gate
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(qs, ds, ynp)
    return out_v[:m], out_i[:m]


def fused_knn(dataset, queries, k, *, metric="l2", mode="f32", keep_mask=None,
              sqrt=False, row_bias=None, qt=128, nblk=4096, interpret=False):
    """Exact brute-force kNN via the fused Pallas kernel.

    ``metric``: "l2" (squared euclidean; ``sqrt=True`` for euclidean) or
    "ip" (inner product; larger = closer, like the reference's
    DistanceType::InnerProduct contract).  Cosine is "ip" over pre-normalized
    inputs (the caller normalizes, as distance/pairwise._cosine does).

    ``mode="s8"`` requires int8 inputs (uint8 callers shift by -128 first —
    L2 is shift-invariant; see brute_force._as_signed). ``row_bias`` (n,)
    f32 is subtracted from every row's score before selection — the hook ip
    callers use to restore uint8 inner products from shifted operands
    (q·v = q'·v' + 128·Σv' + const(q), where the Σv' term is the row bias
    with sign flipped).

    Returns (distances (m, k) f32, indices (m, k) int32).  Rows with fewer
    than k admissible dataset points (under ``keep_mask``) get -1 indices and
    +inf distances in the unfilled slots, matching brute_force.knn.
    """
    from ..core.errors import expects

    n, d = dataset.shape
    expects(0 < k <= FUSED_KNN_MAX_K,
            "fused_knn supports k in (0, %d], got %d — use brute_force.knn "
            "for larger k", FUSED_KNN_MAX_K, k)
    # Mosaic block shapes need 128-lane alignment, and the (qt, nblk) f32
    # score scratch must fit VMEM alongside the operand blocks
    expects(nblk % 128 == 0 and 128 <= nblk <= 16384,
            "nblk must be a multiple of 128 lanes in [128, 16384]")
    if mode == "s8":
        expects(dataset.dtype == jnp.int8 and queries.dtype == jnp.int8,
                "mode='s8' requires int8 operands (shift uint8 by -128 "
                "first), got %s/%s", dataset.dtype, queries.dtype)
    l2 = metric == "l2"
    yn = (jnp.sum(dataset.astype(jnp.float32) ** 2, axis=1) if l2 else None)
    if row_bias is not None:
        rb = jnp.asarray(row_bias, jnp.float32)
        expects(rb.shape == (n,), "row_bias must be (n,)")
        yn = rb if yn is None else yn + rb
    keep = None if keep_mask is None else jnp.asarray(keep_mask).astype(bool)
    # shrink the dataset block if the feature dim would blow the VMEM budget
    # (in whole 128-lane segments so the invariant above survives the shrink)
    while nblk > 512 and (qt + nblk) * max(d, 128) * 4 + qt * nblk * 4 > 24 * 2**20:
        nblk = (nblk // 2 // 128) * 128
    out_v, out_i = _fused_knn_impl(dataset, queries, yn, keep, int(k),
                                   l2, mode, qt, nblk, interpret)
    empty = out_v <= _NEG / 2
    out_i = jnp.where(empty, -1, out_i)
    if l2:
        qn = jnp.sum(queries.astype(jnp.float32) ** 2, axis=1, keepdims=True)
        dist = jnp.maximum(qn - out_v, 0.0)
        if sqrt:
            dist = jnp.sqrt(dist)
        dist = jnp.where(empty, jnp.inf, dist)
    else:
        dist = out_v                                  # similarity, larger=closer
        dist = jnp.where(empty, -jnp.inf, dist)
    return dist, out_i
