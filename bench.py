"""raft_tpu benchmark entry point (run by the driver on real TPU hardware).

Prints a full-result JSON line after every completed row (take the LAST
line). The primary metric stays the exact brute-force kNN
search throughput on 100k x 128, k=10, batch 10k (the protocol BENCH_r01
recorded, so rounds are comparable), now served by the fused Pallas
distance+top-k kernel (ops/fused_knn.py). A "rows" field carries the
regression suite the driver archives per round: exact kNN plus IVF-Flat and
CAGRA at 1M with QPS and recall@10, mirroring the reference harness's
(recall, QPS) operating points (cpp/bench/ann/src/common/benchmark.hpp:111-200).

Measurement notes:
- batches are chained inside ONE jitted program with DISTINCT query data and
  materialized to host: the device tunnel caches repeated identical dispatches
  and under-reports blocking waits, so anything else reports fantasy QPS;
- all data is generated on-device (jax.random) — a 512 MB host->device
  transfer through the tunnel would dominate the timings;
- 1M rows build cold-jit in-process (~2-6 min total); rows degrade gracefully:
  if a row fails or the soft time budget is exceeded, remaining rows are
  reported as skipped rather than failing the whole bench;
- a complete JSON line is (re)printed after every finished row, so if the
  driver kills the process on a slow-chip day, the LAST printed line still
  carries every row completed so far.
"""

from __future__ import annotations

import json
import sys
import time

SOFT_BUDGET_S = 480.0  # stop starting new rows beyond this
_T0 = time.perf_counter()


def _elapsed():
    return time.perf_counter() - _T0


def _note(msg):
    print(f"[bench +{_elapsed():.0f}s] {msg}", file=sys.stderr, flush=True)


def _recall(ids, gt):
    import numpy as np

    ids, gt = np.asarray(ids), np.asarray(gt)
    k = gt.shape[1]
    return float(np.mean([len(set(ids[r, :k]) & set(gt[r])) / k
                          for r in range(gt.shape[0])]))


def _measure_qps(search_fn, query_sets, m, use_jit=True):
    """Best-of-N wall time over distinct query sets, host-materialized.

    ``use_jit=False`` for index searches: they carry their own internal jit
    caches, and an enclosing jit would re-trace the whole 1M-scale pipeline
    into one giant program (minutes of extra compile for no steady-state
    gain).
    """
    import jax
    import numpy as np

    jax.block_until_ready(query_sets)
    f = jax.jit(search_fn) if use_jit else search_fn
    np.asarray(jax.tree_util.tree_leaves(f(query_sets[0]))[0])  # compile+warm
    best = float("inf")
    out = None
    for qs in query_sets[1:]:
        t0 = time.perf_counter()
        out = f(qs)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return m / best, out


def _flagship_exact(rows):
    """Exact kNN 100k x 128 — identical protocol to BENCH_r01.

    Returns (primary_qps, fused_ok): qps is 0.0 when nothing measured (a
    complete environmental failure) — main() still emits the snapshot."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from raft_tpu.neighbors.brute_force import _bf_knn_fused
    from raft_tpu.distance.types import DistanceType

    n, d, m, k = 100_000, 128, 10_000, 10
    n_batches = 10
    key = jax.random.key(0)
    kd, *kq = jax.random.split(key, 5)
    dataset = jax.random.uniform(kd, (n, d), jnp.float32)

    def one_set(kk):
        return jax.random.uniform(kk, (n_batches, m, d), jnp.float32)

    def searches(qs):
        return lax.map(lambda q: _bf_knn_fused(
            dataset, q, k, DistanceType.L2Expanded, "float32", None), qs)

    qsets = [one_set(kk) for kk in kq]
    fused_ok = True
    try:
        qps, _ = _measure_qps(searches, qsets, n_batches * m)
        rows.append({"name": "exact_fused_knn_100k", "qps": round(qps, 1),
                     "recall": 1.0, "build_s": 0.0})
    except Exception as e:  # pragma: no cover - bench resilience
        # fused-kernel failure (e.g. a Mosaic lowering change) must not kill
        # the whole bench: fall back to the XLA GEMM+top_k pipeline so A
        # primary number still prints, clearly labeled as the fallback (the
        # top-level vs_baseline is nulled by main() so rounds are not
        # compared apples-to-oranges)
        from raft_tpu.neighbors.brute_force import _bf_knn

        fused_ok = False
        rows.append({"name": "exact_fused_knn_100k", "error": str(e)[:200]})
        try:
            def searches_xla(qs):
                return lax.map(lambda q: _bf_knn(
                    dataset, q, k, DistanceType.L2Expanded, 2.0, 1000, 1000), qs)

            qps, _ = _measure_qps(searches_xla, qsets, n_batches * m)
            rows.append({"name": "exact_xla_knn_100k_fallback",
                         "qps": round(qps, 1), "recall": 1.0, "build_s": 0.0})
        except Exception as e2:  # environmental: emit what we have
            rows.append({"name": "exact_xla_knn_100k_fallback",
                         "error": str(e2)[:200]})
            return 0.0, False

    # bf16-compute row measured alongside (VERDICT r1 #2): same kernel, one
    # MXU pass instead of six; ~0.98 worst-case set recall on uniform data.
    # Guarded: a bf16-path failure must not lose the measured f32 row; and if
    # the fused kernel already failed, don't recompile it just to fail again.
    if not fused_ok:
        return qps, fused_ok
    try:
        def searches_bf16(qs):
            return lax.map(lambda q: _bf_knn_fused(
                dataset, q, k, DistanceType.L2Expanded, "bfloat16", None), qs)

        qps16, _ = _measure_qps(searches_bf16, qsets, n_batches * m)
        rows.append({"name": "exact_fused_knn_100k_bf16",
                     "qps": round(qps16, 1), "recall": None, "build_s": 0.0})
    except Exception as e:  # pragma: no cover - bench resilience
        rows.append({"name": "exact_fused_knn_100k_bf16", "error": str(e)[:200]})
    return qps, fused_ok


def _make_1m():
    """Clustered synthetic 1M x 128 + 10k queries, generated on-device
    (same distribution as bench/ann/run.py load_dataset: 2000 blobs)."""
    import jax
    import jax.numpy as jnp

    n, d, m, ncl = 1_000_000, 128, 10_000, 2000
    kc, kl, kn, kq1, kq2, kq3 = jax.random.split(jax.random.key(42), 6)
    centers = jax.random.uniform(kc, (ncl, d), jnp.float32) * 10.0

    def draw(kk_lab, kk_noise, count):
        labels = jax.random.randint(kk_lab, (count,), 0, ncl)
        return centers[labels] + 0.5 * jax.random.normal(kk_noise, (count, d))

    dataset = draw(kl, kn, n)
    qsets = []
    for kk in (kq1, kq2, kq3):
        ka, kb = jax.random.split(kk)
        qsets.append(draw(ka, kb, m))
    return dataset, qsets


def _emit(primary_qps, rows, fused_ok=True):
    """Print the full result line; called after every completed row so the
    last line on stdout is always a complete, parseable snapshot. When the
    fused kernel did not run, vs_baseline is null — the fallback's XLA number
    must not read as a regression of the same pipeline."""
    print(json.dumps({
        "metric": "exact brute-force kNN QPS (100k x 128 f32, k=10, batch 10k)",
        "value": round(primary_qps, 1),
        "unit": "QPS",
        "vs_baseline": round(primary_qps / 110805.2, 3) if fused_ok else None,
        "rows": rows,
        "elapsed_s": round(_elapsed(), 1),
    }), flush=True)


def main():
    import jax
    import numpy as np

    rows = []
    _note("flagship exact 100k")
    primary_qps, fused_ok = _flagship_exact(rows)
    _emit(primary_qps, rows, fused_ok)

    gt = None
    try:
        if _elapsed() < SOFT_BUDGET_S:
            _note("generating 1M dataset")
            dataset, qsets = _make_1m()
            jax.block_until_ready([dataset] + qsets)

            # ground truth for recall on the first 1000 queries of set 0
            from raft_tpu.neighbors.brute_force import _bf_knn_fused
            from raft_tpu.distance.types import DistanceType
            _note("ground truth 1k queries")
            # _measure_qps returns the output for the LAST query set — ground
            # truth must cover those same queries
            gt_q = qsets[-1][:1000]
            _, gt = _bf_knn_fused(dataset, gt_q, 10,
                                  DistanceType.L2Expanded, "float32", None)
            gt = np.asarray(gt)
    except Exception as e:  # pragma: no cover - bench resilience
        rows.append({"name": "dataset_1m", "error": str(e)[:200]})

    if gt is not None and _elapsed() < SOFT_BUDGET_S:
        try:
            from raft_tpu.neighbors import ivf_flat

            _note("ivf_flat build")
            t0 = time.perf_counter()
            idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=1024, seed=0), dataset)
            jax.block_until_ready(idx.list_data)
            build_s = time.perf_counter() - t0
            sp = ivf_flat.SearchParams(n_probes=8)
            qps, out = _measure_qps(
                lambda q: ivf_flat.search(sp, idx, q, 10), qsets,
                qsets[0].shape[0], use_jit=False)
            rows.append({"name": "ivf_flat_1m_p8",
                         "qps": round(qps, 1),
                         "recall": round(_recall(np.asarray(out[1])[:1000], gt), 4),
                         "build_s": round(build_s, 1)})
        except Exception as e:  # pragma: no cover
            rows.append({"name": "ivf_flat_1m_p8", "error": str(e)[:200]})
        _emit(primary_qps, rows, fused_ok)

    if gt is not None and _elapsed() < SOFT_BUDGET_S:
        try:
            from raft_tpu.neighbors import cagra

            _note("cagra build")
            t0 = time.perf_counter()
            idx = cagra.build(cagra.IndexParams(), dataset)
            jax.block_until_ready(idx.graph)
            build_s = time.perf_counter() - t0
            sp = cagra.SearchParams(itopk_size=32)
            qps, out = _measure_qps(
                lambda q: cagra.search(sp, idx, q, 10), qsets,
                qsets[0].shape[0], use_jit=False)
            rows.append({"name": "cagra_1m_itopk32",
                         "qps": round(qps, 1),
                         "recall": round(_recall(np.asarray(out[1])[:1000], gt), 4),
                         "build_s": round(build_s, 1)})
        except Exception as e:  # pragma: no cover
            rows.append({"name": "cagra_1m_itopk32", "error": str(e)[:200]})

    # the reference publishes no absolute numbers (BASELINE.md); the recorded
    # round-1 flagship (110,805 QPS, BENCH_r01.json) is the progress baseline
    _emit(primary_qps, rows, fused_ok)


if __name__ == "__main__":
    main()
