"""raft_tpu benchmark entry point (run by the driver on real TPU hardware).

Prints ONE JSON line: the flagship metric is exact-kNN search throughput
(QPS) on a synthetic 100k x 128 dataset, k=10 — the brute-force operating
point of the reference's ANN harness (cpp/bench/ann, batch-mode QPS metric,
cpp/bench/ann/src/common/benchmark.hpp:168). The reference publishes no
numbers (BASELINE.md), so vs_baseline is reported as 1.0 by definition of
"no published baseline"; cross-framework comparison happens via the recorded
absolute QPS.
"""

from __future__ import annotations

import json
import time


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.neighbors import knn

    n, d, m, k = 100_000, 128, 10_000, 10
    rng = np.random.default_rng(0)
    dataset = jnp.asarray(rng.random((n, d), np.float32))
    queries = jnp.asarray(rng.random((m, d), np.float32))

    # warmup / compile
    out = knn(dataset, queries, k, metric="sqeuclidean")
    jax.block_until_ready(out)

    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        out = knn(dataset, queries, k, metric="sqeuclidean")
        jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters

    qps = m / dt
    print(
        json.dumps(
            {
                "metric": "brute-force kNN QPS (100k x 128 f32, k=10, batch 10k)",
                "value": round(qps, 1),
                "unit": "QPS",
                "vs_baseline": 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
