"""raft_tpu benchmark entry point (run by the driver on real TPU hardware).

Prints ONE JSON line: the flagship metric is exact-kNN search throughput
(QPS) on a synthetic 100k x 128 dataset, k=10 — the brute-force operating
point of the reference's ANN harness (cpp/bench/ann, batch-mode QPS metric,
cpp/bench/ann/src/common/benchmark.hpp:168). The reference publishes no
numbers (BASELINE.md), so vs_baseline is reported as 1.0 by definition of
"no published baseline"; cross-framework comparison happens via the recorded
absolute QPS.

Measurement notes:
- batches are chained inside ONE jitted program (lax.map over distinct query
  batches) and the result is materialized to host — the device tunnel in this
  environment caches repeated identical dispatches and under-reports blocking
  waits, so naive per-call timing with block_until_ready reports fantasy QPS;
- every batch has distinct query data; reported QPS divides total queries by
  total wall time including the final host sync.
"""

from __future__ import annotations

import json
import time


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.neighbors.brute_force import _bf_knn
    from raft_tpu.distance.types import DistanceType

    n, d, m, k = 100_000, 128, 10_000, 10
    n_batches = 10
    rng = np.random.default_rng(0)
    dataset = jnp.asarray(rng.random((n, d), np.float32))
    batches = jnp.asarray(rng.random((n_batches, m, d), np.float32))

    def one_batch(q):
        return _bf_knn(dataset, q, k, DistanceType.L2Expanded, 2.0, 1000, 1000)

    chained = jax.jit(lambda qs: jax.lax.map(one_batch, qs))

    # warmup / compile (distinct data so nothing is reusable)
    warm = jnp.asarray(rng.random((n_batches, m, d), np.float32))
    np.asarray(jax.tree_util.tree_leaves(chained(warm))[0])

    # best of 3: tunnel RPC latency and transient device contention add
    # tens-of-percent run-to-run noise; min is the standard de-noiser
    batch_sets = [batches] + [
        jnp.asarray(rng.random((n_batches, m, d), np.float32)) for _ in range(2)
    ]
    dt = float("inf")
    for bs in batch_sets:
        t0 = time.perf_counter()
        out = chained(bs)
        np.asarray(jax.tree_util.tree_leaves(out)[0])  # host materialization
        dt = min(dt, time.perf_counter() - t0)

    qps = n_batches * m / dt
    print(
        json.dumps(
            {
                "metric": "exact brute-force kNN QPS (100k x 128 f32, k=10, batch 10k)",
                "value": round(qps, 1),
                "unit": "QPS",
                "vs_baseline": 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
