"""raft_tpu benchmark entry point (run by the driver on real TPU hardware).

Prints a full-result JSON line after every completed row (take the LAST
line), and is contractually unkillable: ANY Python-visible failure —
including jax import errors, TPU backend init raising OR hanging (watchdog),
and SIGTERM delivered while the interpreter is running Python code — still
emits a complete, parseable snapshot with the failure recorded as a row, and
the process exits 0. (SIGKILL, or a SIGTERM arriving inside a non-yielding
native call, can still drop only the rows after the last printed line.) This
mirrors the reference harness, which always writes its result files and
confines each benchmark case to its own try/catch
(cpp/bench/ann/src/common/benchmark.hpp:111-200).

The primary metric stays the exact brute-force kNN search throughput on
100k x 128, k=10, batch 10k (the protocol BENCH_r01 recorded, so rounds are
comparable), served by the fused Pallas distance+top-k kernel
(ops/fused_knn.py). The "rows" field carries the regression suite the driver
archives per round:

  exact_fused_knn_100k           f32 (exact) flagship — the primary value
  exact_xla_control              plain XLA GEMM+top_k, SAME process/queries —
                                 the fused/control ratio is the session-
                                 independent round-over-round signal
  exact_fused_knn_100k_bf16      same kernel, single-pass bf16 MXU mode
  exact_fused_knn_100k_f32x3     compensated bf16x3 mode (f32-class accuracy)
  exact_fused_knn_100k_i8        same data quantized to int8: s8 x s8 -> s32
                                 MXU mode, 1/4 the dataset DMA bytes; carries
                                 i8_over_f32 (recall is vs the f32 row's ids)
  ivf_pq_1m_lid_pq4x64_r4        IVF-PQ on the SIFT-class low-intrinsic-dim
                                 1M set: pq4x64, p8, bf16 LUT, refine 4
  ivf_pq_1m_i8                   the same LID set quantized to int8 bytes
                                 (BigANN regime): byte build + byte refine;
                                 carries i8_over_f32 vs the f32 LID row
  serve_ivf_pq_100k              raft_tpu.serve A/B: closed-loop threaded
                                 load through SearchService (micro-batched)
                                 vs sequential batch-1 search on the same
                                 index; carries serve_over_seq, p50/p99 ms,
                                 mean batch occupancy, and the mid-load
                                 hot-swap proof (swap.failed == 0,
                                 swap.compile_s == 0). `--serve` runs ONLY
                                 this row (parameter iteration loop).
  serve_pipeline_100k            host-free flush pipeline A/B (ISSUE 12):
                                 the SAME closed-loop threaded load served
                                 synchronously (pipeline_depth=0, the
                                 BENCH_r05-era flush) vs pipelined
                                 (bounded in-flight completion + pinned
                                 double-buffered staging with donation) —
                                 per-flush QPS and p50/p99 both modes at
                                 identical recall, the queue-wait vs
                                 flush-time decomposition per mode (the
                                 win must land on the flush side), mean
                                 dispatches per flush, zero failed
                                 queries, zero cold compiles across the
                                 pipelined window, and flat staging-ledger
                                 bytes across post-load waves (donation
                                 returns the previous query buffer).
                                 `--serve-pipeline` runs ONLY this row.
  serve_churn_ivf_pq_100k        raft_tpu.stream churn row: closed-loop
                                 mixed read/write load on a
                                 MutableIndex(ivf_pq) — p50/p99 search
                                 latency + write throughput under sustained
                                 upsert+delete, >= 2 mid-load compaction
                                 swaps with zero failed queries
                                 (churn.failed == 0), mid-churn recall@10
                                 within 0.01 of a fresh-oracle build
                                 (recall_gap), and zero cold compiles on
                                 the search hot path (churn.compile_s == 0,
                                 rehearsal-warmed). `--serve-churn` runs
                                 ONLY the churn rows.
  serve_churn_cagra_100k         the same churn protocol on a CAGRA-backed
                                 MutableIndex: compactions run the REBUILD
                                 path (no extend for graphs), so the row
                                 measures build speed as serving capacity —
                                 write_rows_per_s is bounded by the rebuild
                                 wall (churn.compaction_wall_s); the r07
                                 mini-batch coarse EM + sharded builds
                                 surface here as write throughput.
  serve_shard_churn_100k         sharded serving tier (ISSUE 9):
                                 ShardedMutableIndex(ivf_flat) scatter-
                                 gathered over 1/2/4/8 device-pinned
                                 shards at proportional operating points —
                                 closed-loop QPS per shard count
                                 (qps_by_shards, scaling_1_to_4, cores),
                                 then a mixed read/write churn window at
                                 the top shard count with STAGGERED
                                 one-shard-per-cycle compactions (>= 2
                                 folds, churn.failed == 0), zero cold
                                 compiles (rehearsal-warmed; includes the
                                 mesh-wide canary's shadow reranks), and
                                 the fresh-oracle recall inside the live
                                 canary's Wilson interval. `--serve-shard`
                                 runs ONLY this row.
  canary_smoke_100k              raft_tpu.obs.quality overhead A/B
                                 (ISSUE 8): closed-loop served QPS with
                                 canary sampling at 0% vs 1% vs 5% (the
                                 background drainer shadow-reranking
                                 against the exact live-corpus kNN), the
                                 streaming recall estimate + Wilson
                                 interval bracketing the offline truth
                                 (canary.oracle_in_interval), and the
                                 compile-free hot path with monitoring ON
                                 (compile_s == 0). `--canary-smoke` runs
                                 ONLY this row. The churn rows above also
                                 carry a "canary" field: the estimate
                                 measured UNDER churn with compaction
                                 swaps, bracketed against recall_mut.
  tune_smoke_10k                 raft_tpu.tune loop proof (ISSUE 7): a
                                 tiny-budget autotune sweep on a 10k IVF-PQ
                                 index — chosen vs grid-head (hand-picked)
                                 operating point with the QPS ratio in the
                                 row; the full sweeps write TUNE_rXX.json
                                 via bench/tune_sweep.py. `--tune-smoke`
                                 runs ONLY this row.
  fault_smoke_100k               availability proof (ISSUE 11): a sharded
                                 mesh with per-shard replica groups serves
                                 a loaded window during which one replica
                                 is killed (fault-injected) and later
                                 revived — zero failed queries (same-flush
                                 failover to the surviving twin), the
                                 victim actually fenced (strikes > 0) and
                                 healed through the backoff re-probe
                                 (recovery_s), zero cold compiles across
                                 the fence/failover/probe window
                                 (rehearsal-warmed). `--fault-smoke` runs
                                 ONLY the fault rows.
  crash_recovery_100k            crash-durability proof (ISSUE 11): a 100k
                                 MutableIndex with a write-ahead log takes
                                 an un-compacted write burst, "dies" via a
                                 SimulatedCrash between WAL append and
                                 memtable insert, and recovers through
                                 stream.load(wal=) + replay + warm() —
                                 recall_recovered == 1.0 vs an uncrashed
                                 twin (gated by bench/compare.py),
                                 recovery_s + replay_rows_per_s recorded,
                                 zero cold compiles post-warm.
  reshard_churn_100k             elastic-resharding proof (ISSUE 13): a
                                 loaded 2-shard x 2-replica mesh DOUBLES
                                 its shard count online — reader threads
                                 live through fold, carry-over and the
                                 atomic flip with one replica killed
                                 mid-migration — zero failed queries,
                                 zero cold compiles (rehearsal protocol;
                                 the successors' ladders + the doubled
                                 merge warm pre-flip), recall_pre/post vs
                                 the exact mesh oracle held across the
                                 flip (compare.py-gated), plus a measured
                                 crash-mid-reshard recovery: SimulatedCrash
                                 between successor swap and manifest
                                 write, load() recovers the OLD topology
                                 id-for-id (recall_crash_recovered).
                                 `--reshard` runs ONLY this row.
  ivf_flat_1m_p8                 IVF-Flat on the isotropic clustered 1M set
  cagra_1m_itopk32               CAGRA on the same set

  Ratio fields ride IN the rows (fused_over_control on exact_xla_control,
  i8_over_f32 on the i8 rows) so BASELINE round notes can be regenerated
  from the JSON artifact alone (VERDICT item 7).

  Each guarded row scope also attaches an "obs" attribution dict
  (compile_s, cache_hits/misses, collective_bytes — from raft_tpu.obs via
  jax.monitoring) so the artifact says WHERE the seconds went, not just the
  QPS; `--no-metrics` disables the whole obs surface (rows then carry no
  "obs" field) and proves the disabled path the obs_overhead test guards.

Measurement notes:
- batches are chained inside ONE jitted program with DISTINCT query data and
  materialized to host: the device tunnel caches repeated identical dispatches
  and under-reports blocking waits, so anything else reports fantasy QPS;
- all data is generated on-device (jax.random) — a 512 MB host->device
  transfer through the tunnel would dominate the timings;
- the persistent XLA compilation cache (~/.cache/raft_tpu/jit) is enabled at
  startup, so 1M index builds are cold-jit only the first time this machine
  runs them (IVF-Flat ~145 s cold / seconds warm);
- rows degrade gracefully: each row has its own try/except, and rows beyond
  the soft time budget are skipped rather than failing the whole bench;
- a complete JSON line is (re)printed after every finished row, so if the
  driver kills the process on a slow-chip day, the LAST printed line still
  carries every row completed so far.
"""

from __future__ import annotations

import json
import sys
import time

SOFT_BUDGET_S = 480.0  # stop starting new rows beyond this
_T0 = time.perf_counter()

_STATE = {"primary": 0.0, "fused_ok": True, "rows": [], "metrics": True}


def _elapsed():
    return time.perf_counter() - _T0


def _note(msg):
    print(f"[bench +{_elapsed():.0f}s] {msg}", file=sys.stderr, flush=True)


def _emit():
    """Print the full result line; called after every completed row so the
    last line on stdout is always a complete, parseable snapshot. When the
    fused kernel did not run, vs_baseline is null — a fallback's XLA number
    must not read as a regression of the same pipeline. Depends on nothing
    but the stdlib, so it works even when jax itself is broken."""
    print(json.dumps({
        "metric": "exact brute-force kNN QPS (100k x 128 f32, k=10, batch 10k)",
        "value": round(_STATE["primary"], 1),
        "unit": "QPS",
        "vs_baseline": (round(_STATE["primary"] / 110805.2, 3)
                        if _STATE["fused_ok"] and _STATE["primary"] > 0
                        else None),
        "rows": _STATE["rows"],
        "metrics_enabled": _STATE["metrics"],
        "elapsed_s": round(_elapsed(), 1),
    }), flush=True)


def _obs_snap():
    """Flat obs snapshot, or None when metrics are disabled/unavailable —
    never fatal (the bench must survive a broken raft_tpu import)."""
    try:
        from raft_tpu import obs

        if not obs.enabled():
            return None
        return obs.to_json()
    except Exception:
        return None


def _obs_attach(rows, start, before):
    """Attach the compile/cache/collective attribution of one guarded row
    scope to every row it appended (ISSUE 2: BENCH artifacts carry the
    attribution alongside QPS). Rows produced by the same scope share the
    scope's delta; under --no-metrics no "obs" field appears at all (the
    disabled-path proof)."""
    if before is None:
        return
    after = _obs_snap()
    if after is None:
        return
    try:
        from raft_tpu import obs

        d = obs.delta(before, after)

        def tot(prefix):
            return sum(v for k, v in d.items() if k.startswith(prefix))

        summary = {
            "compile_s": round(
                tot('raft_tpu_compile_seconds_sum{stage="compile"}'), 3),
            "cache_hits": int(tot(
                'raft_tpu_compile_cache_total{outcome="hit"}')),
            "cache_misses": int(tot(
                'raft_tpu_compile_cache_total{outcome="miss"}')),
            "collective_bytes": int(tot("raft_tpu_collective_bytes_total")),
        }
        for r in rows[start:]:
            r.setdefault("obs", summary)
    except Exception:
        pass


def _mem_snap():
    """Ledger totals at a row-scope start (peak re-based so the scope's
    peak is the ROW's peak), or None when metrics are disabled — the
    disabled bench carries no "mem" field, mirroring the "obs" field."""
    try:
        from raft_tpu.obs import mem as obs_mem
        from raft_tpu.obs import metrics as obs_metrics

        if not obs_metrics.enabled():
            return None
        obs_mem.reset_peak()
        try:
            from raft_tpu.stream import tiered as _tiered

            _tiered.reset_tier_peak()
        except Exception:
            pass
        return obs_mem.totals()
    except Exception:
        return None


def _mem_attach(rows, start, before):
    """Attach the ledger's peak device/host bytes over one guarded row
    scope to every row it appended (ISSUE 10: BENCH rows carry memory
    alongside QPS — the capacity half of the perf story). Peaks are the
    scope's own (reset at _mem_snap); deltas subtract the scope-entry
    totals, so a row that allocates and frees reports delta ~0 with a
    real peak."""
    if before is None:
        return
    try:
        from raft_tpu.obs import mem as obs_mem

        after = obs_mem.totals()
        summary = {
            "device_bytes": after["device_bytes"],
            "device_peak_bytes": after["device_peak_bytes"],
            "device_delta_bytes":
                after["device_bytes"] - before["device_bytes"],
            "host_bytes": after["host_bytes"],
            "host_peak_bytes": after["host_peak_bytes"],
            "host_delta_bytes": after["host_bytes"] - before["host_bytes"],
        }
        try:
            # per-tier attribution (ISSUE 15): rows whose scope held a
            # TieredStore carry the tier byte split — the per-scope
            # WATERMARK, not the live totals: a row's store is usually a
            # frame local already freed by the time attribution attaches.
            # Gated by bench/compare.py like recall fields (a lost tier
            # measurement must fail, not pass silently)
            from raft_tpu.stream import tiered as _tiered

            tiers = _tiered.tier_peak()
            if tiers:
                summary["tiers"] = tiers
        except Exception:
            pass
        for r in rows[start:]:
            r.setdefault("mem", summary)
    except Exception:
        pass


def _events_snap():
    """Journal counts-by-kind at a row-scope start, or None when metrics
    are disabled — the disabled bench carries no "events" field, mirroring
    the "obs"/"mem" fields. Cumulative counts, so ring eviction during the
    scope cannot under-report."""
    try:
        from raft_tpu.obs import events as obs_events
        from raft_tpu.obs import metrics as obs_metrics

        if not obs_metrics.enabled():
            return None
        return obs_events.counts_by_kind()
    except Exception:
        return None


def _events_delta(before):
    """Per-kind event counts emitted since ``before`` (ISSUE 17: the
    fault/reshard/tiered rows carry what the event plane SAW — a fence
    that fired zero ``replica_fenced`` events is a lost measurement).
    Gated by bench/compare.py on field presence like recall fields."""
    if before is None:
        return None
    try:
        from raft_tpu.obs import events as obs_events

        after = obs_events.counts_by_kind()
        delta = {k: after[k] - before.get(k, 0) for k in sorted(after)
                 if after[k] - before.get(k, 0) > 0}
        return delta
    except Exception:
        return None


def _recall(ids, gt):
    import numpy as np

    ids, gt = np.asarray(ids), np.asarray(gt)
    k = gt.shape[1]
    return float(np.mean([len(set(ids[r, :k]) & set(gt[r])) / k
                          for r in range(gt.shape[0])]))


def _measure_qps(search_fn, query_sets, m, use_jit=True):
    """Best-of-N wall time over distinct query sets, host-materialized.

    ``use_jit=False`` for index searches: they carry their own internal jit
    caches, and an enclosing jit would re-trace the whole 1M-scale pipeline
    into one giant program (minutes of extra compile for no steady-state
    gain).
    """
    import jax
    import numpy as np

    jax.block_until_ready(query_sets)
    f = jax.jit(search_fn) if use_jit else search_fn
    np.asarray(jax.tree_util.tree_leaves(f(query_sets[0]))[0])  # compile+warm
    best = float("inf")
    out = None
    for qs in query_sets[1:]:
        t0 = time.perf_counter()
        out = f(qs)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return m / best, out


def _flagship_exact(rows, n=100_000, d=128, m=10_000, k=10, n_batches=10):
    """Exact kNN 100k x 128 — identical protocol to BENCH_r01 (the shape
    arguments exist ONLY so the CPU smoke test can exercise every row body
    at interpret-mode scale; the driver always runs the defaults).

    Sets _STATE["primary"]/_STATE["fused_ok"]; every sub-measurement is
    individually guarded so one mode's failure never loses another's row."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from raft_tpu.neighbors.brute_force import _bf_knn_fused
    from raft_tpu.distance.types import DistanceType
    key = jax.random.key(0)
    kd, *kq = jax.random.split(key, 5)
    dataset = jax.random.uniform(kd, (n, d), jnp.float32)

    def one_set(kk):
        return jax.random.uniform(kk, (n_batches, m, d), jnp.float32)

    qsets = [one_set(kk) for kk in kq]

    def mode_searches(mode):
        def searches(qs):
            return lax.map(lambda q: _bf_knn_fused(
                dataset, q, k, DistanceType.L2Expanded, mode, None), qs)
        return searches

    # ONE definition of the plain XLA GEMM+top_k pipeline, shared by the
    # fused-failure fallback and the in-process control row — the two must
    # measure the same pipeline by construction
    def searches_xla(qs):
        from raft_tpu.neighbors.brute_force import _bf_knn

        return lax.map(lambda q: _bf_knn(
            dataset, q, k, DistanceType.L2Expanded, 2.0, 1000, 1000), qs)

    try:
        qps, out_f32 = _measure_qps(mode_searches("float32"), qsets,
                                    n_batches * m)
        _STATE["primary"] = qps
        rows.append({"name": "exact_fused_knn_100k", "qps": round(qps, 1),
                     "recall": 1.0, "build_s": 0.0})
        _emit()  # the primary row must survive a kill during bf16/f32x3
    except Exception as e:  # pragma: no cover - bench resilience
        # fused-kernel failure (e.g. a Mosaic lowering change) must not kill
        # the whole bench: fall back to the XLA GEMM+top_k pipeline so a
        # primary number still prints, clearly labeled as the fallback (the
        # top-level vs_baseline is nulled so rounds are not compared
        # apples-to-oranges)
        _STATE["fused_ok"] = False
        rows.append({"name": "exact_fused_knn_100k", "error": str(e)[:200]})
        try:
            qps, _ = _measure_qps(searches_xla, qsets, n_batches * m)
            _STATE["primary"] = qps
            rows.append({"name": "exact_xla_knn_100k_fallback",
                         "qps": round(qps, 1), "recall": 1.0, "build_s": 0.0})
        except Exception as e2:  # environmental: emit what we have
            rows.append({"name": "exact_xla_knn_100k_fallback",
                         "error": str(e2)[:200]})
        return

    # in-process control (VERDICT r4 #7): the plain XLA GEMM+top_k pipeline
    # measured in the SAME process on the SAME query sets. Tunnel sessions
    # swing tens of percent between runs (BASELINE.md protocol), so the
    # round-over-round signal is the fused/control RATIO within one process,
    # not the absolute vs_baseline quotient across sessions.
    try:
        qps_c, _ = _measure_qps(searches_xla, qsets, n_batches * m)
        rows.append({"name": "exact_xla_control", "qps": round(qps_c, 1),
                     "recall": 1.0, "build_s": 0.0,
                     "fused_over_control": round(_STATE["primary"] / qps_c, 3)})
    except Exception as e:  # pragma: no cover - bench resilience
        rows.append({"name": "exact_xla_control", "error": str(e)[:200]})
    _emit()

    # bf16 (one MXU pass instead of six; ~0.98 worst-case set recall on
    # uniform data) and f32x3 (three passes, f32-class accuracy) modes,
    # measured alongside (VERDICT r2 #2). Each row's recall is the set recall
    # of its ids against the f32 row's ids on the same query set (VERDICT r3
    # #7: the accuracy claims must live in the driver artifact, not
    # docstrings). Guarded per mode.
    import numpy as np

    ref_ids = np.asarray(out_f32[1])[0, :1000]  # first batch, 1k queries
    for mode, row_name in (("bfloat16", "exact_fused_knn_100k_bf16"),
                           ("float32x3", "exact_fused_knn_100k_f32x3")):
        try:
            qps_m, out_m = _measure_qps(mode_searches(mode), qsets,
                                        n_batches * m)
            rec = _recall(np.asarray(out_m[1])[0, :1000], ref_ids)
            rows.append({"name": row_name, "qps": round(qps_m, 1),
                         "recall": round(rec, 4), "build_s": 0.0})
        except Exception as e:  # pragma: no cover - bench resilience
            rows.append({"name": row_name, "error": str(e)[:200]})
        _emit()

    # int8 row (the byte-dataset tentpole): the SAME uniform data quantized
    # onto the 256 byte levels — one quarter of the f32 dataset DMA bytes,
    # s8 x s8 -> s32 MXU contraction (~2x bf16 peak). Recall is vs the f32
    # row's ids on identical queries, so the row's recall claim is "vs exact
    # f32 ground truth" (it folds in the quantization of the 1/255-wide
    # bins, not just kernel error); the i8_over_f32 ratio rides in the row
    # so round notes regenerate from the JSON artifact alone.
    try:
        from raft_tpu.neighbors.brute_force import _bf_knn_s8

        def to_i8(a):
            return jnp.clip(jnp.round(a * 255.0 - 128.0),
                            -128, 127).astype(jnp.int8)

        ds_i8 = to_i8(dataset)
        qsets_i8 = [to_i8(qs) for qs in qsets]

        def searches_s8(qs):
            return lax.map(lambda q: _bf_knn_s8(
                ds_i8, q, k, DistanceType.L2Expanded, None), qs)

        qps_i, out_i = _measure_qps(searches_s8, qsets_i8, n_batches * m)
        rec = _recall(np.asarray(out_i[1])[0, :1000], ref_ids)
        rows.append({"name": "exact_fused_knn_100k_i8",
                     "qps": round(qps_i, 1), "recall": round(rec, 4),
                     "build_s": 0.0,
                     "i8_over_f32": round(qps_i / _STATE["primary"], 3)})
    except Exception as e:  # pragma: no cover - bench resilience
        rows.append({"name": "exact_fused_knn_100k_i8", "error": str(e)[:200]})
    _emit()


def _make_clustered(n, d, m, ncl, n_qsets=3, seed=42):
    """Isotropic clustered synthetic set + query sets, generated on-device
    (same distribution as bench/ann/run.py load_dataset: gaussian blobs with
    full-dimensional residuals — PQ's worst case). Shared by the 1M rows and
    the serve row (which runs it at 100k)."""
    import jax
    import jax.numpy as jnp

    keys = jax.random.split(jax.random.key(seed), 3 + n_qsets)
    kc, kl, kn = keys[:3]
    centers = jax.random.uniform(kc, (ncl, d), jnp.float32) * 10.0

    def draw(kk_lab, kk_noise, count):
        labels = jax.random.randint(kk_lab, (count,), 0, ncl)
        return centers[labels] + 0.5 * jax.random.normal(kk_noise, (count, d))

    dataset = draw(kl, kn, n)
    qsets = []
    for kk in keys[3:]:
        ka, kb = jax.random.split(kk)
        qsets.append(draw(ka, kb, m))
    return dataset, qsets


def _make_1m():
    return _make_clustered(1_000_000, 128, 10_000, 2000)


def _make_lid_1m():
    """SIFT-class proxy 1M x 128 (r04 redesign — BASELINE.md "Round-4
    SIFT-class dataset study"): low intrinsic dimension AND multi-scale
    local density. 2000 clusters x 16 sub-clumps x ~31 points; residuals
    live in a per-cluster random 16-dim subspace (clump offsets std 0.5,
    fine residuals std 0.15). The r01-r03 generator drew single-gaussian
    residuals, which concentrate ALL neighbor margins at one scale
    (gaussian shell) — PQ's worst case (refine4 recall 0.55, BENCH_r03) and
    unlike real descriptor data, whose near-duplicate multi-scale structure
    gives PQ a coarse clump-vs-rest job with refine doing the fine ranking
    (real SIFT-1M sits near 0.99 at this operating point). The committed
    generator measures refine4 recall >= 0.95 with MLE intrinsic dimension
    ~6-8 (``_lid_estimate``, reported in the bench row). Ref dataset
    machinery: cpp/bench/ann/src/common/dataset.h:38-108,
    conf/sift-128-euclidean.json."""
    import jax
    import jax.numpy as jnp

    n, d, m, ncl, idim, nclump = 1_000_000, 128, 10_000, 2000, 16, 16
    kc, kb, ko, kl, kj, kz, kq1, kq2, kq3 = jax.random.split(
        jax.random.key(7), 9)
    centers = jax.random.uniform(kc, (ncl, d), jnp.float32) * 10.0
    # per-cluster random basis (idim, d), unit rows
    bases = jax.random.normal(kb, (ncl, idim, d), jnp.float32)
    bases = bases / jnp.linalg.norm(bases, axis=-1, keepdims=True)
    offsets = 0.5 * jax.random.normal(ko, (ncl, nclump, idim), jnp.float32)

    def draw(kk_lab, kk_clump, kk_noise, count):
        labels = jax.random.randint(kk_lab, (count,), 0, ncl)
        clump = jax.random.randint(kk_clump, (count,), 0, nclump)
        z = offsets[labels, clump] + 0.15 * jax.random.normal(
            kk_noise, (count, idim))
        return centers[labels] + jnp.einsum(
            "ni,nid->nd", z, bases[labels], precision="highest")

    # chunked: a single 1M draw would gather bases[labels] into a
    # (1M, 16, 128) f32 temporary (~8.2 GB — over half of v5e HBM); 50k-row
    # blocks bound the temp to ~410 MB
    blk = 50_000
    kls = jax.random.split(kl, n // blk)
    kjs = jax.random.split(kj, n // blk)
    kzs = jax.random.split(kz, n // blk)
    dataset = jnp.concatenate(
        [draw(kls[i], kjs[i], kzs[i], blk) for i in range(n // blk)])
    qsets = []
    for kk in (kq1, kq2, kq3):
        ka, kb2, kc2 = jax.random.split(kk, 3)
        qsets.append(draw(ka, kb2, kc2, m))
    return dataset, qsets


def _lid_estimate(dataset, k=20, n_sample=1000):
    """Levina-Bickel MLE intrinsic-dimension estimate from k-NN radii of a
    dataset sample (the measured grounding VERDICT r3 #2 asked for; real
    descriptor data reports ~5-15 at comparable scales)."""
    import jax
    import numpy as np

    from raft_tpu.distance.types import DistanceType
    from raft_tpu.neighbors.brute_force import _bf_knn_fused

    ids = jax.random.choice(jax.random.key(1), dataset.shape[0],
                            (n_sample,), replace=False)
    d2, _ = _bf_knn_fused(dataset, dataset[ids], k + 1,
                          DistanceType.L2Expanded, "float32", None)
    r = np.sqrt(np.maximum(np.asarray(d2)[:, 1:], 1e-12))  # drop self
    inv = np.log(r[:, -1:] / np.maximum(r[:, :-1], 1e-12)).mean(axis=1)
    return float(np.mean(1.0 / np.maximum(inv, 1e-9)))


def _ground_truth(dataset, queries, k=10):
    import numpy as np

    from raft_tpu.neighbors.brute_force import _bf_knn_fused
    from raft_tpu.distance.types import DistanceType

    _, gt = _bf_knn_fused(dataset, queries, k,
                          DistanceType.L2Expanded, "float32", None)
    return np.asarray(gt)


def _row_ivf_pq_lid(rows, box=None):
    """IVF-PQ regression row (VERDICT r2 missing #2): the shipped default
    config (pq4x64, bits-aware auto pq_dim) + refine 4 on the SIFT-class set
    — the r02 sweep's headline operating point (0.9991 @ 26.4k QPS).
    ``box`` (optional dict) receives the generated dataset/qsets so the i8
    row can quantize the same data instead of paying a second 1M draw."""
    import jax
    import numpy as np

    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.refine import refine

    _note("LID 1M dataset")
    dataset, qsets = _make_lid_1m()
    jax.block_until_ready([dataset] + qsets)
    if box is not None:
        box["dataset"], box["qsets"] = dataset, qsets
    _note("LID estimate")
    lid = _lid_estimate(dataset)
    _note("LID ground truth 1k queries")
    gt = _ground_truth(dataset, qsets[-1][:1000])

    _note("ivf_pq build")
    t0 = time.perf_counter()
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=1024, pq_bits=4, pq_dim=64, seed=0), dataset)
    jax.block_until_ready(idx.list_codes)
    build_s = time.perf_counter() - t0
    sp = ivf_pq.SearchParams(n_probes=8, lut_dtype="bfloat16")

    def searcher(q):
        _, cand = ivf_pq.search(sp, idx, q, 40)
        return refine(dataset, q, cand, 10)

    qps, out = _measure_qps(searcher, qsets, qsets[0].shape[0], use_jit=False)
    rows.append({"name": "ivf_pq_1m_lid_pq4x64_r4",
                 "qps": round(qps, 1),
                 "recall": round(_recall(np.asarray(out[1])[:1000], gt), 4),
                 "build_s": round(build_s, 1),
                 "lid_estimate": round(lid, 1)})


def _row_ivf_pq_i8(rows, dataset, qsets, n_lists=1024, pq_dim=64):
    """IVF-PQ on int8 bytes (the byte-dataset tentpole; reference ships
    dedicated ivf_pq int8_t/uint8_t instantiations — BigANN-class byte data
    is PQ's home regime): the LID set affinely quantized onto the 256 byte
    levels. Ground truth is the exact kNN of the SAME bytes (s8 MXU path,
    exact integer distances), so the row's recall measures the index, not
    the quantization; the i8_over_f32 QPS ratio vs the f32 LID row rides in
    the row itself so round notes regenerate from the JSON artifact."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.brute_force import knn
    from raft_tpu.neighbors.refine import refine

    lo = float(dataset.min())
    scale = 255.0 / max(float(dataset.max()) - lo, 1e-9)

    def to_i8(a):
        return jnp.clip(jnp.round((a - lo) * scale - 128.0),
                        -128, 127).astype(jnp.int8)

    ds = to_i8(dataset)
    qs = [to_i8(q) for q in qsets]
    jax.block_until_ready([ds] + qs)
    _note("i8 ground truth 1k queries")
    _, gt = knn(ds, qs[-1][:1000], 10)  # exact s8 kNN of the bytes
    gt = np.asarray(gt)

    _note("ivf_pq i8 build")
    t0 = time.perf_counter()
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=n_lists, pq_bits=4, pq_dim=pq_dim,
                           seed=0), ds)
    jax.block_until_ready(idx.list_codes)
    build_s = time.perf_counter() - t0
    sp = ivf_pq.SearchParams(n_probes=8, lut_dtype="bfloat16")

    def searcher(q):
        _, cand = ivf_pq.search(sp, idx, q, 40)
        return refine(ds, q, cand, 10)  # exact byte refine (1-byte gathers)

    qps, out = _measure_qps(searcher, qs, qs[0].shape[0], use_jit=False)
    f32_qps = next((r["qps"] for r in rows
                    if r.get("name") == "ivf_pq_1m_lid_pq4x64_r4"
                    and "qps" in r), None)
    rows.append({"name": "ivf_pq_1m_i8",
                 "qps": round(qps, 1),
                 "recall": round(_recall(np.asarray(out[1])[:1000], gt), 4),
                 "build_s": round(build_s, 1),
                 "i8_over_f32": (round(qps / f32_qps, 3)
                                 if f32_qps else None)})


def _row_serve(rows, n=100_000, d=128, n_lists=1024, pq_dim=64, k=10,
               n_probes=8, threads=8, per_thread=400, seq_queries=512,
               max_batch=64, max_wait_us=2000.0, ncl=2000):
    """Serving-layer A/B (raft_tpu.serve): closed-loop multi-threaded load
    through SearchService vs the same index searched sequentially at
    batch 1 — the protocol every caller WITHOUT a batcher runs today.

    Three claims ride in the row (the ISSUE 3 acceptance set):
    - ``serve_over_seq`` — micro-batching amortizes per-dispatch overhead
      across the bucket; the acceptance bar is >= 3x at identical recall
      (same index, same params, so recall is measured once on the service's
      own outputs against exact ground truth).
    - a **mid-load hot-swap**: a second index (pre-built outside the timed
      window) is published while the closed loop runs; ``swap.failed`` MUST
      be 0 (in-flight requests finish on the old version).
    - **zero cold compiles on the serving path**: the whole loaded window —
      including the swap's warmup and flip — runs under obs compile
      attribution; ``swap.compile_s``/``swap.cache_misses`` must be 0
      because publish() warmed every bucket BEFORE the flip and the rebuilt
      index is HLO-identical at every bucket shape.

    p50/p99 are per-request milliseconds measured by the submitting
    threads; occupancy is the obs histogram's mean over the window."""
    import threading

    import jax
    import numpy as np

    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.obs import metrics as obs_metrics
    from raft_tpu.serve import SearchService

    _note("serve: dataset")
    dataset, qsets = _make_clustered(n, d, max(threads * per_thread, 1000),
                                     ncl, n_qsets=1, seed=11)
    jax.block_until_ready([dataset] + qsets)
    _note("serve: ground truth")
    gt = _ground_truth(dataset, qsets[0][:1000], k=k)  # gt width = serving k
    # host copy: the submitters slice single rows per request, and eager
    # jax slicing would compile one tiny program per offset — the serve
    # path must stay on the warmed bucket programs only
    pool = np.asarray(qsets[0])

    _note("serve: ivf_pq build v1")
    t0 = time.perf_counter()
    params = ivf_pq.IndexParams(n_lists=n_lists, pq_bits=4, pq_dim=pq_dim,
                                seed=0)
    idx = ivf_pq.build(params, dataset)
    jax.block_until_ready(idx.list_codes)
    build_s = time.perf_counter() - t0
    sp = ivf_pq.SearchParams(n_probes=n_probes, lut_dtype="bfloat16")

    # the served pipeline is the flagship operating point: PQ candidates at
    # 4k wide + exact refine (the ivf_pq_1m_lid_pq4x64_r4 pattern) —
    # published as a CUSTOM hook (any callable with kind/dim/query_dtype),
    # the serve surface for composed pipelines
    def hook_for(index):
        from raft_tpu.neighbors.refine import refine

        def fn(queries, k_):
            _, cand = ivf_pq.search(sp, index, queries, 4 * k_)
            return refine(dataset, queries, cand, k_)

        fn.kind, fn.dim, fn.query_dtype = "ivf_pq+refine", d, "float32"
        return fn

    serving = hook_for(idx)

    # sequential batch-1 baseline: warm the batch-1 program first, then a
    # timed loop of one-query calls — the no-batcher serving pattern
    _note("serve: sequential batch-1 baseline")

    def one(q):
        out = serving(q, k)
        jax.block_until_ready(out)
        return out

    one(pool[:1])
    t0 = time.perf_counter()
    for j in range(seq_queries):
        one(pool[j:j + 1])
    seq_qps = seq_queries / (time.perf_counter() - t0)

    # the swap target is built OUTSIDE the timed window (a production
    # rebuild happens on a builder host); only publish() lands mid-load
    _note("serve: ivf_pq build v2 (swap target)")
    idx2 = ivf_pq.build(params, dataset)
    jax.block_until_ready(idx2.list_codes)

    _note("serve: closed-loop load, %d threads" % threads)
    svc = SearchService(max_batch=max_batch, max_wait_us=max_wait_us,
                        max_queue_rows=max(4 * max_batch * threads, 256))
    svc.publish("serve", serving, k=k)
    stream = f"serve.k{k}"
    occ_before = obs_metrics.to_json()
    n_req = threads * per_thread
    lats, results, failures = [], {}, []
    lock = threading.Lock()
    swap_at = n_req // 2
    served = [0]
    swap_gate = threading.Event()

    def submitter(tid):
        my_lats, my_res = [], {}
        for j in range(per_thread):
            qi = (tid + j * threads) % pool.shape[0]
            t0 = time.perf_counter()
            try:
                _, ids = svc.search("serve", pool[qi:qi + 1], k)
            except Exception as e:  # pragma: no cover - any loss fails the row
                with lock:
                    failures.append(f"{type(e).__name__}: {str(e)[:80]}")
                    served[0] += 1  # the swap gate must open even on losses
                    if served[0] >= swap_at:
                        swap_gate.set()
                continue
            my_lats.append(time.perf_counter() - t0)
            if qi < 1000:
                my_res[qi] = np.asarray(ids)[0]
            with lock:
                served[0] += 1
                if served[0] >= swap_at:
                    swap_gate.set()
        with lock:
            lats.extend(my_lats)
            results.update(my_res)

    with obs_compile.attribution() as serving_rec:
        workers = [threading.Thread(target=submitter, args=(t,))
                   for t in range(threads)]
        t_load = time.perf_counter()
        for w in workers:
            w.start()
        # hot-swap at mid-load: warm + flip while the loop is in flight
        swap_gate.wait(timeout=600)
        swap_report = svc.publish("serve", hook_for(idx2), k=k)
        for w in workers:
            w.join(600)
        load_s = time.perf_counter() - t_load
    svc.shutdown()

    occ_delta = obs_metrics.delta(occ_before, obs_metrics.to_json())
    occ_sum = occ_delta.get(
        'raft_tpu_serve_batch_occupancy_sum{stream="%s"}' % stream, 0.0)
    occ_cnt = occ_delta.get(
        'raft_tpu_serve_batch_occupancy_count{stream="%s"}' % stream, 0)
    lats_ms = np.sort(np.array(lats if lats else [0.0])) * 1e3
    if results:
        got = np.stack([results[i] for i in sorted(results)])
        recall = round(_recall(got, gt[sorted(results)]), 4)
    else:  # pragma: no cover - every request failed; the row still emits
        recall = None
    rows.append({
        "name": "serve_ivf_pq_100k",
        "qps": round((n_req - len(failures)) / load_s, 1),
        "seq_qps": round(seq_qps, 1),
        "serve_over_seq": round(
            (n_req - len(failures)) / load_s / seq_qps, 3),
        "p50_ms": round(float(lats_ms[len(lats_ms) // 2]), 3),
        "p99_ms": round(float(lats_ms[int(len(lats_ms) * 0.99) - 1]), 3),
        "mean_batch_occupancy": round(occ_sum / max(occ_cnt, 1), 3),
        "recall": recall,
        "build_s": round(build_s, 1),
        "threads": threads, "max_batch": max_batch,
        "max_wait_us": max_wait_us,
        "swap": {
            "failed": len(failures),
            "version": swap_report["version"],
            # zero-cold-compile proof for the WHOLE loaded window (swap
            # warmup + flip + every flush): publish warmed before the flip
            # and the rebuilt index is HLO-identical per bucket
            "compile_s": round(serving_rec.compile_s, 3),
            "cache_misses": serving_rec.cache_misses,
        },
        "failures": failures[:5],
    })


def _row_serve_pipeline(rows, n=100_000, d=128, n_lists=1024, pq_dim=64,
                        k=10, n_probes=8, threads=8, per_thread=300,
                        max_batch=64, max_wait_us=2000.0, ncl=2000,
                        depth=2, waves=3):
    """Host-free flush pipeline A/B (ISSUE 12): the same closed-loop
    threaded load through SearchService served with the synchronous flush
    (``pipeline_depth=0`` — the batcher blocks on the device per flush,
    the BENCH_r05-era protocol) vs the pipelined flush (bounded in-flight
    completion stage + pinned double-buffered staging with donation).

    The acceptance set rides in the row:

    - ``pipelined_over_sync`` — per-flush QPS ratio at identical recall
      (same index, same query pool, both modes' recall in the row);
    - ``decomp`` — the PR 7 split histograms per mode: a request's p99
      decomposes into queue wait + flush share, and the pipeline's win
      must land on the FLUSH side (overlapped H2D/compute/D2H), not on
      queue accounting;
    - ``dispatches_per_flush_mean`` — the obs.dispatch fusion meter
      (pipelined mode; the sync flush materializes inline and records
      none);
    - zero failed queries both modes and ZERO cold compiles across the
      whole pipelined loaded window (publish warmed the bucket ladder,
      the committed-placement executables, and the per-bucket donated
      stage programs before the first flush);
    - ``staging`` — uploads/donation-frees counters plus per-wave
      samples across ``waves`` post-load single-bucket waves: the
      ledger's accounted staging bytes stay FLAT (the footprint is
      constant by design — one slot per bucket) while
      ``donation_frees`` ADVANCES every wave, i.e. XLA actually deleted
      the previous flush's query buffer on every donated upload (the
      frees counter, fed by ``is_deleted()``, is the observation that
      donation works; a backend that ignored ``donate_argnums`` would
      flatline it).
    """
    import threading

    import jax
    import numpy as np

    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.obs import mem as obs_mem
    from raft_tpu.obs import metrics as obs_metrics
    from raft_tpu.serve import SearchService

    _note("pipeline: dataset")
    dataset, qsets = _make_clustered(n, d, max(threads * per_thread, 1000),
                                     ncl, n_qsets=1, seed=13)
    jax.block_until_ready([dataset] + qsets)
    _note("pipeline: ground truth")
    gt = _ground_truth(dataset, qsets[0][:1000], k=k)
    # host copy: single-row slices per request must not compile per offset
    pool = np.asarray(qsets[0])

    _note("pipeline: ivf_pq build")
    params = ivf_pq.IndexParams(n_lists=n_lists, pq_bits=4, pq_dim=pq_dim,
                                seed=0)
    idx = ivf_pq.build(params, dataset)
    jax.block_until_ready(idx.list_codes)
    sp = ivf_pq.SearchParams(n_probes=n_probes, lut_dtype="bfloat16")

    # the flagship composed pipeline (PQ candidates at 4k + exact refine),
    # published as a custom hook — the same serving surface _row_serve uses
    def hook():
        from raft_tpu.neighbors.refine import refine

        def fn(queries, k_):
            _, cand = ivf_pq.search(sp, idx, queries, 4 * k_)
            return refine(dataset, queries, cand, k_)

        fn.kind, fn.dim, fn.query_dtype = "ivf_pq+refine", d, "float32"
        return fn

    stream = f"pipe.k{k}"
    n_req = threads * per_thread

    def load(svc):
        """One closed-loop window — identical protocol both modes."""
        lats, results, failures = [], {}, []
        lock = threading.Lock()

        def submitter(tid):
            my_lats, my_res = [], {}
            for j in range(per_thread):
                qi = (tid + j * threads) % pool.shape[0]
                t0 = time.perf_counter()
                try:
                    _, ids = svc.search("pipe", pool[qi:qi + 1], k)
                except Exception as e:  # pragma: no cover - fails the row
                    with lock:
                        failures.append(f"{type(e).__name__}: {str(e)[:80]}")
                    continue
                my_lats.append(time.perf_counter() - t0)
                if qi < 1000:
                    my_res[qi] = np.asarray(ids)[0]
            with lock:
                lats.extend(my_lats)
                results.update(my_res)

        before = obs_metrics.to_json()
        workers = [threading.Thread(target=submitter, args=(t,))
                   for t in range(threads)]
        t_load = time.perf_counter()
        for w in workers:
            w.start()
        for w in workers:
            w.join(600)
        load_s = time.perf_counter() - t_load
        delta = obs_metrics.delta(before, obs_metrics.to_json())

        def hist_ms(nm):
            s = delta.get('raft_tpu_serve_%s_sum{stream="%s"}'
                          % (nm, stream), 0.0)
            c = delta.get('raft_tpu_serve_%s_count{stream="%s"}'
                          % (nm, stream), 0)
            return round(1e3 * s / max(c, 1), 3)

        lats_ms = np.sort(np.array(lats if lats else [0.0])) * 1e3
        recall = None
        if results:  # pragma: no branch - losses already fail the row
            got = np.stack([results[i] for i in sorted(results)])
            recall = round(_recall(got, gt[sorted(results)]), 4)
        disp_c = delta.get(
            'raft_tpu_serve_dispatches_per_flush_count{stream="%s"}'
            % stream, 0)
        disp_s = delta.get(
            'raft_tpu_serve_dispatches_per_flush_sum{stream="%s"}'
            % stream, 0.0)
        return {
            "qps": round((n_req - len(failures)) / load_s, 1),
            "p50_ms": round(float(lats_ms[len(lats_ms) // 2]), 3),
            "p99_ms": round(float(lats_ms[int(len(lats_ms) * 0.99) - 1]), 3),
            "recall": recall, "failed": len(failures),
            "failures": failures[:5],
            "queue_wait_ms_mean": hist_ms("queue_wait_seconds"),
            "flush_ms_mean": hist_ms("flush_seconds"),
            "dispatches_per_flush_mean":
                round(disp_s / disp_c, 2) if disp_c else None,
        }

    _note("pipeline: sync (depth=0) closed loop, %d threads" % threads)
    svc = SearchService(max_batch=max_batch, max_wait_us=max_wait_us,
                        max_queue_rows=max(4 * max_batch * threads, 256),
                        pipeline_depth=0)
    svc.publish("pipe", hook(), k=k)
    sync = load(svc)
    svc.shutdown()

    _note("pipeline: pipelined (depth=%d) closed loop" % depth)
    svc = SearchService(max_batch=max_batch, max_wait_us=max_wait_us,
                        max_queue_rows=max(4 * max_batch * threads, 256),
                        pipeline_depth=depth,
                        staging_device=jax.devices()[0])
    report = svc.publish("pipe", hook(), k=k)
    with obs_compile.attribution() as rec:
        piped = load(svc)
        # donation/no-growth proof: serial single-row waves AFTER the load
        # (bucket-1 flushes only, every slot long since resident) — the
        # accounted staging bytes stay FLAT while donation_frees ADVANCES
        # every wave (the previous buffer actually deleted per upload)
        levels = []
        for _ in range(waves):
            for j in range(2 * max_batch):
                svc.search("pipe", pool[j:j + 1], k)
            ent = [e for e in obs_mem.breakdown()
                   if e["component"] == "serve/staging"
                   and e["name"] == stream]
            stw = svc.staging_stats().get(stream, {})
            levels.append({
                "ledger_bytes": (int(ent[0]["device_bytes"]
                                     + ent[0]["host_bytes"])
                                 if ent else -1),
                "donation_frees": stw.get("donation_frees", -1),
                "uploads": stw.get("uploads", -1),
            })
    staging = dict(svc.staging_stats().get(stream, {}))
    staging["by_wave"] = levels
    svc.shutdown()

    rows.append({
        "name": "serve_pipeline_100k",
        "qps": piped["qps"],
        "p50_ms": piped["p50_ms"], "p99_ms": piped["p99_ms"],
        "recall": piped["recall"],
        "sync_qps": sync["qps"],
        "sync_p50_ms": sync["p50_ms"], "sync_p99_ms": sync["p99_ms"],
        "sync_recall": sync["recall"],
        "pipelined_over_sync": round(
            piped["qps"] / max(sync["qps"], 1e-9), 3),
        "decomp": {
            mode: {"queue_wait_ms_mean": r["queue_wait_ms_mean"],
                   "flush_ms_mean": r["flush_ms_mean"]}
            for mode, r in (("sync", sync), ("pipelined", piped))},
        "dispatches_per_flush_mean": piped["dispatches_per_flush_mean"],
        "staging": staging,
        "failed": sync["failed"] + piped["failed"],
        "failures": (sync["failures"] + piped["failures"])[:5],
        "pipeline": {
            "depth": depth,
            "staging_warmed": report.get("staging_warmed"),
            # zero-cold-compile proof for the WHOLE pipelined window
            # (load + the ledger waves): publish warmed the ladder, the
            # committed placements, and the donated stage programs
            "compile_s": round(rec.compile_s, 3),
            "cache_misses": rec.cache_misses,
        },
        "threads": threads, "max_batch": max_batch,
        "max_wait_us": max_wait_us,
    })


def _row_serve_churn(rows, n=100_000, d=128, n_lists=1024, pq_dim=64, k=10,
                     n_probes=8, threads=8, writer_steps=64,
                     upserts_per_step=96, deletes_per_step=32,
                     delta_capacity=4096, compact_fill=0.75,
                     max_batch=64, max_wait_us=2000.0, ncl=2000,
                     n_eval=512):
    """Mutable-index churn A/B (raft_tpu.stream, ISSUE 5): closed-loop
    mixed read/write load on MutableIndex(ivf_pq) at 100k — reader threads
    search through SearchService while a writer upserts + deletes and the
    compactor folds the delta into the sealed index mid-load (>= 2 swaps).

    Four claims ride in the row (the ISSUE 5 acceptance set):
    - **zero failed/dropped queries** across the whole churn window,
      compaction swaps included (``churn.failed == 0``);
    - **mid-churn recall parity**: recall@10 of the live mutable index
      (measured through the service, at warmed bucket shapes, right after
      the first compaction) within 0.01 of a fresh oracle ivf_pq build over
      exactly the live rows at that instant (``recall_gap``);
    - **write throughput** (``write_rows_per_s``) alongside p50/p99 search
      latency — the mixed-load numbers a capacity plan needs;
    - **zero cold compiles on the search hot path**: the whole loaded
      window — reads, writes, both compaction folds, the publish warms and
      flips — runs under obs compile attribution and must report
      ``compile_s == 0`` / ``cache_misses == 0``. The compaction-epoch
      programs are compiled beforehand by a REHEARSAL of the same
      (deterministic) write schedule against a throwaway wrapper of the
      same sealed index — the production analogue of provisioning warmup
      (docs/warm_builds.md): the write schedule alone determines every
      post-compaction shape, so the rehearsal compiles exactly the program
      set the live window replays, and the attribution then PROVES the
      swaps and the hot path are compile-free.

    The writer triggers ``Compactor.run_once`` synchronously at the
    delta-fill watermark (writer-driven rather than the background poll
    thread, so fold sizes are schedule-deterministic and the rehearsal's
    shapes match); the background-thread mode is covered by
    tests/test_stream.py."""
    from raft_tpu.neighbors import ivf_pq

    params = ivf_pq.IndexParams(n_lists=n_lists, pq_bits=4, pq_dim=pq_dim,
                                seed=0)
    sp = ivf_pq.SearchParams(n_probes=n_probes, lut_dtype="bfloat16")
    _serve_churn_impl(
        rows, name="serve_churn_ivf_pq_100k", note="churn",
        build=lambda x: ivf_pq.build(params, x),
        materialize=lambda idx: idx.list_codes,
        search_params=sp,
        oracle_search=lambda idx, q, kk: ivf_pq.search(sp, idx, q, kk),
        # the live recall canary rides this row (ISSUE 8): the mutable
        # retains the raw rows so the canary's exact shadow oracle covers
        # sealed + delta with tombstones applied
        mutable_kwargs=dict(retain_vectors=False), canary_rate=0.05,
        n=n, d=d, k=k, threads=threads, writer_steps=writer_steps,
        upserts_per_step=upserts_per_step, deletes_per_step=deletes_per_step,
        delta_capacity=delta_capacity, compact_fill=compact_fill,
        max_batch=max_batch, max_wait_us=max_wait_us, ncl=ncl, n_eval=n_eval)


def _row_serve_churn_cagra(rows, n=100_000, d=128, k=10, itopk=32,
                           threads=8, writer_steps=48, upserts_per_step=96,
                           deletes_per_step=32, delta_capacity=4096,
                           compact_fill=0.75, max_batch=64,
                           max_wait_us=2000.0, ncl=2000, n_eval=512):
    """CAGRA-backed MutableIndex churn row (ISSUE 6): same protocol and
    acceptance claims as ``_row_serve_churn``, but compaction runs the
    REBUILD path — CAGRA has no extend(), so every fold reconstructs the
    sealed graph from the retained live rows (reclaiming tombstones). That
    makes the row the direct measurement of the build-speed-as-a-serving
    -feature claim: sustainable ``write_rows_per_s`` is bounded by the
    rebuild wall (``churn.compaction_wall_s``), so coarse-EM and sharded
    -build speedups surface here as measured write throughput. Rehearsal
    still proves zero cold compiles: the deterministic schedule fixes every
    epoch's sealed row count, so the rehearsal compiles the exact rebuild +
    search program set the live window replays."""
    from raft_tpu.neighbors import cagra

    params = cagra.IndexParams(seed=0)
    sp = cagra.SearchParams(itopk_size=itopk)
    _serve_churn_impl(
        rows, name="serve_churn_cagra_100k", note="churn-cagra",
        build=lambda x: cagra.build(params, x),
        materialize=lambda idx: idx.graph,
        search_params=sp,
        oracle_search=lambda idx, q, kk: cagra.search(sp, idx, q, kk),
        # rebuild compaction: row store auto-recovered from the sealed
        # dataset; index_params configure each rebuild
        mutable_kwargs=dict(index_params=params),
        n=n, d=d, k=k, threads=threads, writer_steps=writer_steps,
        upserts_per_step=upserts_per_step, deletes_per_step=deletes_per_step,
        delta_capacity=delta_capacity, compact_fill=compact_fill,
        max_batch=max_batch, max_wait_us=max_wait_us, ncl=ncl, n_eval=n_eval)


def _serve_churn_impl(rows, *, name, note, build, materialize, search_params,
                      oracle_search, mutable_kwargs, n, d, k, threads,
                      writer_steps, upserts_per_step, deletes_per_step,
                      delta_capacity, compact_fill, max_batch, max_wait_us,
                      ncl, n_eval, canary_rate=0.0):
    """The shared churn protocol (see _row_serve_churn's docstring for the
    claims): dataset + sealed build, rehearsal (compiles every compaction
    epoch's program set), the attributed live window, then the fresh-oracle
    recall snapshot. ``build``/``oracle_search`` close over the index
    module's params so the IVF-PQ and CAGRA rows differ only in the sealed
    kind and therefore in the fold mode (extend vs rebuild).

    ``canary_rate > 0`` additionally rides the live recall canary
    (ISSUE 8): the mutable retains its raw rows, a RecallCanary samples
    that fraction of served queries at the flush path and shadow-reranks
    them against the exact live-corpus kNN at every write step; the row
    then carries the streaming estimate + Wilson interval and whether the
    fresh-oracle offline recall (recall_mut) fell inside it. The canary
    runs INSIDE the attributed window, so churn.compile_s == 0 also proves
    the canary added zero cold compiles on or off the hot path — its
    per-epoch exact programs are covered by the rehearsal (which warms the
    rehearsal canary after every fold of the same deterministic
    schedule)."""
    import threading

    import jax
    import numpy as np

    from raft_tpu import stream
    from raft_tpu.neighbors.brute_force import knn
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.obs import quality
    from raft_tpu.serve import IndexRegistry, SearchService

    total_upserts = writer_steps * upserts_per_step
    total_deletes = writer_steps * deletes_per_step
    assert total_deletes < n, "delete schedule exceeds the dataset"

    _note(f"{note}: dataset")
    dataset, qsets = _make_clustered(n + total_upserts, d, max(n_eval, 1000),
                                     ncl, n_qsets=1, seed=13)
    jax.block_until_ready([dataset] + qsets)
    x_host = np.asarray(dataset[:n])
    churn_host = np.asarray(dataset[n:])  # the upsert pool, same distribution
    pool = np.asarray(qsets[0])
    eval_q = pool[:n_eval]

    _note(f"{note}: sealed build")
    t0 = time.perf_counter()
    idx = build(dataset[:n])
    jax.block_until_ready(materialize(idx))
    build_s = time.perf_counter() - t0
    sp = search_params

    policy = stream.CompactionPolicy(delta_fill=compact_fill,
                                     tombstone_ratio=None, max_age_s=None)
    mk = dict(mutable_kwargs)
    if canary_rate > 0:
        # the canary's exact oracle needs the raw live rows (PQ codes
        # cannot reconstruct them); CAGRA/brute-force recover them from
        # the sealed dataset already
        mk.pop("retain_vectors", None)
        mk.setdefault("dataset", x_host)

    def write_schedule(mutable, comp, on_step=None, after_compact=None):
        """The deterministic churn schedule — run once as the rehearsal and
        once for real. Returns (#compactions, list of compaction reports)."""
        reports = []
        for step in range(writer_steps):
            lo = step * upserts_per_step
            mutable.upsert(churn_host[lo:lo + upserts_per_step],
                           ids=n + np.arange(lo, lo + upserts_per_step))
            dlo = step * deletes_per_step
            mutable.delete(np.arange(dlo, dlo + deletes_per_step))
            while comp.due():
                reports.append(comp.run_once())
                if after_compact is not None:
                    after_compact()
            if on_step is not None:
                on_step(step, len(reports))
        return reports

    # ---- rehearsal: compile every compaction-epoch program off-line ------
    _note(f"{note}: rehearsal (compiles the epoch program set)")
    from raft_tpu.serve import bucket_sizes

    m0 = stream.MutableIndex(idx, search_params=sp,
                             delta_capacity=delta_capacity, name="rehearsal",
                             **mk)
    reg0 = IndexRegistry(buckets=bucket_sizes(max_batch))
    reg0.publish("churn-rehearsal", m0, k=k)
    m0.warm(reg0.buckets, ks=(k,))
    canary0 = after_compact0 = None
    if canary_rate > 0:
        # the rehearsal canary never samples — it exists to compile the
        # exact-oracle program of EVERY epoch's sealed-store shape (the
        # schedule is deterministic, so the live window replays them)
        canary0 = quality.RecallCanary(
            quality.exact_oracle(m0), k=k, sample_rate=0.0,
            buckets=bucket_sizes(max_batch), name="churn-rehearsal")
        canary0.warm()
        after_compact0 = canary0.warm
    comp0 = stream.Compactor(m0, publisher=reg0, name="churn-rehearsal",
                             ks=(k,), policy=policy)
    rehearsal_reports = write_schedule(m0, comp0,
                                       after_compact=after_compact0)
    del m0, comp0, reg0, canary0

    # ---- the real, attributed window -------------------------------------
    _note(f"{note}: live window, {threads} reader threads")
    m = stream.MutableIndex(idx, search_params=sp,
                            delta_capacity=delta_capacity, name=note,
                            **mk)
    canary = None
    if canary_rate > 0:
        canary = quality.RecallCanary(
            quality.exact_oracle(m), k=k, sample_rate=canary_rate,
            reservoir=1024, buckets=bucket_sizes(max_batch), name="churn")
    svc = SearchService(max_batch=max_batch, max_wait_us=max_wait_us,
                        max_queue_rows=max(4 * max_batch * threads, 256),
                        canary=canary)
    svc.publish("churn", m, k=k)
    m.warm(svc.buckets, ks=(k,))
    if canary is not None:
        canary.warm()  # epoch-0 programs (cache-hot from the rehearsal)
    comp = stream.Compactor(m, publisher=svc, name="churn", ks=(k,),
                            policy=policy)

    done = threading.Event()
    lats, failures, served = [], [], [0]
    lock = threading.Lock()
    eval_box = {}

    def reader(tid):
        my_lats, j = [], 0
        while not done.is_set():
            qi = (tid + j * threads) % pool.shape[0]
            j += 1
            t0 = time.perf_counter()
            try:
                svc.search("churn", pool[qi:qi + 1], k)
            except Exception as e:  # pragma: no cover - any loss fails the row
                with lock:
                    failures.append(f"{type(e).__name__}: {str(e)[:80]}")
                continue
            my_lats.append(time.perf_counter() - t0)
        with lock:
            lats.extend(my_lats)
            served[0] += len(my_lats)

    def on_step(step, n_compactions):
        # the canary's shadow rerank runs every step, off the reader hot
        # path, on the writer's cadence (deterministic drains; zero cold
        # compiles — the rehearsal covered every epoch's oracle program)
        if canary is not None:
            canary.drain()
        # mid-churn recall snapshot: right after the schedule's midpoint
        # (past the first compaction), query the service at warmed bucket
        # shapes and record the exact live-set bookkeeping for the oracle
        if step == writer_steps // 2 and "ids" not in eval_box:
            got = []
            for lo in range(0, n_eval, max_batch):
                _, ids = svc.search("churn", eval_q[lo:lo + max_batch], k)
                got.append(np.asarray(ids))
            eval_box["ids"] = np.concatenate(got)
            eval_box["del_done"] = (step + 1) * deletes_per_step
            eval_box["ins_done"] = (step + 1) * upserts_per_step
            eval_box["compactions_at_eval"] = n_compactions

    with obs_compile.attribution() as rec:
        workers = [threading.Thread(target=reader, args=(t,))
                   for t in range(threads)]
        t_load = time.perf_counter()
        for w in workers:
            w.start()
        t_write = time.perf_counter()
        reports = write_schedule(m, comp, on_step)
        write_s = time.perf_counter() - t_write
        done.set()
        for w in workers:
            w.join(600)
        if canary is not None:
            canary.drain()  # flush the tail samples inside the window
        load_s = time.perf_counter() - t_load
    svc.shutdown()

    # ---- oracle: fresh build over the mid-churn live rows ----------------
    _note(f"{note}: fresh-oracle build over the mid-churn live set")
    del_done, ins_done = eval_box["del_done"], eval_box["ins_done"]
    live_mat = np.concatenate([x_host[del_done:], churn_host[:ins_done]])
    live_gids = np.concatenate([np.arange(del_done, n),
                                n + np.arange(ins_done)])
    _, gt_pos = knn(live_mat, eval_q, k)
    gt_gids = live_gids[np.asarray(gt_pos)]
    recall_mut = _recall(eval_box["ids"], gt_gids)
    oracle = build(live_mat)
    jax.block_until_ready(materialize(oracle))
    _, o_pos = oracle_search(oracle, eval_q, k)
    o_pos = np.asarray(o_pos)
    oracle_gids = np.where(o_pos >= 0, live_gids[np.clip(o_pos, 0, None)], -1)
    recall_oracle = _recall(oracle_gids, gt_gids)

    lats_ms = np.sort(np.array(lats if lats else [0.0])) * 1e3
    canary_field = None
    if canary is not None:
        est = canary.estimate()
        canary_field = {
            "rate": canary_rate,
            "recall": round(est["recall"], 4),
            "wilson_low": round(est["wilson_low"], 4),
            "wilson_high": round(est["wilson_high"], 4),
            "reranked": est["reranked"], "seen": est["seen"],
            # the acceptance check: the fresh-oracle offline measurement
            # (recall_mut below) inside the canary's live Wilson interval
            "oracle_in_interval": bool(canary.in_interval(recall_mut)),
        }
    rows.append({
        "name": name,
        "qps": round(served[0] / load_s, 1),
        "p50_ms": round(float(lats_ms[len(lats_ms) // 2]), 3),
        "p99_ms": round(float(lats_ms[int(len(lats_ms) * 0.99) - 1]), 3),
        "write_rows_per_s": round(
            (total_upserts + total_deletes) / write_s, 1),
        "recall_mut": round(recall_mut, 4),
        "recall_oracle": round(recall_oracle, 4),
        "recall_gap": round(recall_mut - recall_oracle, 4),
        "build_s": round(build_s, 1),
        "threads": threads, "max_batch": max_batch,
        "delta_capacity": delta_capacity,
        "canary": canary_field,
        "churn": {
            "failed": len(failures),
            "compactions": len(reports),
            "compaction_wall_s": [r["wall_s"] for r in reports],
            "folded_rows": [r["folded"] for r in reports],
            "upserts": total_upserts, "deletes": total_deletes,
            # zero-cold-compile proof for the WHOLE loaded window (both
            # folds, their publish warms + flips, every flush): the
            # rehearsal pre-compiled the epoch program set, so a non-zero
            # value here means something compiled ON the serving path
            "compile_s": round(rec.compile_s, 3),
            "cache_misses": rec.cache_misses,
        },
        "failures": failures[:5],
    })


def _row_serve_shard(rows, n=100_000, d=128, n_lists=1024, k=10,
                     n_probes=32, shard_counts=(1, 2, 4, 8), threads=8,
                     per_thread=150, writer_steps=48, upserts_per_step=96,
                     deletes_per_step=32, delta_capacity=2048,
                     compact_fill=0.5, max_batch=64, max_wait_us=2000.0,
                     ncl=2000, n_eval=512, canary_rate=0.05):
    """Sharded serving tier (ISSUE 9): the whole serve+stream stack
    scatter-gathered across the mesh — ShardedMutableIndex(ivf_flat) at
    1/2/4/8 shards, per-shard operating points sized PROPORTIONALLY
    (``n_lists/S`` lists, ``n_probes/S`` probes — constant scanned-corpus
    fraction, so recall holds and total per-query compute is flat while
    the critical path spreads over S devices; docs/using_comms.md
    "Serving-tier sizing").

    Claims riding in the row (the ROADMAP-1 done-bar):
    - ``qps_by_shards`` + ``scaling_1_to_4`` — closed-loop served QPS per
      shard count; scaling = (qps[4]/qps[1])/4, i.e. the fraction of
      linear. On real multi-chip hardware the per-shard searches execute
      concurrently (one device each — candidates, never rows, cross the
      interconnect); on a CPU mesh the virtual devices share host cores,
      so the ceiling is min(S, cores)/S — ``cores`` rides in the row so
      the artifact prices that in.
    - **staggered mid-load compaction**: a writer churns
      upserts+deletes while readers serve; the Compactor folds ONE shard
      per cycle (>= 2 folds, distinct-shard staggering recorded in
      ``churn.compaction_shards``) with ``churn.failed == 0`` across every
      fold's warm republish.
    - **zero cold compiles** across the whole loaded churn window — every
      flush, every fold, every publish warm, the canary's shadow reranks —
      proven by obs compile attribution after a rehearsal twin replays the
      same deterministic schedule (the churn-row protocol, sharded).
    - **mid-churn recall inside the canary's Wilson interval**: the live
      RecallCanary shadow-reranks against the exact mesh-wide oracle
      (``exact_search`` composed through the same one-dispatch merge) and
      the fresh-oracle offline measurement must land inside its interval
      (``canary.oracle_in_interval``)."""
    import os
    import threading

    import jax
    import numpy as np

    from raft_tpu import stream
    from raft_tpu.neighbors import brute_force, ivf_flat
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.obs import quality
    from raft_tpu.serve import SearchService, bucket_sizes

    total_upserts = writer_steps * upserts_per_step
    total_deletes = writer_steps * deletes_per_step
    assert total_deletes < n, "delete schedule exceeds the dataset"

    _note("shard: dataset")
    dataset, qsets = _make_clustered(n + total_upserts, d, max(n_eval, 1000),
                                     ncl, n_qsets=1, seed=23)
    jax.block_until_ready([dataset] + qsets)
    x_host = np.asarray(dataset[:n])
    churn_host = np.asarray(dataset[n:])
    pool = np.asarray(qsets[0])
    eval_q = pool[:n_eval]
    devs = jax.devices()

    def make_sharded(S, name):
        # proportional sizing: constant scanned-corpus fraction per query
        nl = max(n_lists // S, 8)
        sp = ivf_flat.SearchParams(n_probes=max(n_probes // S, 1))
        return stream.ShardedMutableIndex(
            x_host, n_shards=S,
            build=lambda rows: ivf_flat.build(
                ivf_flat.IndexParams(n_lists=nl, seed=0), rows),
            search_params=sp, delta_capacity=delta_capacity,
            devices=[devs[s % len(devs)] for s in range(S)], name=name)

    # ---- read-only QPS ladder over shard counts --------------------------
    qps_by_shards = {}
    failures = []

    def loaded_window(svc, name):
        def worker(tid):
            for j in range(per_thread):
                qi = (tid + j * threads) % pool.shape[0]
                try:
                    svc.search(name, pool[qi:qi + 1], k)
                except Exception as e:  # pragma: no cover - fails the row
                    failures.append(f"{type(e).__name__}: {str(e)[:80]}")
        ws = [threading.Thread(target=worker, args=(t,))
              for t in range(threads)]
        t0 = time.perf_counter()
        for w in ws:
            w.start()
        for w in ws:
            w.join(600)
        return threads * per_thread / (time.perf_counter() - t0)

    t0 = time.perf_counter()
    for S in shard_counts:
        _note(f"shard: build + serve at {S} shard(s)")
        sm = make_sharded(S, f"mesh{S}")
        svc = SearchService(max_batch=max_batch, max_wait_us=max_wait_us,
                            max_queue_rows=max(4 * max_batch * threads, 256))
        svc.publish("mesh", sm, k=k)
        sm.warm(svc.buckets, ks=(k,))
        loaded_window(svc, "mesh")  # warm the closed loop itself
        qps_by_shards[str(S)] = round(loaded_window(svc, "mesh"), 1)
        svc.shutdown()
        del sm, svc
    build_s = time.perf_counter() - t0

    # ---- staggered-compaction churn at the largest shard count -----------
    S = shard_counts[-1]
    policy = stream.CompactionPolicy(delta_fill=compact_fill,
                                     tombstone_ratio=None, max_age_s=None)

    def write_schedule(sm, comp, on_step=None, after_compact=None):
        reports = []
        for step in range(writer_steps):
            lo = step * upserts_per_step
            sm.upsert(churn_host[lo:lo + upserts_per_step],
                      ids=n + np.arange(lo, lo + upserts_per_step))
            dlo = step * deletes_per_step
            sm.delete(np.arange(dlo, dlo + deletes_per_step))
            while comp.due():
                reports.append(comp.run_once())  # ONE shard per cycle
                if after_compact is not None:
                    after_compact()
            if on_step is not None:
                on_step(step, len(reports))
        return reports

    _note(f"shard: rehearsal at {S} shards (compiles the epoch program set)")
    m0 = make_sharded(S, "shard-rehearsal")
    svc0 = SearchService(max_batch=max_batch, max_wait_us=max_wait_us,
                         max_queue_rows=max(4 * max_batch * threads, 256))
    svc0.publish("shard-rehearsal", m0, k=k)
    m0.warm(svc0.buckets, ks=(k,))
    canary0 = quality.RecallCanary(
        quality.exact_oracle(m0), k=k, sample_rate=0.0,
        buckets=bucket_sizes(max_batch), name="shard-rehearsal")
    canary0.warm()
    comp0 = stream.Compactor(m0, publisher=svc0, name="shard-rehearsal",
                             ks=(k,), policy=policy)
    write_schedule(m0, comp0, after_compact=canary0.warm)
    svc0.shutdown()
    del m0, comp0, canary0, svc0

    _note(f"shard: live churn window at {S} shards, {threads} readers")
    sm = make_sharded(S, "shard")
    canary = quality.RecallCanary(
        quality.exact_oracle(sm), k=k, sample_rate=canary_rate,
        reservoir=1024, buckets=bucket_sizes(max_batch), name="shard")
    svc = SearchService(max_batch=max_batch, max_wait_us=max_wait_us,
                        max_queue_rows=max(4 * max_batch * threads, 256),
                        canary=canary)
    svc.publish("shard", sm, k=k)
    sm.warm(svc.buckets, ks=(k,))
    canary.warm()
    comp = stream.Compactor(sm, publisher=svc, name="shard", ks=(k,),
                            policy=policy)

    done = threading.Event()
    lats, served = [], [0]
    lock = threading.Lock()
    eval_box = {}

    def reader(tid):
        my_lats, j = [], 0
        while not done.is_set():
            qi = (tid + j * threads) % pool.shape[0]
            j += 1
            t0 = time.perf_counter()
            try:
                svc.search("shard", pool[qi:qi + 1], k)
            except Exception as e:  # pragma: no cover - fails the row
                with lock:
                    failures.append(f"{type(e).__name__}: {str(e)[:80]}")
                continue
            my_lats.append(time.perf_counter() - t0)
        with lock:
            lats.extend(my_lats)
            served[0] += len(my_lats)

    def on_step(step, n_compactions):
        canary.drain()  # shadow reranks on the writer cadence, off-path
        if step == writer_steps // 2 and "ids" not in eval_box:
            got = []
            for lo in range(0, n_eval, max_batch):
                _, ids = svc.search("shard", eval_q[lo:lo + max_batch], k)
                got.append(np.asarray(ids))
            eval_box["ids"] = np.concatenate(got)
            eval_box["del_done"] = (step + 1) * deletes_per_step
            eval_box["ins_done"] = (step + 1) * upserts_per_step

    with obs_compile.attribution() as rec:
        workers = [threading.Thread(target=reader, args=(t,))
                   for t in range(threads)]
        t_load = time.perf_counter()
        for w in workers:
            w.start()
        t_write = time.perf_counter()
        reports = write_schedule(sm, comp, on_step)
        write_s = time.perf_counter() - t_write
        done.set()
        for w in workers:
            w.join(600)
        canary.drain()
        load_s = time.perf_counter() - t_load
    svc.shutdown()

    # ---- fresh oracle over the mid-churn live rows -----------------------
    _note("shard: fresh-oracle build over the mid-churn live set")
    del_done, ins_done = eval_box["del_done"], eval_box["ins_done"]
    live_mat = np.concatenate([x_host[del_done:], churn_host[:ins_done]])
    live_gids = np.concatenate([np.arange(del_done, n),
                                n + np.arange(ins_done)])
    _, gt_pos = brute_force.knn(live_mat, eval_q, k)
    gt_gids = live_gids[np.asarray(gt_pos)]
    recall_mut = _recall(eval_box["ids"], gt_gids)
    oracle = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=n_lists, seed=0), live_mat)
    jax.block_until_ready(oracle.list_data)
    _, o_pos = ivf_flat.search(ivf_flat.SearchParams(n_probes=n_probes),
                               oracle, eval_q, k)
    o_pos = np.asarray(o_pos)
    oracle_gids = np.where(o_pos >= 0, live_gids[np.clip(o_pos, 0, None)], -1)
    recall_oracle = _recall(oracle_gids, gt_gids)

    lats_ms = np.sort(np.array(lats if lats else [0.0])) * 1e3
    est = canary.estimate()
    q1 = qps_by_shards.get(str(shard_counts[0]), 0)
    q4 = qps_by_shards.get("4")
    rows.append({
        "name": "serve_shard_churn_100k",
        "qps": round(served[0] / load_s, 1),
        "qps_by_shards": qps_by_shards,
        "scaling_1_to_4": (round(q4 / q1 / 4.0, 3)
                           if q4 and q1 else None),
        "cores": os.cpu_count(),
        "shards": S,
        "p50_ms": round(float(lats_ms[len(lats_ms) // 2]), 3),
        "p99_ms": round(float(lats_ms[int(len(lats_ms) * 0.99) - 1]), 3),
        "write_rows_per_s": round(
            (total_upserts + total_deletes) / write_s, 1),
        "recall_mut": round(recall_mut, 4),
        "recall_oracle": round(recall_oracle, 4),
        "recall_gap": round(recall_mut - recall_oracle, 4),
        "build_s": round(build_s, 1),
        "threads": threads, "max_batch": max_batch,
        "delta_capacity": delta_capacity,
        "canary": {
            "rate": canary_rate,
            "recall": round(est["recall"], 4),
            "wilson_low": round(est["wilson_low"], 4),
            "wilson_high": round(est["wilson_high"], 4),
            "reranked": est["reranked"], "seen": est["seen"],
            "oracle_in_interval": bool(canary.in_interval(recall_mut)),
        },
        "churn": {
            "failed": len(failures),
            "compactions": len(reports),
            # one shard per fold — the staggering record (a global
            # stop-the-world would show as one shard repeated back-to-back
            # with every delta full; distinct shards = staggered)
            "compaction_shards": [r["shard"] for r in reports],
            "compaction_wall_s": [r["wall_s"] for r in reports],
            "folded_rows": [r["folded"] for r in reports],
            "upserts": total_upserts, "deletes": total_deletes,
            "compile_s": round(rec.compile_s, 3),
            "cache_misses": rec.cache_misses,
        },
        "failures": failures[:5],
    })


def _row_canary_smoke(rows, n=100_000, d=128, n_lists=1024, pq_dim=64, k=10,
                      n_probes=8, threads=8, per_thread=150,
                      rates=(0.0, 0.01, 0.05), max_batch=64,
                      max_wait_us=2000.0, ncl=2000, n_eval=512):
    """Canary overhead A/B (ISSUE 8): the same closed-loop served load at
    canary sampling 0% vs 1% vs 5%, with the background drainer running its
    exact shadow reranks concurrently — the row measures what live quality
    monitoring actually costs the serving path. Three claims ride in it:

    - ``qps_by_rate`` / ``slowdown_at_5pct``: sampling is a host-side
      reservoir tap and the rerank is off the hot path, so the cost should
      be device contention only (a few percent at 5%);
    - the **hot path stays compile-free with the canary on**: the whole
      loaded window (all three rates, drains included) runs under obs
      compile attribution and must report ``compile_s == 0`` — the canary
      was warmed at every rerank bucket beforehand;
    - the canary's streaming estimate brackets the offline truth:
      ``recall_offline`` (held-out queries through the service vs the
      exact oracle) must sit inside the Wilson interval
      (``canary.oracle_in_interval``)."""
    import threading

    import jax
    import numpy as np

    from raft_tpu import stream
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.obs import quality
    from raft_tpu.serve import SearchService, bucket_sizes

    _note("canary: dataset")
    dataset, qsets = _make_clustered(n, d, max(threads * per_thread, 1000),
                                     ncl, n_qsets=1, seed=17)
    jax.block_until_ready([dataset] + qsets)
    x_host = np.asarray(dataset)
    pool = np.asarray(qsets[0])
    eval_q = pool[:n_eval]

    _note("canary: ivf_pq build")
    t0 = time.perf_counter()
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=n_lists, pq_bits=4, pq_dim=pq_dim,
                           seed=0), dataset)
    jax.block_until_ready(idx.list_codes)
    build_s = time.perf_counter() - t0
    sp = ivf_pq.SearchParams(n_probes=n_probes, lut_dtype="bfloat16")
    m = stream.MutableIndex(idx, search_params=sp, dataset=x_host,
                            name="canary")
    canary = quality.RecallCanary(
        quality.exact_oracle(m), k=k, sample_rate=0.0, reservoir=1024,
        buckets=bucket_sizes(max_batch), name="canary")
    svc = SearchService(max_batch=max_batch, max_wait_us=max_wait_us,
                        max_queue_rows=max(4 * max_batch * threads, 256),
                        canary=canary)
    svc.publish("canary", m, k=k)
    m.warm(svc.buckets, ks=(k,))
    _note("canary: oracle warm")
    canary.warm()

    # offline truth at warmed bucket shapes: the served pipeline's recall
    # vs the exact live-corpus oracle on held-out queries
    got = []
    for lo in range(0, n_eval, max_batch):
        _, ids = svc.search("canary", eval_q[lo:lo + max_batch], k)
        got.append(np.asarray(ids))
    _, oracle_ids = m.exact_search(eval_q, k)
    recall_offline = _recall(np.concatenate(got), np.asarray(oracle_ids))

    failures = []

    def loaded_window():
        def worker(tid):
            for j in range(per_thread):
                qi = (tid + j * threads) % pool.shape[0]
                try:
                    svc.search("canary", pool[qi:qi + 1], k)
                except Exception as e:  # pragma: no cover - fails the row
                    failures.append(f"{type(e).__name__}: {str(e)[:80]}")
        ws = [threading.Thread(target=worker, args=(t,))
              for t in range(threads)]
        t0 = time.perf_counter()
        for w in ws:
            w.start()
        for w in ws:
            w.join(600)
        return threads * per_thread / (time.perf_counter() - t0)

    qps_by_rate = {}
    with obs_compile.attribution() as rec:
        for rate in rates:
            _note(f"canary: loaded window at rate {rate:g}")
            canary.set_rate(rate)
            if rate > 0:
                canary.start(poll_interval_s=0.005)
            qps_by_rate[f"{rate:g}"] = round(loaded_window(), 1)
            if rate > 0:
                canary.stop()  # drains the tail INSIDE the attribution
    svc.shutdown()  # free the worker threads + index before later rows
    est = canary.estimate()
    base = qps_by_rate[f"{rates[0]:g}"]
    worst = qps_by_rate[f"{rates[-1]:g}"]
    rows.append({
        "name": "canary_smoke_100k",
        "qps": base,
        "qps_by_rate": qps_by_rate,
        "slowdown_at_5pct": round(base / max(worst, 1e-9), 3),
        "recall_offline": round(recall_offline, 4),
        "canary": {
            "recall": round(est["recall"], 4),
            "wilson_low": round(est["wilson_low"], 4),
            "wilson_high": round(est["wilson_high"], 4),
            "reranked": est["reranked"], "seen": est["seen"],
            "oracle_in_interval": bool(canary.in_interval(recall_offline)),
        },
        "build_s": round(build_s, 1),
        "threads": threads, "max_batch": max_batch,
        # zero-cold-compile proof with the canary ON: sampling, draining
        # and reranking across the whole loaded window compiled nothing
        "compile_s": round(rec.compile_s, 3),
        "cache_misses": rec.cache_misses,
        "failed": len(failures),
        "failures": failures[:5],
    })


def _row_tune_smoke(rows, n=10_000, d=64, ncl=200, n_lists=64, k=10, m=512,
                    repeats=2):
    """Tiny-budget autotune sweep riding the default bench (ISSUE 7): a
    10k IVF-PQ index swept over the 3-point smoke grid through
    raft_tpu.tune — proving the measure→choose→record loop end-to-end on
    whatever hardware the bench runs, without wall-clock blowup. The row
    carries the chosen operating point, the grid-head (hand-picked)
    baseline, and their QPS ratio; by the engine's choice rule the chosen
    point matches or beats the head at equal-or-better recall. Heavy
    sweeps live in bench/tune_sweep.py (the TUNE_rXX.json driver)."""
    import jax

    from raft_tpu import tune
    from raft_tpu.neighbors import ivf_pq

    _note("tune: dataset")
    dataset, qsets = _make_clustered(n, d, m, ncl, n_qsets=1, seed=19)
    jax.block_until_ready([dataset] + qsets)
    _note("tune: ivf_pq build")
    t0 = time.perf_counter()
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=n_lists, pq_bits=4,
                           pq_dim=max(min(32, d // 2), 1), seed=0), dataset)
    jax.block_until_ready(idx.list_codes)
    build_s = time.perf_counter() - t0
    _note("tune: sweep")
    dec = tune.sweep(idx, qsets[0], k=k, dataset=dataset,
                     grid=tune.smoke_grid("ivf_pq"),
                     recall_target="default", repeats=repeats)
    ev = dec.evidence
    rows.append({
        "name": "tune_smoke_10k",
        "qps": ev["chosen_qps"], "recall": ev["chosen_recall"],
        "build_s": round(build_s, 1),
        "decision": dec.key, "chosen": dict(dec.params),
        "default": dict(ev["default_params"]),
        "default_qps": ev["default_qps"],
        "default_recall": ev["default_recall"],
        "recall_target": ev["recall_target"],
        "n_trials": len(ev["trials"]),
        "chosen_qps_over_default": ev["chosen_qps_over_default"],
    })


def _row_mem_smoke(rows, n=100_000, d=64, n_lists=512, k=10, cycles=3):
    """Capacity-observability proof riding the default bench (ISSUE 10):
    ``cycles`` publish→retire cycles of same-config IVF-PQ indexes through
    one registry, measured by the obs.mem ledger. Asserted per cycle:

    - accounted device bytes return to the (baseline + one live index)
      level after every retire + gc — the registry free path does not
      leak (the PR 9 leak class, now a bench-gated invariant);
    - the per-cycle ledger PEAK stays flat from cycle 2 onward (each swap
      double-buffers old+new while warming; flat steady-state peaks = no
      monotonic growth across swaps);
    - cycles after the first compile NOTHING (same static config = same
      program set; compile attribution must read 0);
    - the retirement audit is clean after the final gc;
    - ``obs.mem.plan()`` brackets the measured index bytes within ±20%
      (the estimator's accuracy contract at 100k, on bench hardware).
    """
    import gc

    import jax
    import numpy as np

    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.obs import mem as obs_mem
    from raft_tpu.serve import IndexRegistry

    assert cycles >= 2, "mem smoke needs >= 2 cycles (steady-state " \
                        "assertions compare against the post-warmup peak)"
    _note("mem smoke: dataset")
    rng = np.random.default_rng(7)
    dataset = rng.random((n, d), np.float32)
    params = ivf_pq.IndexParams(n_lists=n_lists, pq_bits=4,
                                pq_dim=max(min(32, d // 2), 1), seed=0)
    reg = IndexRegistry(buckets=(1, 8, 64))
    gc.collect()
    baseline = obs_mem.totals()["device_bytes"]
    peaks, levels, compile_steady = [], [], 0.0
    measured = None
    t0 = time.perf_counter()
    for c in range(cycles):
        obs_mem.reset_peak()
        with obs_compile.attribution() as rec:
            idx = ivf_pq.build(params, dataset)
            jax.block_until_ready(idx.list_codes)
            measured = int(idx.list_codes.nbytes + idx.list_ids.nbytes
                           + idx.list_sizes.nbytes + idx.centers.nbytes
                           + idx.centers_rot.nbytes + idx.rotation.nbytes
                           + idx.codebooks.nbytes + idx.list_consts.nbytes
                           + idx.list_scales.nbytes)
            reg.publish("mem_smoke", idx, k=k)
            del idx  # the registry version now holds the only reference
        if c > 0:
            compile_steady += rec.compile_s
        gc.collect()
        peaks.append(obs_mem.totals()["device_peak_bytes"])
        levels.append(obs_mem.totals()["device_bytes"])
        # one live index remains published; everything a retired cycle
        # allocated must be gone
        assert levels[-1] <= baseline + measured + 1024, (
            f"cycle {c}: accounted {levels[-1]} B > baseline {baseline} + "
            f"live index {measured} — the retire path leaked")
    # cycle 1 starts from an empty registry; every later cycle builds the
    # successor WHILE the predecessor is still published, so the steady
    # state is a double-buffer peak — flat from cycle 2 onward is the
    # no-monotonic-growth invariant
    assert max(peaks[1:]) <= peaks[1] * 1.05 + 1024, (
        f"per-cycle peak grew past the steady-state double-buffer: {peaks}")
    assert compile_steady == 0.0, (
        f"steady-state cycles compiled {compile_steady}s — same-config "
        "publish must reuse every program")
    audit = obs_mem.audit(collect=True)
    assert audit["clean"], f"retirement audit: {audit['retired_unfreed']}"
    est = obs_mem.plan("ivf_pq", params, n, d)["index_bytes"]
    assert abs(est - measured) <= 0.20 * measured, (
        f"plan {est} vs measured {measured} outside 20%")
    out = reg.active("mem_smoke")  # metadata read keeps the API honest
    rows.append({
        "name": "mem_smoke_100k",
        "cycles": cycles, "wall_s": round(time.perf_counter() - t0, 1),
        "baseline_bytes": baseline, "index_bytes": measured,
        "plan_bytes": est, "plan_ratio": round(est / measured, 3),
        "peak_bytes_by_cycle": peaks, "level_bytes_by_cycle": levels,
        "steady_compile_s": round(compile_steady, 3),
        "audit_clean": audit["clean"], "published_version": out.version,
        "mem_note": "levels = baseline + one live index per cycle; "
                    "peaks flat across publish→retire swaps",
    })


def _row_fault_smoke(rows, n=100_000, d=64, n_lists=512, k=10,
                     n_probes=16, shards=2, replicas=2, steps=160,
                     qbatch=64, fence_at=40, heal_at=110,
                     write_every=10, write_rows=16, delta_capacity=2048):
    """Availability proof riding the default bench (ISSUE 11): a sharded
    mesh with per-shard replica groups serves a loaded window during which
    one replica is killed outright (fault-injected search failures) and
    later revived. Asserted:

    - **zero failed queries**: every batch in the window answers — the
      scatter retries the surviving twin in the same call (one dead
      replica = degraded capacity, never a failed query);
    - the dead replica is actually FENCED (breaker strikes observed) and,
      after the fault clears, HEALS through the backoff re-probe —
      ``recovery_s`` records fault-cleared → all replicas serving again;
    - **zero cold compiles** across the measured window, fence, failover
      retries, probes and writes included — rehearsal protocol: the same
      schedule replays unmeasured first, then obs compile attribution
      must read 0 over the measured pass;
    - writes keep applying to the fenced replica (fenced-for-READS is not
      stale: it missed nothing) so the heal needs no rebuild.
    """
    import jax
    import numpy as np

    from raft_tpu import stream
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.testing import faults

    assert fence_at < heal_at < steps
    ev_before = _events_snap()
    _note("fault smoke: dataset")
    rng = np.random.default_rng(11)
    x = rng.random((n, d), np.float32)
    pool = rng.random((1024, d), np.float32)
    churn = rng.random((steps * write_rows, d), np.float32)
    nl = max(n_lists // shards, 8)
    sp = ivf_flat.SearchParams(n_probes=max(n_probes // shards, 1))

    def run_window(sm):
        """The deterministic schedule: searches + light writes; at
        fence_at the replica shard 0 currently PREFERS (lowest scan-wall
        EWMA, breaker closed — the one `_pick` returns next) is killed,
        revived at heal_at. Killing the preferred twin, not a fixed
        ordinal, is what makes the strike deterministic: the next scatter
        is guaranteed to pick it, strike it, and fail over."""
        failed, t_heal, recovery_s = 0, None, None
        t0 = time.perf_counter()
        try:
            for i in range(steps):
                if i == fence_at:
                    grp = sm._shards[0]
                    with grp._lock:
                        j = min((jj for jj, h in enumerate(grp._health)
                                 if h.fenced_until is None and not h.stale),
                                key=lambda jj: grp._health[jj].ewma or 0.0)
                    victim = grp._replicas[j].name
                    faults.inject(
                        "replica/search", exc=faults.FaultError("killed"),
                        match=lambda c, v=victim: c["replica"] == v)
                if i == heal_at:
                    faults.clear("replica/search")
                    t_heal = time.perf_counter()
                q = pool[(i * qbatch) % 960:(i * qbatch) % 960 + qbatch]
                try:
                    dq, iq = sm.search(q, k)
                    assert np.asarray(iq).shape == (qbatch, k)
                except Exception:
                    failed += 1
                if write_every and i % write_every == 0:
                    sm.upsert(churn[i * write_rows:(i + 1) * write_rows])
                if (t_heal is not None and recovery_s is None
                        and sm.health()["healthy_min"] == replicas):
                    recovery_s = time.perf_counter() - t_heal
        finally:
            faults.clear("replica/search")
        # drain the fence if the loop ended before the probe window
        while recovery_s is None:
            sm.search(pool[:qbatch], k)
            if sm.health()["healthy_min"] == replicas:
                recovery_s = time.perf_counter() - t_heal
        # settle: "healthy" above can mean the fence merely EXPIRED —
        # one more search routes the pending probe (probes win _pick)
        # so the breaker actually closes and the heal reaches the
        # event journal (replica_probe ok + replica_unfenced)
        sm.search(pool[:qbatch], k)
        return {"failed": failed, "recovery_s": recovery_s,
                "wall_s": time.perf_counter() - t0}

    def make_mesh(name):
        sm = stream.ShardedMutableIndex(
            x, n_shards=shards, replicas=replicas,
            build=lambda r: ivf_flat.build(
                ivf_flat.IndexParams(n_lists=nl, seed=0), r),
            search_params=sp, delta_capacity=delta_capacity,
            fencing=stream.FencingPolicy(max_consecutive=2,
                                         backoff_s=0.05,
                                         backoff_max_s=0.5),
            name=name)
        sm.warm((qbatch,), ks=(k,))
        jax.block_until_ready(sm.search(pool[:qbatch], k))  # sealed side
        return sm

    _note("fault smoke: rehearsal")
    rehearsal = make_mesh("fault_rehearsal")
    run_window(rehearsal)
    del rehearsal

    _note("fault smoke: measured window")
    mesh = make_mesh("fault")
    with obs_compile.attribution() as rec:
        out = run_window(mesh)
    strikes = sum(h.strikes for h in mesh._shards[0]._health)
    assert out["failed"] == 0, (
        f"{out['failed']} queries failed during the fence window — the "
        "failover contract is zero failed queries")
    assert strikes > 0, "the victim replica was never struck — the fault " \
                        "window did not exercise failover"
    assert rec.compile_s == 0.0, (
        f"loaded window compiled {rec.compile_s}s after rehearsal — "
        "failover/probe paths minted a new program")
    row = {
        "name": "fault_smoke_100k", "n": n, "shards": shards,
        "replicas": replicas, "queries": steps,
        "failed_queries": out["failed"], "strikes": strikes,
        "recovery_s": round(out["recovery_s"], 3),
        "qps": round(steps * qbatch / out["wall_s"], 1),
        "compile_s_loaded": rec.compile_s,
        "wall_s": round(out["wall_s"], 1),
        "fault_note": "one replica killed mid-load and revived; zero "
                      "failed queries, zero cold compiles; recovery_s = "
                      "fault cleared -> every replica serving",
    }
    events = _events_delta(ev_before)   # gated by compare.py on presence
    if events is not None:
        row["events"] = events
    rows.append(row)


def _row_crash_recovery(rows, n=100_000, d=64, n_lists=512, k=10,
                        n_probes=16, write_steps=40, write_rows=64,
                        delete_rows=8, delta_capacity=4096, n_eval=256):
    """Crash-durability proof riding the default bench (ISSUE 11): a
    100k MutableIndex with a write-ahead log takes ``write_steps``
    un-compacted upsert+delete batches, then the process "dies" — a
    :class:`~raft_tpu.testing.faults.SimulatedCrash` injected between the
    WAL append and the memtable insert of the final write, after which
    the in-memory object is abandoned. Recovery is the real cold-start
    path: ``stream.load(snapshot, wal=)`` (atomic snapshot + WAL replay)
    + ``warm()``. Asserted and recorded:

    - **every logged write is recovered** — an uncrashed twin replays the
      identical write script in-process and the recovered index matches
      it id-for-id over ``n_eval`` queries (``recall_recovered`` = match
      fraction, gated at 1.0 by bench/compare.py like every recall
      field);
    - ``recovery_s`` (load + replay wall), ``replay_rows_per_s`` and the
      WAL's size/record count ride the artifact — the measured price of
      crash durability at 100k;
    - **zero cold compiles** on the post-warm serving window (compile
      attribution over a query loop after ``warm()``).
    """
    import os
    import shutil
    import tempfile

    import jax
    import numpy as np

    from raft_tpu import stream
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.testing import faults

    _note("crash recovery: dataset + sealed build")
    rng = np.random.default_rng(13)
    x = rng.random((n, d), np.float32)
    churn = rng.random((write_steps * write_rows, d), np.float32)
    eval_q = rng.random((n_eval, d), np.float32)
    sealed = ivf_flat.build(ivf_flat.IndexParams(n_lists=n_lists, seed=0), x)
    jax.block_until_ready(sealed.list_data)
    sp = ivf_flat.SearchParams(n_probes=n_probes)

    tmp = tempfile.mkdtemp(prefix="raft_crash_")
    try:
        snap = os.path.join(tmp, "snap.bin")
        wpath = os.path.join(tmp, "wal.log")

        def write_script(m):
            """The acknowledged writes (deterministic — the twin replays it)."""
            for s in range(write_steps - 1):
                m.upsert(churn[s * write_rows:(s + 1) * write_rows])
                m.delete(list(range(s * delete_rows, (s + 1) * delete_rows)))
            return churn[(write_steps - 1) * write_rows:]

        _note("crash recovery: write burst + injected crash")
        m = stream.MutableIndex(sealed, search_params=sp,
                                delta_capacity=delta_capacity, wal=wpath)
        stream.save(m, snap)  # the pre-burst snapshot (atomic)
        last_batch = write_script(m)
        wal_bytes = m._wal.size_bytes
        with faults.scope():
            faults.inject("stream/post-wal", faults.SimulatedCrash("kill -9"))
            try:
                m.upsert(last_batch)
                raise AssertionError("crash fault never fired")
            except faults.SimulatedCrash:
                pass
        replayable = write_steps * write_rows  # every LOGGED upsert row
        del m  # the process is gone; snap + wal.log are all that survive

        _note("crash recovery: load + WAL replay")
        t0 = time.perf_counter()
        rec = stream.load(snap, wal=wpath, search_params=sp)
        recovery_s = time.perf_counter() - t0
        assert rec.last_recovery["replayed"] == 2 * (write_steps - 1) + 1, (
            f"replay applied {rec.last_recovery['replayed']} records, "
            f"expected every logged write")
        t0 = time.perf_counter()
        rec.warm((n_eval,), ks=(k,))
        warm_s = time.perf_counter() - t0
        jax.block_until_ready(rec.search(eval_q, k))  # sealed-side rehearsal
        with obs_compile.attribution() as att:
            for _ in range(3):
                dr, ir = rec.search(eval_q, k)
            jax.block_until_ready((dr, ir))
        assert att.compile_s == 0.0, (
            f"post-warm serving compiled {att.compile_s}s — the recovered "
            "cold-start path must be compile-free after warm()")

        _note("crash recovery: uncrashed twin parity")
        twin = stream.MutableIndex(sealed, search_params=sp,
                                   delta_capacity=delta_capacity)
        last = write_script(twin)
        twin.upsert(last)  # the crashed write WAS logged, so replay applies it
        dt, it = twin.search(eval_q, k)
        ids_match = float(np.mean(np.asarray(ir) == np.asarray(it)))
        assert rec.size == twin.size, (rec.size, twin.size)
        assert ids_match == 1.0, (
            f"recovered index diverges from the uncrashed twin "
            f"(id match {ids_match:.4f}) — an acknowledged write was lost")
        rows.append({
            "name": "crash_recovery_100k", "n": n,
            "wal_records": rec.last_recovery["replayed"],
            "wal_bytes": wal_bytes,
            "recovered_rows": replayable,
            "recall_recovered": ids_match,  # gated by bench/compare.py
            "recovery_s": round(recovery_s, 3),
            "warm_s": round(warm_s, 3),
            "replay_rows_per_s": round(replayable / recovery_s, 1),
            "compile_s_post_warm": att.compile_s,
            "crash_note": "SimulatedCrash between WAL append and memtable "
                          "insert of the final write; recovery = atomic "
                          "snapshot + replay of every logged record",
        })
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _row_reshard_churn(rows, n=100_000, d=64, n_lists=512, k=10,
                       n_probes=16, shards=2, replicas=2, steps=40,
                       qbatch=64, reshard_at=20, write_every=4,
                       write_rows=16, delta_capacity=4096, n_eval=256,
                       readers=2):
    """Elastic-resharding proof riding the default bench (ISSUE 13): a
    loaded ``shards``×``replicas`` mesh DOUBLES its shard count online —
    reader threads hammer the scatter-gather for the whole window, one
    replica of shard 0 is killed the moment the migration starts (the
    currently-preferred twin, so the next pick strikes deterministically),
    and writes land mid-migration through the reshard/split fault seam (so
    successor shapes stay schedule-deterministic for the rehearsal
    protocol). Asserted:

    - **zero failed queries** across fold, kill, carry-over and flip —
      failover covers the dead twin, leases drain on the old topology;
    - **zero cold compiles** over the measured window (rehearsal protocol:
      the identical schedule replays warm; the successors' ladders and the
      doubled-merge shape were compiled pre-flip);
    - **recall anchor held**: recall@k vs the exact mesh oracle measured
      before and after the flip (``recall_pre``/``recall_post``, both
      gated by bench/compare.py like every recall field);
    - **measured crash-mid-reshard recovery**: a third durable mesh takes
      the same write burst, a SimulatedCrash fires at ``reshard/flip``
      (between the successor swap and the manifest write), and
      ``ShardedMutableIndex.load`` recovers the OLD topology —
      ``crash_recovery_s`` recorded, ``recall_crash_recovered`` == 1.0
      id-for-id vs an uncrashed twin (gated).
    """
    import os
    import shutil
    import tempfile
    import threading

    import jax
    import numpy as np

    from raft_tpu import stream
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.testing import faults

    assert reshard_at < steps and replicas >= 2
    ev_before = _events_snap()
    _note("reshard churn: dataset")
    rng = np.random.default_rng(17)
    x = rng.random((n, d), np.float32)
    pool = rng.random((1024, d), np.float32)
    churn = rng.random(((steps + 2) * write_rows, d), np.float32)
    eval_q = rng.random((n_eval, d), np.float32)
    nl = max(n_lists // shards, 8)
    sp = ivf_flat.SearchParams(n_probes=max(n_probes // shards, 1))

    def build(r):
        return ivf_flat.build(ivf_flat.IndexParams(n_lists=nl, seed=0), r)

    def recall_vs_oracle(sm):
        _, ia = sm.search(eval_q, k)
        _, ie = sm.exact_search(eval_q, k)
        ia, ie = np.asarray(ia), np.asarray(ie)
        return float(np.mean([len(set(a.tolist()) & set(b.tolist())) / k
                              for a, b in zip(ia, ie)]))

    def make_mesh(name, dir_=None):
        sm = stream.ShardedMutableIndex(
            x, n_shards=shards, replicas=replicas, build=build,
            search_params=sp, delta_capacity=delta_capacity,
            wal_dir=dir_,
            fencing=stream.FencingPolicy(max_consecutive=2, backoff_s=0.05,
                                         backoff_max_s=0.5),
            name=name)
        sm.warm((qbatch, n_eval), ks=(k,))
        jax.block_until_ready(sm.search(pool[:qbatch], k))  # sealed side
        jax.block_until_ready(sm.search(eval_q, k))
        jax.block_until_ready(sm.exact_search(eval_q, k))  # oracle shapes
        return sm

    def run_window(sm):
        """The deterministic schedule: the main thread writes and
        reshards while reader threads search continuously (fixed qbatch —
        readers cannot perturb program shapes). The replica kill and the
        mid-migration write ride the reshard/split fault seam, so they
        land at the same schedule point in rehearsal and measured runs."""
        failed = [0]
        served = [0]
        stop = threading.Event()
        lock = threading.Lock()

        def reader(tid):
            j = 0
            while not stop.is_set():
                q = pool[((tid * 61 + j) * qbatch) % 960:
                         ((tid * 61 + j) * qbatch) % 960 + qbatch]
                try:
                    _, iq = sm.search(q, k)
                    assert np.asarray(iq).shape == (qbatch, k)
                    with lock:
                        served[0] += 1
                except Exception:
                    with lock:
                        failed[0] += 1
                j += 1

        def on_fold(ctx):
            if ctx.get("donors") == (0,):
                # kill the preferred twin of shard 0 the moment its fold
                # starts (lowest EWMA, breaker closed — what _pick returns
                # next, making the strike deterministic)
                grp = sm.shards[0]
                with grp._lock:
                    j = min((jj for jj, h in enumerate(grp._health)
                             if h.fenced_until is None and not h.stale),
                            key=lambda jj: grp._health[jj].ewma or 0.0)
                sm._victim = grp._replicas[j].name
                faults.inject(
                    "replica/search", exc=faults.FaultError("killed"),
                    match=lambda c, v=sm._victim: c["replica"] == v)
            else:
                # a write only the carry-over (and, durably, the
                # successor WALs) can deliver
                sm.upsert(churn[steps * write_rows:
                                (steps + 1) * write_rows])

        out = {}
        t0 = time.perf_counter()
        threads = [threading.Thread(target=reader, args=(t,), daemon=True)
                   for t in range(readers)]
        for t in threads:
            t.start()
        try:
            for i in range(steps):
                if i == reshard_at:
                    out["recall_pre"] = recall_vs_oracle(sm)
                    donors = list(sm.shards)  # strike state dies with them
                    faults.inject("reshard/split", callback=on_fold)
                    rep = sm.reshard(2 * shards,
                                     warm_buckets=(qbatch, n_eval))
                    faults.clear("reshard/split")
                    faults.clear("replica/search")
                    out["reshard_s"] = rep["wall_s"]
                    out["rows_moved"] = rep["rows_moved"]
                    out["carried_over"] = rep["steps"][0]["carried_over"]
                    out["strikes"] = sum(
                        h.strikes for grp in donors
                        for h in getattr(grp, "_health", []))
                if i % write_every == 0:
                    sm.upsert(churn[i * write_rows:(i + 1) * write_rows])
            out["recall_post"] = recall_vs_oracle(sm)
        finally:
            faults.clear("reshard/split")
            faults.clear("replica/search")
            stop.set()
            for t in threads:
                t.join(60)
                assert not t.is_alive(), "reader wedged"
        out["failed"] = failed[0]
        out["served"] = served[0]
        out["wall_s"] = time.perf_counter() - t0
        return out

    _note("reshard churn: rehearsal")
    rehearsal = make_mesh("reshard_rehearsal")
    run_window(rehearsal)
    del rehearsal

    _note("reshard churn: measured window")
    mesh = make_mesh("reshard")
    with obs_compile.attribution() as rec:
        out = run_window(mesh)
    assert out["failed"] == 0, (
        f"{out['failed']} queries failed across the reshard window — the "
        "topology flip must never fail a query")
    assert mesh.n_shards == 2 * shards
    assert out["strikes"] > 0, (
        "the killed replica was never struck — the migration window did "
        "not exercise failover")
    assert rec.compile_s == 0.0, (
        f"reshard window compiled {rec.compile_s}s after rehearsal — the "
        "flip minted a program the pre-flip warm missed")
    assert out["recall_post"] >= out["recall_pre"] - 0.02, out

    _note("reshard churn: crash-mid-reshard recovery")
    tmp = tempfile.mkdtemp(prefix="raft_reshard_")
    try:
        dur = make_mesh("reshard_crash", dir_=os.path.join(tmp, "mesh"))
        twin = make_mesh("reshard_twin")
        for sm2 in (dur, twin):
            for s in range(6):
                sm2.upsert(churn[s * write_rows:(s + 1) * write_rows],
                           ids=np.arange(n + s * write_rows,
                                         n + (s + 1) * write_rows))
                sm2.delete(list(range(s * 8, s * 8 + 8)))
        with faults.scope():
            faults.inject("reshard/flip", faults.SimulatedCrash("kill -9"))
            try:
                dur.reshard(2 * shards)
                raise AssertionError("crash fault never fired")
            except faults.SimulatedCrash:
                pass
        del dur  # the process is gone; the wal_dir is all that survives
        t0 = time.perf_counter()
        rec2 = stream.ShardedMutableIndex.load(os.path.join(tmp, "mesh"),
                                               search_params=sp)
        crash_recovery_s = time.perf_counter() - t0
        assert rec2.n_shards == shards, (
            "crash before the manifest write must recover the OLD topology")
        _, ir = rec2.search(eval_q, k)
        _, it = twin.search(eval_q, k)
        ids_match = float(np.mean(np.asarray(ir) == np.asarray(it)))
        assert ids_match == 1.0, (
            f"recovered mesh diverges from the uncrashed twin "
            f"(id match {ids_match:.4f}) — an acknowledged write was lost")
        replayed = rec2.last_recovery["replayed"]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    row = {
        "name": "reshard_churn_100k", "n": n,
        "shards_from": shards, "shards_to": 2 * shards,
        "replicas": replicas,
        "queries": out["served"] * qbatch,
        "failed_queries": out["failed"],
        "strikes": out["strikes"],
        "rows_moved": out["rows_moved"],
        "carried_over": out["carried_over"],
        "reshard_s": round(out["reshard_s"], 3),
        "recall_pre": round(out["recall_pre"], 4),   # gated by compare.py
        "recall_post": round(out["recall_post"], 4),  # gated by compare.py
        "qps": round(out["served"] * qbatch / out["wall_s"], 1),
        "compile_s_loaded": rec.compile_s,
        "crash_recovery_s": round(crash_recovery_s, 3),
        "recall_crash_recovered": ids_match,          # gated by compare.py
        "wal_records_replayed": replayed,
        "wall_s": round(out["wall_s"], 1),
        "reshard_note": "shard count doubled under live read/write load "
                        "with one replica killed mid-migration; zero "
                        "failed queries, zero cold compiles across the "
                        "flip; crash_recovery_s = load of a mesh killed "
                        "between successor swap and manifest write",
    }
    events = _events_delta(ev_before)   # gated by compare.py on presence
    if events is not None:
        row["events"] = events
    rows.append(row)


def _row_controller_drift(rows, n=100_000, d=64, ncl=256, n_lists=256,
                          k=10, m=512, n_eval=256, qbatch=64, repeats=1):
    """Self-driving retune proof (ISSUE 18): a heavytail corpus serves
    under a deliberately-collapsed operating point (``n_probes=1``), the
    drift detector's ``retune_advised`` sensor event reaches the
    controller through the journal tap, and the controller runs its
    bounded sweep and republishes ``tuned=`` through the registry's
    warm-before-flip seam. Asserted:

    - **recall recovers**: ``recall_recovered`` (post-retune, gated by
      bench/compare.py) beats the collapsed pre-retune point (recorded as
      ``pre_retune_at_k`` — deliberately NOT a ``recall*`` field: it is
      low by construction and must not be gated upward);
    - **zero failed queries** across the flip — the old version serves
      until the tuned successor is warm;
    - **zero cold compiles** over the measured window (rehearsal
      protocol: the identical sense→decide→actuate schedule replays
      against a fresh registry/controller with every program warm);
    - **the causal seq chain** sensor → ``control/decision`` →
      ``control/action_completed`` → ``serve_published`` holds in the
      journal, with the decision/trigger seqs cross-referenced — the
      whole actuation replays from the journal alone.
    """
    import numpy as np

    from raft_tpu import tune
    from raft_tpu.control import ControlPolicy, Controller
    from raft_tpu.neighbors import brute_force, ivf_flat
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.obs import events as obs_events
    from raft_tpu.obs import quality
    from raft_tpu.serve import IndexRegistry
    from raft_tpu.tune import reference

    ev_before = _events_snap()
    _note("controller drift: dataset")
    x, q = reference._clustered(n, d, m, ncl, seed=29, heavytail=True)
    xq = np.asarray(q)
    eval_q = xq[:n_eval]
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=n_lists, seed=0), x)
    _, gt_i = brute_force.BruteForce().build(x).search(eval_q, k)
    gt = np.asarray(gt_i)
    family = tune.family_of(idx, x)
    # the collapsed pin: right family (the guard is not what this row
    # exercises), starved operating point
    pin = tune.Decision(kind="ivf_flat", dtype="float32", family=family,
                        params={"n_probes": 1})
    grid = [{"n_probes": max(n_lists // 8, 2)},
            {"n_probes": max(n_lists // 4, 4)},
            {"n_probes": max(n_lists // 2, 8)}]

    def run_window():
        reg = IndexRegistry(buckets=(qbatch,))
        reg.publish("drift", idx, tuned=pin, k=(k,), warm_data=x[:1024])
        ctl = Controller(publisher=reg,
                         policy=ControlPolicy(retune_cooldown_s=0.0))
        ctl.watch("drift", idx, xq[:128], dataset=x, k=k, ks=(k,),
                  grid=grid, repeats=repeats, warm_data=x[:1024],
                  decision=pin)
        ctl.arm()
        det = quality.DriftDetector(tune.shape_family(n, d, "bal"),
                                    name="drift", min_rows=256)
        out = {"failed": 0, "served": 0}

        def measure():
            v = reg.active("drift")
            hits = 0
            for b in range(0, n_eval, qbatch):
                try:
                    _, ii = v.searcher(eval_q[b:b + qbatch], k)
                except Exception:
                    out["failed"] += 1
                    continue
                out["served"] += 1
                for r_, g_ in zip(np.asarray(ii), gt[b:b + qbatch]):
                    hits += len(set(r_.tolist()) & set(g_.tolist()))
            return hits / (n_eval * k)

        t0 = time.perf_counter()
        try:
            out["pre"] = measure()
            det.offer_rows(np.asarray(x[:2048]))
            det.check()          # heavytail vs the "bal" pin -> advised
            out["handled"] = ctl.step()
            out["post"] = measure()
            out["version"] = reg.active("drift").version
        finally:
            ctl.disarm()
        out["wall_s"] = time.perf_counter() - t0
        return out

    _note("controller drift: rehearsal")
    run_window()

    _note("controller drift: measured window")
    with obs_compile.attribution() as rec:
        out = run_window()
    assert out["failed"] == 0, (
        f"{out['failed']} query batches failed across the retune flip")
    assert out["handled"] == 1, out
    assert out["version"] == 2, out
    assert out["post"] > out["pre"], (
        f"retune did not recover recall: {out['pre']} -> {out['post']}")
    assert rec.compile_s == 0.0, (
        f"measured window compiled {rec.compile_s}s after rehearsal — the "
        "controller's republish minted a cold program on the hot path")
    # the causal seq chain, straight off the journal (newest = measured run)
    sensor = obs_events.query(kind="retune_advised", name="drift")[-1]
    dec = obs_events.query(kind="control/decision", name="drift")[-1]
    done = obs_events.query(kind="control/action_completed",
                            name="drift")[-1]
    pub = obs_events.query(kind="serve_published", name="drift")[-1]
    assert sensor["seq"] < dec["seq"] < done["seq"], (sensor, dec, done)
    assert dec["evidence"]["trigger_seq"] == sensor["seq"], dec
    assert done["evidence"]["decision_seq"] == dec["seq"], done
    assert pub["evidence"]["cause"]["decision_seq"] == dec["seq"], pub

    row = {
        "name": "controller_drift_100k", "n": n, "d": d,
        "queries": out["served"] * qbatch,
        "failed_queries": out["failed"],
        "pre_retune_at_k": round(out["pre"], 4),      # collapsed on purpose
        "recall_recovered": round(out["post"], 4),    # gated by compare.py
        "retuned_version": out["version"],
        "trigger_seq": sensor["seq"],
        "decision_seq": dec["seq"],
        "compile_s_loaded": rec.compile_s,
        "wall_s": round(out["wall_s"], 1),
        "controller_note": "drift sensor -> journal tap -> bounded sweep "
                           "-> tuned republish through warm-before-flip; "
                           "recall recovered with zero failed queries and "
                           "zero cold compiles; decision seq chain "
                           "asserted from the journal alone",
    }
    events = _events_delta(ev_before)   # gated by compare.py on presence
    if events is not None:
        row["events"] = events
    rows.append(row)


def _row_controller_ramp(rows, n=100_000, d=64, n_lists=256, k=10,
                         shards=2, n_probes=16, qbatch=64, n_eval=256,
                         ramp_steps=8, ramp_rows=512,
                         delta_capacity=8192):
    """Self-driving reshard proof (ISSUE 18): an upsert ramp pushes a
    mesh past the compactor's ``reshard_rows_per_shard`` watermark, the
    standing ``reshard_advised`` event reaches the controller through the
    journal tap, and the controller doubles the topology online under its
    headroom/burn admission (library mode: ``warm_buckets`` pre-warms the
    successors, so the flip mints no program). Asserted: zero failed
    queries across the ramp AND the flip, zero cold compiles over the
    measured window (rehearsal protocol), recall vs the exact mesh oracle
    held across the flip (``recall_pre``/``recall_post``, gated), and the
    causal chain sensor → decision → ``reshard_started`` → completed.
    """
    import numpy as np

    from raft_tpu import stream
    from raft_tpu.control import Controller
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.obs import events as obs_events

    import jax

    ev_before = _events_snap()
    _note("controller ramp: dataset")
    rng = np.random.default_rng(23)
    x = rng.random((n, d), np.float32)
    ramp = rng.random((ramp_steps * ramp_rows, d), np.float32)
    eval_q = rng.random((n_eval, d), np.float32)
    nl = max(n_lists // shards, 8)
    sp = ivf_flat.SearchParams(n_probes=max(n_probes // shards, 1))
    # the watermark trips mid-ramp: base load sits under it, the ramp
    # crosses it
    threshold = (n + ramp_steps * ramp_rows // 2) // shards

    def build(r):
        return ivf_flat.build(ivf_flat.IndexParams(n_lists=nl, seed=0), r)

    def recall_vs_oracle(sm):
        _, ia = sm.search(eval_q, k)
        _, ie = sm.exact_search(eval_q, k)
        ia, ie = np.asarray(ia), np.asarray(ie)
        return float(np.mean([len(set(a.tolist()) & set(b.tolist())) / k
                              for a, b in zip(ia, ie)]))

    def run_window(tag):
        mesh = stream.ShardedMutableIndex(
            x, n_shards=shards, build=build, search_params=sp,
            delta_capacity=delta_capacity, name=f"ramp_{tag}")
        mesh.warm((qbatch, n_eval), ks=(k,))
        jax.block_until_ready(mesh.search(eval_q, k))
        jax.block_until_ready(mesh.exact_search(eval_q, k))
        comp = stream.Compactor(
            mesh, policy=stream.CompactionPolicy(
                delta_fill=None, tombstone_ratio=None,
                reshard_rows_per_shard=threshold))
        ctl = Controller()
        ctl.attach_mesh(mesh, warm_buckets=(qbatch, n_eval), ks=(k,))
        ctl.attach_compactor(comp)
        ctl.arm()
        out = {"failed": 0, "served": 0}

        def serve():
            for b in range(0, n_eval, qbatch):
                try:
                    _, ii = mesh.search(eval_q[b:b + qbatch], k)
                    assert np.asarray(ii).shape[0] > 0
                    out["served"] += 1
                except Exception:
                    out["failed"] += 1

        t0 = time.perf_counter()
        try:
            out["recall_pre"] = recall_vs_oracle(mesh)
            for i in range(ramp_steps):
                mesh.upsert(ramp[i * ramp_rows:(i + 1) * ramp_rows])
                serve()
                comp.run_once()   # the advisory rides every poll
                ctl.step()        # ... and the controller acts on it
            out["recall_post"] = recall_vs_oracle(mesh)
            out["shards"] = mesh.n_shards
        finally:
            ctl.disarm()
        out["wall_s"] = time.perf_counter() - t0
        return mesh, out

    _note("controller ramp: rehearsal")
    run_window("rehearsal")

    _note("controller ramp: measured window")
    with obs_compile.attribution() as rec:
        mesh, out = run_window("measured")
    assert out["failed"] == 0, (
        f"{out['failed']} query batches failed across the ramp window")
    assert out["shards"] == 2 * shards, (
        f"the controller never resharded: {out['shards']} shards after "
        f"the ramp (threshold {threshold})")
    assert rec.compile_s == 0.0, (
        f"measured window compiled {rec.compile_s}s after rehearsal — the "
        "controller's flip minted a program the pre-flip warm missed")
    assert out["recall_post"] >= out["recall_pre"] - 0.02, out
    # the causal chain, straight off the journal (newest = measured run)
    sensor = obs_events.query(kind="reshard_advised",
                              name=mesh.name)[-1]
    dec = obs_events.query(kind="control/decision", name=mesh.name)[-1]
    started = obs_events.query(kind="reshard_started",
                               name=mesh.name)[-1]
    done = obs_events.query(kind="control/action_completed",
                            name=mesh.name)[-1]
    assert sensor["seq"] < dec["seq"] < started["seq"] < done["seq"], (
        sensor["seq"], dec["seq"], started["seq"], done["seq"])
    assert dec["evidence"]["trigger_seq"] == sensor["seq"], dec
    assert started["evidence"]["cause"]["decision_seq"] == dec["seq"], \
        started
    assert done["evidence"]["decision_seq"] == dec["seq"], done

    row = {
        "name": "controller_ramp_100k", "n": n, "d": d,
        "shards_from": shards, "shards_to": out["shards"],
        "queries": out["served"] * qbatch,
        "failed_queries": out["failed"],
        "recall_pre": round(out["recall_pre"], 4),    # gated by compare.py
        "recall_post": round(out["recall_post"], 4),  # gated by compare.py
        "reshard_threshold": threshold,
        "trigger_seq": sensor["seq"],
        "decision_seq": dec["seq"],
        "compile_s_loaded": rec.compile_s,
        "wall_s": round(out["wall_s"], 1),
        "controller_note": "compactor watermark -> reshard_advised -> "
                           "controller admission -> online topology "
                           "double; zero failed queries, zero cold "
                           "compiles, recall held; causal seq chain "
                           "asserted from the journal alone",
    }
    events = _events_delta(ev_before)   # gated by compare.py on presence
    if events is not None:
        row["events"] = events
    rows.append(row)


def _row_tiered(rows, n=100_000, d=128, n_lists=1024, pq_dim=16, k=10,
                n_probes=8, ratio=4, m=1024, bucket=256, waves=3, ncl=2000):
    """Beyond-HBM tiered storage A/B (ISSUE 15 acceptance): the SAME
    corpus served through the refined IVF-PQ pipeline twice — all-HBM
    (``storage="hbm"``: raw rows resident on device) vs tiered
    (``storage="tiered"``: rows in host RAM under a device
    ``memory_budget_bytes`` that the raw-row footprint EXCEEDS, so the
    store provably cannot promote). The acceptance bits ride in the row
    body (a violation converts to an error row):

    - **recall anchor holds**: the tiered twin's refined ids are
      BIT-EQUAL to the all-HBM twin's (tiering moves where rows live,
      never what a query answers), so recall is identical by
      construction and recorded once per twin for the compare.py gate.
    - **zero failed queries, zero cold compiles** across the measured
      waves (rehearsal wave first — the documented warm protocol — then
      compile attribution must stay at 0).
    - **per-tier ledger bytes flat across waves**: the double-buffered
      gather slots allocate once, then steady-state device bytes are
      constant (the slot-ring replacement contract, ledger-provable).
    - the measured **host-hop cost**: tiered vs all-HBM QPS, with the
      host-gather wall (``host_hop_s``) and H2D bytes decomposed per
      wave so the QPS delta is attributable to the hop, not noise.
    """
    import gc

    import jax
    import numpy as np

    from raft_tpu.core.resources import default_resources
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.obs import mem as obs_mem
    from raft_tpu.stream import MutableIndex, TierPolicy

    ev_before = _events_snap()
    _note("tiered: dataset")
    dataset, qsets = _make_clustered(n, d, m, ncl, n_qsets=2, seed=13)
    jax.block_until_ready([dataset] + qsets)
    _note("tiered: ground truth")
    gt = _ground_truth(dataset, qsets[-1][:1000], k=k)
    host_rows = np.asarray(dataset)
    store_bytes = host_rows.nbytes

    _note("tiered: ivf_pq build")
    t0 = time.perf_counter()
    params = ivf_pq.IndexParams(n_lists=n_lists, pq_bits=4, pq_dim=pq_dim,
                                seed=0)
    idx = ivf_pq.build(params, dataset)
    jax.block_until_ready(idx.list_codes)
    build_s = time.perf_counter() - t0
    sp = ivf_pq.SearchParams(n_probes=n_probes, lut_dtype="bfloat16")

    pools = [np.asarray(q) for q in qsets]

    def run_waves(mut, label):
        """Rehearse every (bucket, k) shape once, then measure `waves`
        full passes: per-wave wall, failures, last-wave outputs, and
        compile attribution over the measured (post-rehearsal) window."""
        rehearse = pools[0][:bucket]
        jax.block_until_ready(mut.search_refined(rehearse, k, ratio)[0])
        walls, fails, outs = [], 0, None
        with obs_compile.attribution() as rec:
            for w in range(waves):
                pool = pools[w % len(pools)]
                wave_out = []
                t0 = time.perf_counter()
                for off in range(0, m, bucket):
                    try:
                        _, ids = mut.search_refined(
                            pool[off:off + bucket], k, ratio)
                        wave_out.append(np.asarray(ids))
                    except Exception:  # any loss fails the row's claim
                        fails += 1
                walls.append(time.perf_counter() - t0)
                if w % len(pools) == len(pools) - 1:
                    outs = np.concatenate(wave_out) if wave_out else None
        _note(f"tiered: {label} waves done")
        return walls, fails, outs, rec

    # ---- all-HBM twin ------------------------------------------------------
    m_hbm = MutableIndex(idx, search_params=sp, index_params=params,
                         dataset=host_rows, name="tiered_ab_hbm")
    walls_h, fails_h, out_h, rec_h = run_waves(m_hbm, "all-HBM")
    del m_hbm
    gc.collect()

    # ---- tiered twin under a squeezing device budget -----------------------
    # the budget the corpus EXCEEDS: everything accounted so far plus half
    # the raw-row footprint — the store cannot promote (placement decides
    # cold, hit-rate promotes are refused by headroom), which is the
    # beyond-HBM claim: the corpus serves anyway
    res = default_resources()
    prev_budget = res.memory_budget_bytes
    budget = obs_mem.totals()["device_bytes"] + store_bytes // 2
    res.memory_budget_bytes = budget
    try:
        m_tier = MutableIndex(idx, search_params=sp, index_params=params,
                              dataset=host_rows, name="tiered_ab_tiered",
                              storage="tiered", tier=TierPolicy())
        ts = m_tier.tiered_store
        assert not ts.mirror_resident, (
            "the squeezing budget must keep the store cold — residency "
            f"{ts.residency!r} under budget {budget}")
        hop0 = ts.stats()
        walls_t, fails_t, out_t, rec_t = run_waves(m_tier, "tiered")
        # per-tier ledger bytes flat across waves: steady-state slots only
        levels = []
        for w in range(2):
            jax.block_until_ready(
                m_tier.search_refined(pools[0][:bucket], k, ratio)[0])
            levels.append(dict(ts.tier_bytes()))
        assert levels[0] == levels[-1], (
            f"per-tier bytes must be flat across waves, got {levels}")
        assert not ts.mirror_resident, (
            "hit-rate promote must stay refused under the budget")
        hop1 = ts.stats()
        tier_bytes = ts.tier_bytes()
    finally:
        res.memory_budget_bytes = prev_budget

    assert fails_h == 0 and fails_t == 0, (
        f"zero failed queries required (hbm={fails_h}, tiered={fails_t})")
    assert rec_t.compile_s == 0.0 and rec_t.cache_misses == 0, (
        f"zero cold compiles across refine double-buffer cycles, got "
        f"{rec_t.compile_s}s / {rec_t.cache_misses} misses")
    # the twin's window must be equally hot, or a sneaked compile would
    # deflate qps_hbm and inflate the headline hbm_over_tiered ratio
    assert rec_h.compile_s == 0.0 and rec_h.cache_misses == 0, (
        f"cold compile in the all-HBM twin's measured waves: "
        f"{rec_h.compile_s}s / {rec_h.cache_misses} misses")
    assert out_h is not None and out_t is not None
    assert (out_h == out_t).all(), (
        "tiered refined ids must be bit-equal to the all-HBM twin")
    recall = round(_recall(out_t[:1000], gt), 4)

    qps_h = round(m * waves / sum(walls_h), 1)
    qps_t = round(m * waves / sum(walls_t), 1)
    row = {
        "name": "tiered_100k", "n": n, "k": k, "refine_ratio": ratio,
        "qps": qps_t,
        "qps_hbm": qps_h,
        "hbm_over_tiered": round(qps_h / max(qps_t, 1e-9), 3),
        "recall": recall,            # gated by compare.py
        "recall_hbm": recall,        # bit-equal twins (asserted above)
        "build_s": round(build_s, 1),
        "budget_bytes": int(budget),
        "store_bytes": int(store_bytes),
        "tier_residency": ts.residency,
        "tier_bytes": {t: int(b) for t, b in tier_bytes.items()},
        "host_hop_s": round(hop1["fetch_wall_s"] - hop0["fetch_wall_s"], 4),
        "h2d_bytes": hop1["h2d_bytes"] - hop0["h2d_bytes"],
        "hit_ratio": round(hop1["hit_ratio"], 4),
        "spills": hop1["spills"], "promotes": hop1["promotes"],
        "failed_queries": 0,
        "steady_compile_s": rec_t.compile_s,
        "steady_cache_misses": rec_t.cache_misses,
        "tiered_note": "same corpus, refined pipeline, raw rows exceed "
                       "the device budget: ids bit-equal to the all-HBM "
                       "twin, per-tier bytes flat across waves, zero "
                       "failed queries, zero cold compiles; "
                       "hbm_over_tiered is the measured host-hop cost",
    }
    events = _events_delta(ev_before)   # gated by compare.py on presence
    if events is not None:
        row["events"] = events
    rows.append(row)


def _row_ooc_build(rows, n=100_000, d=128, n_lists=1024, pq_dim=16, k=10,
                   n_probes=8, chunk_rows=16384, ncl=2000):
    """Out-of-core streamed build A/B (ISSUE 19 acceptance): the SAME
    clustered corpus built twice with identical IVF-PQ parameters —
    in-core (whole corpus materialized through the classic build path)
    vs streamed off a temp-file ``.npy`` ``np.memmap`` through
    ``core.chunked.ChunkedReader``. The acceptance bits ride in the row
    body (a violation converts to an error row):

    - **bit-equal indexes**: every array field of the streamed index is
      identical to the in-core twin's, so recall is shared by
      construction — recorded once for the compare.py gate.
    - **peak build device bytes flat across chunks**: the streamed
      twin's measured ledger peak brackets within the ±20% envelope of
      ``obs.mem.plan(streamed=True)``, whose staging term is TWO chunks
      regardless of corpus size — the whole-corpus device copy is gone
      from the build path, so corpus scale buys index bytes only.
    - the measured **streaming cost**: build walls plus device AND host
      ledger peaks for both twins, so the HBM savings (and the host-side
      price of staging) are attributable, not inferred.
    """
    import gc
    import os
    import tempfile

    import jax
    import numpy as np

    from raft_tpu.core import chunked
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.obs import mem as obs_mem

    ev_before = _events_snap()
    _note("ooc: dataset")
    dataset, qsets = _make_clustered(n, d, 1024, ncl, n_qsets=1, seed=19)
    jax.block_until_ready([dataset] + qsets)
    _note("ooc: ground truth")
    gt = _ground_truth(dataset, qsets[0][:1000], k=k)
    host_rows = np.asarray(dataset)

    params = ivf_pq.IndexParams(n_lists=n_lists, pq_bits=4, pq_dim=pq_dim,
                                seed=0)

    def measured_build(x):
        gc.collect()
        base = obs_mem.totals()
        obs_mem.reset_peak()
        t0 = time.perf_counter()
        idx = ivf_pq.build(params, x)
        jax.block_until_ready(idx.list_codes)
        wall = time.perf_counter() - t0
        tot = obs_mem.totals()
        return (idx, wall, tot["device_peak_bytes"] - base["device_bytes"],
                tot["host_peak_bytes"] - base["host_bytes"])

    _note("ooc: in-core twin build")
    idx_a, wall_a, dev_a, host_a = measured_build(dataset)

    with tempfile.TemporaryDirectory(prefix="raft_tpu_ooc_") as tmp:
        path = os.path.join(tmp, "corpus.npy")
        np.save(path, host_rows)
        reader = chunked.ChunkedReader.from_file(path, chunk_rows=chunk_rows)
        est = obs_mem.plan("ivf_pq", params, n, d, streamed=True,
                           chunk_rows=chunk_rows)
        _note("ooc: streamed twin build")
        idx_b, wall_b, dev_b, host_b = measured_build(reader)

    import dataclasses
    for f in dataclasses.fields(idx_a):
        va, vb = getattr(idx_a, f.name), getattr(idx_b, f.name)
        if hasattr(va, "shape"):
            assert np.array_equal(np.asarray(va), np.asarray(vb)), (
                f"streamed build must be bit-equal to in-core: {f.name}")

    # plan(streamed) is the ADMISSION envelope: the measured ledger peak
    # must stay inside it (ivf_pq's transient trainset scratch is priced
    # by plan but outside the accounted window, so the measurement may
    # legitimately under-run; the two-sided ±20% contract is tier-1 on
    # ivf_flat, whose streamed terms the ledger mirrors exactly)
    assert dev_b <= 1.2 * est["build_peak_bytes"], (
        f"streamed peak {dev_b} above plan {est['build_peak_bytes']} "
        f"+20% — the flat-across-chunks staging claim failed")

    _note("ooc: recall")
    sp = ivf_pq.SearchParams(n_probes=n_probes, lut_dtype="bfloat16")
    _, ids = ivf_pq.search(sp, idx_b, qsets[0][:1000], k)
    recall = round(_recall(np.asarray(ids), gt), 4)

    row = {
        "name": "ooc_build_100k", "n": n, "d": d, "k": k,
        "recall": recall,            # gated by compare.py; shared by the
        "recall_incore": recall,     # bit-equal twins (asserted above)
        "build_s": round(wall_b, 2),
        "build_s_incore": round(wall_a, 2),
        "peak_dev_bytes": int(dev_b),
        "peak_dev_bytes_incore": int(dev_a),
        "peak_host_bytes": int(host_b),
        "peak_host_bytes_incore": int(host_a),
        "plan_dev_bytes": int(est["build_peak_bytes"]),
        "plan_host_bytes": int(est["host_peak_bytes"]),
        "staging_dev_bytes": 2 * chunk_rows * d * 4,
        "n_chunks": reader.n_chunks,
        "corpus_bytes": int(host_rows.nbytes),
        "bit_equal": True,
        "ooc_note": "same corpus, same params, in-core vs memmap-streamed: "
                    "indexes bit-equal, streamed device peak within "
                    "plan(streamed)'s ±20% whose staging term is two "
                    "chunks regardless of corpus size",
    }
    events = _events_delta(ev_before)   # gated by compare.py on presence
    if events is not None:
        row["events"] = events
    rows.append(row)


def _row_quant_funnel(rows, n=100_000, d=128, n_lists=1024, pq_dim=64, k=10,
                      m=1024, bucket=256, waves=3, ncl=2000, repeats=2):
    """Quantization-funnel capacity A/B (ISSUE 16 acceptance): the SAME
    clustered corpus built twice with identical codec parameters — classic
    PQ (``fast_scan="none"``) vs the funnel twin carrying the bit-packed
    1-bit signature tier — then swept over ``tune.funnel_grid`` so the
    recall-vs-QPS-vs-bytes frontier lands in a decision log. The grid HEAD
    is the classic operating point, so ``recall_target="default"`` anchors
    the funnel pin to the classic scan's recall. Acceptance bits ride in
    the row body (a violation converts to an error row):

    - **width-1 bit-equality**: the funnel twin searched at
      ``funnel_widen=1`` routes through the untouched classic scan and
      answers bit-equal to the classic twin (same seed → same codebooks;
      the signature tier is pure addition);
    - **recall anchor holds**: the chosen funnel point's measured recall
      on the held-out query set stays within tolerance of the classic
      anchor (the sweep's choice rule enforces it on the sweep set);
    - **capacity claim**: the funnel's hot-scan bytes per probed row
      (packed signatures + ids, streamed by stage A) price ≥2× more rows
      per HBM byte than the classic scan (unpacked PQ codes + ids) —
      ``bytes_per_row``/``rows_per_hbm_byte`` are the fields
      ``bench/compare.py`` gates on presence;
    - **zero cold compiles** across the measured waves of both twins
      (rehearsal wave first — the documented warm protocol).
    """
    import jax
    import numpy as np

    from raft_tpu import tune
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.tune.apply import search_fn

    _note("quant: dataset")
    dataset, qsets = _make_clustered(n, d, m, ncl, n_qsets=2, seed=13)
    jax.block_until_ready([dataset] + qsets)
    _note("quant: ground truth")
    gt = _ground_truth(dataset, qsets[-1][:1000], k=k)
    pools = [np.asarray(q) for q in qsets]

    base = dict(n_lists=n_lists, pq_bits=4, pq_dim=pq_dim, seed=0)
    _note("quant: classic build")
    t0 = time.perf_counter()
    idx_c = ivf_pq.build(ivf_pq.IndexParams(**base), dataset)
    jax.block_until_ready(idx_c.list_codes)
    build_c = time.perf_counter() - t0
    _note("quant: funnel build (1bit tier)")
    t0 = time.perf_counter()
    idx_f = ivf_pq.build(ivf_pq.IndexParams(fast_scan="1bit", **base),
                         dataset)
    jax.block_until_ready(idx_f.list_sig)
    build_f = time.perf_counter() - t0

    # width-1 bit-equality: widen=1 routes through the classic scan on the
    # same codebooks, so the tier must not change a single answer
    _, ids_f1 = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=8, funnel_widen=1), idx_f,
        pools[0][:bucket], k)
    _, ids_c1 = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=8), idx_c, pools[0][:bucket], k)
    assert (np.asarray(ids_f1) == np.asarray(ids_c1)).all(), (
        "funnel twin at funnel_widen=1 must answer bit-equal to the "
        "classic-PQ twin")

    _note("quant: funnel_grid sweep")
    log = tune.DecisionLog()
    dec = tune.sweep(idx_f, qsets[0], k=k, dataset=dataset, gt=None,
                     grid=tune.funnel_grid(), recall_target="default",
                     repeats=repeats, log=log)
    ev = dec.evidence

    fn_funnel = search_fn(idx_f, dec, dataset=dataset)
    # the classic anchor serves the grid head's operating point on the
    # no-tier twin — the honest bytes/QPS baseline
    fn_classic = search_fn(
        idx_c, {"n_probes": 8, "refine_ratio": 4}, dataset=dataset)

    def run_waves(fn, label):
        """Rehearse the (bucket, k) shape once, then measure ``waves``
        full passes with compile attribution over the measured window."""
        jax.block_until_ready(fn(pools[0][:bucket], k)[0])
        walls, outs = [], None
        with obs_compile.attribution() as rec:
            for w in range(waves):
                pool = pools[w % len(pools)]
                wave_out = []
                t0 = time.perf_counter()
                for off in range(0, m, bucket):
                    _, ids = fn(pool[off:off + bucket], k)
                    wave_out.append(np.asarray(ids))
                walls.append(time.perf_counter() - t0)
                if w % len(pools) == len(pools) - 1:
                    outs = np.concatenate(wave_out)
        _note(f"quant: {label} waves done")
        return walls, outs, rec

    walls_c, out_c, rec_c = run_waves(fn_classic, "classic")
    walls_f, out_f, rec_f = run_waves(fn_funnel, "funnel")
    assert rec_c.compile_s == 0.0 and rec_c.cache_misses == 0, (
        f"cold compile in the classic twin's measured waves: "
        f"{rec_c.compile_s}s / {rec_c.cache_misses} misses")
    assert rec_f.compile_s == 0.0 and rec_f.cache_misses == 0, (
        f"cold compile in the funnel twin's measured waves: "
        f"{rec_f.compile_s}s / {rec_f.cache_misses} misses")

    recall_c = round(_recall(out_c[:1000], gt), 4)
    recall_f = round(_recall(out_f[:1000], gt), 4)
    assert recall_f >= recall_c - 0.02, (
        f"funnel recall {recall_f} broke the classic anchor {recall_c} "
        "on the held-out set")

    # hot-scan bytes per probed row: what stage A streams per candidate.
    # Classic scans the unpacked PQ codes + ids; the funnel scans the
    # packed signatures + ids and touches codes only for the k_widen
    # survivors (gather, not stream).
    bpr_c = int(idx_c.list_codes.shape[2]) + 4
    bpr_f = int(idx_f.list_sig.shape[2]) + 4
    capacity_x = bpr_c / bpr_f
    assert capacity_x >= 2.0, (
        f"funnel must price >=2x rows per HBM byte, got {capacity_x:.2f} "
        f"(classic {bpr_c} B/row vs funnel {bpr_f} B/row)")

    qps_c = round(m * waves / sum(walls_c), 1)
    qps_f = round(m * waves / sum(walls_f), 1)
    rows.append({
        "name": "quant_funnel_100k", "n": n, "k": k,
        "qps": qps_f,
        "qps_classic": qps_c,
        "recall": recall_f,           # gated by compare.py
        "recall_classic": recall_c,   # the anchor, gated too
        "bytes_per_row": bpr_f,       # presence-gated by compare.py
        "rows_per_hbm_byte": round(1.0 / bpr_f, 6),
        "bytes_per_row_classic": bpr_c,
        "rows_per_hbm_byte_classic": round(1.0 / bpr_c, 6),
        "capacity_x": round(capacity_x, 3),
        "build_s": round(build_f, 1),
        "build_classic_s": round(build_c, 1),
        "decision": dec.key, "chosen": dict(dec.params),
        "n_trials": len(ev["trials"]),
        "frontier": ev["frontier"],
        "chosen_qps_over_default": ev["chosen_qps_over_default"],
        "steady_compile_s": rec_f.compile_s,
        "steady_cache_misses": rec_f.cache_misses,
        "quant_note": "same corpus, same codec seed: funnel twin bit-equal "
                      "to classic at width 1, recall anchored to the "
                      "classic operating point by the funnel_grid head, "
                      "capacity_x is hot-scan bytes/row priced classic "
                      "over funnel, frontier recorded in the decision log",
    })


def _row_ivf_flat(rows, dataset, qsets, gt):
    import numpy as np

    from raft_tpu.neighbors import ivf_flat

    _note("ivf_flat build")
    t0 = time.perf_counter()
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=1024, seed=0), dataset)
    import jax
    jax.block_until_ready(idx.list_data)
    build_s = time.perf_counter() - t0
    sp = ivf_flat.SearchParams(n_probes=8)
    qps, out = _measure_qps(
        lambda q: ivf_flat.search(sp, idx, q, 10), qsets,
        qsets[0].shape[0], use_jit=False)
    rows.append({"name": "ivf_flat_1m_p8",
                 "qps": round(qps, 1),
                 "recall": round(_recall(np.asarray(out[1])[:1000], gt), 4),
                 "build_s": round(build_s, 1)})


def _row_cagra(rows, dataset, qsets, gt):
    import numpy as np

    from raft_tpu.neighbors import cagra

    _note("cagra build")
    t0 = time.perf_counter()
    idx = cagra.build(cagra.IndexParams(), dataset)
    import jax
    jax.block_until_ready(idx.graph)
    build_s = time.perf_counter() - t0
    sp = cagra.SearchParams(itopk_size=32)
    qps, out = _measure_qps(
        lambda q: cagra.search(sp, idx, q, 10), qsets,
        qsets[0].shape[0], use_jit=False)
    rows.append({"name": "cagra_1m_itopk32",
                 "qps": round(qps, 1),
                 "recall": round(_recall(np.asarray(out[1])[:1000], gt), 4),
                 "build_s": round(build_s, 1)})


def _render_note(artifact: dict) -> str:
    """Markdown round-note table generated FROM a BENCH_rXX.json artifact
    (VERDICT r5 #7: the r05 BASELINE note described a different session than
    the committed artifact — prose and artifact must be the same bytes).
    Pure stdlib, no jax: runs anywhere, including the doc-writing host.

        python bench.py --note BENCH_r06.json >> BASELINE.md   # then edit

    Ratio fields that ride IN the rows (fused_over_control, i8_over_f32,
    serve_over_seq) are printed from the rows, never recomputed elsewhere.
    """
    if "parsed" in artifact and isinstance(artifact["parsed"], dict):
        # driver wrapper ({n, cmd, rc, tail, parsed}): the bench's own
        # result line lives under "parsed"
        artifact = artifact["parsed"]
    lines = [
        "| row | QPS | recall | build_s | ratio |",
        "|---|---|---|---|---|",
    ]
    for r in artifact.get("rows", []):
        name = r.get("name", "?")
        if "error" in r:
            lines.append(f"| {name} | ERROR | | | {r['error'][:60]} |")
            continue
        if "qps" not in r:
            continue
        ratio = ""
        for key, label in (("fused_over_control", "fused/control"),
                           ("i8_over_f32", "i8/f32"),
                           ("serve_over_seq", "serve/seq"),
                           ("hbm_over_tiered", "hbm/tiered")):
            if r.get(key) is not None:
                ratio = f"{label} **{r[key]}**"
        rec = r.get("recall")
        lines.append(
            f"| {name} | {r['qps']:,.1f} | "
            f"{'' if rec is None else format(rec, '.4f')} | "
            f"{r.get('build_s', '')} | {ratio} |")
    head = (
        f"Flagship {artifact.get('value', 0):,.1f} {artifact.get('unit', '')}"
        f" (vs_baseline {artifact.get('vs_baseline')}), "
        f"elapsed {artifact.get('elapsed_s')}s, "
        f"metrics_enabled={artifact.get('metrics_enabled')}. "
        "Table generated by `python bench.py --note <artifact>` — the "
        "numbers below ARE the artifact's.")
    return head + "\n\n" + "\n".join(lines)


def _backend_or_exit(rows, timeout_s=150.0):
    """Force backend init under a watchdog, emitting + exiting 0 on failure.

    The axon TPU tunnel has two observed failure modes: raising
    (r02: ``RuntimeError: Unable to initialize backend 'axon'``) and HANGING
    indefinitely inside device discovery (reproduced r03) — so a try/except
    alone cannot keep the unkillable contract; the probe runs in a daemon
    thread and a hang past ``timeout_s`` converts to a labeled row +
    ``os._exit(0)`` (all output is already flushed; atexit has nothing to do).
    """
    import os
    import threading

    box = {}

    def probe():
        try:
            import jax

            box["n"] = len(jax.devices())
        except BaseException as e:  # labeled, never propagated
            box["err"] = f"{type(e).__name__}: {str(e)[:240]}"

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    err = (f"backend init did not return within {timeout_s:.0f}s "
           "(device tunnel hang)" if t.is_alive() else box.get("err"))
    if err is not None:
        rows.append({"name": "backend", "error": err})
        _emit()
        os._exit(0)


def _row_net_serve(rows, n=100_000, d=64, n_lists=512, k=10, n_probes=16,
                   thread_ladder=(1, 4, 8), per_thread=150, max_batch=64,
                   max_wait_us=2000.0, n_eval=512, ncl=500):
    """Network front door A/B (ISSUE 20): the SAME published service
    driven closed-loop in-process (``svc.search``) and over the loopback
    wire (NetClient -> NetServer -> svc) at each rung of a concurrency
    ladder. Same index, same flush programs, so recall over the wire must
    equal the in-process measurement exactly (both fields gated by
    bench/compare.py); the wire tax is the QPS ratio at the top rung; the
    request p99 decomposes into wire/queue/flush from the serve
    histograms plus the front door's wire-wall histogram. The whole
    serving window — both paths, every rung — runs under compile
    attribution and MUST be compile-free: publish() warmed the bucket
    ladder and the wire path replays the same program set."""
    import threading

    import jax
    import numpy as np

    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.net.client import NetClient
    from raft_tpu.net.server import NetServer
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.obs import metrics as obs_metrics
    from raft_tpu.serve import SearchService

    _note("net: dataset")
    dataset, qsets = _make_clustered(n, d, 2000, ncl, n_qsets=1, seed=29)
    jax.block_until_ready([dataset] + qsets)
    pool = np.asarray(qsets[0])
    eval_q = pool[:n_eval]
    _note("net: ground truth")
    gt = _ground_truth(dataset, eval_q, k=k)

    _note("net: ivf_flat build + publish")
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=n_lists, seed=0),
                         dataset)
    jax.block_until_ready(idx.list_data)
    sp = ivf_flat.SearchParams(n_probes=n_probes)

    def serving(queries, k_):
        return ivf_flat.search(sp, idx, queries, k_)

    serving.kind, serving.dim, serving.query_dtype = "ivf_flat", d, "float32"
    svc = SearchService(max_batch=max_batch, max_wait_us=max_wait_us,
                        max_queue_rows=4096)
    svc.publish("net", serving, k=k)  # the warm ladder IS the rehearsal

    failures = []

    def ladder(search_one):
        qps = {}
        for T in thread_ladder:
            def worker(tid):
                for j in range(per_thread):
                    qi = (tid + j * T) % pool.shape[0]
                    try:
                        search_one(pool[qi:qi + 1])
                    except Exception as e:  # pragma: no cover - fails row
                        failures.append(
                            f"{type(e).__name__}: {str(e)[:80]}")
            ws = [threading.Thread(target=worker, args=(t,))
                  for t in range(T)]
            t0 = time.perf_counter()
            for w in ws:
                w.start()
            for w in ws:
                w.join(600)
            qps[str(T)] = round(
                T * per_thread / (time.perf_counter() - t0), 1)
        return qps

    def recall_of(search_batch):
        got = []
        for lo in range(0, n_eval, max_batch):
            _, ids = search_batch(eval_q[lo:lo + max_batch])
            got.append(np.asarray(ids))
        return _recall(np.concatenate(got), gt)

    with NetServer(svc) as srv:
        cli = NetClient(f"http://127.0.0.1:{srv.port}")
        # settle both paths' first flush OUTSIDE the attribution window:
        # publish() compiled the ladder; these replay it from cache
        svc.search("net", pool[:1], k)
        cli.search("net", pool[:1], k)
        with obs_compile.attribution() as rec:
            _note("net: in-process ladder")
            qps_in = ladder(lambda q: svc.search("net", q, k))
            _note("net: wire ladder")
            qps_wire = ladder(lambda q: cli.search("net", q, k))
            recall_in = recall_of(lambda q: svc.search("net", q, k))
            recall_wire = recall_of(lambda q: cli.search("net", q, k))
    svc.shutdown()

    p99 = None
    if _STATE["metrics"]:
        stream_label = f"net.k{k}"
        p99 = {
            "wire_total_ms": round(obs_metrics.quantile(
                "raft_tpu_net_wire_seconds", 0.99,
                route="/v1/search") * 1e3, 3),
            "queue_ms": round(obs_metrics.quantile(
                "raft_tpu_serve_queue_wait_seconds", 0.99,
                stream=stream_label) * 1e3, 3),
            "flush_ms": round(obs_metrics.quantile(
                "raft_tpu_serve_flush_seconds", 0.99,
                stream=stream_label) * 1e3, 3),
        }
    top = str(thread_ladder[-1])
    assert not failures, failures[:3]
    assert rec.cache_misses == 0, (
        f"cold compiles on the serving window: {rec.cache_misses}")
    rows.append({
        "name": "net_serve_100k",
        "qps": qps_wire[top],
        "qps_inproc": qps_in[top],
        "wire_tax": (round(qps_wire[top] / qps_in[top], 3)
                     if qps_in[top] else None),
        "qps_by_threads": {"inproc": qps_in, "wire": qps_wire},
        "recall_inproc": round(recall_in, 4),
        "recall_wire": round(recall_wire, 4),
        "recall_gap": round(recall_wire - recall_in, 4),
        "p99_decomp": p99,
        "compile_s": round(rec.compile_s, 3),
        "cache_misses": rec.cache_misses,
        "threads": list(thread_ladder), "max_batch": max_batch,
        "k": k, "n_probes": n_probes, "n_lists": n_lists,
    })


def _row_net_kill_worker(rows, n=100_000, d=64, k=10, threads=6,
                         duration_s=8.0, kill_after_s=3.0, n_eval=256,
                         max_batch=64):
    """Mesh availability over the wire (ISSUE 20): a 2-shard x 2-replica
    ProcessMesh serves a closed loop through the network front door; one
    worker process is SIGKILLed mid-load. The router's breaker must turn
    the kill into strike->fence->failover with ZERO failed queries (the
    PR 11 semantics crossing process boundaries), post-kill recall must
    stay exact (brute-force workers — any drop means the merge lost a
    shard's candidates), and the surviving fleet reports zero cold
    compiles: each worker warmed its bucket ladder at boot, before the
    front door ever saw traffic."""
    import threading

    import numpy as np

    from raft_tpu.net.client import NetClient
    from raft_tpu.net.mesh import MeshSpec, ProcessMesh
    from raft_tpu.net.server import NetServer
    from raft_tpu.obs import events as obs_events

    _note("net-kill: dataset")
    rng = np.random.default_rng(31)
    dataset = rng.standard_normal((n, d)).astype(np.float32)
    pool = rng.standard_normal((2000, d)).astype(np.float32)
    eval_q = pool[:n_eval]
    gt = _ground_truth(dataset, eval_q, k=k)

    _note("net-kill: boot 2x2 worker mesh (spawn + warm ladders)")
    seq0 = obs_events.last_seq()
    t0 = time.perf_counter()
    mesh = ProcessMesh(dataset, spec=MeshSpec(
        n_shards=2, n_replicas=2, name="corpus", ks=(k,),
        max_batch=max_batch))
    boot_s = time.perf_counter() - t0

    failures, served = [], [0]
    lock = threading.Lock()
    done = threading.Event()
    kill_box = {}
    try:
        with NetServer(mesh, stats=mesh.stats) as srv:
            cli = NetClient(f"http://127.0.0.1:{srv.port}")

            def reader(tid):
                cnt, j = 0, 0
                while not done.is_set():
                    qi = (tid + j * threads) % pool.shape[0]
                    j += 1
                    try:
                        cli.search("corpus", pool[qi:qi + 1], k)
                        cnt += 1
                    except Exception as e:  # pragma: no cover - fails row
                        with lock:
                            failures.append(
                                f"{type(e).__name__}: {str(e)[:80]}")
                with lock:
                    served[0] += cnt

            _note(f"net-kill: {threads}-thread load, kill s0r0 at "
                  f"{kill_after_s:.0f}s")
            ws = [threading.Thread(target=reader, args=(t,))
                  for t in range(threads)]
            t_load = time.perf_counter()
            for w in ws:
                w.start()
            time.sleep(kill_after_s)
            kill_box["pid"] = mesh.kill_worker(0, 0)
            kill_box["at_s"] = round(time.perf_counter() - t_load, 2)
            time.sleep(max(duration_s - kill_after_s, 1.0))
            done.set()
            for w in ws:
                w.join(60)
            load_s = time.perf_counter() - t_load

            got = []
            for lo in range(0, n_eval, max_batch):
                _, ids = cli.search("corpus", eval_q[lo:lo + max_batch], k)
                got.append(np.asarray(ids))
            recall_after = _recall(np.concatenate(got), gt)
            st = mesh.stats()
            health = mesh.health()
    finally:
        mesh.close()

    kinds = [e["kind"] for e in obs_events.query(since_seq=seq0)]
    failovers = kinds.count("net_worker_failover")
    assert not failures, (
        f"{len(failures)} failed queries: {failures[:3]}")
    assert failovers >= 1, "the kill produced no observed failover"
    assert st["cache_misses"] == 0, (
        f"cold compiles in the surviving fleet: {st['cache_misses']}")
    rows.append({
        "name": "net_kill_worker_100k",
        "qps": round(served[0] / load_s, 1),
        "queries": served[0],
        "failed": len(failures),
        "recall_after_kill": round(recall_after, 4),
        "failovers": failovers,
        "fenced": kinds.count("net_worker_fenced"),
        "kill": {"shard": 0, "replica": 0, "pid": kill_box["pid"],
                 "at_s": kill_box["at_s"]},
        "healthy_by_shard": [g["healthy"] for g in health["shards"]],
        "fleet": {"compile_s": st["compile_s"],
                  "cache_misses": st["cache_misses"],
                  "workers_reporting": st["workers"]},
        "boot_s": round(boot_s, 1),
        "shards": 2, "replicas": 2, "threads": threads,
        "max_batch": max_batch, "k": k,
    })


def _row_guard(rows, name, fn, timeout_s=None, _exit=None):
    """Run one row's body under a watchdog (VERDICT r3 weak #6).

    Exceptions convert to a labeled error row and the bench continues. A
    HANG past the per-row deadline — the observed mid-build tunnel failure
    mode, which a try/except cannot catch — converts to a labeled error row,
    a final emit, and ``os._exit(0)``: a wedged device tunnel will hang every
    subsequent row too, so the airtight move is to exit with the snapshot
    printed instead of relying on the driver's external kill. The default
    deadline is the remaining soft budget plus a margin (a row that would
    blow the whole budget is not worth waiting on); ``_exit`` is injectable
    for the hang-injection unit test.
    """
    import os
    import threading

    if timeout_s is None:
        timeout_s = max(60.0, SOFT_BUDGET_S + 180.0 - _elapsed())
    box = {}
    start = len(rows)
    obs_before = _obs_snap()
    mem_before = _mem_snap()

    def body():
        try:
            fn()
        except BaseException as e:
            box["err"] = f"{type(e).__name__}: {str(e)[:200]}"

    t = threading.Thread(target=body, daemon=True)
    t.start()
    t.join(timeout_s)
    if not t.is_alive():
        # attribution attaches only on completed scopes; the hang path below
        # exits the process, so a timed-out row's zombie thread can never
        # pollute a later row's delta
        _obs_attach(rows, start, obs_before)
        _mem_attach(rows, start, mem_before)
    if t.is_alive():
        # don't shadow a success row the body already emitted under this
        # name (e.g. the flagship primary row printed before a later mode
        # hung) — consumers key rows by name
        if any(r.get("name") == name for r in rows):
            name = f"{name}_watchdog"
        rows.append({"name": name,
                     "error": f"row hung past {timeout_s:.0f}s watchdog "
                              "(device tunnel hang)"})
        _emit()
        (_exit or os._exit)(0)
        return  # only reached under the injected test exit
    if "err" in box:
        if any(r.get("name") == name for r in rows):
            name = f"{name}_error"
        rows.append({"name": name, "error": box["err"]})


def _setup(rows):
    """Shared preamble of _run and --serve: cache, obs subscription, backend
    probe. Each piece degrades to a labeled error row, never a crash."""
    try:
        from raft_tpu.config import enable_compilation_cache

        enable_compilation_cache()
    except Exception as e:  # cache is an optimization, never fatal
        rows.append({"name": "compilation_cache", "error": str(e)[:200]})

    if _STATE["metrics"]:
        try:
            # subscribe to jax.monitoring BEFORE the first compile so every
            # row's obs delta carries compile_s + cache outcomes
            from raft_tpu.obs import compile as _obs_compile

            _obs_compile.install()
        except Exception as e:  # observability is never fatal either
            rows.append({"name": "obs_install", "error": str(e)[:200]})

    _backend_or_exit(rows)


def _run(rows):
    """Bench body. Every row is individually guarded; _run itself may still
    raise only out of the first few lines (jax import), which main()
    converts into a labeled row."""
    _setup(rows)
    import jax

    _note(f"backend: {jax.default_backend()}")

    _note("flagship exact 100k")
    _row_guard(rows, "exact_fused_knn_100k", lambda: _flagship_exact(rows))
    _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "serve_ivf_pq_100k", lambda: _row_serve(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "serve_pipeline_100k",
                   lambda: _row_serve_pipeline(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "serve_churn_ivf_pq_100k",
                   lambda: _row_serve_churn(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "serve_churn_cagra_100k",
                   lambda: _row_serve_churn_cagra(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "serve_shard_churn_100k",
                   lambda: _row_serve_shard(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "canary_smoke_100k",
                   lambda: _row_canary_smoke(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "tune_smoke_10k", lambda: _row_tune_smoke(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "mem_smoke_100k", lambda: _row_mem_smoke(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "fault_smoke_100k",
                   lambda: _row_fault_smoke(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "crash_recovery_100k",
                   lambda: _row_crash_recovery(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "reshard_churn_100k",
                   lambda: _row_reshard_churn(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "controller_drift_100k",
                   lambda: _row_controller_drift(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "controller_ramp_100k",
                   lambda: _row_controller_ramp(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "tiered_100k", lambda: _row_tiered(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "ooc_build_100k", lambda: _row_ooc_build(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "quant_funnel_100k",
                   lambda: _row_quant_funnel(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "net_serve_100k", lambda: _row_net_serve(rows))
        _emit()

    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "net_kill_worker_100k",
                   lambda: _row_net_kill_worker(rows))
        _emit()

    lid_box = {}
    if _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "ivf_pq_1m_lid_pq4x64_r4",
                   lambda: _row_ivf_pq_lid(rows, lid_box))
        _emit()

    if "dataset" in lid_box and _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "ivf_pq_1m_i8", lambda: _row_ivf_pq_i8(
            rows, lid_box["dataset"], lid_box["qsets"]))
        _emit()
    lid_box.clear()  # release the 512 MB LID set before the isotropic draw

    box = {}
    if _elapsed() < SOFT_BUDGET_S:
        def make_dataset():
            _note("isotropic 1M dataset")
            dataset, qsets = _make_1m()
            jax.block_until_ready([dataset] + qsets)
            # ground truth for recall on the first 1000 queries of the LAST
            # set — _measure_qps returns the output for that set
            _note("ground truth 1k queries")
            box["gt"] = _ground_truth(dataset, qsets[-1][:1000])
            box["dataset"], box["qsets"] = dataset, qsets

        _row_guard(rows, "dataset_1m", make_dataset)

    if "gt" in box and _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "ivf_flat_1m_p8", lambda: _row_ivf_flat(
            rows, box["dataset"], box["qsets"], box["gt"]))
        _emit()

    if "gt" in box and _elapsed() < SOFT_BUDGET_S:
        _row_guard(rows, "cagra_1m_itopk32", lambda: _row_cagra(
            rows, box["dataset"], box["qsets"], box["gt"]))


def main(argv=None):
    import signal

    rows = _STATE["rows"]
    argv = sys.argv[1:] if argv is None else argv
    if "--note" in argv:
        # render a round-note table from a committed artifact and exit —
        # never touches jax, so it cannot fail on a broken backend
        path = argv[argv.index("--note") + 1]
        with open(path) as f:
            print(_render_note(json.load(f)))
        return 0
    if "--no-metrics" in argv:
        # the disabled-path proof: every obs touch point reduces to one
        # module-flag check and rows carry no "obs" attribution field
        _STATE["metrics"] = False
        try:
            from raft_tpu import obs

            obs.disable()
        except Exception:
            pass

    def _on_term(signum, frame):  # driver SIGTERM -> the emit path below
        raise SystemExit(f"signal {signum}")

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):  # pragma: no cover - non-main thread
        pass
    try:
        if "--serve-churn" in argv:
            # mutable-lifecycle churn rows only (ISSUE 5/6): the quick loop
            # for iterating on stream/compactor parameters — IVF-PQ (extend
            # folds) and CAGRA (rebuild folds, the build-speed payoff row)
            _setup(rows)
            _row_guard(rows, "serve_churn_ivf_pq_100k",
                       lambda: _row_serve_churn(rows))
            _row_guard(rows, "serve_churn_cagra_100k",
                       lambda: _row_serve_churn_cagra(rows))
        elif "--serve-shard" in argv:
            # sharded serving tier only (ISSUE 9): the iteration loop for
            # the scatter-gather serve path — QPS ladder over shard counts
            # + the staggered-compaction churn window
            _setup(rows)
            _row_guard(rows, "serve_shard_churn_100k",
                       lambda: _row_serve_shard(rows))
        elif "--canary-smoke" in argv:
            # canary overhead loop only (ISSUE 8): sampling-rate QPS A/B +
            # the compile-free-hot-path proof with live quality monitoring
            # on; the heavy drift sweep is bench/drift_sweep.py
            _setup(rows)
            _row_guard(rows, "canary_smoke_100k",
                       lambda: _row_canary_smoke(rows))
        elif "--mem-smoke" in argv:
            # memory-ledger loop proof only (ISSUE 10): publish→retire
            # flat-peak + zero-leak + estimator-accuracy assertions; the
            # regression gate over artifacts is bench/compare.py
            _setup(rows)
            _row_guard(rows, "mem_smoke_100k",
                       lambda: _row_mem_smoke(rows))
        elif "--fault-smoke" in argv:
            # availability loop only (ISSUE 11): replica kill + same-flush
            # failover + breaker heal, then the injected-crash WAL-replay
            # recovery row — the iteration path for fencing/WAL parameters
            _setup(rows)
            _row_guard(rows, "fault_smoke_100k",
                       lambda: _row_fault_smoke(rows))
            _row_guard(rows, "crash_recovery_100k",
                       lambda: _row_crash_recovery(rows))
        elif "--reshard" in argv:
            # elastic-resharding loop only (ISSUE 13): the iteration path
            # for split/merge, carry-over and manifest-commit parameters —
            # the loaded topology-doubling window + the crash-mid-reshard
            # recovery measurement
            _setup(rows)
            _row_guard(rows, "reshard_churn_100k",
                       lambda: _row_reshard_churn(rows))
        elif "--controller" in argv:
            # closed-loop controller only (ISSUE 18): the iteration path
            # for ControlPolicy thresholds — the drift→retune recovery
            # window and the ramp→reshard topology double, each with the
            # causal seq chain asserted off the journal
            _setup(rows)
            _row_guard(rows, "controller_drift_100k",
                       lambda: _row_controller_drift(rows))
            _row_guard(rows, "controller_ramp_100k",
                       lambda: _row_controller_ramp(rows))
        elif "--tiered" in argv:
            # beyond-HBM tiering loop only (ISSUE 15): the iteration path
            # for TierPolicy / refine-hop parameters — the all-HBM vs
            # tiered A/B under a squeezing device budget
            _setup(rows)
            _row_guard(rows, "tiered_100k", lambda: _row_tiered(rows))
        elif "--ooc-build" in argv:
            # out-of-core streamed build loop only (ISSUE 19): the
            # iteration path for chunk_rows / staging parameters — the
            # in-core vs memmap-streamed build A/B with bit-equality and
            # the plan(streamed) peak envelope asserted in the row
            _setup(rows)
            _row_guard(rows, "ooc_build_100k",
                       lambda: _row_ooc_build(rows))
        elif "--quant" in argv:
            # quantization-funnel loop only (ISSUE 16): the iteration path
            # for fast-scan / funnel-width / rotation parameters — the
            # classic-PQ vs funnel-twin capacity A/B with the funnel_grid
            # sweep; the heavy 1M OPQ sweep is the slow-manifest test
            _setup(rows)
            _row_guard(rows, "quant_funnel_100k",
                       lambda: _row_quant_funnel(rows))
        elif "--tune-smoke" in argv:
            # autotune loop proof only (ISSUE 7): the quick iteration
            # path for the tune sweep engine; heavy sweeps are
            # bench/tune_sweep.py
            _setup(rows)
            _row_guard(rows, "tune_smoke_10k",
                       lambda: _row_tune_smoke(rows))
        elif "--net-serve" in argv:
            # network front door only (ISSUE 20): the iteration loop for
            # wire/mesh parameters — the in-process vs over-the-wire
            # closed-loop A/B at identical recall, then the mid-load
            # worker kill with the zero-failed-queries failover proof
            _setup(rows)
            _row_guard(rows, "net_serve_100k",
                       lambda: _row_net_serve(rows))
            _row_guard(rows, "net_kill_worker_100k",
                       lambda: _row_net_kill_worker(rows))
        elif "--serve-pipeline" in argv:
            # host-free flush pipeline A/B only (ISSUE 12): the iteration
            # loop for pipeline_depth / staging parameters — sync vs
            # pipelined per-flush QPS with the queue/flush decomposition
            _setup(rows)
            _row_guard(rows, "serve_pipeline_100k",
                       lambda: _row_serve_pipeline(rows))
        elif "--serve" in argv:
            # serving-layer A/B only (ISSUE 3): the quick loop for
            # iterating on batcher/registry parameters
            _setup(rows)
            _row_guard(rows, "serve_ivf_pq_100k", lambda: _row_serve(rows))
        else:
            _run(rows)
    except BaseException as e:  # pragma: no cover - the unkillable contract:
        # even jax-import or TPU-backend-init failures (r02's BENCH crash was
        # `RuntimeError: Unable to initialize backend 'axon'` before any
        # output) must still produce a parseable snapshot and rc=0
        rows.append({"name": "fatal",
                     "error": f"{type(e).__name__}: {str(e)[:260]}"})
    # the reference publishes no absolute numbers (BASELINE.md); the recorded
    # round-1 flagship (110,805 QPS, BENCH_r01.json) is the progress baseline
    _emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
