"""Measure pq8 nibble-split vs pq4 at 1M on TPU (VERDICT r2 #5).

Rows: pq4x64 (default), pq8x32 split (same code bytes as the reference's
default pq8 config), pq8x32 joint (the r02 measured-slow path) — bare and
+refine4 — on the LID (SIFT-class) 1M set. Done-bar: split pq8x32 within 2x
of pq4x64 QPS.
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import enable_compilation_cache
from raft_tpu.neighbors import ivf_pq
from raft_tpu.neighbors.refine import refine
from raft_tpu.distance.types import DistanceType


def make_lid_1m():
    n, d, m, ncl, idim = 1_000_000, 128, 10_000, 2000, 16
    kc, kb, kl, kz, kq1, kq2, kq3 = jax.random.split(jax.random.key(7), 7)
    centers = jax.random.uniform(kc, (ncl, d), jnp.float32) * 10.0
    bases = jax.random.normal(kb, (ncl, idim, d), jnp.float32)
    bases = bases / jnp.linalg.norm(bases, axis=-1, keepdims=True)

    def draw(kk_lab, kk_noise, count):
        labels = jax.random.randint(kk_lab, (count,), 0, ncl)
        z = 0.5 * jax.random.normal(kk_noise, (count, idim))
        return centers[labels] + jnp.einsum(
            "ni,nid->nd", z, bases[labels], precision="highest")

    blk = 50_000
    kls = jax.random.split(kl, n // blk)
    kzs = jax.random.split(kz, n // blk)
    dataset = jnp.concatenate(
        [draw(kls[i], kzs[i], blk) for i in range(n // blk)])
    qsets = []
    for kk in (kq1, kq2, kq3):
        ka, kb2 = jax.random.split(kk)
        qsets.append(draw(ka, kb2, m))
    return dataset, qsets


def measure(search_fn, qsets):
    out = None
    best = float("inf")
    np.asarray(jax.tree_util.tree_leaves(search_fn(qsets[0]))[0])
    for qs in qsets[1:]:
        t0 = time.perf_counter()
        out = search_fn(qs)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return qsets[0].shape[0] / best, out


def rec(ids, gt):
    ids = np.asarray(ids)
    return float(np.mean([len(set(ids[r, :10]) & set(gt[r])) / 10
                          for r in range(gt.shape[0])]))


def main():
    enable_compilation_cache()
    print("dataset...", flush=True)
    dataset, qsets = make_lid_1m()
    jax.block_until_ready([dataset] + qsets)
    from raft_tpu.neighbors.brute_force import _bf_knn_fused

    _, gt = _bf_knn_fused(dataset, qsets[-1][:1000], 10,
                          DistanceType.L2Expanded, "float32", None)
    gt = np.asarray(gt)

    configs = [
        ("pq4x64", dict(n_lists=1024, pq_bits=4, pq_dim=64, seed=0)),
        ("pq8x32-split", dict(n_lists=1024, pq_bits=8, pq_dim=32, seed=0)),
    ]
    if "--joint" in sys.argv:
        configs.append(
            ("pq8x32-joint", dict(n_lists=1024, pq_bits=8, pq_dim=32,
                                  pq8_split=False, seed=0)))

    for name, kw in configs:
        t0 = time.perf_counter()
        idx = ivf_pq.build(ivf_pq.IndexParams(**kw), dataset)
        jax.block_until_ready(idx.list_codes)
        build_s = time.perf_counter() - t0
        sp = ivf_pq.SearchParams(n_probes=8, lut_dtype="bfloat16")

        qps, out = measure(lambda q: ivf_pq.search(sp, idx, q, 10), qsets)
        print(f"{name:14s} bare    qps={qps:9.1f} recall={rec(out[1][:1000], gt):.4f} "
              f"build={build_s:.1f}s", flush=True)

        def searcher(q):
            _, cand = ivf_pq.search(sp, idx, q, 40)
            return refine(dataset, q, cand, 10)

        qps_r, out_r = measure(searcher, qsets)
        print(f"{name:14s} refine4 qps={qps_r:9.1f} recall={rec(out_r[1][:1000], gt):.4f}",
              flush=True)


if __name__ == "__main__":
    main()
