"""Interleaved A/B of the CAGRA build-chunk candidate select: wide-k Pallas
selector vs lax.top_k, at the EXACT call site it was commissioned for
(VERDICT r4 #5 / r5 #3 — `cagra.py _build_chunk_step` → `ivf_pq.search`'s
k = gpu_top_k + 1 = 193 per-chunk + final-merge selects).

Two measurements, one process:

1. ``chunk``: the full `_build_chunk_step` (PQ search + exact refine +
   self-edge drop — the program the 1M build dispatches ~62 times) with
   select_impl in {"xla", "pallas"}. The r04 selection-share probe bounded
   selection at ~8% of the chunk, so the expected delta is small — this is
   the commissioned proof either way.
2. ``select``: the bare ivf_pq.search at the same shapes, isolating the
   select from the refine so the per-select ratio is readable, ACROSS a
   column-width sweep (the per-chunk width probe_chunk*capacity is ~10-40k
   cols — BELOW the 65536-col threshold the r05 study measured at, so this
   sweep is the data that decides whether the auto wide-k threshold drops).

Run on the TPU host:

    python bench/cagra_build_select_ab.py [--n 1000000] [--rounds 3]
"""

from __future__ import annotations

import argparse
import sys
import time
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--chunk", type=int, default=16384)
    args = ap.parse_args()

    from raft_tpu.config import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench as drv
    from raft_tpu.core.resources import default_resources
    from raft_tpu.distance.types import resolve_metric
    from raft_tpu.neighbors import cagra, ivf_pq

    print(f"backend: {jax.default_backend()}", file=sys.stderr)
    dataset, _ = (drv._make_1m() if args.n >= 1_000_000 else
                  drv._make_clustered(args.n, 128, 1000,
                                      max(args.n // 500, 8)))
    x = jnp.asarray(dataset)
    jax.block_until_ready(x)
    n, d = x.shape

    params = cagra.IndexParams()
    k, gpu_top_k, n_lists, pq_bits = cagra.knn_build_plan(params, n, d)
    res = default_resources()
    t0 = time.perf_counter()
    pq = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=n_lists, metric=params.metric,
                           pq_bits=pq_bits, seed=params.seed), x)
    jax.block_until_ready(pq.list_codes)
    print(f"ivf_pq build {time.perf_counter() - t0:.1f}s "
          f"(n_lists={n_lists}, capacity={pq.capacity}, "
          f"select k={gpu_top_k + 1})", file=sys.stderr)
    mt = resolve_metric(params.metric)
    chunk = args.chunk
    xb = x[:chunk]
    rows = jnp.arange(chunk, dtype=jnp.int32)

    # --- 1. full build-chunk A/B (the commissioned measurement) ---
    impls = ("xla", "pallas", "auto")
    outs = {}
    for impl in impls:
        t0 = time.perf_counter()
        out = cagra._build_chunk_step(x, pq, xb, rows, 32, int(gpu_top_k),
                                      int(k), mt, int(res.workspace_bytes),
                                      impl)
        np.asarray(out)
        outs[impl] = out
        print(f"chunk[{impl}] compile+run {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
    # identical edge lists = the routing changed nothing but the selector
    for impl in impls[1:]:
        same = float(np.mean(np.asarray(outs[impl]) == np.asarray(outs["xla"])))
        print(f"chunk[{impl}] edge agreement vs xla: {same:.4f}")
    times = {impl: [] for impl in impls}
    for r in range(args.rounds):
        for impl in impls:
            t0 = time.perf_counter()
            np.asarray(cagra._build_chunk_step(
                x, pq, xb, rows, 32, int(gpu_top_k), int(k), mt,
                int(res.workspace_bytes), impl))
            times[impl].append(time.perf_counter() - t0)
    for impl in impls:
        best = min(times[impl])
        print(f"chunk[{impl}] best {best:.3f}s "
              f"({chunk / best:,.0f} rows/s)  all "
              f"{[f'{t:.2f}' for t in times[impl]]}")
    print(f"chunk pallas/xla speedup: "
          f"{min(times['xla']) / min(times['pallas']):.3f}x")

    # --- 2. bare select sweep: the per-select ratio vs column width ---
    for n_probes in (8, 16, 32):
        sps = {impl: ivf_pq.SearchParams(n_probes=n_probes, select_impl=impl)
               for impl in ("xla", "pallas")}
        for impl, sp in sps.items():
            np.asarray(ivf_pq.search(sp, pq, xb, gpu_top_k + 1)[1])  # warm
        best = {}
        for impl, sp in sps.items():
            bt = float("inf")
            for r in range(args.rounds):
                t0 = time.perf_counter()
                np.asarray(ivf_pq.search(sp, pq, xb, gpu_top_k + 1)[1])
                bt = min(bt, time.perf_counter() - t0)
            best[impl] = bt
        print(f"search p={n_probes:2d} (<= {n_probes * pq.capacity} cols) "
              f"xla {best['xla']:.3f}s pallas {best['pallas']:.3f}s "
              f"ratio {best['xla'] / best['pallas']:.3f}x")


if __name__ == "__main__":
    main()
