"""CAGRA graph-blocked layout experiment (VERDICT r2 #6).

Hypothesis (BASELINE.md r02): hops are latency-bound row gathers; reordering
dataset rows so graph neighbors fall in shared blocks (coarse-cluster order)
turns the per-hop (m, width*deg) row gather into a friendlier DMA pattern.

Method: build ONE 1M CAGRA index, then measure search QPS on (a) the index
as built, (b) the same index with rows permuted into cluster-sorted order and
the graph relabeled (identical graph structure -> identical recall, so any
QPS delta is pure memory-layout effect). Run on real TPU.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.config import enable_compilation_cache
from raft_tpu.neighbors import cagra
from raft_tpu.neighbors._list_utils import assign_to_lists
from raft_tpu.distance.types import DistanceType


def make_1m():
    n, d, m, ncl = 1_000_000, 128, 10_000, 2000
    kc, kl, kn, kq1, kq2, kq3 = jax.random.split(jax.random.key(42), 6)
    centers = jax.random.uniform(kc, (ncl, d), jnp.float32) * 10.0

    def draw(kk_lab, kk_noise, count):
        labels = jax.random.randint(kk_lab, (count,), 0, ncl)
        return centers[labels] + 0.5 * jax.random.normal(kk_noise, (count, d))

    dataset = draw(kl, kn, n)
    qsets = []
    for kk in (kq1, kq2, kq3):
        ka, kb = jax.random.split(kk)
        qsets.append(draw(ka, kb, m))
    return dataset, qsets


def measure(idx, qsets, sp, k=10):
    out = None
    best = float("inf")
    _ = np.asarray(cagra.search(sp, idx, qsets[0], k)[1])  # warm
    for qs in qsets[1:]:
        t0 = time.perf_counter()
        out = cagra.search(sp, idx, qs, k)
        np.asarray(out[1])
        best = min(best, time.perf_counter() - t0)
    return qsets[0].shape[0] / best, out


def recall(ids, gt):
    ids = np.asarray(ids)
    return float(np.mean([len(set(ids[r, :10]) & set(gt[r])) / 10
                          for r in range(gt.shape[0])]))


def main():
    enable_compilation_cache()
    print("dataset...", flush=True)
    dataset, qsets = make_1m()
    jax.block_until_ready([dataset] + qsets)

    from raft_tpu.neighbors.brute_force import _bf_knn_fused

    _, gt = _bf_knn_fused(dataset, qsets[-1][:1000], 10,
                          DistanceType.L2Expanded, "float32", None)
    gt = np.asarray(gt)

    print("build...", flush=True)
    t0 = time.perf_counter()
    idx = cagra.build(cagra.IndexParams(), dataset)
    jax.block_until_ready(idx.graph)
    print(f"build {time.perf_counter() - t0:.1f}s", flush=True)

    sp = cagra.SearchParams(itopk_size=32)
    qps, out = measure(idx, qsets, sp)
    print(f"baseline       qps={qps:9.1f} recall={recall(out[1][:1000], gt):.4f}",
          flush=True)

    # --- blocked layout: rows sorted by coarse cluster ---
    print("cluster + permute...", flush=True)
    kb = KMeansBalancedParams(n_iters=10, seed=0, max_train_points=200_000)
    centers = kmeans_balanced.fit(kb, dataset, 1024)
    labels = assign_to_lists(dataset, centers, DistanceType.L2Expanded, 4096)
    perm = jnp.argsort(labels, stable=True)          # new_row -> old_row
    inv = jnp.zeros_like(perm).at[perm].set(jnp.arange(perm.shape[0]))
    data_p = jnp.take(dataset, perm, axis=0)
    graph_p = jnp.take(inv.astype(jnp.int32),
                       jnp.take(idx.graph, perm, axis=0), axis=0)
    idx_p = cagra.CagraIndex(dataset=data_p, graph=graph_p, metric=idx.metric)
    jax.block_until_ready(idx_p.graph)

    qps_p, out_p = measure(idx_p, qsets, sp)
    ids_back = jnp.take(perm, jnp.maximum(out_p[1], 0))[:1000]
    print(f"cluster-sorted qps={qps_p:9.1f} recall={recall(ids_back, gt):.4f}",
          flush=True)
    print(f"delta: {qps_p / qps - 1:+.1%}", flush=True)


if __name__ == "__main__":
    main()
