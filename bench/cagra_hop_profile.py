"""In-kernel per-phase profile of the fused CAGRA hop at 1M (VERDICT r4 #1
done-bar: the negative-result evidence must localize the kernel's own cost —
scoring vs dedup vs merge vs the XLA-side gathers).

Variants (one process, interleaved):
  full       the shipping fused hop
  nodedup    beam-membership masks skipped
  nomerge    dedup+extraction skipped (beam passes through; pick still runs)
  noscore    distance computation skipped (gathers still happen)
  nogate     arena merges only: insertion loop UNGATED (full-vs-nogate =
             the threshold gate's measured worth; r06 residual carve)
  gatheronly no kernel at all — the while_loop + two gathers + trivial ops

``--merge`` profiles a specific merge impl (extract | arena | arena_smem) —
the r06 residual attack carves the ARENA loop, the r05 study carved extract.

Run on the TPU host:  python bench/cagra_hop_profile.py [--rounds 3]
                      python bench/cagra_hop_profile.py --merge arena
"""

from __future__ import annotations

import argparse
import functools
import sys
import time
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--itopk", type=int, default=32)
    ap.add_argument("--merge", default="extract",
                    choices=["extract", "arena", "arena_smem"])
    args = ap.parse_args()

    from raft_tpu.config import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    import bench as drv
    from raft_tpu.neighbors import cagra
    from raft_tpu.ops.cagra_hop import cagra_hop

    print(f"backend: {jax.default_backend()}", file=sys.stderr)
    dataset, qsets = drv._make_1m()
    jax.block_until_ready([dataset] + qsets)
    idx = cagra.build(cagra.IndexParams(), dataset)
    jax.block_until_ready(idx.graph)
    print("build done", file=sys.stderr)

    itopk = args.itopk
    deg = idx.graph_degree
    n, d = idx.dataset.shape
    max_iter = itopk + 10
    m = qsets[0].shape[0]

    @jax.jit
    def init_state(queries, key, data):
        qf = queries.astype(jnp.float32)
        dn2 = jnp.sum(data.astype(jnp.float32) ** 2, axis=1)
        pool_ids = jax.random.choice(key, n, (16384,), replace=False).astype(jnp.int32)
        pool_vecs = data[pool_ids].astype(jnp.float32)
        pool_d = dn2[pool_ids][None, :] - 2.0 * qf @ pool_vecs.T
        _, best = lax.top_k(-pool_d, itopk)
        init_ids = pool_ids[best]
        vecs0 = data[init_ids]
        init_d = jnp.sum((vecs0 - qf[:, None, :]) ** 2, axis=-1)
        order = jnp.argsort(init_d, axis=1)
        bd = jnp.full((m, 128), jnp.inf, jnp.float32
                      ).at[:, :itopk].set(jnp.take_along_axis(init_d, order, 1))
        bi = jnp.full((m, 128), -1, jnp.int32
                      ).at[:, :itopk].set(jnp.take_along_axis(init_ids, order, 1))
        bv = jnp.ones((m, 128), jnp.int32).at[:, :itopk].set(0)
        return qf, bd, bi, bv

    merge = args.merge

    @functools.partial(jax.jit, static_argnames=("profile",))
    def run(state, data, graph, profile):
        qf, bd, bi, bv = state

        if profile == "gatheronly":
            def body(state):
                bd, bi, bv, pick, nocand, it = state
                nbrs = graph[pick[:, 0]]
                vecs = data[jnp.maximum(nbrs, 0)].astype(jnp.float32)
                # trivial consumption standing in for the kernel
                s = jnp.sum(vecs, axis=(1, 2), keepdims=False)[:, None]
                pick = (pick + nbrs[:, :1] + (s > 0)) % n
                return bd, bi, bv, pick, nocand, it + 1

            st = (bd, bi, bv, jnp.zeros((m, 1), jnp.int32),
                  jnp.zeros((m, 1), jnp.int32), 0)
            bd, bi, *_ = lax.while_loop(
                lambda s: s[-1] < max_iter, body, st)
            return bd[:, :10], bi[:, :10]

        zero_nbrs = jnp.full((m, deg), -1, jnp.int32)
        zero_vecs = jnp.zeros((m, deg, d), jnp.float32)
        bd, bi, bv, pick, nocand = cagra_hop(
            qf, bd, bi, bv, zero_nbrs, zero_vecs,
            jnp.zeros((m, deg), jnp.int32), itopk, width=1, profile=profile,
            merge=merge)

        def body(state):
            bd, bi, bv, pick, nocand, it = state
            nbrs = graph[jnp.minimum(pick[:, 0], n - 1)]
            vecs = data[jnp.maximum(nbrs, 0)].astype(jnp.float32)
            valid = jnp.repeat(1 - nocand, deg, axis=1)
            bd, bi, bv, pick, nocand = cagra_hop(
                qf, bd, bi, bv, nbrs, vecs, valid, itopk, width=1,
                profile=profile, merge=merge)
            return bd, bi, bv, pick, nocand, it + 1

        bd, bi, *_ = lax.while_loop(
            lambda s: jnp.logical_and(s[-1] < max_iter,
                                      jnp.logical_not(jnp.all(s[-2] > 0))),
            body, (bd, bi, bv, pick, nocand, 0))
        return bd[:, :10], bi[:, :10]

    variants = ["full", "nodedup", "nomerge", "noscore", "gatheronly"]
    if merge in ("arena", "arena_smem"):
        # the arena folds dedup into insertion, so nodedup is meaningless;
        # nogate prices the threshold gate instead
        variants = ["full", "nogate", "nomerge", "noscore", "gatheronly"]
    key = jax.random.key(0)
    states = [init_state(qs, key, idx.dataset) for qs in qsets]
    jax.block_until_ready(states)
    print("init states ready", file=sys.stderr)
    live = []
    for v in variants:
        try:  # compile+warm; isolate tunnel compile failures per variant
            t0 = time.perf_counter()
            jax.block_until_ready(run(states[0], idx.dataset, idx.graph, v))
            print(f"{v} compiled in {time.perf_counter()-t0:.0f}s",
                  file=sys.stderr)
            live.append(v)
        except Exception as e:
            print(f"{v} FAILED to compile/run: {str(e)[:160]}",
                  file=sys.stderr)
    times = {v: [] for v in live}
    for r in range(args.rounds):
        for v in live:
            best = float("inf")
            for st in states[1:]:
                t0 = time.perf_counter()
                jax.block_until_ready(run(st, idx.dataset, idx.graph, v))
                best = min(best, time.perf_counter() - t0)
            times[v].append(m / best)
    for v in live:
        print(f"{v:11s} QPS {[f'{x/1e3:.1f}k' for x in times[v]]}")


if __name__ == "__main__":
    main()
