"""Tuning harness for the fused distance+top-k kernel (VERDICT r2 #3).

Sweeps (qt, nblk) x mode on the flagship config and prints one line per
combination. Run on real TPU. Protocol matches bench.py: distinct-data
chained batches inside one jitted program, host-materialized, best of 3.
"""

from __future__ import annotations

import itertools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.config import enable_compilation_cache
from raft_tpu.ops.fused_knn import fused_knn


def measure(dataset, qsets, k, mode, qt, nblk, n_batches, m):
    if mode == "xla":
        from raft_tpu.neighbors.brute_force import _bf_knn
        from raft_tpu.distance.types import DistanceType

        def searches(qs):
            return lax.map(lambda q: _bf_knn(
                dataset, q, k, DistanceType.L2Expanded, 2.0, 1000, 1000), qs)
    else:
        def searches(qs):
            return lax.map(
                lambda q: fused_knn(dataset, q, k, mode=mode, qt=qt, nblk=nblk), qs)

    f = jax.jit(searches)
    np.asarray(jax.tree_util.tree_leaves(f(qsets[0]))[0])
    best = float("inf")
    for qs in qsets[1:]:
        t0 = time.perf_counter()
        out = f(qs)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return n_batches * m / best


def main():
    enable_compilation_cache()
    import os
    n, d, m, k = 100_000, 128, 10_000, int(os.environ.get("TUNE_K", "10"))
    n_batches = 10
    key = jax.random.key(0)
    kd, *kq = jax.random.split(key, 5)
    dataset = jax.random.uniform(kd, (n, d), jnp.float32)
    qsets = [jax.random.uniform(kk, (n_batches, m, d), jnp.float32)
             for kk in kq]
    jax.block_until_ready([dataset] + qsets)

    modes = sys.argv[1].split(",") if len(sys.argv) > 1 else ["f32", "bf16"]
    qts = [int(x) for x in sys.argv[2].split(",")] if len(sys.argv) > 2 else [256, 512]
    nblks = [int(x) for x in sys.argv[3].split(",")] if len(sys.argv) > 3 else [4096, 8192]

    flops = 2.0 * n * d  # per query
    for mode, qt, nblk in itertools.product(modes, qts, nblks):
        try:
            qps = measure(dataset, qsets, k, mode, qt, nblk, n_batches, m)
            print(f"mode={mode:6s} qt={qt:4d} nblk={nblk:5d}  "
                  f"qps={qps:10.1f}  eff={qps * flops / 1e12:6.2f} TFLOP/s",
                  flush=True)
        except Exception as e:
            print(f"mode={mode:6s} qt={qt:4d} nblk={nblk:5d}  ERROR {str(e)[:120]}",
                  flush=True)


if __name__ == "__main__":
    main()
