"""Parallel-driver overhead bound (VERDICT r4 #8): on ONE real chip, a
1-device-mesh A/B of the distributed drivers vs their single-chip twins —
the shard_map + allgather + merge cost with zero actual communication, the
only multi-chip perf evidence obtainable on one chip.

Run on the TPU host:  python bench/parallel_overhead_ab.py [--rounds 3]
"""

from __future__ import annotations

import argparse
import sys
import time
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    args = ap.parse_args()

    from raft_tpu.config import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    import bench as drv
    from raft_tpu import parallel
    from raft_tpu.comms import Comms
    from raft_tpu.neighbors import brute_force, ivf_flat

    print(f"backend: {jax.default_backend()}", file=sys.stderr)
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    comms = Comms(mesh, "data")

    dataset, qsets = drv._make_1m()
    jax.block_until_ready([dataset] + qsets)
    m = qsets[0].shape[0]

    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=1024, seed=0), dataset)
    jax.block_until_ready(idx.list_data)
    print("build done", file=sys.stderr)

    # the distributed IVF search pads n_lists to a mesh multiple — on a
    # 1-device mesh that's a no-op, isolating pure driver overhead
    variants = {
        "bf_single": lambda q: brute_force.knn(dataset, q, 10),
        "bf_parallel": lambda q: parallel.knn.knn(comms, dataset, q, k=10),
        "ivf_single": lambda q: ivf_flat.search(
            ivf_flat.SearchParams(n_probes=8), idx, q, 10),
        "ivf_parallel": lambda q: parallel.ivf.search(
            comms, ivf_flat.SearchParams(n_probes=8), idx, q, 10),
    }
    outs = {}
    for name, fn in variants.items():
        t0 = time.perf_counter()
        outs[name] = fn(qsets[0])
        np.asarray(outs[name][0])
        print(f"{name} compiled {time.perf_counter()-t0:.0f}s",
              file=sys.stderr)
    times = {n: [] for n in variants}
    for r in range(args.rounds):
        for name, fn in variants.items():
            best = float("inf")
            for qs in qsets[1:]:
                t0 = time.perf_counter()
                out = fn(qs)
                np.asarray(out[0])
                best = min(best, time.perf_counter() - t0)
            times[name].append(m / best)
    for name in variants:
        print(f"{name:13s} QPS {[f'{v/1e3:.1f}k' for v in times[name]]}")
    for pair in (("bf_parallel", "bf_single"), ("ivf_parallel", "ivf_single")):
        ratio = max(times[pair[0]]) / max(times[pair[1]])
        print(f"{pair[0]}/{pair[1]}: {ratio:.3f}")
    # sanity: same neighbor sets
    for a, b in (("bf_single", "bf_parallel"), ("ivf_single", "ivf_parallel")):
        ia, ib = np.asarray(outs[a][1])[:500], np.asarray(outs[b][1])[:500]
        ov = np.mean([len(set(ia[r]) & set(ib[r])) / ia.shape[1]
                      for r in range(500)])
        print(f"overlap {a} vs {b}: {ov:.4f}")


if __name__ == "__main__":
    main()
