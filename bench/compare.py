"""BENCH artifact regression gate: diff two BENCH_rXX.json files.

The bench trajectory (BENCH_r01..r05, and every round after) has so far
been compared by eye; this is the tooling: per-row QPS and recall diffs
with tolerances, a non-zero exit on regression (CI-gateable), and a
stdlib ``--table`` renderer for round notes.

    python bench/compare.py BENCH_r05.json BENCH_r06.json
    python bench/compare.py old.json new.json --qps-tol 0.10 --recall-tol 0.005 --table

Rows are matched by ``name``. A row REGRESSES when the new QPS falls more
than ``--qps-tol`` (fractional, default 0.15 — bench QPS on a shared CPU
box is noisy; tighten on dedicated hardware) below the old, or any
recall-like field (``recall``, ``recall_mut``, ...) falls more than
``--recall-tol`` (absolute, default 0.01) below the old. Rows only in one
artifact are reported but never gate (new rows appear every round); a row
that errored in the NEW artifact but not the old is a regression, and so
is a QPS/recall field present in the old row but missing from the new —
a lost measurement must not pass as "ok". The per-tier ``mem.tiers.*``
sub-fields (rows served through a TieredStore) gate the same way on
PRESENCE: byte levels shift legitimately between runs, but a tier
measurement the old artifact had and the new lost fails the gate. The
quantization-funnel capacity fields (``bytes_per_row``,
``rows_per_hbm_byte``) follow the same presence rule, as do the
per-kind ``events.*`` sub-fields (fault/reshard/tiered rows carry the
event-journal counts their scope emitted — a fence window that stops
producing ``replica_fenced`` events is a lost measurement).

Accepts both the committed driver wrapper (``{n, cmd, rc, tail, parsed}``)
and a bare bench snapshot (``{metric, value, rows, ...}``); an artifact
compared against itself passes by construction (asserted in
``tests/test_bench_harness.py``). Pure stdlib — no jax import, so it runs
anywhere, including CI hosts with no accelerator stack.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["compare", "load_rows", "render_table", "main"]


def load_rows(artifact: dict) -> dict:
    """``{name: row}`` from a BENCH artifact (driver wrapper or bare
    snapshot). Later duplicates win (the bench appends error rows under
    suffixed names, so duplicates are rare by construction)."""
    if "parsed" in artifact and isinstance(artifact["parsed"], dict):
        artifact = artifact["parsed"]
    return {r["name"]: r for r in artifact.get("rows", [])
            if isinstance(r, dict) and "name" in r}


def _recall_keys(row: dict):
    return sorted(k for k, v in row.items()
                  if k.startswith("recall") and isinstance(v, (int, float)))


def _tier_keys(row: dict):
    """Per-tier ``mem`` sub-fields (``mem.tiers.device`` ...): present in
    a row whose scope held a live TieredStore. Gated like recall fields —
    PRESENCE only (byte levels shift legitimately run to run, but a lost
    tier measurement must fail, not pass silently)."""
    tiers = row.get("mem", {}).get("tiers", {}) if isinstance(
        row.get("mem"), dict) else {}
    return sorted(k for k, v in tiers.items()
                  if isinstance(v, (int, float)))


# capacity fields of the quantization-funnel rows (quant_funnel_100k and
# friends): gated on PRESENCE, like the per-tier mem sub-fields — the
# measured bytes shift with codec parameters, but a run that LOSES the
# capacity measurement must fail the gate, not pass as "ok"
_CAPACITY_FIELDS = ("bytes_per_row", "rows_per_hbm_byte")


def _capacity_keys(row: dict):
    return [k for k in _CAPACITY_FIELDS
            if isinstance(row.get(k), (int, float))]


def _event_keys(row: dict):
    """Per-kind ``events`` sub-fields (``events.replica_fenced`` ...):
    present in rows whose scope rode the event journal (ISSUE 17). Gated
    like the per-tier mem sub-fields — PRESENCE only: counts shift
    legitimately run to run, but an event kind the old artifact observed
    and the new lost must fail the gate, not pass silently."""
    events = row.get("events")
    if not isinstance(events, dict):
        return []
    return sorted(k for k, v in events.items() if isinstance(v, int))


def _tier_get(row: dict, key: str):
    mem = row.get("mem")
    if not isinstance(mem, dict) or not isinstance(mem.get("tiers"), dict):
        return None
    return mem["tiers"].get(key)


def compare(old: dict, new: dict, *, qps_tol: float = 0.15,
            recall_tol: float = 0.01) -> dict:
    """Diff two artifacts (see module doc). Returns ``{"rows": [per-row
    dicts], "regressions": [names], "only_old": [...], "only_new":
    [...]}`` — ``regressions`` non-empty means the gate fails."""
    o_rows, n_rows = load_rows(old), load_rows(new)
    out: dict = {"rows": [], "regressions": [],
                 "only_old": sorted(set(o_rows) - set(n_rows)),
                 "only_new": sorted(set(n_rows) - set(o_rows))}
    for name in sorted(set(o_rows) & set(n_rows)):
        o, n = o_rows[name], n_rows[name]
        row = {"name": name, "status": "ok", "checks": []}
        if "error" in o:
            # an old error row gates nothing — it carried no numbers
            row["status"] = "skipped" if "error" in n else "fixed"
            out["rows"].append(row)
            continue
        if "error" in n:
            row["status"] = "regression"
            row["checks"].append(
                {"field": "error", "old": None, "new": n["error"][:120]})
            out["rows"].append(row)
            out["regressions"].append(name)
            continue
        if isinstance(o.get("qps"), (int, float)) and o["qps"] > 0:
            if not isinstance(n.get("qps"), (int, float)):
                # a measurement the old artifact had and the new lost is a
                # gate failure, not a skip — a harness bug that drops the
                # field must not sail through as "ok"
                row["status"] = "regression"
                row["checks"].append({"field": "qps", "old": o["qps"],
                                      "new": None, "missing": True,
                                      "regression": True})
            else:
                ratio = n["qps"] / o["qps"]
                check = {"field": "qps", "old": o["qps"], "new": n["qps"],
                         "ratio": round(ratio, 4)}
                if ratio < 1.0 - qps_tol:
                    check["regression"] = True
                    row["status"] = "regression"
                row["checks"].append(check)
        for key in _recall_keys(o):
            if not isinstance(n.get(key), (int, float)):
                row["status"] = "regression"
                row["checks"].append({"field": key, "old": o[key],
                                      "new": None, "missing": True,
                                      "regression": True})
                continue
            delta = n[key] - o[key]
            check = {"field": key, "old": o[key], "new": n[key],
                     "delta": round(delta, 6)}
            if delta < -recall_tol:
                check["regression"] = True
                row["status"] = "regression"
            row["checks"].append(check)
        for key in _capacity_keys(o):
            if not isinstance(n.get(key), (int, float)):
                row["status"] = "regression"
                row["checks"].append({"field": key, "old": o[key],
                                      "new": None, "missing": True,
                                      "regression": True})
            else:
                row["checks"].append({"field": key, "old": o[key],
                                      "new": n[key]})
        for key in _tier_keys(o):
            got = _tier_get(n, key)
            if not isinstance(got, (int, float)):
                row["status"] = "regression"
                row["checks"].append({"field": f"mem.tiers.{key}",
                                      "old": o["mem"]["tiers"][key],
                                      "new": None, "missing": True,
                                      "regression": True})
            else:
                row["checks"].append({"field": f"mem.tiers.{key}",
                                      "old": o["mem"]["tiers"][key],
                                      "new": got})
        for key in _event_keys(o):
            got = n.get("events", {}).get(key) if isinstance(
                n.get("events"), dict) else None
            if not isinstance(got, int):
                row["status"] = "regression"
                row["checks"].append({"field": f"events.{key}",
                                      "old": o["events"][key],
                                      "new": None, "missing": True,
                                      "regression": True})
            else:
                row["checks"].append({"field": f"events.{key}",
                                      "old": o["events"][key],
                                      "new": got})
        out["rows"].append(row)
        if row["status"] == "regression":
            out["regressions"].append(name)
    return out


def render_table(result: dict) -> str:
    """Markdown comparison table from a :func:`compare` result (stdlib —
    the same renderer discipline as ``bench.py --note``: the table IS the
    diff, nothing recomputed elsewhere)."""
    lines = ["| row | field | old | new | change | verdict |",
             "|---|---|---|---|---|---|"]

    def fmt(v):
        if isinstance(v, float):
            return f"{v:,.4f}" if abs(v) < 100 else f"{v:,.1f}"
        return "" if v is None else str(v)

    for row in result["rows"]:
        if not row["checks"]:
            lines.append(f"| {row['name']} | — | | | | {row['status']} |")
            continue
        for c in row["checks"]:
            change = (f"x{c['ratio']}" if "ratio" in c
                      else (f"{c['delta']:+.4f}" if "delta" in c else ""))
            verdict = "**REGRESSION**" if c.get("regression") else "ok"
            lines.append(f"| {row['name']} | {c['field']} | {fmt(c['old'])} "
                         f"| {fmt(c['new'])} | {change} | {verdict} |")
    for name in result["only_old"]:
        lines.append(f"| {name} | — | present | absent | | dropped (no gate) |")
    for name in result["only_new"]:
        lines.append(f"| {name} | — | absent | present | | new (no gate) |")
    verdict = ("FAIL: " + ", ".join(result["regressions"])
               if result["regressions"] else "PASS")
    return "\n".join(lines) + f"\n\n{verdict}\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline BENCH_rXX.json")
    ap.add_argument("new", help="candidate BENCH_rXX.json")
    ap.add_argument("--qps-tol", type=float, default=0.15,
                    help="fractional QPS drop tolerance (default 0.15)")
    ap.add_argument("--recall-tol", type=float, default=0.01,
                    help="absolute recall drop tolerance (default 0.01)")
    ap.add_argument("--table", action="store_true",
                    help="render the markdown diff table")
    args = ap.parse_args(argv)
    with open(args.old) as f:
        old = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    result = compare(old, new, qps_tol=args.qps_tol,
                     recall_tol=args.recall_tol)
    if args.table:
        print(render_table(result))
    else:
        print(json.dumps(result, indent=2))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
