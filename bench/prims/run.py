#!/usr/bin/env python
"""raft_tpu primitive micro-benchmarks.

Counterpart of the reference's google-benchmark prim suite
(cpp/bench/prims/{distance,matrix,cluster,neighbors}/ — e.g.
distance/distance_exp_l2.cu, matrix/select_k.cu, cluster/kmeans.cu). Each
case reports wall ms and achieved GB/s or GFLOP/s.

Timing protocol (see docs/ann_benchmarks.md "Measurement honesty"): every
iteration gets distinct input slices, iterations are chained inside one XLA
program via lax.map, and the output is materialized to host — immune to
device tunnels that no-op block_until_ready.

Usage: python bench/prims/run.py [--filter substr] [--iters N]
"""

from __future__ import annotations

import argparse
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def measure(make_fn, batches, iters: int):
    """make_fn() -> jitted fn over stacked batches; returns s/iter."""
    import jax
    import numpy as np

    f = make_fn()
    np.asarray(jax.tree_util.tree_leaves(f(batches[0]))[0])  # compile+warm
    best = float("inf")
    for b in batches[1:]:
        t0 = time.perf_counter()
        np.asarray(jax.tree_util.tree_leaves(f(b))[0])
        best = min(best, time.perf_counter() - t0)
    return best / iters


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--filter", default="")
    ap.add_argument("--iters", type=int, default=4, help="chained iterations per timing call")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    rng = np.random.default_rng(0)
    iters = args.iters
    rows = []

    def bench(name, make_fn, batches, work, unit, n_iters=None):
        if args.filter and args.filter not in name:
            return
        sec = measure(make_fn, batches, n_iters or iters)
        rate = work / sec / 1e9
        rows.append((name, sec * 1e3, rate, unit))
        print(f"{name:42s} {sec*1e3:9.2f} ms   {rate:9.1f} {unit}")

    # ---- pairwise distance (ref: distance_exp_l2.cu) ----
    m, n, d = 4096, 4096, 128
    for metric in ("sqeuclidean", "cosine", "l1"):
        from raft_tpu.distance.pairwise import _pairwise
        from raft_tpu.distance.types import resolve_metric

        mt = resolve_metric(metric)
        xs = [jnp.asarray(rng.random((iters, m, d), np.float32)) for _ in range(3)]
        y = jnp.asarray(rng.random((n, d), np.float32))

        def mk(mt=mt):
            def one(x):
                return jnp.sum(_pairwise(x, y, mt, 2.0, 1024))
            return jax.jit(lambda xb: lax.map(one, xb))

        bench(f"pairwise_distance/{metric} {m}x{n}x{d}", mk, xs,
              iters * 2.0 * m * n * d, "GFLOP/s")

    # ---- fused L2 1-NN (ref: distance/fused_l2_nn.cu) ----
    from raft_tpu.distance.fused_nn import _fused_l2_nn

    k_centers = 1024
    c = jnp.asarray(rng.random((k_centers, d), np.float32))
    xs = [jnp.asarray(rng.random((iters, m, d), np.float32)) for _ in range(3)]

    def mk_fnn():
        def one(x):
            return _fused_l2_nn(x, c, False, 2048)[1]
        return jax.jit(lambda xb: lax.map(one, xb))

    bench(f"fused_l2_nn {m}x{k_centers}x{d}", mk_fnn, xs,
          iters * 2.0 * m * k_centers * d, "GFLOP/s")

    # ---- select_k (ref: matrix/select_k.cu) ----
    from raft_tpu.matrix.select_k import _select_k

    for nn_cols, kk in ((16384, 64), (65536, 10)):
        xs = [jnp.asarray(rng.random((iters, 512, nn_cols), np.float32)) for _ in range(3)]

        def mk_sel(kk=kk):
            def one(x):
                return _select_k(x, None, kk, True)
            return jax.jit(lambda xb: lax.map(one, xb))

        bench(f"select_k n={nn_cols} k={kk} rows=512", mk_sel, xs,
              iters * 512 * nn_cols * 4, "GB/s")

    # ---- kmeans one Lloyd step (ref: cluster/kmeans.cu) ----
    from raft_tpu.cluster.kmeans import _assign, _update

    kc = 256
    xs = [jnp.asarray(rng.random((iters, 65536, 64), np.float32)) for _ in range(3)]
    c0 = jnp.asarray(rng.random((kc, 64), np.float32))

    def mk_km():
        def one(x):
            _, labels = _assign(x, c0, 8192)
            sums, counts = _update(x, labels, None, kc)
            return sums
        return jax.jit(lambda xb: lax.map(one, xb))

    bench(f"kmeans_lloyd_step 65536x64 k={kc}", mk_km, xs,
          iters * 2.0 * 65536 * kc * 64 * 2, "GFLOP/s")

    # ---- brute-force knn (ref: neighbors/knn.cuh) ----
    from raft_tpu.neighbors.brute_force import _bf_knn
    from raft_tpu.distance.types import DistanceType

    ds = jnp.asarray(rng.random((100_000, 128), np.float32))
    xs = [jnp.asarray(rng.random((iters, 2000, 128), np.float32)) for _ in range(3)]

    def mk_knn():
        def one(q):
            return _bf_knn(ds, q, 10, DistanceType.L2Expanded, 2.0, 1000, 1000)[1]
        return jax.jit(lambda xb: lax.map(one, xb))

    bench("bf_knn 100k x 128, q=2000, k=10", mk_knn, xs,
          iters * 2.0 * 2000 * 100_000 * 128, "GFLOP/s")

    # ---- sparse prims at scale (VERDICT r4 #9; ref: bench/prims/sparse/) --
    # sparse pairwise L2: 4096-query tiles vs a 100k x 10k, ~1% density CSR
    # dataset — exercises the ELL-densify-per-tile path at real width
    sp_name = "sparse_l2 4096x100000 d=10000 nnz/row=100"
    if not args.filter or args.filter in sp_name:
        from raft_tpu.sparse.types import make_csr
        from raft_tpu.sparse import distance as spdist

        n_rows, n_cols, nnz_row = 100_000, 10_000, 100
        qrows = 4096
        # ~1% density: exactly nnz_row nonzeros per row (ELL-friendly,
        # matches the reference's uniform-density sparse bench inputs)
        idxs = rng.integers(0, n_cols, (n_rows, nnz_row)).astype(np.int32)
        vals = rng.random((n_rows, nnz_row)).astype(np.float32)
        indptr = np.arange(n_rows + 1, dtype=np.int32) * nnz_row
        y_csr = make_csr(jnp.asarray(indptr), jnp.asarray(idxs.reshape(-1)),
                         jnp.asarray(vals.reshape(-1)),
                         (n_rows, n_cols))
        qi = rng.integers(0, n_cols, (qrows, nnz_row)).astype(np.int32)
        qv = [jnp.asarray(rng.random((qrows, nnz_row), np.float32))
              for _ in range(3)]
        q_indptr = jnp.asarray(
            np.arange(qrows + 1, dtype=np.int32) * nnz_row)
        qi_flat = jnp.asarray(qi.reshape(-1))

        def mk_sp():
            # NOT jitted: pairwise_distance is host-orchestrated (it sizes
            # the ELL width from data-dependent degrees) and jits its tiles
            # internally — wrapping it would trip a ConcretizationTypeError
            def one(qvals):
                x_csr = make_csr(q_indptr, qi_flat, qvals.reshape(-1),
                                 (qrows, n_cols))
                return spdist.pairwise_distance(x_csr, y_csr,
                                                metric="sqeuclidean")
            return one

        # one (qrows, n_rows) distance block per call (no iters chaining);
        # work ~ dense-equivalent GEMM
        bench(sp_name, mk_sp, qv, 2.0 * qrows * n_rows * n_cols,
              "GFLOP/s(dense-eq)", n_iters=1)

    # Boruvka MST on a 1M-edge random graph (ref: sparse/mst.cu)
    mst_name = "mst 200000v 1000000e"
    if not args.filter or args.filter in mst_name:
        from raft_tpu.solver.mst import mst
        from raft_tpu.sparse.types import make_coo

        n_v, n_e = 200_000, 1_000_000
        mst_batches = []
        for s in range(3):
            r2 = np.random.default_rng(s)
            # connected-ish: a random spanning chain + random extra edges
            chain_r = np.arange(n_v - 1, dtype=np.int32)
            chain_c = chain_r + 1
            er = r2.integers(0, n_v, n_e - (n_v - 1)).astype(np.int32)
            ec = r2.integers(0, n_v, n_e - (n_v - 1)).astype(np.int32)
            rr = np.concatenate([chain_r, er])
            cc = np.concatenate([chain_c, ec])
            ww = r2.random(n_e).astype(np.float32)
            mst_batches.append(make_coo(jnp.asarray(rr), jnp.asarray(cc),
                                        jnp.asarray(ww), (n_v, n_v)))

        def mk_mst():
            return jax.jit(lambda g: mst(g).weights)

        # rate unit is Medges/s: pass work = edges * 1e3 so bench()'s /1e9
        # yields Medges/s in-place
        bench(mst_name, mk_mst, mst_batches, n_e * 1e3, "Medges/s",
              n_iters=1)

    # Lanczos k=8 on a 100k-node graph Laplacian (ref: sparse/lanczos.cu)
    lz_name = "lanczos k=8 laplacian 100000v"
    if not args.filter or args.filter in lz_name:
        from raft_tpu.solver.lanczos import eigsh
        from raft_tpu.sparse.linalg import laplacian
        from raft_tpu.sparse.types import make_coo
        from raft_tpu.sparse.convert import coo_to_csr

        n_v, n_e = 100_000, 1_000_000
        lz_batches = []
        for s in range(3):
            r2 = np.random.default_rng(10 + s)
            rr = r2.integers(0, n_v, n_e).astype(np.int32)
            cc = r2.integers(0, n_v, n_e).astype(np.int32)
            ww = np.abs(r2.random(n_e)).astype(np.float32)
            # symmetrize by doubling (rows+cols swapped)
            coo = make_coo(jnp.asarray(np.concatenate([rr, cc])),
                           jnp.asarray(np.concatenate([cc, rr])),
                           jnp.asarray(np.concatenate([ww, ww])),
                           (n_v, n_v))
            lz_batches.append(coo_to_csr(coo))

        def mk_lz():
            def one(csr):
                lap = laplacian(csr)
                vals, _, _ = eigsh(lap, k=8, max_iter=200, seed=0)
                return vals
            return jax.jit(one)

        bench(lz_name, mk_lz, lz_batches, 2 * n_e * 200, "Gnnz-mv/s",
              n_iters=1)

    return 0


if __name__ == "__main__":
    sys.exit(main())
