#!/usr/bin/env python
"""raft_tpu primitive micro-benchmarks.

Counterpart of the reference's google-benchmark prim suite
(cpp/bench/prims/{distance,matrix,cluster,neighbors}/ — e.g.
distance/distance_exp_l2.cu, matrix/select_k.cu, cluster/kmeans.cu). Each
case reports wall ms and achieved GB/s or GFLOP/s.

Timing protocol (see docs/ann_benchmarks.md "Measurement honesty"): every
iteration gets distinct input slices, iterations are chained inside one XLA
program via lax.map, and the output is materialized to host — immune to
device tunnels that no-op block_until_ready.

Usage: python bench/prims/run.py [--filter substr] [--iters N]
"""

from __future__ import annotations

import argparse
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def measure(make_fn, batches, iters: int):
    """make_fn() -> jitted fn over stacked batches; returns s/iter."""
    import jax
    import numpy as np

    f = make_fn()
    np.asarray(jax.tree_util.tree_leaves(f(batches[0]))[0])  # compile+warm
    best = float("inf")
    for b in batches[1:]:
        t0 = time.perf_counter()
        np.asarray(jax.tree_util.tree_leaves(f(b))[0])
        best = min(best, time.perf_counter() - t0)
    return best / iters


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--filter", default="")
    ap.add_argument("--iters", type=int, default=4, help="chained iterations per timing call")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    rng = np.random.default_rng(0)
    iters = args.iters
    rows = []

    def bench(name, make_fn, batches, work, unit):
        if args.filter and args.filter not in name:
            return
        sec = measure(make_fn, batches, iters)
        rate = work / sec / 1e9
        rows.append((name, sec * 1e3, rate, unit))
        print(f"{name:42s} {sec*1e3:9.2f} ms   {rate:9.1f} {unit}")

    # ---- pairwise distance (ref: distance_exp_l2.cu) ----
    m, n, d = 4096, 4096, 128
    for metric in ("sqeuclidean", "cosine", "l1"):
        from raft_tpu.distance.pairwise import _pairwise
        from raft_tpu.distance.types import resolve_metric

        mt = resolve_metric(metric)
        xs = [jnp.asarray(rng.random((iters, m, d), np.float32)) for _ in range(3)]
        y = jnp.asarray(rng.random((n, d), np.float32))

        def mk(mt=mt):
            def one(x):
                return jnp.sum(_pairwise(x, y, mt, 2.0, 1024))
            return jax.jit(lambda xb: lax.map(one, xb))

        bench(f"pairwise_distance/{metric} {m}x{n}x{d}", mk, xs,
              iters * 2.0 * m * n * d, "GFLOP/s")

    # ---- fused L2 1-NN (ref: distance/fused_l2_nn.cu) ----
    from raft_tpu.distance.fused_nn import _fused_l2_nn

    k_centers = 1024
    c = jnp.asarray(rng.random((k_centers, d), np.float32))
    xs = [jnp.asarray(rng.random((iters, m, d), np.float32)) for _ in range(3)]

    def mk_fnn():
        def one(x):
            return _fused_l2_nn(x, c, False, 2048)[1]
        return jax.jit(lambda xb: lax.map(one, xb))

    bench(f"fused_l2_nn {m}x{k_centers}x{d}", mk_fnn, xs,
          iters * 2.0 * m * k_centers * d, "GFLOP/s")

    # ---- select_k (ref: matrix/select_k.cu) ----
    from raft_tpu.matrix.select_k import _select_k

    for nn_cols, kk in ((16384, 64), (65536, 10)):
        xs = [jnp.asarray(rng.random((iters, 512, nn_cols), np.float32)) for _ in range(3)]

        def mk_sel(kk=kk):
            def one(x):
                return _select_k(x, None, kk, True)
            return jax.jit(lambda xb: lax.map(one, xb))

        bench(f"select_k n={nn_cols} k={kk} rows=512", mk_sel, xs,
              iters * 512 * nn_cols * 4, "GB/s")

    # ---- kmeans one Lloyd step (ref: cluster/kmeans.cu) ----
    from raft_tpu.cluster.kmeans import _assign, _update

    kc = 256
    xs = [jnp.asarray(rng.random((iters, 65536, 64), np.float32)) for _ in range(3)]
    c0 = jnp.asarray(rng.random((kc, 64), np.float32))

    def mk_km():
        def one(x):
            _, labels = _assign(x, c0, 8192)
            sums, counts = _update(x, labels, None, kc)
            return sums
        return jax.jit(lambda xb: lax.map(one, xb))

    bench(f"kmeans_lloyd_step 65536x64 k={kc}", mk_km, xs,
          iters * 2.0 * 65536 * kc * 64 * 2, "GFLOP/s")

    # ---- brute-force knn (ref: neighbors/knn.cuh) ----
    from raft_tpu.neighbors.brute_force import _bf_knn
    from raft_tpu.distance.types import DistanceType

    ds = jnp.asarray(rng.random((100_000, 128), np.float32))
    xs = [jnp.asarray(rng.random((iters, 2000, 128), np.float32)) for _ in range(3)]

    def mk_knn():
        def one(q):
            return _bf_knn(ds, q, 10, DistanceType.L2Expanded, 2.0, 1000, 1000)[1]
        return jax.jit(lambda xb: lax.map(one, xb))

    bench("bf_knn 100k x 128, q=2000, k=10", mk_knn, xs,
          iters * 2.0 * 2000 * 100_000 * 128, "GFLOP/s")

    return 0


if __name__ == "__main__":
    sys.exit(main())
