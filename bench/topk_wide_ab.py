"""Interleaved A/B of the wide-k streaming selector (64 < k <= 256) vs
lax.top_k (VERDICT r4 #5 done-bar shapes: 10k rows, >= 65k cols,
k in {128, 256}; plus the CAGRA-build-relevant k=193).

Protocol (BASELINE.md measurement rules): one process, round-robin variants,
distinct inputs chained inside one jitted program per timing call, only a
checksum materialized to host. Run on the TPU host:

    python bench/topk_wide_ab.py [--rows 10000] [--cols 65536] [--rounds 4]
"""

from __future__ import annotations

import argparse
import sys
import time
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_000)
    ap.add_argument("--cols", type=int, default=65_536)
    ap.add_argument("--ks", default="128,193,256")
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--chain", type=int, default=4,
                    help="distinct matrices chained per timing call")
    args = ap.parse_args()

    from raft_tpu.config import enable_compilation_cache

    enable_compilation_cache()
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from raft_tpu.ops.topk import topk_pallas

    print(f"backend: {jax.default_backend()}", file=sys.stderr)
    m, n, chain = args.rows, args.cols, args.chain
    nmats = max(chain, 4)
    keys = jax.random.split(jax.random.key(0), nmats)
    mats = [jax.random.uniform(k, (m, n), jnp.float32) for k in keys]
    jax.block_until_ready(mats)
    bytes_gb = m * n * 4 * max(chain, 1) / 1e9

    for k in (int(s) for s in args.ks.split(",")):

        @functools.partial(jax.jit, static_argnames=())
        def chain_pallas(ms, k=k):
            ms = ms[:chain]
            acc = jnp.zeros((), jnp.float32)
            for x in ms:
                v, i = topk_pallas(x, k, select_min=True)
                acc = acc + v[:, k - 1].sum() + (i[:, 0] % 7).sum()
            return acc

        @functools.partial(jax.jit, static_argnames=())
        def chain_lax(ms, k=k):
            ms = ms[:chain]
            acc = jnp.zeros((), jnp.float32)
            for x in ms:
                nv, ni = lax.top_k(-x, k)
                acc = acc + (-nv)[:, k - 1].sum() + (ni[:, 0] % 7).sum()
            return acc

        if chain == 1:
            # unchained mode: one kernel per call on ROTATING distinct
            # matrices (two kh=256 pallas_calls chained in one XLA program
            # hit a TPU-internal error; standalone calls are fine — see
            # BASELINE.md wide-k study). Distinct inputs per call keep the
            # tunnel's dispatch cache honest.
            def make_unchained(op, k=k):
                cnt = {"i": 0}

                def f(ms):
                    x = ms[cnt["i"] % len(ms)]
                    cnt["i"] += 1
                    if op == "pallas":
                        v, i = topk_pallas(x, k, select_min=True)
                        return v[:, k - 1].sum() + (i[:, 0] % 7).sum()
                    nv, ni = lax.top_k(-x, k)
                    return (-nv)[:, k - 1].sum() + (ni[:, 0] % 7).sum()
                return f

            chain_pallas = make_unchained("pallas")
            chain_lax = make_unchained("lax")

        variants = {"pallas": chain_pallas, "lax": chain_lax}
        # correctness spot-check before timing
        v, i = topk_pallas(mats[0][:64], k, select_min=True)
        v0, i0 = lax.top_k(-mats[0][:64], k)
        np.testing.assert_allclose(np.asarray(v), -np.asarray(v0), atol=0)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(i0))

        for name, fn in variants.items():
            float(fn(mats))  # compile + warm
        times = {name: [] for name in variants}
        for r in range(args.rounds):
            for name, fn in variants.items():
                t0 = time.perf_counter()
                float(fn(mats))
                times[name].append(time.perf_counter() - t0)
        best = {name: min(ts) for name, ts in times.items()}
        for name, ts in times.items():
            print(f"k={k:4d} {name:7s} best {best[name]*1e3:8.2f} ms "
                  f"({bytes_gb/best[name]:6.1f} GB/s)  all "
                  f"{[f'{t*1e3:.1f}' for t in ts]}")
        print(f"k={k:4d} pallas/lax speedup: {best['lax']/best['pallas']:.3f}x")


if __name__ == "__main__":
    main()
