"""Minimal repro + parameter bisect for the kh=256 two-instance chaining
failure (VERDICT r5 weak #1 / next #3).

History: chaining TWO wide-k (k > 128 → kh=256) topk_pallas instances inside
ONE XLA program hit "TPU backend error (Internal)" on the r05 toolchain,
while every standalone call — and kh=128 chains 4-deep — compiled fine
(BASELINE.md "Round-5 wide-k selector study"). The r05 kernel's one
structural feature unique to kh=256 was its 2*kh = 512-lane merge
intermediates; r06 reformulated the merge to cap every intermediate at kh
lanes (ops/topk.py wide_merge="half") and lifted the select_k dispatch to
k <= 256 on that basis. This harness is the evidence machine:

  * ``--mode repro``  — ONE jit program with two chained wide-k instances at
    the CAGRA build-chunk shapes (the commissioned call site: per-chunk
    select over probe_chunk*capacity cols, then the final merge over
    n_chunks*k cols, k = gpu_top_k+1 = 193). Runs each wide_merge form and
    prints PASS/FAIL — "concat" reproduces the r05 failure if the toolchain
    still has it; "half" must PASS or the r06 dispatch lift is wrong and
    RAFT_TPU_WIDE_SELECT_CAP=128 should be set while bisecting.
  * ``--mode bisect`` — sweeps the kernel parameters the failure could key
    on (kh via k, qt, blk, vmem_limit, one-vs-two instances, same-vs-
    different shapes) and prints a PASS/FAIL grid that localizes the
    trigger: if ONLY (concat, two-instance, kh=256) rows fail, the 512-lane
    width is root-caused as the distinguishing feature and the failure is a
    Mosaic limit worth reporting upstream (reference bar: one-kernel k<=1024,
    matrix/detail/select_radix.cuh).

CPU (interpret) runs validate numerics only; the failure is TPU-compile-time,
so run on the TPU host:

    python bench/topk_chain_repro.py --mode repro
    python bench/topk_chain_repro.py --mode bisect
"""

from __future__ import annotations

import argparse
import functools
import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def _try(label, fn):
    import numpy as np

    try:
        out = fn()
        np.asarray(out)
        print(f"PASS  {label}")
        return True
    except Exception as e:
        msg = str(e).replace("\n", " ")[:140]
        print(f"FAIL  {label}: {type(e).__name__}: {msg}")
        return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["repro", "bisect"], default="repro")
    ap.add_argument("--rows", type=int, default=2048,
                    help="query rows (the build chunk runs 16384; 2048 "
                    "keeps the bisect grid fast — the failure keyed on "
                    "kernel config, not m)")
    ap.add_argument("--cols", type=int, default=10432,
                    help="first-instance cols (build chunk: probe_chunk * "
                    "capacity; 8 * 1304 at the 1M defaults)")
    ap.add_argument("--k", type=int, default=193,
                    help="gpu_top_k + 1 at the CAGRA build defaults")
    args = ap.parse_args()

    from raft_tpu.config import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import jax.numpy as jnp

    from raft_tpu.ops.topk import topk_pallas

    print(f"backend: {jax.default_backend()}", file=sys.stderr)
    if jax.default_backend() != "tpu":
        print("note: not a TPU backend — interpret-mode numerics only; the "
              "chaining failure is TPU-compile-time", file=sys.stderr)
    m, n, k = args.rows, args.cols, args.k
    x = jax.random.uniform(jax.random.key(0), (m, n), jnp.float32)
    n2 = 4 * k  # final-merge width (n_chunks * k at 4 probe chunks)

    def chained(wm, k1, k2, qt=256, blk=4096):
        """Two wide instances in ONE program: select k1 over (m, n), then
        re-select k2 over the (m, 4*k1) concatenation of the results —
        exactly the per-chunk + final-merge composition of _pq_search."""

        @jax.jit
        def f(x):
            v1, i1 = topk_pallas(x, k1, blk=blk, qt=qt, wide_merge=wm)
            pool = jnp.tile(v1, (1, 4))
            v2, i2 = topk_pallas(pool, k2, blk=blk, qt=qt, wide_merge=wm)
            return v2.sum() + (i2 % 7).sum() + (i1 % 5).sum()

        return f(x)

    if args.mode == "repro":
        ok = {}
        for wm in ("half", "concat"):
            ok[wm] = _try(f"two kh=256 instances, wide_merge={wm} "
                          f"(m={m}, n={n}->{n2}, k={k})",
                          functools.partial(chained, wm, k, k))
        if ok.get("half") and not ok.get("concat"):
            print("=> r05 failure reproduced on 'concat'; 'half' fixed it "
                  "(the 512-lane intermediates were the trigger)")
        elif all(ok.values()):
            print("=> both forms pass on this toolchain (failure gone or "
                  "environment-specific); the dispatch lift stands")
        elif not ok.get("half"):
            print("=> 'half' STILL FAILS: set RAFT_TPU_WIDE_SELECT_CAP=128 "
                  "and run --mode bisect")
        return

    # bisect grid: localize what the failure keys on
    cases = []
    for wm in ("half", "concat"):
        cases += [
            (f"{wm} one-instance k=193", lambda wm=wm: jax.jit(
                lambda x: topk_pallas(x, 193, wide_merge=wm)[0].sum())(x)),
            (f"{wm} two-instance k=193/193", functools.partial(
                chained, wm, 193, 193)),
            (f"{wm} two-instance k=193/129", functools.partial(
                chained, wm, 193, 129)),
            (f"{wm} two-instance mixed k=193/96 (kh 256+128)",
             functools.partial(chained, wm, 193, 96)),
            (f"{wm} two-instance k=128/128 (kh=128 control)",
             functools.partial(chained, wm, 128, 128)),
            (f"{wm} two-instance k=193/193 qt=128", functools.partial(
                chained, wm, 193, 193, qt=128)),
            (f"{wm} two-instance k=193/193 blk=2048", functools.partial(
                chained, wm, 193, 193, blk=2048)),
        ]
    results = {label: _try(label, fn) for label, fn in cases}
    fails = [l for l, ok in results.items() if not ok]
    print(f"\n{len(fails)}/{len(results)} failing: {fails or 'none'}")


if __name__ == "__main__":
    main()
