"""A/B the IVF-PQ scan formulations at 1M (one-hot MXU contraction vs
compare+select gather vs the Pallas fused kernel when present).

Protocol matches bench.py's driver rows: LID 1M x 128 dataset, pq4x64 (and
optionally pq8x32-split), n_probes=8, k=10, 10k-query sets, best-of-2 wall
time with host materialization. Run on the TPU host:

    python bench/pq_scan_ab.py [--pq8] [--lut bfloat16]
"""

from __future__ import annotations

import argparse
import sys
import time
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pq8", action="store_true", help="also run pq8x32-split")
    ap.add_argument("--pq8-only", action="store_true")
    ap.add_argument("--lut", default="bfloat16",
                    help="comma list of lut dtypes (each crossed with impls)")
    ap.add_argument("--impls", default="onehot,select")
    ap.add_argument("--probes", type=int, default=8)
    args = ap.parse_args()

    from raft_tpu.config import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import numpy as np

    import bench as drv
    from raft_tpu.neighbors import ivf_pq

    print(f"backend: {jax.default_backend()}", file=sys.stderr)
    dataset, qsets = drv._make_lid_1m()
    jax.block_until_ready([dataset] + qsets)
    gt = drv._ground_truth(dataset, qsets[-1][:1000])

    configs = [("pq4x64", dict(n_lists=1024, pq_bits=4, pq_dim=64, seed=0))]
    if args.pq8 or args.pq8_only:
        configs.append(("pq8x32s", dict(n_lists=1024, pq_bits=8, pq_dim=32, seed=0)))
    if args.pq8_only:
        configs = configs[1:]

    for cname, cfg in configs:
        t0 = time.perf_counter()
        idx = ivf_pq.build(ivf_pq.IndexParams(**cfg), dataset)
        jax.block_until_ready(idx.list_codes)
        print(f"{cname} build {time.perf_counter() - t0:.1f}s", file=sys.stderr)

        impls = [(i, lt) for i in args.impls.split(",")
                 for lt in args.lut.split(",")]
        searchers = {}
        m = qsets[0].shape[0]
        for impl, lt in impls:
            sp = ivf_pq.SearchParams(n_probes=args.probes, lut_dtype=lt,
                                     scan_impl=impl)
            fn = (lambda q, sp=sp: ivf_pq.search(sp, idx, q, 10))
            np.asarray(fn(qsets[0])[1])  # compile + warm
            searchers[(impl, lt)] = fn

        # tunnel throughput drifts tens of percent between minutes, so the
        # impls are timed INTERLEAVED round-robin and every round is printed;
        # compare within rounds, not across runs
        times = {i: [] for i in impls}
        for rnd in range(4):
            for key in impls:
                q = qsets[1 + rnd % 2]
                t0 = time.perf_counter()
                out = searchers[key](q)
                np.asarray(out[1])
                times[key].append(time.perf_counter() - t0)
        for impl, lt in impls:
            out = searchers[(impl, lt)](qsets[-1])
            rec = drv._recall(np.asarray(out[1])[:1000], gt)
            qps = [m / t for t in times[(impl, lt)]]
            print(f"{cname} impl={impl} lut={lt} p={args.probes} "
                  f"QPS rounds={[f'{x:.0f}' for x in qps]} best={max(qps):.0f} "
                  f"recall={rec:.4f}", flush=True)


if __name__ == "__main__":
    main()
