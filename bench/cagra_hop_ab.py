"""Interleaved A/B of the fused CAGRA hop kernel vs the XLA hop loop at 1M
(VERDICT r4 #1 done-bar: driver-protocol 1M itopk=32, >= 1.5x in the same
process at recall parity).

Protocol matches bench.py's cagra_1m_itopk32 row: isotropic clustered 1M x
128, 10k-query sets, best-of wall time with host materialization, variants
round-robin in one process. Run on the TPU host:

    python bench/cagra_hop_ab.py [--rounds 4] [--itopk 32]
"""

from __future__ import annotations

import argparse
import sys
import time
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--itopk", type=int, default=32)
    ap.add_argument("--lid", action="store_true",
                    help="use the SIFT-class LID dataset instead of isotropic")
    args = ap.parse_args()

    from raft_tpu.config import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import numpy as np

    import bench as drv
    from raft_tpu.neighbors import cagra

    print(f"backend: {jax.default_backend()}", file=sys.stderr)
    dataset, qsets = (drv._make_lid_1m() if args.lid else drv._make_1m())
    jax.block_until_ready([dataset] + qsets)
    gt = drv._ground_truth(dataset, qsets[-1][:1000])

    t0 = time.perf_counter()
    idx = cagra.build(cagra.IndexParams(), dataset)
    jax.block_until_ready(idx.graph)
    print(f"build {time.perf_counter() - t0:.1f}s "
          f"(seed_pool_hint={idx.seed_pool_hint})", file=sys.stderr)

    m = qsets[0].shape[0]
    variants = {
        "xla": cagra.SearchParams(itopk_size=args.itopk, hop_impl="xla"),
        "fused": cagra.SearchParams(itopk_size=args.itopk, hop_impl="fused"),
        # r06 arena (register-carried gate, value-carried candidate pool)
        # vs the r05 arena (SMEM handshake + scratch pool) — the A/B that
        # prices the named ~5 us/query residual (VERDICT r5 #4)
        "arena": cagra.SearchParams(itopk_size=args.itopk,
                                    hop_impl="fused_arena"),
        "arena_smem": cagra.SearchParams(itopk_size=args.itopk,
                                         hop_impl="fused_arena_smem"),
    }
    outs = {}
    for name, sp in variants.items():
        out = cagra.search(sp, idx, qsets[0], 10)  # compile + warm
        np.asarray(out[0])
        outs[name] = out

    times = {name: [] for name in variants}
    for r in range(args.rounds):
        for name, sp in variants.items():
            best = float("inf")
            for qs in qsets[1:]:
                t0 = time.perf_counter()
                out = cagra.search(sp, idx, qs, 10)
                np.asarray(out[0])
                best = min(best, time.perf_counter() - t0)
                outs[name] = out
            times[name].append(m / best)

    for name in variants:
        rec = drv._recall(np.asarray(outs[name][1])[:1000], gt)
        qps = times[name]
        print(f"{name:6s} recall {rec:.4f}  QPS "
              f"{[f'{v/1e3:.1f}k' for v in qps]}")
    for name in ("fused", "arena", "arena_smem"):
        sp_ratio = [f / x for f, x in zip(times[name], times["xla"])]
        print(f"{name}/xla per round: {[f'{r:.3f}' for r in sp_ratio]}  "
              f"best-ratio {max(times[name])/max(times['xla']):.3f}")


if __name__ == "__main__":
    main()
