"""Generate a disk-backed big-ANN dataset (.fbin) through the native runtime.

The reference's ANN harness is built around on-disk datasets
(cpp/bench/ann/conf/sift-128-euclidean.json; bigann .fbin/.u8bin formats,
docs/source/cuda_ann_benchmarks.md). This environment has no network, so the
equivalent end-to-end IO path is: generate the clustered-synthetic
distribution once, persist it as .fbin via the native writer
(cpp/runtime.cpp write_bin), and point a conf's ``base_file``/``query_file``
at it — the harness then reads it back through the pread-based chunked
loader like any downloaded bigann file.

  python bench/ann/make_fbin.py --out /tmp/ann-data --n 1000000 --dim 128
  python bench/ann/run.py --conf bench/ann/conf/fbin-1M-128.json --build --search
"""

from __future__ import annotations

import argparse
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--n-queries", type=int, default=10_000)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--clusters", type=int, default=2000)
    ap.add_argument("--cluster-std", type=float, default=0.5)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import numpy as np

    from raft_tpu.runtime import write_bin

    out = pathlib.Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(args.seed)
    centers = (rng.random((args.clusters, args.dim), np.float32) * 10).astype(np.float32)

    def draw(count):
        labels = rng.integers(0, args.clusters, count)
        return (centers[labels]
                + rng.normal(0, args.cluster_std, (count, args.dim))).astype(np.float32)

    base_path = out / f"base-{args.n}x{args.dim}.fbin"
    query_path = out / f"query-{args.n_queries}x{args.dim}.fbin"
    # write in chunks so peak host memory stays bounded at big-ANN scale
    chunk = 200_000
    first = draw(min(chunk, args.n))
    write_bin(str(base_path), first)
    written = first.shape[0]
    if written < args.n:
        with open(base_path, "r+b") as f:
            # fix the header once to the final row count, then stream chunks
            np.array([args.n, args.dim], np.uint32).tofile(f)
            f.seek(8 + written * args.dim * 4)
            while written < args.n:
                block = draw(min(chunk, args.n - written))
                block.tofile(f)
                written += block.shape[0]
    write_bin(str(query_path), draw(args.n_queries))
    print(f"wrote {base_path} ({args.n}x{args.dim}) and {query_path}")


if __name__ == "__main__":
    main()
