#!/usr/bin/env python
"""raft_tpu ANN benchmark harness.

Re-design of the reference's standalone ANN benchmark
(cpp/bench/ann/src/common/benchmark.hpp — build mode :111, search mode :168;
JSON configs cpp/bench/ann/conf/*.json; QPS-vs-recall workflow
docs/source/cuda_ann_benchmarks.md). Same JSON schema shape: a ``dataset``
section (big-ANN .fbin/.u8bin files via the native runtime loader, or a
``synthetic`` spec so the harness runs hermetically), ``search_basic_param``
(batch_size, k, run_count), and an ``index`` list with ``build_param`` +
``search_params`` sweeps.

Usage:
  python bench/ann/run.py --conf bench/ann/conf/synthetic-64.json --build
  python bench/ann/run.py --conf bench/ann/conf/synthetic-64.json --search
  # or both passes in one go:
  python bench/ann/run.py --conf ... --build --search

Outputs one CSV row per (index, search_param): algo, params, build_s,
recall@k, qps — written to ``results/<dataset>.csv`` next to the conf file
and echoed to stdout.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

# honor JAX_PLATFORMS even when a sitecustomize pre-imported jax and
# registered an accelerator backend (env vars alone are read too early)
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # the shared recipe lives in raft_tpu.core.platform.force_virtual_cpu;
    # this path keeps the user's explicit platform choice instead of cpu

# persistent jit cache: repeat harness runs skip 1M-scale compiles entirely
# (docs/warm_builds.md); RAFT_TPU_NO_JIT_CACHE=1 opts out for cold timings
if not os.environ.get("RAFT_TPU_NO_JIT_CACHE"):
    import raft_tpu.config

    raft_tpu.config.enable_compilation_cache()


def load_dataset(spec: dict):
    """Return (base (n,d) f32, queries (m,d) f32, metric str)."""
    import numpy as np

    metric = spec.get("distance", "euclidean")
    metric = {"euclidean": "sqeuclidean", "inner": "inner_product"}.get(metric, metric)
    if "synthetic" in spec:
        syn = spec["synthetic"]
        rng = np.random.default_rng(syn.get("seed", 0))
        n_clusters = syn.get("clusters", 0)
        if syn.get("dtype") == "uint8":
            # BigANN-class byte descriptors (reference:
            # cpp/bench/ann/conf/bigann-100M.json over .u8bin files):
            # clustered integer vectors in [0, 255], kept uint8 end-to-end
            # so the int8 storage/scoring path is what gets measured
            dim = syn["dim"]
            expects_clusters = max(n_clusters, 1)
            centers = rng.integers(30, 226, (expects_clusters, dim))
            std = syn.get("cluster_std", 12.0)

            def draw_u8(count):
                labels = rng.integers(0, expects_clusters, count)
                x = centers[labels] + rng.normal(0, std, (count, dim))
                return np.clip(np.rint(x), 0, 255).astype(np.uint8)

            return draw_u8(syn["n"]), draw_u8(syn["n_queries"]), metric
        if syn.get("family") == "heavytail":
            # second independent realistic family (VERDICT r4 #10),
            # deliberately breaking the siftclass generator's symmetries:
            # - cluster POPULATIONS are Zipf-distributed (a few huge
            #   clusters, a long tail of tiny ones) — stresses list
            #   splitting and probe allocation;
            # - per-cluster intrinsic dims VARY (4..32) and the subspaces
            #   are CORRELATED across clusters (each cluster draws its
            #   basis rows from one shared 64-direction pool, the way real
            #   descriptor manifolds share global structure);
            # - residual scales are LOGNORMAL per cluster — local density
            #   varies by orders of magnitude, unlike one fine_std.
            dim = syn["dim"]
            ncl = syn.get("clusters", 2000)
            zipf = syn.get("zipf", 1.0)
            w = (1.0 / np.arange(1, ncl + 1)) ** zipf
            w /= w.sum()
            centers = rng.random((ncl, dim)).astype(np.float32) * 10
            pool = rng.normal(size=(64, dim)).astype(np.float32)
            pool /= np.linalg.norm(pool, axis=1, keepdims=True)
            max_id = 32
            idims = rng.integers(4, max_id + 1, ncl)
            basis_rows = np.stack([rng.choice(64, max_id, replace=False)
                                   for _ in range(ncl)])
            bases = pool[basis_rows]                       # (ncl, 32, dim)
            mask = (np.arange(max_id)[None, :]
                    < idims[:, None]).astype(np.float32)   # (ncl, 32)
            scales = rng.lognormal(mean=np.log(0.25), sigma=0.8,
                                   size=ncl).astype(np.float32)

            def draw_ht(count):
                parts = []
                for s in range(0, count, 50_000):
                    c = min(50_000, count - s)
                    labels = rng.choice(ncl, c, p=w)
                    z = (rng.normal(size=(c, max_id)).astype(np.float32)
                         * mask[labels] * scales[labels][:, None])
                    parts.append((centers[labels] + np.einsum(
                        "ni,nid->nd", z, bases[labels])).astype(np.float32))
                return np.concatenate(parts) if len(parts) > 1 else parts[0]

            return draw_ht(syn["n"]), draw_ht(syn["n_queries"]), metric
        if n_clusters:
            dim = syn["dim"]
            centers = rng.random((n_clusters, dim), np.float32) * 10
            std = syn.get("cluster_std", 0.5)
            idim = syn.get("intrinsic_dim", 0)
            if idim:
                # SIFT-class: low intrinsic dimension + multi-scale local
                # density (sub-clumps within each cluster) — the same
                # dataset CLASS as bench.py:_make_lid_1m (the driver
                # regression row; BASELINE.md "Round-4 SIFT-class dataset
                # study"), not the same instance: bench.py draws on-device
                # with jax.random (a host generator would cost a 512 MB
                # tunnel upload), this harness draws host-side; parameters
                # live in the conf so the two stay tuned together
                n_clumps = syn.get("clumps", 16)
                fine_std = syn.get("fine_std", 0.15)
                bases = rng.normal(size=(n_clusters, idim, dim)).astype(np.float32)
                bases /= np.linalg.norm(bases, axis=-1, keepdims=True)
                offsets = (std * rng.normal(
                    size=(n_clusters, n_clumps, idim))).astype(np.float32)

                def draw(count):
                    # chunked: bases[labels] is a (count, idim, dim) f32
                    # temporary (~8.2 GB at 1M x 16 x 128 — the same hazard
                    # bench.py bounds with 50k-row blocks)
                    parts = []
                    for s in range(0, count, 50_000):
                        c = min(50_000, count - s)
                        labels = rng.integers(0, n_clusters, c)
                        clump = rng.integers(0, n_clumps, c)
                        z = (offsets[labels, clump]
                             + fine_std * rng.normal(size=(c, idim))
                             ).astype(np.float32)
                        parts.append((centers[labels] + np.einsum(
                            "ni,nid->nd", z, bases[labels])).astype(np.float32))
                    return np.concatenate(parts) if len(parts) > 1 else parts[0]

                return draw(syn["n"]), draw(syn["n_queries"]), metric

            # clustered data (gaussian blobs): realistic IVF/graph recall
            # behavior, unlike uniform noise
            def draw(count):
                labels = rng.integers(0, n_clusters, count)
                return (centers[labels] + rng.normal(0, std, (count, dim))).astype(np.float32)

            return draw(syn["n"]), draw(syn["n_queries"]), metric
        base = rng.random((syn["n"], syn["dim"]), np.float32)
        queries = rng.random((syn["n_queries"], syn["dim"]), np.float32)
        return base, queries, metric
    from raft_tpu.runtime import load_bin

    def native(arr):
        # int8/uint8 files stay integer (the indexes take them first-class:
        # int8 list storage + s8 MXU scoring); floats normalize to f32
        return arr if arr.dtype in (np.int8, np.uint8) else arr.astype(np.float32)

    base = native(load_bin(spec["base_file"]))
    queries = native(load_bin(spec["query_file"]))
    if "subset_size" in spec:
        base = base[: spec["subset_size"]]
    return base, queries, metric


def ground_truth(base, queries, k: int, metric: str, cache: pathlib.Path):
    import numpy as np

    if cache.exists():
        gt = np.load(cache)
        if gt.shape == (queries.shape[0], k):
            return gt
    from raft_tpu.neighbors import knn

    _, idx = knn(base, queries, k, metric=metric)
    gt = np.asarray(idx)
    cache.parent.mkdir(parents=True, exist_ok=True)
    np.save(cache, gt)
    return gt


def recall(found, gt) -> float:
    import numpy as np

    m, k = gt.shape
    hits = 0
    for i in range(m):
        hits += len(set(found[i].tolist()) & set(gt[i].tolist()))
    return hits / (m * k)


# ---------------------------------------------------------------------------
# Algorithm wrappers (the reference's per-library src/<algo> adapters)
# ---------------------------------------------------------------------------


class Algo:
    """build(dataset) -> index state; search(queries, k, params) -> ids."""

    def __init__(self, metric: str, build_param: dict):
        self.metric = metric
        self.build_param = build_param

    def build(self, dataset):
        raise NotImplementedError

    def search(self, queries, k: int, params: dict):
        raise NotImplementedError


class BruteForceAlgo(Algo):
    def build(self, dataset):
        import jax.numpy as jnp

        self.dataset = jnp.asarray(dataset)

    def search(self, queries, k, params):
        from raft_tpu.neighbors import knn

        return knn(self.dataset, queries, k, metric=self.metric)[1]


class IvfFlatAlgo(Algo):
    def build(self, dataset):
        from raft_tpu.neighbors import ivf_flat

        params = ivf_flat.IndexParams(metric=self.metric, **self.build_param)
        self.index = ivf_flat.build(params, dataset)

    def search(self, queries, k, params):
        from raft_tpu.neighbors import ivf_flat

        return ivf_flat.search(ivf_flat.SearchParams(**params), self.index, queries, k)[1]


class IvfPqAlgo(Algo):
    def build(self, dataset):
        from raft_tpu.neighbors import ivf_pq

        params = ivf_pq.IndexParams(metric=self.metric, **self.build_param)
        self.index = ivf_pq.build(params, dataset)

    def search(self, queries, k, params):
        from raft_tpu.neighbors import ivf_pq

        refine_ratio = params.pop("refine_ratio", 1)
        sp = ivf_pq.SearchParams(**params)
        if refine_ratio > 1:
            from raft_tpu.neighbors import refine

            d, i = ivf_pq.search(sp, self.index, queries, k * refine_ratio)
            return refine(self._dataset, queries, i, k, metric=self.metric)[1]
        return ivf_pq.search(sp, self.index, queries, k)[1]

    def build_and_keep(self, dataset):
        # device-resident copy: refine gathers from it every search call, and
        # re-uploading an n x d f32 dataset per call (512 MB at 1M x 128)
        # dominates the measurement through the host tunnel
        import jax.numpy as jnp

        self._dataset = jnp.asarray(dataset)


class CagraAlgo(Algo):
    def build(self, dataset):
        from raft_tpu.neighbors import cagra

        params = cagra.IndexParams(metric=self.metric, **self.build_param)
        self.index = cagra.build(params, dataset)

    def search(self, queries, k, params):
        from raft_tpu.neighbors import cagra

        return cagra.search(cagra.SearchParams(**params), self.index, queries, k)[1]


class BallCoverAlgo(Algo):
    def build(self, dataset):
        from raft_tpu.neighbors import ball_cover

        self.index = ball_cover.build(dataset, metric=self.metric, **self.build_param)

    def search(self, queries, k, params):
        from raft_tpu.neighbors import ball_cover

        return ball_cover.knn_query(self.index, queries, k, **params)[1]


ALGOS = {
    "raft_tpu.brute_force": BruteForceAlgo,
    "raft_tpu.ivf_flat": IvfFlatAlgo,
    "raft_tpu.ivf_pq": IvfPqAlgo,
    "raft_tpu.cagra": CagraAlgo,
    "raft_tpu.ball_cover": BallCoverAlgo,
}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--conf", required=True, help="JSON config path")
    ap.add_argument("--build", action="store_true")
    ap.add_argument("--search", action="store_true")
    ap.add_argument("--index-filter", default=None,
                    help="only run index entries whose name contains this substring")
    args = ap.parse_args()
    if not (args.build or args.search):
        ap.error("pass --build and/or --search")

    import jax
    import numpy as np

    conf_path = pathlib.Path(args.conf)
    conf = json.loads(conf_path.read_text())
    out_dir = conf_path.parent.parent / "results"
    out_dir.mkdir(parents=True, exist_ok=True)

    base, queries, metric = load_dataset(conf["dataset"])
    basic = conf.get("search_basic_param", {})
    k = basic.get("k", 10)
    run_count = basic.get("run_count", 3)
    batch_size = min(basic.get("batch_size", len(queries)), len(queries))
    queries = queries[:batch_size]
    # one host->device upload; per-call re-upload would bill the tunnel RPC
    # (and 5 MB/call of PCIe-equivalent traffic) to every algorithm equally
    queries_dev = jax.numpy.asarray(queries)

    gt = None
    rows = []
    built = {}

    entries = conf["index"]
    if args.index_filter:
        entries = [e for e in entries if args.index_filter in e["name"]]

    for entry in entries:
        name, algo_id = entry["name"], entry["algo"]
        if algo_id not in ALGOS:
            print(f"[skip] {name}: unknown algo {algo_id}", file=sys.stderr)
            continue
        algo = ALGOS[algo_id](metric, entry.get("build_param", {}))
        build_s = float("nan")
        if args.build or args.search:  # build in-process (indexes are pytrees)
            t0 = time.perf_counter()
            algo.build(base)
            if hasattr(algo, "build_and_keep"):
                algo.build_and_keep(base)
            build_s = time.perf_counter() - t0
            built[name] = build_s
            print(f"[build] {name}: {build_s:.2f}s")
        if not args.search:
            continue
        if gt is None:
            # cache key covers the FULL dataset spec (seed/clusters/std/files
            # all change the true neighbors) plus the loaded shape, so a
            # file regenerated in place with a different size also misses
            import hashlib

            spec_hash = hashlib.md5(
                json.dumps(conf["dataset"], sort_keys=True).encode()
            ).hexdigest()[:10]
            gt = ground_truth(
                base, queries, k, metric,
                out_dir / (
                    f"gt-{spec_hash}-{metric}-n{base.shape[0]}-d{base.shape[1]}"
                    f"-q{len(queries)}-k{k}.npy"
                ),
            )
        for sp in entry.get("search_params", [{}]):
            sp_label = json.dumps(sp, sort_keys=True)
            try:
                ids = algo.search(queries_dev, k, dict(sp))  # warmup/compile
                ids_np = np.asarray(ids)
                times = []
                for _ in range(run_count):
                    # host materialization, not block_until_ready: device
                    # tunnels can no-op the latter and report fantasy QPS
                    t0 = time.perf_counter()
                    ids = algo.search(queries_dev, k, dict(sp))
                    ids_np = np.asarray(ids)
                    times.append(time.perf_counter() - t0)
                qps = len(queries) / min(times)
                rec = recall(ids_np, gt)
            except Exception as e:  # parameter combos can be invalid (k > pool)
                print(f"[error] {name} {sp_label}: {e}", file=sys.stderr)
                continue
            rows.append({
                "name": name, "algo": algo_id, "search_params": sp_label,
                "k": k, "batch_size": len(queries), "build_s": round(build_s, 3),
                f"recall@{k}": round(rec, 4), "qps": round(qps, 1),
            })
            print(f"[search] {name} {sp_label}: recall@{k}={rec:.4f} qps={qps:.1f}")

    if rows:
        # keyed by the conf file, not the dataset name: several configs share
        # a dataset (variant/split-factor sweeps) and must not clobber the
        # full-config results they are compared against
        out_csv = out_dir / f"{conf_path.stem}.csv"
        with open(out_csv, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
            w.writeheader()
            w.writerows(rows)
        print(f"wrote {out_csv} ({len(rows)} rows)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
