"""Distributed-build overhead A/B: ``parallel.ivf.build`` vs ``ivf_flat.build``
on a 1-device mesh (VERDICT r5 item 8).

The search drivers got this control in r05 (per-call retrace found and fixed
to ~0%); the build drivers never did. On a 1-device mesh the distributed
build pays its full orchestration — psum-EM coarse training, the S-step
list-block psum fill, shard_map staging — with ZERO communication to hide it,
so the A/B bounds the pure driver overhead. Run on hardware:

    python bench/build_ab.py --n 1000000 --d 128 --n-lists 1024

Emits one JSON line: cold + warm walls for both paths and the warm ratio
(warm is what a steady-state pipeline pays; cold is dominated by compile and
attributed separately via raft_tpu.obs). The CPU-mesh variant of this A/B is
recorded in BASELINE.md ("Round-6 distributed-build overhead study").
"""

from __future__ import annotations

import argparse
import json
import time


def measure(n: int, d: int, n_lists: int, repeats: int = 2) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from raft_tpu.comms.comms import Comms
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.parallel import ivf as pivf

    obs_compile.install()
    comms = Comms(Mesh(np.array(jax.devices()[:1]), ("data",)), "data")
    x = jax.random.uniform(jax.random.key(0), (n, d), jnp.float32)
    jax.block_until_ready(x)
    params = ivf_flat.IndexParams(n_lists=n_lists, seed=0)

    def timed(fn):
        walls, compile_s = [], []
        for _ in range(repeats + 1):
            t0 = time.perf_counter()
            with obs_compile.attribution() as rec:
                idx = fn()
                jax.block_until_ready(idx.list_data)
            walls.append(time.perf_counter() - t0)
            compile_s.append(rec.compile_s)
            del idx
        # first call is cold (compile-dominated); best of the rest is warm
        return {"cold_s": round(walls[0], 2),
                "cold_compile_s": round(compile_s[0], 2),
                "warm_s": round(min(walls[1:]), 2)}

    single = timed(lambda: ivf_flat.build(params, x))
    dist = timed(lambda: pivf.build(comms, params, x))
    return {
        "n": n, "d": d, "n_lists": n_lists,
        "single": single, "distributed": dist,
        "warm_overhead": round(dist["warm_s"] / single["warm_s"] - 1.0, 3),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--d", type=int, default=128)
    ap.add_argument("--n-lists", type=int, default=1024)
    ap.add_argument("--repeats", type=int, default=2)
    args = ap.parse_args()
    print(json.dumps(measure(args.n, args.d, args.n_lists, args.repeats)),
          flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
