"""Build-speed A/B driver (ISSUE 6, the Round-6 follow-up): mini-batch vs
full coarse EM, sharded vs single CAGRA builds, and the distributed-build
overhead control — one artifact, one renderer.

The Round-6 study (BASELINE.md "Round-6 distributed-build overhead") named
the balanced coarse trainer's ~22 full-dataset assignment passes as the
dominant IVF build cost (+187% warm at 1M distributed, 50.3-51.3 s of the 1M
single-chip build). This driver measures the r07 remedies:

- ``--ab em``       mini-batch vs full coarse EM on the IVF-PQ build: warm
                    build wall + the recall anchor at the BENCH operating
                    point (held within tolerance is the acceptance bar).
- ``--ab overhead`` the Round-6 1-device-mesh distributed-vs-single warm
                    overhead A/B, run in BOTH EM modes — the within-15%
                    acceptance bar reads off the minibatch row.
- ``--ab cagra``    sharded-merged vs single CAGRA build
                    (parallel.cagra.build_merged): build wall + recall@10 of
                    both indexes against exact ground truth.
- ``--ab all``      everything above into one artifact.

Run on hardware (the committed CPU-mesh artifact is the reduced-scale
control):

    python bench/build_ab.py --ab all --n 1000000 --cagra-n 1000000 \
        --out BUILD_AB_r07.json

Render the BASELINE follow-up table FROM the artifact (stdlib only — no
prose drift; the numbers in the doc ARE the artifact's):

    python bench/build_ab.py --table BUILD_AB_r07.json >> BASELINE.md
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _timed_builds(fn, materialize, repeats: int):
    """cold + warm walls + cold compile attribution for a build closure."""
    import jax

    from raft_tpu.obs import compile as obs_compile

    walls, compile_s = [], []
    for _ in range(repeats + 1):
        t0 = time.perf_counter()
        with obs_compile.attribution() as rec:
            idx = fn()
            jax.block_until_ready(materialize(idx))
        walls.append(time.perf_counter() - t0)
        compile_s.append(rec.compile_s)
    # first call is cold (compile-dominated); best of the rest is warm
    return {"cold_s": round(walls[0], 2),
            "cold_compile_s": round(compile_s[0], 2),
            "warm_s": round(min(walls[1:]), 2)}, idx


def _clustered(n: int, d: int, ncl: int, seed: int = 0):
    import jax
    import jax.numpy as jnp

    from raft_tpu.random import make_blobs

    x, _ = make_blobs(n, d, n_clusters=ncl, cluster_std=1.0, seed=seed)
    x = jnp.asarray(x, jnp.float32)
    jax.block_until_ready(x)
    return x


def _recall(ids, gt):
    import numpy as np

    ids, gt = np.asarray(ids), np.asarray(gt)
    k = gt.shape[1]
    return float(np.mean([len(set(ids[r].tolist()) & set(gt[r].tolist())) / k
                          for r in range(gt.shape[0])]))


def measure_em_ab(n: int, d: int, n_lists: int, pq_dim: int = 64,
                  n_probes: int = 8, k: int = 10, repeats: int = 2,
                  n_eval: int = 1000, ncl: int = 2000,
                  batch_rows: int = 65536) -> dict:
    """Mini-batch vs full coarse EM on the IVF-PQ build: warm build wall +
    the recall anchor at the BENCH operating point (pq4, bf16 LUT). The
    acceptance bar: warm build cut >= 30% at 1M with the anchor held."""
    import dataclasses

    from raft_tpu.neighbors import brute_force, ivf_pq

    x = _clustered(n, d, ncl)
    q = x[:n_eval]
    _, gt = brute_force.knn(x, q, k)
    base = ivf_pq.IndexParams(n_lists=n_lists, pq_bits=4, pq_dim=pq_dim,
                              kmeans_batch_rows=batch_rows, seed=0)
    sp = ivf_pq.SearchParams(n_probes=n_probes, lut_dtype="bfloat16")
    out = {"name": f"em_ab_ivf_pq_{n//1000}k", "n": n, "d": d,
           "n_lists": n_lists, "n_probes": n_probes, "k": k}
    for mode in ("full", "minibatch"):
        params = dataclasses.replace(base, kmeans_train_mode=mode)
        timing, idx = _timed_builds(lambda p=params: ivf_pq.build(p, x),
                                    lambda i: i.list_codes, repeats)
        _, ids = ivf_pq.search(sp, idx, q, k)
        timing["recall"] = round(_recall(ids, gt), 4)
        out[mode] = timing
        del idx
    out["warm_cut"] = round(
        1.0 - out["minibatch"]["warm_s"] / max(out["full"]["warm_s"], 1e-9), 3)
    out["recall_gap"] = round(
        out["minibatch"]["recall"] - out["full"]["recall"], 4)
    return out


def measure_overhead(n: int, d: int, n_lists: int, repeats: int = 2,
                     batch_rows: int = 65536) -> dict:
    """The Round-6 1-device-mesh distributed-vs-single build A/B, in both EM
    modes: full reproduces the r06 +187%-class overhead (the psum-EM's full
    -dataset passes), minibatch is the r07 remedy — the within-15% bar."""
    import dataclasses

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from raft_tpu.comms.comms import Comms
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.parallel import ivf as pivf

    comms = Comms(Mesh(np.array(jax.devices()[:1]), ("data",)), "data")
    x = _clustered(n, d, max(n // 500, 16))
    base = ivf_flat.IndexParams(n_lists=n_lists, kmeans_batch_rows=batch_rows,
                                seed=0)
    out = {"name": f"dist_overhead_{n//1000}k", "n": n, "d": d,
           "n_lists": n_lists}
    for mode in ("full", "minibatch"):
        params = dataclasses.replace(base, kmeans_train_mode=mode)
        single, _ = _timed_builds(
            lambda p=params: ivf_flat.build(p, x), lambda i: i.list_data,
            repeats)
        dist, _ = _timed_builds(
            lambda p=params: pivf.build(comms, p, x), lambda i: i.list_data,
            repeats)
        out[mode] = {
            "single": single, "distributed": dist,
            "warm_overhead": round(
                dist["warm_s"] / max(single["warm_s"], 1e-9) - 1.0, 3)}
    return out


def measure_cagra_ab(n: int, d: int, shards: int, itopk: int = 32,
                     k: int = 10, n_eval: int = 1000, ncl: int | None = None,
                     repeats: int = 1, batch_rows: int = 65536) -> dict:
    """Sharded-merged vs single CAGRA build: wall + recall@10 of BOTH
    indexes against exact ground truth (the r06 64k/8-shard result said the
    merged graph holds recall; this prices the build-speed side).

    ``ncl`` defaults to the BENCH family's rows-per-cluster (~500, the 1M
    set's proportions). Shard-local graphs' recall depends on CLUSTER
    MEMBERS PER SHARD, not shard rows: the r07 CPU artifact measured a
    -0.058 recall gap at 2 members/shard (32k rows, 2000 clusters, 8
    shards) vs parity at bench proportions — pass ``ncl`` explicitly to
    probe that boundary (docs/using_comms.md records the sizing rule)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    import dataclasses

    from raft_tpu.comms.comms import Comms
    from raft_tpu.neighbors import brute_force, cagra
    from raft_tpu.parallel import cagra as pcagra

    ndev = len(jax.devices())
    comms = Comms(Mesh(np.array(jax.devices()[:min(shards, ndev)]),
                       ("data",)), "data")
    if ncl is None:
        ncl = max(n // 500, 16)
    x = _clustered(n, d, ncl)
    q = x[:n_eval]
    _, gt = brute_force.knn(x, q, k)
    params = cagra.IndexParams(build_kmeans_batch_rows=batch_rows, seed=0)
    sp = cagra.SearchParams(itopk_size=itopk)
    out = {"name": f"cagra_build_ab_{n//1000}k_{ncl}cl", "n": n, "d": d,
           "ncl": ncl, "shards": comms.size(), "itopk": itopk, "k": k}
    single, idx1 = _timed_builds(lambda: cagra.build(params, x),
                                 lambda i: i.graph, repeats)
    _, ids = cagra.search(sp, idx1, q, k)
    single["recall"] = round(_recall(ids, gt), 4)
    del idx1
    merged, idx2 = _timed_builds(
        lambda: pcagra.build_merged(comms, params, x), lambda i: i.graph,
        repeats)
    _, ids = cagra.search(sp, idx2, q, k)
    merged["recall"] = round(_recall(ids, gt), 4)
    # the beam-width recovery arm: one beam over S disconnected shard
    # subgraphs needs a wider itopk — the r07 CPU artifact measured
    # 0.9371 -> 0.995 -> 0.9999 at itopk 32/64/128 vs the single graph's
    # 1.0 @ 32 (docs/using_comms.md sizing rule)
    sweep = {}
    for t in (2 * itopk, 4 * itopk):
        _, ids = cagra.search(
            dataclasses.replace(sp, itopk_size=t), idx2, q, k)
        sweep[str(t)] = round(_recall(ids, gt), 4)
    merged["itopk_sweep"] = sweep
    del idx2
    out["single"] = single
    out["merged"] = merged
    out["warm_cut"] = round(
        1.0 - merged["warm_s"] / max(single["warm_s"], 1e-9), 3)
    out["recall_gap"] = round(merged["recall"] - single["recall"], 4)
    return out


# ---------------------------------------------------------------------------
# artifact → markdown (stdlib only: runs on the doc-writing host)
# ---------------------------------------------------------------------------

def render_table(artifact: dict) -> str:
    """The BASELINE "Round-6 follow-up" table generated FROM the artifact —
    the committed prose and the committed JSON are the same bytes."""
    lines = [
        "| row | arm | warm_s | cold_s | recall | delta |",
        "|---|---|---|---|---|---|",
    ]
    for r in artifact.get("rows", []):
        name = r.get("name", "?")
        if "error" in r:
            lines.append(f"| {name} | ERROR | | | | {r['error'][:60]} |")
            continue
        if name.startswith("em_ab"):
            for arm in ("full", "minibatch"):
                a = r[arm]
                lines.append(
                    f"| {name} | {arm} | {a['warm_s']} | {a['cold_s']} | "
                    f"{a['recall']:.4f} | |")
            lines.append(
                f"| {name} | | | | | warm_cut **{r['warm_cut']}**, "
                f"recall_gap {r['recall_gap']} |")
        elif name.startswith("dist_overhead"):
            for arm in ("full", "minibatch"):
                a = r[arm]
                lines.append(
                    f"| {name} | {arm} single | {a['single']['warm_s']} | "
                    f"{a['single']['cold_s']} | | |")
                lines.append(
                    f"| {name} | {arm} distributed | "
                    f"{a['distributed']['warm_s']} | "
                    f"{a['distributed']['cold_s']} | | warm_overhead "
                    f"**{a['warm_overhead']}** |")
        elif name.startswith("cagra_build_ab"):
            for arm in ("single", "merged"):
                a = r[arm]
                lines.append(
                    f"| {name} | {arm} (S={r['shards']}) | {a['warm_s']} | "
                    f"{a['cold_s']} | {a['recall']:.4f} | |")
            sweep = (r["merged"].get("itopk_sweep")
                     or r.get("merged_itopk_sweep"))
            if sweep:
                arm = ", ".join(f"itopk {t}: {v:.4f}"
                                for t, v in sorted(sweep.items(),
                                                   key=lambda kv: int(kv[0])))
                lines.append(f"| {name} | merged, wider beam | | | {arm} | |")
            lines.append(
                f"| {name} | | | | | warm_cut **{r['warm_cut']}**, "
                f"recall_gap {r['recall_gap']} |")
    head = (f"elapsed {artifact.get('elapsed_s')}s, config "
            f"{json.dumps(artifact.get('config', {}))}. Table generated by "
            "`python bench/build_ab.py --table <artifact>` — the numbers "
            "below ARE the artifact's.")
    return head + "\n\n" + "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ab", choices=("em", "overhead", "cagra", "all"),
                    default="all")
    ap.add_argument("--n", type=int, nargs="*", default=[100_000, 1_000_000],
                    help="IVF A/B scales (em + overhead)")
    ap.add_argument("--d", type=int, default=128)
    ap.add_argument("--n-lists", type=int, default=1024)
    ap.add_argument("--cagra-n", type=int, default=1_000_000)
    ap.add_argument("--cagra-ncl", type=int, nargs="*", default=None,
                    help="cluster counts for the CAGRA A/B set, one row per "
                         "value (default: one row at bench-family "
                         "proportions, n/500; the committed r07 artifact "
                         "used 65 + a deliberately thin 2000)")
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--batch-rows", type=int, default=65536,
                    help="kmeans_batch_rows for every build (shrink it to "
                         "demonstrate the cut at reduced CPU-mesh scales)")
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--table", type=str, default=None,
                    help="render the BASELINE table from an artifact and exit")
    args = ap.parse_args(argv)

    if args.table:
        with open(args.table) as f:
            print(render_table(json.load(f)))
        return 0

    from raft_tpu.obs import compile as obs_compile

    obs_compile.install()
    t0 = time.perf_counter()
    rows = []

    def guarded(fn, *a, **kw):
        try:
            rows.append(fn(*a, **kw))
        except Exception as e:  # labeled row, keep going (bench contract)
            rows.append({"name": getattr(fn, "__name__", "?"),
                         "error": f"{type(e).__name__}: {str(e)[:200]}"})

    if args.ab in ("em", "all"):
        for n in args.n:
            guarded(measure_em_ab, n, args.d, args.n_lists,
                    repeats=args.repeats, batch_rows=args.batch_rows)
    if args.ab in ("overhead", "all"):
        for n in args.n:
            guarded(measure_overhead, n, args.d, args.n_lists,
                    repeats=args.repeats, batch_rows=args.batch_rows)
    if args.ab in ("cagra", "all"):
        for ncl in (args.cagra_ncl or [None]):
            guarded(measure_cagra_ab, args.cagra_n, args.d, args.shards,
                    ncl=ncl, repeats=max(args.repeats - 1, 1),
                    batch_rows=args.batch_rows)

    artifact = {
        "rows": rows, "elapsed_s": round(time.perf_counter() - t0, 1),
        "config": {"n": args.n, "d": args.d, "n_lists": args.n_lists,
                   "cagra_n": args.cagra_n,
                   "cagra_ncl": args.cagra_ncl, "shards": args.shards,
                   "repeats": args.repeats, "batch_rows": args.batch_rows},
    }
    line = json.dumps(artifact)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
