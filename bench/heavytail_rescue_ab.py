"""Per-remedy recall A/B for IVF-PQ on the heavytail family (VERDICT r5 #2).

Measures the four remedy combinations — per_subspace (the collapsed
baseline), codebook_kind="per_cluster", residual_scale_norm=True, and both —
at matched build/search params, reporting bare and refine4 recall@10 plus
QPS. Recall is hardware-independent, so `--n 100000` on the CPU mesh gives
the remedy ranking cheaply; the 1M QPS-bearing rows ride
`bench/ann/conf/heavytail-1M-128.json` (ivf_pq_pq4x64_refine4_scalenorm /
_percluster) through the usual harness on the TPU host:

    python bench/heavytail_rescue_ab.py [--n 1000000] [--clusters 2000]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--clusters", type=int, default=0,
                    help="0 = scale 2000 with n/1M (keeps rows/cluster)")
    ap.add_argument("--n-queries", type=int, default=1000)
    ap.add_argument("--n-lists", type=int, default=0, help="0 = n/1M * 1024")
    ap.add_argument("--probes", type=int, default=16)
    args = ap.parse_args()

    from raft_tpu.config import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import numpy as np

    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.brute_force import knn
    from raft_tpu.neighbors.refine import refine

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent / "ann"))
    from run import load_dataset  # the committed heavytail generator

    n = args.n
    frac = max(n / 1_000_000, 0.01)
    ncl = args.clusters or max(int(2000 * frac), 8)
    n_lists = args.n_lists or max(int(1024 * frac), 8)
    print(f"backend: {jax.default_backend()}  n={n} ncl={ncl} "
          f"n_lists={n_lists}", file=sys.stderr)
    spec = {"distance": "euclidean",
            "synthetic": {"family": "heavytail", "n": n,
                          "n_queries": args.n_queries, "dim": args.dim,
                          "clusters": ncl, "zipf": 1.0, "seed": 21}}
    x, q, _ = load_dataset(spec)
    import jax.numpy as jnp

    x, q = jnp.asarray(x), jnp.asarray(q)
    jax.block_until_ready((x, q))
    _, gt = knn(x, q, 10)
    gt = np.asarray(gt)

    def recall(ids):
        return float(np.mean([len(set(ids[r]) & set(gt[r])) / 10
                              for r in range(gt.shape[0])]))

    rows = []
    for name, kind, norm in (("per_subspace", "per_subspace", False),
                             ("per_cluster", "per_cluster", False),
                             ("scale_norm", "per_subspace", True),
                             ("per_cluster+scale_norm", "per_cluster", True)):
        t0 = time.perf_counter()
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=n_lists, pq_bits=4, pq_dim=64, codebook_kind=kind,
            residual_scale_norm=norm, seed=0), x)
        jax.block_until_ready(idx.list_codes)
        build_s = time.perf_counter() - t0
        sp = ivf_pq.SearchParams(n_probes=args.probes, lut_dtype="bfloat16")

        def searcher(qq):
            _, cand = ivf_pq.search(sp, idx, qq, 40)
            return refine(x, qq, cand, 10)

        _, ids_bare = ivf_pq.search(sp, idx, q, 10)
        out = searcher(q)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = searcher(q)
        jax.block_until_ready(out)
        qps = q.shape[0] / (time.perf_counter() - t0)
        row = {"variant": name, "build_s": round(build_s, 1),
               "bare_recall": round(recall(np.asarray(ids_bare)), 4),
               "refine4_recall": round(recall(np.asarray(out[1])), 4),
               "qps": round(qps, 1)}
        rows.append(row)
        print(json.dumps(row), flush=True)
    print(json.dumps({"n": n, "clusters": ncl, "n_lists": n_lists,
                      "probes": args.probes, "rows": rows}))


if __name__ == "__main__":
    main()
