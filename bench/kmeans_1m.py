#!/usr/bin/env python
"""BASELINE config 2: k-means fit on make_blobs(1M x 128), k=1024, one chip.

Counterpart of the reference's cluster bench (cpp/bench/prims/cluster/kmeans.cu)
at the BASELINE.md table-2 operating point. Reports fit wall time (excluding
the first-call compile, which is timed separately), per-iteration time, and
inertia parity against the generating blob centers (the inertia of labeling
every point by its true generator is the achievable floor; a correct Lloyd
run from kmeans++ lands within a few percent of it).

Since r07 the bench ALSO measures the balanced coarse trainer — the k-means
that actually runs inside every IVF build — which now defaults to mini-batch
EM at this scale (KMeansBalancedParams.train_mode="auto": rotating 65536-row
batches, one closing full pass; the Round-6-measured ~22 full-dataset
assignment passes are gone). ``--full-em`` pins the pre-r07 full-EM behavior
for the A/B; the drift test asserting the new defaults lives in
tests/test_kmeans.py::test_params_defaults_drift.

Usage: python bench/kmeans_1m.py [--n 1000000] [--k 1024] [--iters 20]
       [--full-em] [--skip-lloyd]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--d", type=int, default=128)
    ap.add_argument("--k", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--full-em", action="store_true",
                    help="pin the balanced trainer to the pre-r07 full-EM "
                         "path (train_mode='full') for the A/B")
    ap.add_argument("--batch-rows", type=int, default=65536,
                    help="mini-batch rows for the balanced trainer")
    ap.add_argument("--skip-lloyd", action="store_true",
                    help="skip the plain-Lloyd BASELINE table-2 measurement")
    args = ap.parse_args()

    import jax
    import numpy as np

    from raft_tpu.cluster import kmeans, kmeans_balanced
    from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
    from raft_tpu.random import make_blobs

    rng = np.random.default_rng(0)
    true_centers = rng.uniform(-10.0, 10.0, (args.k, args.d)).astype(np.float32)
    x, _ = make_blobs(args.n, args.d, centers=true_centers, cluster_std=1.0, seed=0)
    jax.block_until_ready(x)

    # inertia floor: cost of the generating centers
    floor = float(kmeans.cluster_cost(x, true_centers))
    out = {}

    if not args.skip_lloyd:
        params = kmeans.KMeansParams(
            n_clusters=args.k, max_iter=args.iters, tol=0.0, init="kmeans++", seed=0
        )

        t0 = time.perf_counter()
        res = kmeans.fit(params, x)
        np.asarray(res.centroids)
        first = time.perf_counter() - t0

        t0 = time.perf_counter()
        res = kmeans.fit(params, x)
        np.asarray(res.centroids)
        fit_s = time.perf_counter() - t0

        out.update({
            "metric": f"kmeans fit {args.n}x{args.d} k={args.k} ({args.iters} iters)",
            "fit_s": round(fit_s, 2),
            "first_call_s": round(first, 2),
            "s_per_iter": round(fit_s / max(int(res.n_iter), 1), 3),
            "n_iter": int(res.n_iter),
            "inertia": float(res.inertia),
            "inertia_floor": floor,
            "inertia_ratio": round(float(res.inertia) / floor, 4) if floor else None,
        })

    # -- balanced coarse trainer (the IVF-build path; minibatch default) ----
    mode = "full" if args.full_em else "auto"
    kb = KMeansBalancedParams(n_iters=args.iters, seed=0, train_mode=mode,
                              batch_rows=args.batch_rows)
    resolved = kmeans_balanced.resolve_train_mode(mode, args.n,
                                                  args.batch_rows)

    t0 = time.perf_counter()
    centers = kmeans_balanced.fit(kb, x, args.k)
    np.asarray(centers)
    b_first = time.perf_counter() - t0

    t0 = time.perf_counter()
    centers = kmeans_balanced.fit(kb, x, args.k)
    np.asarray(centers)
    b_fit_s = time.perf_counter() - t0
    b_inertia = float(kmeans.cluster_cost(x, centers))

    out.update({
        "balanced_metric": (
            f"kmeans_balanced fit {args.n}x{args.d} k={args.k} "
            f"({args.iters} iters, {resolved} EM)"),
        "balanced_train_mode": resolved,
        "balanced_batch_rows": args.batch_rows,
        "balanced_fit_s": round(b_fit_s, 2),
        "balanced_first_call_s": round(b_first, 2),
        "balanced_inertia": b_inertia,
        "balanced_inertia_ratio": round(b_inertia / floor, 4) if floor else None,
    })

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
