#!/usr/bin/env python
"""BASELINE config 2: k-means fit on make_blobs(1M x 128), k=1024, one chip.

Counterpart of the reference's cluster bench (cpp/bench/prims/cluster/kmeans.cu)
at the BASELINE.md table-2 operating point. Reports fit wall time (excluding
the first-call compile, which is timed separately), per-iteration time, and
inertia parity against the generating blob centers (the inertia of labeling
every point by its true generator is the achievable floor; a correct Lloyd
run from kmeans++ lands within a few percent of it).

Usage: python bench/kmeans_1m.py [--n 1000000] [--k 1024] [--iters 20]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--d", type=int, default=128)
    ap.add_argument("--k", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    import jax
    import numpy as np

    from raft_tpu.cluster import kmeans
    from raft_tpu.random import make_blobs

    rng = np.random.default_rng(0)
    true_centers = rng.uniform(-10.0, 10.0, (args.k, args.d)).astype(np.float32)
    x, _ = make_blobs(args.n, args.d, centers=true_centers, cluster_std=1.0, seed=0)
    jax.block_until_ready(x)

    # inertia floor: cost of the generating centers
    floor = float(kmeans.cluster_cost(x, true_centers))

    params = kmeans.KMeansParams(
        n_clusters=args.k, max_iter=args.iters, tol=0.0, init="kmeans++", seed=0
    )

    t0 = time.perf_counter()
    out = kmeans.fit(params, x)
    np.asarray(out.centroids)
    first = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = kmeans.fit(params, x)
    np.asarray(out.centroids)
    fit_s = time.perf_counter() - t0

    print(
        json.dumps(
            {
                "metric": f"kmeans fit {args.n}x{args.d} k={args.k} ({args.iters} iters)",
                "fit_s": round(fit_s, 2),
                "first_call_s": round(first, 2),
                "s_per_iter": round(fit_s / max(int(out.n_iter), 1), 3),
                "n_iter": int(out.n_iter),
                "inertia": float(out.inertia),
                "inertia_floor": floor,
                "inertia_ratio": round(float(out.inertia) / floor, 4) if floor else None,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
