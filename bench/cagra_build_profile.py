"""Profile the CAGRA 1M build (VERDICT r3 #3): where do the ~440 s go?

Replays cagra.build's exact pipeline (bench.py protocol: isotropic 1M x 128,
default IndexParams) with per-phase wall timers: the internal IVF-PQ build,
each knn-graph chunk (separating the first, compile-heavy, call from the
steady state), and optimize (prune + reverse merge). Run on the TPU host:

    python bench/cagra_build_profile.py [--n 1000000] [--chunk 16384]
"""

from __future__ import annotations

import argparse
import sys
import time
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--chunk", type=int, default=16384)
    ap.add_argument("--probes", type=int, default=8)
    args = ap.parse_args()

    from raft_tpu.config import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import jax.numpy as jnp

    import bench as drv
    from raft_tpu.core.resources import default_resources
    from raft_tpu.distance.types import resolve_metric
    from raft_tpu.neighbors import cagra, ivf_pq
    from raft_tpu.neighbors.cagra import (_build_chunk_step, knn_build_plan,
                                          optimize)

    t_all = time.perf_counter()
    dataset, _ = drv._make_1m()
    if args.n < dataset.shape[0]:
        dataset = dataset[:args.n]
    jax.block_until_ready(dataset)
    n, d = dataset.shape
    print(f"dataset {n}x{d} ready +{time.perf_counter()-t_all:.1f}s",
          flush=True)

    params = cagra.IndexParams(build_chunk=args.chunk,
                               build_n_probes=args.probes)
    res = default_resources()
    k, gpu_top_k, n_lists, pq_bits = knn_build_plan(params, n, d)

    t0 = time.perf_counter()
    pq = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=n_lists,
                           metric=params.metric, pq_bits=pq_bits,
                           seed=params.seed), dataset, res=res)
    jax.block_until_ready(pq.list_codes)
    t_pq = time.perf_counter() - t0
    print(f"phase ivf_pq.build: {t_pq:.1f}s (n_lists={pq.n_lists}, "
          f"pq_bits={pq_bits}, cap={pq.capacity})", flush=True)

    mt = resolve_metric(params.metric)
    chunk = args.chunk
    parts = []
    chunk_times = []
    for s in range(0, n, chunk):
        xb = dataset[s:s + chunk]
        rows = jnp.arange(s, min(s + chunk, n), dtype=jnp.int32)
        t0 = time.perf_counter()
        out = _build_chunk_step(dataset, pq, xb, rows, int(params.build_n_probes),
                                int(gpu_top_k), int(k), mt,
                                int(res.workspace_bytes))
        jax.block_until_ready(out)
        chunk_times.append(time.perf_counter() - t0)
        parts.append(out)
        if len(chunk_times) in (1, 2, 3):
            print(f"  chunk {len(chunk_times)}: {chunk_times[-1]:.2f}s",
                  flush=True)
    knn_graph = jnp.concatenate(parts, axis=0)
    steady = sorted(chunk_times[1:])[len(chunk_times) // 2] if len(
        chunk_times) > 1 else chunk_times[0]
    print(f"phase knn_graph: {sum(chunk_times):.1f}s over "
          f"{len(chunk_times)} chunks (first={chunk_times[0]:.2f}s, "
          f"median-steady={steady:.2f}s, sum-steady="
          f"{sum(chunk_times[1:]):.1f}s)", flush=True)

    t0 = time.perf_counter()
    graph = optimize(knn_graph, params.graph_degree, res=res)
    jax.block_until_ready(graph)
    print(f"phase optimize: {time.perf_counter()-t0:.1f}s", flush=True)
    print(f"TOTAL build-equivalent: {time.perf_counter()-t_all:.1f}s "
          f"(incl. dataset gen)", flush=True)


if __name__ == "__main__":
    main()
