"""Micro-bench for the Pallas PQ LUT-scan kernel (ops/pq_scan.py), isolated
from the full IVF search: one chunk's worth of synthetic codes/LUTs at the
1M-scale shapes (B = query_tile * probe_chunk = 1024, cap ~ 1336, S = 64).

Protocol follows bench.py: ITERS DISTINCT inputs chained in one jitted
program via lax.map, host-materialized, best of 2 distinct stacks — the
device tunnel caches repeated identical dispatches, so naive repeat-timing
reads fantasy numbers. Run on the TPU host:

    python bench/pq_kernel_micro.py
"""

from __future__ import annotations

import sys
import time
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

ITERS = 8


def timeit(fn, stacks):
    """fn maps one (codes, lut) pair; chained over ITERS distinct inputs.
    Only a per-iter checksum leaves the device — a full (B, cap) f32 output
    costs ~50 ms of tunnel transfer and swamps the kernel time."""
    f = jax.jit(lambda cs, ls: lax.map(lambda a: fn(*a), (cs, ls))
                .sum(axis=(1, 2)))
    np.asarray(f(*stacks[0]))  # compile + warm
    best = float("inf")
    for st in stacks[1:]:
        t0 = time.perf_counter()
        sums = np.asarray(f(*st))
        best = min(best, time.perf_counter() - t0)
    # one full output for the correctness check, outside the timing
    out = jax.jit(fn)(*[a[0] for a in stacks[-1]])
    return best / ITERS, np.asarray(out)


def onehot_ref(codes_u8, lut_ks):
    B, cap, S = codes_u8.shape
    K = lut_ks.shape[1]
    oh = codes_u8[..., None] == jnp.arange(K, dtype=jnp.uint8)
    ohf = oh.reshape(B, cap, S * K)
    lutf = jnp.swapaxes(lut_ks, 1, 2).reshape(B, S * K)
    return lax.dot_general(ohf.astype(jnp.bfloat16), lutf.astype(jnp.bfloat16),
                           (((2,), (1,)), ((0,), (0,))),
                           preferred_element_type=jnp.float32)


def main():
    from raft_tpu.ops.pq_scan import pq_lut_scan

    B, cap, S, K = 1024, 1336, 64, 16

    def stack(seed):
        r = np.random.default_rng(seed)
        cs = jnp.asarray(r.integers(0, K, (ITERS, B, cap, S), dtype=np.uint8))
        ls = jnp.asarray(r.random((ITERS, B, K, S), np.float32))
        return cs, ls

    stacks = [stack(s) for s in range(3)]
    jax.block_until_ready(stacks)
    i8_stacks = [(c.astype(jnp.int8), l) for c, l in stacks]
    n_scores = B * cap

    t, ref_last = timeit(onehot_ref, stacks)
    print(f"onehot bf16:  {t*1e3:8.2f} ms  {n_scores/t/1e9:6.2f} Gscore/s",
          flush=True)

    for bt, capb in ((8, None), (8, 256), (8, 128), (16, None), (32, None),
                     (64, None)):
        def f(c, l, bt=bt, capb=capb):
            return pq_lut_scan(c, l, bt=bt, capb=capb)
        try:
            t, out = timeit(f, i8_stacks)
            err = float(np.abs(out - ref_last).max())
            print(f"pallas bt={bt:3d} capb={capb}: "
                  f"{t*1e3:8.2f} ms  {n_scores/t/1e9:6.2f} Gscore/s  "
                  f"maxerr={err:.3f}", flush=True)
        except Exception as e:
            print(f"pallas bt={bt:3d} capb={capb}: ERROR "
                  f"{type(e).__name__}: {str(e)[:160]}", flush=True)


if __name__ == "__main__":
    main()
