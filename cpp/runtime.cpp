// raft_tpu native host runtime.
//
// TPU-native equivalent of the reference's host-side C++ runtime pieces:
//  - big-ANN binary dataset IO (reference: cpp/bench/ann/src/common/dataset.h
//    BinFile — 8-byte header: uint32 n_rows, uint32 dim; suffixes
//    .fbin/.u8bin/.i8bin), here with pread-based chunked access so Python can
//    stream TB-scale datasets into device memory without materializing them;
//  - exact host-side candidate refinement (reference: refine_host,
//    cpp/include/raft/neighbors/detail/refine.cuh:169 — OpenMP loop over
//    queries), used to re-rank ANN candidates against original vectors while
//    the TPU works on the next batch;
//  - host top-k merge of per-shard results (reference: knn_merge_parts,
//    cpp/include/raft/neighbors/detail/knn_merge_parts.cuh), for multi-host
//    result aggregation outside the device mesh.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this toolchain).
// Threading uses std::thread — no OpenMP runtime dependency.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

int num_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 4 : static_cast<int>(n);
}

// Run fn(i) for i in [0, n) over a thread pool (strided like the reference's
// `for (i = omp_get_thread_num(); i < n; i += omp_get_num_threads())`).
template <typename Fn>
void parallel_for(int64_t n, Fn fn) {
  int nt = std::min<int64_t>(num_threads(), n);
  if (nt <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(nt);
  for (int t = 0; t < nt; ++t) {
    pool.emplace_back([=] {
      for (int64_t i = t; i < n; i += nt) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

int64_t rt_num_threads() { return num_threads(); }

// ---------------------------------------------------------------------------
// Big-ANN binary file IO (header: uint32 n, uint32 dim — dataset.h:35-41)
// ---------------------------------------------------------------------------

// Returns 0 on success; fills n_rows/dim.
int rt_bin_info(const char* path, int64_t* n_rows, int64_t* dim) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return -1;
  uint32_t hdr[2];
  size_t got = std::fread(hdr, sizeof(uint32_t), 2, fp);
  std::fclose(fp);
  if (got != 2) return -2;
  *n_rows = hdr[0];
  *dim = hdr[1];
  return 0;
}

// Read rows [row_start, row_start + n_rows) of an (n, dim) record file with
// elem_size-byte scalars into out. Parallel pread chunks saturate the page
// cache / NVMe queue the way the reference's mmap+first-touch does.
int rt_bin_read_chunk(const char* path, int64_t row_start, int64_t n_rows,
                      int64_t dim, int64_t elem_size, void* out) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  const int64_t row_bytes = dim * elem_size;
  const int64_t base = 8 + row_start * row_bytes;  // 8-byte header
  const int64_t total = n_rows * row_bytes;
  std::atomic<int> err{0};
  // split into ~32MB stripes for parallel pread
  const int64_t stripe = 32ll << 20;
  const int64_t n_stripes = (total + stripe - 1) / stripe;
  parallel_for(n_stripes, [&](int64_t s) {
    int64_t off = s * stripe;
    int64_t len = std::min(stripe, total - off);
    char* dst = static_cast<char*>(out) + off;
    int64_t done = 0;
    while (done < len) {
      ssize_t got = ::pread(fd, dst + done, len - done, base + off + done);
      if (got <= 0) {
        err.store(-2);
        return;
      }
      done += got;
    }
  });
  ::close(fd);
  return err.load();
}

// Write an (n, dim) float32 record file with the big-ANN 8-byte header.
int rt_bin_write(const char* path, const void* data, int64_t n_rows,
                 int64_t dim, int64_t elem_size) {
  FILE* fp = std::fopen(path, "wb");
  if (!fp) return -1;
  uint32_t hdr[2] = {static_cast<uint32_t>(n_rows), static_cast<uint32_t>(dim)};
  if (std::fwrite(hdr, sizeof(uint32_t), 2, fp) != 2) {
    std::fclose(fp);
    return -2;
  }
  size_t total = static_cast<size_t>(n_rows) * dim;
  size_t got = std::fwrite(data, elem_size, total, fp);
  std::fclose(fp);
  return got == total ? 0 : -3;
}

// ---------------------------------------------------------------------------
// Host refine (reference: refine_host, detail/refine.cuh:169)
// metric: 0 = L2 (squared), 1 = inner product (negated for ascending sort)
// ---------------------------------------------------------------------------

int rt_refine_host_f32(const float* dataset, int64_t n, int64_t d,
                       const float* queries, int64_t m,
                       const int32_t* candidates, int64_t k_in,
                       int32_t* out_idx, float* out_dist, int64_t k_out,
                       int metric) {
  if (k_out > k_in) return -1;
  std::atomic<int> err{0};
  parallel_for(m, [&](int64_t i) {
    const float* q = queries + i * d;
    std::vector<std::pair<float, int32_t>> scored(k_in);
    for (int64_t j = 0; j < k_in; ++j) {
      int32_t id = candidates[i * k_in + j];
      if (id < 0 || id >= n) {
        scored[j] = {HUGE_VALF, -1};
        continue;
      }
      const float* v = dataset + static_cast<int64_t>(id) * d;
      float acc = 0.f;
      if (metric == 1) {
        for (int64_t c = 0; c < d; ++c) acc -= q[c] * v[c];
      } else {
        for (int64_t c = 0; c < d; ++c) {
          float diff = q[c] - v[c];
          acc += diff * diff;
        }
      }
      scored[j] = {acc, id};
    }
    std::partial_sort(scored.begin(), scored.begin() + k_out, scored.end());
    for (int64_t j = 0; j < k_out; ++j) {
      out_dist[i * k_out + j] =
          (metric == 1 && scored[j].second >= 0) ? -scored[j].first : scored[j].first;
      out_idx[i * k_out + j] = scored[j].second;
    }
  });
  return err.load();
}

// ---------------------------------------------------------------------------
// Host merge of per-shard top-k lists (reference: knn_merge_parts)
// part_dists: (n_parts, m, k); ids already global. select_min: 1 = ascending.
// ---------------------------------------------------------------------------

int rt_knn_merge_parts_f32(const float* part_dists, const int32_t* part_ids,
                           int64_t n_parts, int64_t m, int64_t k_in,
                           float* out_dist, int32_t* out_idx, int64_t k_out,
                           int select_min) {
  if (k_out > n_parts * k_in) return -1;
  parallel_for(m, [&](int64_t i) {
    std::vector<std::pair<float, int32_t>> all(n_parts * k_in);
    for (int64_t p = 0; p < n_parts; ++p) {
      const float* dsrc = part_dists + (p * m + i) * k_in;
      const int32_t* isrc = part_ids + (p * m + i) * k_in;
      for (int64_t j = 0; j < k_in; ++j) {
        float v = dsrc[j];
        all[p * k_in + j] = {select_min ? v : -v, isrc[j]};
      }
    }
    std::partial_sort(all.begin(), all.begin() + k_out, all.end());
    for (int64_t j = 0; j < k_out; ++j) {
      out_dist[i * k_out + j] = select_min ? all[j].first : -all[j].first;
      out_idx[i * k_out + j] = all[j].second;
    }
  });
  return 0;
}

}  // extern "C"
