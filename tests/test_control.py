"""Self-driving serving plane (ISSUE 18, tier-1 ``control`` marker).

The :class:`raft_tpu.control.Controller`'s contracts, each deterministic
(injected clocks, the journal's test ``configure()``, faults via
:mod:`raft_tpu.testing.faults`, no wall sleeps):

- sensor events queue at the journal tap and actuate in :meth:`step`,
  with the causal seq chain (sensor → ``control/decision`` → outcome
  event, plus the ``cause`` dict inside the actuator's own events)
  asserted end to end;
- retune: drift advice → bounded sweep → ``tuned=`` republish through
  the warm-before-flip seam; failures (sweep raise, budget refusal)
  leave the registry serving its previous version, journal as
  ``control/action_failed`` with the error inline, and arm the cooldown;
- reshard: advice → topology doubling under headroom/burn admission;
  a fault at every ``reshard/*`` fault point aborts cleanly with the
  mesh still serving its old topology;
- degrade/restore: latency burn flips a watched name to its cheap
  operating point and hysteresis restores the pin only after the burn
  stays clear for ``restore_clear_s``;
- bounds: per-action cooldowns, the single heavy-actuation slot,
  ``dry_run``, the bounded tap queue;
- the r5 non-transfer hard guard refuses any cross-balance-class
  publish;
- observability: ``status()``, ``/debug/control``, the ``/healthz``
  fold, and the 404 contract listing the new endpoint.
"""

import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import obs, stream, tune
from raft_tpu.control import Controller, ControlPolicy, NonTransferError
from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.obs import events, mem as obs_mem
from raft_tpu.obs.http import MetricsExporter
from raft_tpu.obs.slo import SLOPolicy, SLOTracker
from raft_tpu.serve import IndexRegistry
from raft_tpu.testing import faults
from raft_tpu.tune import Decision, reference

pytestmark = pytest.mark.control


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _fresh_journal():
    obs.enable()
    events.configure(capacity=2048)
    yield
    events.disarm_flight_recorder()
    events.configure(capacity=2048)
    obs.enable()


@pytest.fixture(scope="module")
def corpus():
    """One small ivf_flat family shared by the retune/degrade tests."""
    x, q = reference._clustered(3000, 32, 48, 64, seed=3)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=32, seed=0), x)
    return {"x": x, "q": np.asarray(q)[:8], "idx": idx,
            "family": tune.family_of(idx, x)}


GRID = [{"n_probes": 8}, {"n_probes": 4}]


def make_registry():
    # one warm bucket keeps every publish's compile spend small
    return IndexRegistry(buckets=(8,))


def watched(corpus, clk, *, dry_run=False, policy=None, slo=None,
            res=None, **watch_kw):
    reg = make_registry()
    reg.publish("live", corpus["idx"], k=5, warm_data=corpus["x"][:64])
    ctl = Controller(publisher=reg, clock=clk, slo=slo, res=res,
                     dry_run=dry_run, policy=policy or ControlPolicy())
    ctl.watch("live", corpus["idx"], corpus["q"], dataset=corpus["x"],
              k=5, ks=(5,), grid=GRID, repeats=1, **watch_kw)
    return reg, ctl


def advise_retune(name="live"):
    return events.emit("retune_advised", subject=("quality", name),
                       evidence={"drifted": True, "scale_cv": 1.4,
                                 "observed": "1k-d32-skew"})


def bf_build(x):
    return brute_force.BruteForce().build(jnp.asarray(x))


def make_mesh(rng, n=280, shards=2, **kw):
    data = rng.standard_normal((n, 16)).astype(np.float32)
    mesh = stream.ShardedMutableIndex(data, n_shards=shards,
                                      build=bf_build, delta_capacity=64,
                                      **kw)
    q = rng.standard_normal((3, 16)).astype(np.float32)
    return mesh, q


def advise_reshard(mesh, target):
    return events.emit(
        "reshard_advised", subject=("compactor", mesh.name),
        evidence={"action": "split", "target": int(target),
                  "watermark": "reshard_rows_per_shard", "threshold": 100,
                  "rows_per_shard": 140.0, "shards": mesh.n_shards,
                  "live": 280, "auto_apply": False})


def hot_slo(clk, bad=4):
    """A tracker whose latency burn is far over every threshold."""
    slo = SLOTracker(SLOPolicy(windows_s=(60.0,), slot_s=30.0,
                               latency_bound_s=0.1), clock=clk)
    for _ in range(bad):
        slo.record_request(1.0, 1.0)
    return slo


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# retune loop
# ---------------------------------------------------------------------------


class TestRetune:
    def test_happy_path_causal_chain_and_cooldown(self, corpus):
        clk = FakeClock()
        reg, ctl = watched(corpus, clk)
        ctl.arm()
        sensor = advise_retune()
        assert ctl.step() == 1

        dec = events.query(kind="control/decision")[-1]
        assert dec["evidence"]["action"] == "retune"
        assert dec["evidence"]["trigger_seq"] == sensor["seq"]
        # the triggering evidence rides INLINE — replayable from the
        # journal alone
        assert dec["evidence"]["trigger"]["scale_cv"] == 1.4

        done = events.query(kind="control/action_completed")[-1]
        assert done["evidence"]["decision_seq"] == dec["seq"]
        assert done["evidence"]["trigger_seq"] == sensor["seq"]
        assert done["evidence"]["params"] in GRID
        assert done["evidence"]["version"] == 2

        # the republish itself carries the cause — the chain closes
        # inside the registry's own event
        pub = events.query(kind="serve_published")[-1]
        assert pub["evidence"]["cause"]["decision_seq"] == dec["seq"]
        assert pub["evidence"]["cause"]["trigger_seq"] == sensor["seq"]
        assert reg.active("live").version == 2

        st = ctl.status()
        assert st["last_action"]["action"] == "retune"
        assert st["last_action"]["outcome"] == "completed"
        assert st["cooldowns"]["retune"] > 0

        # within the cooldown a second advisory only logs a skip
        advise_retune()
        ctl.step()
        skip = events.query(kind="control/skipped")[-1]
        assert skip["evidence"]["reason"] == "cooldown"
        assert skip["evidence"]["retry_after_s"] > 0
        assert reg.active("live").version == 2

        # past the cooldown it acts again
        clk.advance(ctl.policy.retune_cooldown_s + 1)
        advise_retune()
        ctl.step()
        assert reg.active("live").version == 3

    def test_dry_run_logs_decision_without_acting(self, corpus):
        clk = FakeClock()
        reg, ctl = watched(corpus, clk, dry_run=True)
        ctl.arm()
        advise_retune()
        ctl.step()
        dec = events.query(kind="control/decision")[-1]
        assert dec["evidence"]["dry_run"] is True
        assert events.query(kind="control/action_completed") == []
        assert reg.active("live").version == 1
        assert ctl.status()["actions"]["retune"]["dry_run"] == 1

    def test_unwatched_name_is_ignored(self, corpus):
        clk = FakeClock()
        reg, ctl = watched(corpus, clk)
        ctl.arm()
        advise_retune(name="someone-else")
        assert ctl.step() == 1
        assert events.query(kind="control/decision") == []
        assert events.query(kind="control/skipped") == []

    def test_inflight_slot_refuses_second_heavy_action(self, corpus):
        clk = FakeClock()
        reg, ctl = watched(corpus, clk)
        ctl.arm()
        advise_retune()
        with ctl._heavy("reshard"):
            ctl.step()
        skip = events.query(kind="control/skipped")[-1]
        assert skip["evidence"]["reason"] == "inflight"
        assert skip["evidence"]["inflight"] == "reshard"
        assert reg.active("live").version == 1

    def test_sweep_raise_leaves_registry_serving_and_arms_cooldown(
            self, corpus):
        clk = FakeClock()
        reg, ctl = watched(corpus, clk)
        # poison the actuator: queries of the wrong dim crash the sweep
        ctl._targets["live"].queries = corpus["q"][:, :16]
        ctl.arm()
        advise_retune()
        ctl.step()
        fail = events.query(kind="control/action_failed")[-1]
        assert fail["severity"] == "error"
        assert fail["evidence"]["outcome"] == "failed"
        assert fail["evidence"]["error"]
        assert reg.active("live").version == 1  # old version still live
        st = ctl.status()
        assert st["last_action"]["outcome"] == "failed"
        assert st["cooldowns"]["retune"] > 0  # no retry storm

    def test_budget_refusal_republish_leaves_registry_serving(
            self, corpus, tmp_path):
        class Tiny:
            memory_budget_bytes = 1  # any publish admission refuses
            host_budget_bytes = None

        clk = FakeClock()
        events.arm_flight_recorder(str(tmp_path), min_interval_s=0.0)
        reg, ctl = watched(corpus, clk, res=Tiny())
        ctl.arm()
        advise_retune()
        ctl.step()
        fail = events.query(kind="control/action_failed")[-1]
        assert "MemoryBudgetError" in fail["evidence"]["error"]
        assert fail["evidence"]["trigger"]["drifted"] is True
        assert reg.active("live").version == 1
        # the armed flight recorder bundled the incident
        assert any(tmp_path.iterdir())


# ---------------------------------------------------------------------------
# reshard loop
# ---------------------------------------------------------------------------


class TestReshard:
    def test_happy_path_doubles_topology_with_cause_chain(self, rng):
        clk = FakeClock()
        mesh, q = make_mesh(rng)
        ctl = Controller(clock=clk)
        ctl.attach_mesh(mesh, warm_buckets=(3,), ks=(3,))
        ctl.arm()
        sensor = advise_reshard(mesh, 4)
        assert ctl.step() == 1
        assert mesh.n_shards == 4

        dec = events.query(kind="control/decision")[-1]
        assert dec["evidence"]["trigger_seq"] == sensor["seq"]
        assert dec["evidence"]["trigger"]["rows_per_shard"] == 140.0
        started = events.query(kind="reshard_started")[-1]
        assert started["evidence"]["cause"]["trigger_seq"] == sensor["seq"]
        assert started["evidence"]["cause"]["decision_seq"] == dec["seq"]
        done = events.query(kind="control/action_completed")[-1]
        assert done["evidence"]["from"] == 2 and done["evidence"]["to"] == 4
        assert done["evidence"]["decision_seq"] == dec["seq"]
        # still serving
        d, i = mesh.search(q, 3)
        assert np.asarray(i).shape == (3, 3)

    def test_stale_advice_skipped(self, rng):
        clk = FakeClock()
        mesh, _ = make_mesh(rng)
        ctl = Controller(clock=clk)
        ctl.attach_mesh(mesh)
        ctl.arm()
        advise_reshard(mesh, 2)  # already at 2 shards
        ctl.step()
        skip = events.query(kind="control/skipped")[-1]
        assert skip["evidence"]["reason"] == "stale"
        assert mesh.n_shards == 2

    def test_headroom_refusal_with_evidence_inline(self, rng):
        class Budget:
            memory_budget_bytes = 100_000_000
            host_budget_bytes = None

        clk = FakeClock()
        mesh, _ = make_mesh(rng)
        ctl = Controller(clock=clk, res=Budget())
        ctl.attach_mesh(mesh)
        ctl.arm()
        hog = obs_mem.account("index/test", name="hog",
                              device_bytes=95_000_000)
        try:
            advise_reshard(mesh, 4)
            ctl.step()
        finally:
            obs_mem.release(hog)
        skip = events.query(kind="control/skipped")[-1]
        assert skip["evidence"]["reason"] == "headroom"
        assert skip["evidence"]["headroom_frac"] < 0.10
        assert skip["evidence"]["budget_bytes"] == 100_000_000
        assert mesh.n_shards == 2

    def test_slo_burn_refusal(self, rng):
        clk = FakeClock()
        mesh, _ = make_mesh(rng)
        slo = hot_slo(clk)
        # degrade loop off (no watched targets) — only the admission runs
        ctl = Controller(clock=clk, slo=slo)
        ctl.attach_mesh(mesh)
        ctl.arm()
        advise_reshard(mesh, 4)
        ctl.step()
        skip = events.query(kind="control/skipped")[-1]
        assert skip["evidence"]["reason"] == "slo_burn"
        assert skip["evidence"]["burn"]["latency"] >= 1.0
        assert mesh.n_shards == 2

    @pytest.mark.parametrize("point", ["reshard/split", "reshard/flip",
                                       "reshard/manifest"])
    def test_fault_aborts_cleanly_mesh_keeps_serving(self, rng, tmp_path,
                                                     point):
        clk = FakeClock()
        mesh, q = make_mesh(rng, wal_dir=str(tmp_path / "wal"))
        before = np.asarray(mesh.search(q, 3)[1])
        ctl = Controller(clock=clk)
        ctl.attach_mesh(mesh)
        ctl.arm()
        events.arm_flight_recorder(str(tmp_path / "rec"),
                                   min_interval_s=0.0)
        with faults.scope():
            faults.inject(point, exc=faults.FaultError(f"boom@{point}"))
            advise_reshard(mesh, 4)
            ctl.step()
        # the mesh still serves its OLD topology, bit-identically
        assert mesh.n_shards == 2
        np.testing.assert_array_equal(np.asarray(mesh.search(q, 3)[1]),
                                      before)
        fail = events.query(kind="control/action_failed")[-1]
        assert "boom@" in fail["evidence"]["error"]
        assert fail["evidence"]["trigger"]["target"] == 4
        assert ctl.status()["cooldowns"]["reshard"] > 0
        assert any((tmp_path / "rec").iterdir())


# ---------------------------------------------------------------------------
# degrade / restore (the burn loop)
# ---------------------------------------------------------------------------


class TestDegradeRestore:
    def test_degrade_then_hysteresis_restore(self, corpus):
        clk = FakeClock()
        slo = hot_slo(clk)
        pin = Decision(kind="ivf_flat", dtype="float32",
                       family=corpus["family"], params={"n_probes": 8})
        policy = ControlPolicy(degrade_cooldown_s=5.0,
                               restore_clear_s=120.0)
        reg, ctl = watched(corpus, clk, slo=slo, policy=policy,
                           decision=pin, degrade_params={"n_probes": 2})
        ctl.arm()
        ctl.step()  # burn loop sees a hot window
        deg = events.query(kind="control/degraded")[-1]
        assert deg["severity"] == "warning"
        assert deg["evidence"]["params"] == {"n_probes": 2}
        assert deg["evidence"]["pinned"] == pin.key
        assert deg["evidence"]["trigger_kind"] == "slo_burn"
        assert deg["evidence"]["trigger"]["burn"]["latency"] >= 1.0
        assert reg.active("live").version == 2
        assert ctl.status()["degraded"] == ["live"]

        # still hot: no restore, no re-degrade (the pinned flag holds)
        clk.advance(10.0)
        slo.record_request(1.0, 1.0)
        ctl.step()
        assert events.query(kind="control/restored") == []
        assert reg.active("live").version == 2

        # burn clears (the ring ages out) — hysteresis holds the restore
        # until the clear persists for restore_clear_s
        clk.advance(100.0)
        ctl.step()  # clear observed: clock starts
        assert events.query(kind="control/restored") == []
        clk.advance(60.0)
        ctl.step()  # 60 < 120: still holding
        assert events.query(kind="control/restored") == []
        clk.advance(70.0)
        ctl.step()  # 130 >= 120: restore
        res = events.query(kind="control/restored")[-1]
        assert res["evidence"]["pinned"] == pin.key
        assert res["evidence"]["trigger_kind"] == "slo_burn_cleared"
        assert reg.active("live").version == 3
        assert ctl.status()["degraded"] == []

    def test_no_cheaper_point_skips_once_per_cooldown(self, corpus):
        clk = FakeClock()
        slo = hot_slo(clk)
        # no decision, no degrade_params: nothing cheaper exists
        reg, ctl = watched(corpus, clk, slo=slo)
        ctl.arm()
        ctl.step()
        ctl.step()  # the armed cooldown keeps the skip from repeating
        skips = [e for e in events.query(kind="control/skipped")
                 if e["evidence"]["reason"] == "no_cheaper_point"]
        assert len(skips) == 1
        assert reg.active("live").version == 1

    def test_non_transfer_guard_refuses_cross_class_restore(self, corpus):
        clk = FakeClock()
        slo = hot_slo(clk)
        wrong = corpus["family"].rsplit("-", 1)[0] + "-clump"
        pin = Decision(kind="ivf_flat", dtype="float32", family=wrong,
                       params={"n_probes": 8})
        reg, ctl = watched(corpus, clk, slo=slo, decision=pin,
                           degrade_params={"n_probes": 2})
        with pytest.raises(NonTransferError, match="never transfer"):
            ctl._guard_transfer(pin, ctl._targets["live"])
        # end to end: the degrade actuation hits the guard and records
        # the refusal as a failed action — the registry is untouched
        ctl.arm()
        ctl.step()
        fail = events.query(kind="control/action_failed")[-1]
        assert "NonTransferError" in fail["evidence"]["error"]
        assert reg.active("live").version == 1


# ---------------------------------------------------------------------------
# compaction pacing (satellite: Compactor.set_pacing)
# ---------------------------------------------------------------------------


class TestCompactionPacing:
    def _due_compactor(self, rng, clk, **kw):
        data = rng.standard_normal((64, 16)).astype(np.float32)
        m = stream.MutableIndex(bf_build(data), delta_capacity=16,
                                clock=clk)
        comp = stream.Compactor(
            m, policy=stream.CompactionPolicy(delta_fill=0.5,
                                              tombstone_ratio=None),
            clock=clk, **kw)
        m.upsert(data[:8] + 0.5)
        assert comp.due() == "delta_fill"
        return m, comp

    def test_controller_burn_defers_then_releases(self, rng):
        clk = FakeClock()
        slo = hot_slo(clk)
        ctl = Controller(clock=clk, slo=slo)
        m, comp = self._due_compactor(rng, clk)
        ctl.attach_compactor(comp)
        assert comp.run_once() is None  # hot: deferred, not folded
        assert comp.last_deferred == "delta_fill"
        assert comp.due() == "delta_fill"  # the debt is still due
        # force overrides pacing (the back-pressure escape hatch)
        rep = comp.run_once(force=True)
        assert rep is not None and rep["folded"] == 8

    def test_burn_clear_lets_the_fold_run(self, rng):
        clk = FakeClock()
        slo = hot_slo(clk)
        ctl = Controller(clock=clk, slo=slo)
        m, comp = self._due_compactor(rng, clk)
        ctl.attach_compactor(comp)
        assert comp.run_once() is None
        clk.advance(120.0)  # the burn window ages out
        rep = comp.run_once()
        assert rep is not None and rep["trigger"] == "delta_fill"

    def test_default_behavior_unchanged_without_hint(self, rng):
        clk = FakeClock()
        m, comp = self._due_compactor(rng, clk)
        rep = comp.run_once()
        assert rep is not None and rep["folded"] == 8
        assert comp.last_deferred is None

    def test_raising_pacing_hint_never_blocks_the_fold(self, rng):
        clk = FakeClock()

        def bad_hint():
            raise RuntimeError("sensor down")

        m, comp = self._due_compactor(rng, clk, pacing=bad_hint)
        rep = comp.run_once()  # a broken sensor must not wedge compaction
        assert rep is not None and rep["folded"] == 8


# ---------------------------------------------------------------------------
# bounds + observability
# ---------------------------------------------------------------------------


class TestBoundsAndObservability:
    def test_bounded_tap_queue_counts_drops(self, corpus):
        clk = FakeClock()
        reg, ctl = watched(corpus, clk,
                           policy=ControlPolicy(queue_capacity=2))
        ctl.arm()
        for _ in range(3):
            advise_retune(name="nobody")
        st = ctl.status()
        assert st["queue"] == 2 and st["queue_dropped"] == 1

    def test_drift_report_carries_replay_evidence(self):
        """Satellite: the retune_advised evidence is replayable from the
        journal alone — thresholds and both balance classes inline."""
        from raft_tpu.obs import quality

        hot, _ = reference._clustered(2000, 32, 8, 64, seed=29,
                                      heavytail=True)
        det = quality.DriftDetector(tune.shape_family(2000, 32, "bal"),
                                    name="ctl-drift", min_rows=256)
        det.offer_rows(np.asarray(hot)[:1024])
        rep = det.check()
        assert rep["drifted"]
        ev = events.query(kind="retune_advised")[-1]["evidence"]
        assert ev["scale_cv_threshold"] == 0.75
        assert ev["pinned_balance"] == "bal"
        assert ev["observed_balance"] == "skew"
        assert ev["scale_cv"] > 0.75

    def test_debug_control_endpoint_and_healthz_fold(self, corpus):
        clk = FakeClock()
        reg, ctl = watched(corpus, clk, dry_run=True)
        ctl.arm()
        advise_retune()
        ctl.step()
        with MetricsExporter(port=0, controller=ctl) as exp:
            import json

            code, body = _get(f"http://127.0.0.1:{exp.port}/debug/control")
            assert code == 200
            payload = json.loads(body)
            assert payload["controller"]["dry_run"] is True
            assert payload["controller"]["targets"] == ["live"]
            kinds = {e["kind"] for e in payload["recent"]}
            assert "control/decision" in kinds
            code, body = _get(f"http://127.0.0.1:{exp.port}/healthz")
            assert code == 200
            h = json.loads(body)
            assert h["control"]["enabled"] is True
            assert h["control"]["dry_run"] is True
            # 404 contract: unknown paths name every endpoint
            code, body = _get(f"http://127.0.0.1:{exp.port}/nope")
            assert code == 404 and "/debug/control" in body

    def test_debug_control_404_without_controller(self):
        with MetricsExporter(port=0) as exp:
            code, body = _get(f"http://127.0.0.1:{exp.port}/debug/control")
            assert code == 404 and "controller=" in body

    def test_start_stop_worker_lifecycle(self, corpus):
        clk = FakeClock()
        reg, ctl = watched(corpus, clk, dry_run=True)
        ctl.start()
        assert ctl.status()["enabled"]
        ctl.stop()
        assert not ctl.status()["enabled"]
