"""Serving-layer tests (tier-1 ``serve`` marker).

Deterministic by construction: the service/batcher take an injected clock
and run with ``start_workers=False``, driven by ``pump()`` — queue policy
(deadlines, buckets, occupancy, overload) is asserted without a single
wall-clock sleep. The two concurrency tests (hot-swap under load, worker
liveness) use real threads but synchronize on futures/joins, never sleeps.
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.serve import (DeadlineExceededError, IndexRegistry,
                            MicroBatcher, OverloadedError, SearchService,
                            ServiceClosedError, bucket_for, bucket_sizes)

pytestmark = pytest.mark.serve


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def dataset(rng):
    return rng.standard_normal((512, 16)).astype(np.float32)


@pytest.fixture
def bf(dataset):
    return brute_force.BruteForce().build(dataset)


def det_service(bf_index, clock, *, max_batch=8, max_wait_us=1000.0,
                max_queue_rows=32, warm=False, **kw):
    """A deterministic service: injected clock, no worker threads."""
    svc = SearchService(max_batch=max_batch, max_wait_us=max_wait_us,
                        max_queue_rows=max_queue_rows, clock=clock,
                        start_workers=False, **kw)
    svc.publish("main", bf_index, k=5, warm=warm)
    return svc


# -- bucket ladder ----------------------------------------------------------

def test_bucket_ladder():
    assert bucket_sizes(64) == (1, 2, 4, 8, 16, 32, 64)
    assert bucket_sizes(1) == (1,)
    with pytest.raises(RaftError):
        bucket_sizes(48)  # not a power of two
    assert [bucket_for(n, 64) for n in (1, 2, 3, 5, 33, 64)] == \
        [1, 2, 4, 8, 64, 64]


# -- batching semantics -----------------------------------------------------

def test_single_row_flushes_after_max_wait(bf, dataset):
    clock = FakeClock()
    svc = det_service(bf, clock, max_wait_us=1000.0)
    fut = svc.submit("main", dataset[:1], 5)
    # deadline not reached: pump() must NOT flush (the request is waiting
    # for companions)
    assert svc.pump() == 0 and not fut.done()
    clock.advance(0.0011)  # past max_wait_us
    assert svc.pump() == 1
    d, i = fut.result(timeout=0)
    assert d.shape == (1, 5) and int(np.asarray(i)[0, 0]) == 0


def test_exactly_max_batch_flushes_immediately(bf, dataset):
    clock = FakeClock()
    svc = det_service(bf, clock, max_batch=8)
    futs = [svc.submit("main", dataset[j:j + 1], 5) for j in range(8)]
    # queue holds exactly max_batch rows -> ready with NO clock advance
    assert svc.pump() == 8
    assert all(f.done() for f in futs)
    # full bucket: occupancy 1.0, no padding
    from raft_tpu import obs

    assert obs.quantile("raft_tpu_serve_batch_occupancy", 0.5,
                        stream="main.k5") == pytest.approx(1.0, abs=0.26)


def test_scatter_matches_unbatched_results(bf, dataset):
    """Rows batched together must get exactly the rows they submitted —
    the scatter is the correctness core of the batcher."""
    clock = FakeClock()
    svc = det_service(bf, clock, max_batch=16)
    blocks = [dataset[0:3], dataset[3:4], dataset[4:9], dataset[9:16]]
    futs = [svc.submit("main", b, 5) for b in blocks]
    assert svc.pump() == 16
    ref_d, ref_i = bf.search(jnp.asarray(dataset[:16]), 5)
    off = 0
    for b, f in zip(blocks, futs):
        d, i = f.result(timeout=0)
        np.testing.assert_array_equal(np.asarray(i),
                                      np.asarray(ref_i)[off:off + len(b)])
        np.testing.assert_allclose(np.asarray(d),
                                   np.asarray(ref_d)[off:off + len(b)],
                                   rtol=1e-5)
        off += len(b)


def test_partial_batch_pads_to_bucket(bf, dataset):
    clock = FakeClock()
    svc = det_service(bf, clock, max_batch=8)
    fut = svc.submit("main", dataset[:3], 5)
    clock.advance(0.01)
    assert svc.pump() == 3  # 3 valid rows -> bucket 4, padded
    d, _ = fut.result(timeout=0)
    assert d.shape == (3, 5)
    from raft_tpu import obs

    # occupancy 3/4 recorded for the padded flush
    q = obs.quantile("raft_tpu_serve_batch_occupancy", 0.5, stream="main.k5")
    assert 0.5 < q <= 1.0


def test_oversized_request_refused(bf, dataset):
    svc = det_service(bf, FakeClock(), max_batch=4)
    with pytest.raises(RaftError):
        svc.submit("main", dataset[:5], 5)


# -- deadlines --------------------------------------------------------------

def test_deadline_expiry_mid_queue_drops_before_batching(bf, dataset):
    """The expired request must be dropped at drain WITHOUT reaching the
    searcher, and its queue-mates must still be served."""
    calls = []

    def spy(queries, k):
        calls.append(int(queries.shape[0]))
        return bf.search(queries, k)

    spy.kind, spy.dim, spy.query_dtype = "spy", 16, "float32"
    clock = FakeClock()
    svc = SearchService(max_batch=8, max_wait_us=100.0, clock=clock,
                        start_workers=False)
    svc.publish("main", spy, k=5, warm=False)
    f_dead = svc.submit("main", dataset[:2], 5, timeout_s=0.005)
    f_live = svc.submit("main", dataset[2:3], 5)  # no deadline
    clock.advance(0.01)  # past both max_wait and f_dead's deadline
    assert svc.pump() == 1  # only the live row flushed
    with pytest.raises(DeadlineExceededError):
        f_dead.result(timeout=0)
    assert f_live.result(timeout=0)[0].shape == (1, 5)
    # the expired rows never hit the device: one flush, bucket 1
    assert calls == [1]


def test_submit_with_expired_timeout_fast_fails(bf, dataset):
    svc = det_service(bf, FakeClock())
    with pytest.raises(DeadlineExceededError):
        svc.submit("main", dataset[:1], 5, timeout_s=0.0)
    assert svc.queue_depth() == 0  # nothing was enqueued


# -- admission control ------------------------------------------------------

def test_overload_fast_fail(bf, dataset):
    clock = FakeClock()
    svc = det_service(bf, clock, max_batch=4, max_queue_rows=6)
    for j in range(6):
        svc.submit("main", dataset[j:j + 1], 5)
    with pytest.raises(OverloadedError):
        svc.submit("main", dataset[:1], 5)
    # a multi-row request crossing the bound is refused too
    svc2 = det_service(bf, clock, max_batch=4, max_queue_rows=6)
    svc2.submit("main", dataset[:4], 5)
    with pytest.raises(OverloadedError):
        svc2.submit("main", dataset[:3], 5)
    # draining reopens admission
    assert svc.pump(force=True) > 0
    while svc.pump(force=True):
        pass
    svc.submit("main", dataset[:1], 5)  # admitted again


def test_unknown_name_rejected(bf, dataset):
    svc = det_service(bf, FakeClock())
    with pytest.raises(RaftError):
        svc.submit("nope", dataset[:1], 5)


# -- shutdown ---------------------------------------------------------------

def test_shutdown_with_nonempty_queue_drains(bf, dataset):
    clock = FakeClock()
    svc = det_service(bf, clock, max_batch=8)
    futs = [svc.submit("main", dataset[j:j + 1], 5) for j in range(3)]
    svc.shutdown(drain=True)
    for f in futs:
        assert f.result(timeout=0)[0].shape == (1, 5)
    with pytest.raises(ServiceClosedError):
        svc.submit("main", dataset[:1], 5)


def test_shutdown_without_drain_fails_pending(bf, dataset):
    clock = FakeClock()
    svc = det_service(bf, clock)
    futs = [svc.submit("main", dataset[j:j + 1], 5) for j in range(3)]
    svc.shutdown(drain=False)
    for f in futs:
        with pytest.raises(ServiceClosedError):
            f.result(timeout=0)
    assert svc.queue_depth() == 0


# -- registry / hot-swap ----------------------------------------------------

def test_publish_warms_every_bucket(bf):
    reg = IndexRegistry(buckets=(1, 2, 4))
    rep = reg.publish("main", bf, k=(5, 3))
    assert rep["version"] == 1
    for kk in (5, 3):
        assert sorted(rep["warm"][kk]) == [1, 2, 4]
        for phase in rep["warm"][kk].values():
            assert phase["wall_s"] >= 0.0 and "compile_s" in phase
    # a re-publish of a same-shape index finds every program warm: the jit
    # cache keys on HLO, and the fresh index matches it bucket for bucket
    bf2 = brute_force.BruteForce().build(np.asarray(bf.dataset)[::-1].copy())
    rep2 = reg.publish("main", bf2, k=(5, 3))
    assert rep2["version"] == 2
    for kk in (5, 3):
        for phase in rep2["warm"][kk].values():
            assert phase["compile_s"] == 0.0 and phase["cache_misses"] == 0


def test_swap_retires_old_version_after_lease_drain(bf, dataset):
    reg = IndexRegistry(buckets=(1,))
    reg.publish("main", bf, k=5, warm=False)
    v1 = reg.active("main")
    with reg.lease("main") as leased:
        assert leased is v1
        bf2 = brute_force.BruteForce().build(dataset)
        reg.publish("main", bf2, k=5, warm=False)
        # v1 still leased: both versions live
        assert reg.live_versions("main") == (1, 2)
        assert leased.searcher is not None  # usable mid-swap
    # lease released -> v1 retired, arrays droppable
    assert reg.live_versions("main") == (2,)
    assert v1.searcher is None


def test_lease_survives_retire_while_publish_mints_new_version(bf, dataset):
    """ISSUE 11 satellite: a lease held across a retire-after-drain while
    a CONCURRENT publish mints a new version. v1's lease is held while v2
    replaces v1 and v3 replaces v2 — v2 (unleased) retires inside v3's
    publish while v1 is still draining; the old lease must stay usable
    throughout and v1 must retire exactly at its release, untouched by
    the sibling retirement."""
    reg = IndexRegistry(buckets=(1,))
    reg.publish("main", bf, k=5, warm=False)
    v1 = reg.active("main")
    with reg.lease("main") as leased:
        reg.publish("main", brute_force.BruteForce().build(dataset),
                    k=5, warm=False)
        v2 = reg.active("main")
        reg.publish("main", brute_force.BruteForce().build(dataset),
                    k=5, warm=False)
        # v2 retired the moment v3 replaced it (zero leases); v1 still
        # drains on its lease; v3 is active
        assert reg.live_versions("main") == (1, 3)
        assert v2.searcher is None
        assert leased is v1 and leased.searcher is not None
        d, i = leased.searcher(dataset[:1], 5)
        assert np.asarray(i).shape == (1, 5)
    assert reg.live_versions("main") == (3,)
    assert v1.searcher is None  # released -> retired, arrays droppable


def test_raising_searcher_releases_lease_and_version_retires(bf, dataset):
    """ISSUE 11 satellite: a searcher that raises mid-flush must leave its
    lease RELEASED (the flush's lease is a context manager, but the gap
    was untested) so the version stays retirable — a leaked lease would
    pin the broken index's arrays forever."""
    from raft_tpu.neighbors._hooks import make_hook

    calls = []

    def boom(queries, k):
        calls.append(len(queries))
        raise RuntimeError("device fault mid-flush")

    clock = FakeClock()
    svc = SearchService(max_batch=4, max_wait_us=1.0, max_queue_rows=32,
                        clock=clock, start_workers=False)
    svc.publish("main", make_hook(boom, "custom", 16), k=5, warm=False)
    v1 = svc.registry.active("main")
    fut = svc.submit("main", dataset[:2], 5)
    clock.advance(1.0)
    svc.pump()
    with pytest.raises(RuntimeError, match="device fault"):
        fut.result(timeout=0)
    assert calls == [2] and v1.leases == 0  # lease released on the raise
    # the broken version is retirable: a republish drops it immediately
    svc.publish("main", make_hook(lambda q, k: boom(q, k), "custom", 16),
                k=5, warm=False)
    assert svc.registry.live_versions("main") == (2,)
    assert v1.searcher is None
    svc.shutdown()


def test_version_numbers_monotonic(bf):
    reg = IndexRegistry(buckets=(1,))
    reg.publish("main", bf, warm=False)
    reg.publish("main", bf, warm=False, version=7)
    with pytest.raises(RaftError):
        reg.publish("main", bf, warm=False, version=3)
    assert reg.active("main").version == 7


def test_hot_swap_under_concurrent_load_loses_nothing(bf, dataset):
    """The acceptance-critical property: a publish landing mid-load must
    not fail a single in-flight or queued request. Real worker + submitter
    threads; synchronization via futures only."""
    svc = SearchService(max_batch=8, max_wait_us=200.0, max_queue_rows=512)
    svc.publish("main", bf, k=5, warm=True)
    n_req, errors, done = 120, [], []
    lock = threading.Lock()

    def submitter(tid):
        for j in range(n_req // 4):
            try:
                d, i = svc.search("main", dataset[(tid * 31 + j) % 500:
                                                 (tid * 31 + j) % 500 + 1], 5)
                with lock:
                    done.append(int(np.asarray(i)[0, 0]))
            except Exception as e:  # any failure is a test failure
                with lock:
                    errors.append(repr(e))

    threads = [threading.Thread(target=submitter, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    # two swaps while the load is in flight
    for _ in range(2):
        bf2 = brute_force.BruteForce().build(dataset)
        svc.publish("main", bf2, k=5)
    for t in threads:
        t.join(60)
        assert not t.is_alive(), "submitter wedged"
    svc.shutdown()
    assert errors == []
    assert len(done) == n_req
    # old versions drained and retired; only the last survives
    assert len(svc.registry.live_versions("main")) == 1


# -- all four index kinds through the registry ------------------------------

def test_all_index_kinds_publishable(dataset):
    reg = IndexRegistry(buckets=(1, 2))
    x = jnp.asarray(dataset)
    idxs = {
        "bf": brute_force.BruteForce().build(x),
        "flat": ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), x),
        "pq": ivf_pq.build(
            ivf_pq.IndexParams(n_lists=8, pq_bits=4, pq_dim=8, seed=0), x),
        "cagra": cagra.build(cagra.IndexParams(seed=0), x),
    }
    params = {"flat": ivf_flat.SearchParams(n_probes=8),
              "pq": ivf_pq.SearchParams(n_probes=8),
              "cagra": cagra.SearchParams(itopk_size=32)}
    for name, idx in idxs.items():
        rep = reg.publish(name, idx, search_params=params.get(name), k=4)
        assert rep["version"] == 1 and 1 in rep["warm"][4]
        with reg.lease(name) as v:
            d, i = v.searcher(x[:2], 4)
            assert d.shape == (2, 4) and i.shape == (2, 4)


def test_byte_index_serves_byte_queries(rng):
    """int8 datasets publish + serve through the same path (the PR 1 byte
    pipeline): warmup draws int8 queries, submit enforces the dtype."""
    xb = rng.integers(-128, 128, (256, 16), dtype=np.int8)
    idx = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=4, list_dtype="int8", seed=0), xb)
    assert idx.data_kind == "int8"
    clock = FakeClock()
    svc = SearchService(max_batch=2, clock=clock, start_workers=False)
    rep = svc.publish("bytes", idx,
                      search_params=ivf_flat.SearchParams(n_probes=4), k=3)
    assert 1 in rep["warm"][3]
    fut = svc.submit("bytes", xb[:1], 3)
    clock.advance(1.0)
    assert svc.pump() == 1
    assert fut.result(timeout=0)[1].shape == (1, 3)
    with pytest.raises(RaftError):  # f32 queries against a byte index
        svc.submit("bytes", np.zeros((1, 16), np.float32), 3)


# -- direct batcher edge cases ----------------------------------------------

def test_batcher_flush_error_fails_whole_batch(dataset):
    def boom(q):
        raise ValueError("kernel exploded")

    clock = FakeClock()
    b = MicroBatcher(boom, max_batch=4, clock=clock, start=False)
    futs = [b.submit(jnp.asarray(dataset[:1])) for _ in range(2)]
    clock.advance(1.0)
    b.pump()
    for f in futs:
        with pytest.raises(ValueError):
            f.result(timeout=0)


def test_batcher_worker_thread_flushes(bf, dataset):
    """Liveness of the real worker: a submitted row completes without any
    pump() call. Bounded by the future's own timeout, not a sleep."""
    b = MicroBatcher(lambda q: bf.search(q, 5), max_batch=4,
                     max_wait_us=500.0, start=True)
    fut = b.submit(jnp.asarray(dataset[:1]))
    d, i = fut.result(timeout=30)
    assert d.shape == (1, 5)
    b.close()


def test_metrics_catalogue(bf, dataset):
    """The serve metric names the docs promise exist and move."""
    from raft_tpu import obs

    clock = FakeClock()
    svc = det_service(bf, clock, max_batch=4, max_queue_rows=4, warm=True)
    svc.submit("main", dataset[:1], 5)
    clock.advance(1.0)
    svc.pump()
    for j in range(4):
        svc.submit("main", dataset[j:j + 1], 5)
    with pytest.raises(OverloadedError):
        svc.submit("main", dataset[:1], 5)
    js = obs.to_json()
    for needed in (
            'raft_tpu_serve_queue_depth{stream="main.k5"}',
            'raft_tpu_serve_queue_wait_seconds_count{stream="main.k5"}',
            'raft_tpu_serve_flush_seconds_count{stream="main.k5"}',
            'raft_tpu_serve_batch_occupancy_count{stream="main.k5"}',
            'raft_tpu_serve_flush_total{bucket="1",stream="main.k5"}',
            'raft_tpu_serve_overload_total{name="main"}',
            'raft_tpu_serve_requests_total{stream="main.k5"}',
            'raft_tpu_serve_versions_live{name="main"}'):
        assert needed in js, f"missing {needed}"
    svc.shutdown(drain=True)


def test_queue_wait_vs_flush_decomposition(bf, dataset):
    """The two latency histograms split a request's life at flush pickup:
    queue wait is clock time from submit to pickup, flush time is the
    flush_fn wall — both in the INJECTED clock's domain, so the split is
    assertable exactly (ISSUE 7 satellite)."""
    from raft_tpu import obs

    clock = FakeClock()
    svc = det_service(bf, clock, max_batch=4)
    before = obs.to_json()
    svc.submit("main", dataset[:1], 5)
    clock.advance(0.25)  # the request waits 0.25 clock-seconds
    svc.pump()
    d = obs.delta(before, obs.to_json())
    wait = d.get('raft_tpu_serve_queue_wait_seconds_sum'
                 '{stream="main.k5"}', 0.0)
    assert wait == pytest.approx(0.25)
    # flush ran entirely between two reads of a frozen clock: 0 observed,
    # count 1 — the histogram exists and attributes no queue time
    assert d.get('raft_tpu_serve_flush_seconds_count'
                 '{stream="main.k5"}') == 1
    svc.shutdown(drain=True)


def test_cancelled_future_dropped_not_crashing(bf, dataset):
    """A caller cancelling a queued future must not crash the flush (which
    would kill the worker and strand the rest of the batch): the cancelled
    request is dropped at drain, its batch-mates are served."""
    clock = FakeClock()
    svc = det_service(bf, clock, max_batch=8)
    f_cancel = svc.submit("main", dataset[:2], 5)
    f_live = svc.submit("main", dataset[2:3], 5)
    assert f_cancel.cancel()
    clock.advance(0.01)
    assert svc.pump() == 1  # only the live row reached the device
    assert f_live.result(timeout=0)[0].shape == (1, 5)
    assert svc.queue_depth() == 0


def test_external_registry_must_cover_service_buckets(bf):
    reg = IndexRegistry(buckets=(1, 2, 4))
    with pytest.raises(RaftError):
        SearchService(reg, max_batch=8)  # ladder up to 8 not covered
    SearchService(reg, max_batch=4).shutdown()  # exact cover is fine


def test_publish_tuned_zero_cold_compile(dataset):
    """ISSUE 7 acceptance: publishing with a tune decision log serves the
    pinned operating point AND the warm ladder covers the tuned programs —
    the post-publish hot path runs compile-free, proven by obs compile
    attribution (the same proof bench.py --serve asserts for swaps)."""
    from raft_tpu import tune
    from raft_tpu.obs import compile as obs_compile

    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), dataset)
    log = tune.DecisionLog()
    log.add(tune.Decision(kind="ivf_flat", dtype="float32",
                          family=tune.family_of(idx),
                          params={"n_probes": 4}))
    clock = FakeClock()
    svc = SearchService(max_batch=4, clock=clock, start_workers=False)
    rep = svc.publish("tuned", idx, k=5, tuned=log)
    assert rep["tuned"] == log.entries()[0].key
    with obs_compile.attribution() as rec:
        for rows in (1, 3, 4):
            futs = [svc.submit("tuned", dataset[j:j + 1], 5)
                    for j in range(rows)]
            clock.advance(1.0)
            svc.pump()
            for f in futs:
                d, i = f.result(timeout=5)
                assert i.shape == (1, 5)
    assert rec.compile_s == 0.0 and rec.cache_misses == 0
    svc.shutdown(drain=True)


def test_publish_tuned_funnel_zero_cold_compile(dataset):
    """ISSUE 16 acceptance: a tuned FUNNEL pin (funnel_widen > 1 on a
    fast-scan index) publishes through the same warm ladder — every
    post-publish bucket serves the widened three-stage path compile-free.
    Widths are static shapes, so an unwarmed width would cold-compile
    here; the attribution proves the ladder covered the pinned one."""
    from raft_tpu import tune
    from raft_tpu.obs import compile as obs_compile

    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=8, pq_dim=8, fast_scan="1bit", seed=0),
        dataset)
    log = tune.DecisionLog()
    log.add(tune.Decision(kind="ivf_pq", dtype="float32",
                          family=tune.family_of(idx, dataset),
                          params={"n_probes": 4, "funnel_widen": 4}))
    clock = FakeClock()
    svc = SearchService(max_batch=4, clock=clock, start_workers=False)
    rep = svc.publish("funnel", idx, k=5, tuned=log)
    assert rep["tuned"] == log.entries()[0].key
    with obs_compile.attribution() as rec:
        for rows in (1, 3, 4):
            futs = [svc.submit("funnel", dataset[j:j + 1], 5)
                    for j in range(rows)]
            clock.advance(1.0)
            svc.pump()
            for f in futs:
                d, i = f.result(timeout=5)
                assert i.shape == (1, 5)
    assert rec.compile_s == 0.0 and rec.cache_misses == 0
    svc.shutdown(drain=True)


def test_publish_tuned_excludes_search_params_and_hooks(bf, dataset):
    from raft_tpu import tune

    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), dataset)
    dec = tune.Decision(kind="ivf_flat", dtype="float32",
                        family=tune.family_of(idx), params={"n_probes": 4})
    reg = IndexRegistry(buckets=(1, 2))
    with pytest.raises(RaftError, match="pass one"):
        reg.publish("x", idx, tuned=dec,
                    search_params=ivf_flat.SearchParams(n_probes=8))
    hook = ivf_flat.batched_searcher(idx)
    with pytest.raises(RaftError, match="plain index"):
        reg.publish("x", hook, tuned=dec)


def test_publish_hook_with_search_params_refused(bf):
    from raft_tpu.neighbors import brute_force as bfm

    reg = IndexRegistry(buckets=(1,))
    hook = bfm.batched_searcher(bf)
    with pytest.raises(RaftError):
        reg.publish("main", hook, search_params=object(), warm=False)


def test_deadline_shorter_than_batching_budget_fails_promptly(bf, dataset):
    """A deadline tighter than max_wait_us must make the stream ready at
    the deadline, not at the batching budget — the caller's future fails
    ~when its deadline passes."""
    clock = FakeClock()
    svc = det_service(bf, clock, max_wait_us=100_000.0)  # 100 ms budget
    fut = svc.submit("main", dataset[:1], 5, timeout_s=0.005)
    clock.advance(0.006)  # past the deadline, far before max_wait
    assert svc.pump() == 0  # ready fired for the expiry, nothing flushed
    with pytest.raises(DeadlineExceededError):
        fut.result(timeout=0)


def test_contract_changing_republish_refused(bf, dataset, rng):
    """A dim- or dtype-changing republish under a live name would wedge the
    pinned streams; publish must refuse it before spending warmup time."""
    reg = IndexRegistry(buckets=(1,))
    reg.publish("main", bf, k=5, warm=False)
    wide = brute_force.BruteForce().build(
        rng.standard_normal((64, 32)).astype(np.float32))
    with pytest.raises(RaftError):
        reg.publish("main", wide, k=5, warm=False)  # 16 -> 32 dims
    assert reg.active("main").version == 1  # flip never happened


def test_unpublished_k_refused(bf, dataset):
    """k is a static jit arg: serving an unwarmed width would cold-compile
    on the hot path, so submit refuses widths publish() did not warm."""
    clock = FakeClock()
    svc = SearchService(max_batch=2, clock=clock, start_workers=False)
    svc.publish("main", bf, k=(5, 3), warm=False)
    svc.submit("main", dataset[:1], 3)  # published width: admitted
    with pytest.raises(RaftError):
        svc.submit("main", dataset[:1], 7)
    assert svc.queue_depth() == 1  # the refusal did not consume the bound


def test_expired_deadline_does_not_early_flush_queue_mates(bf, dataset):
    """One tight-deadline client must not degrade batching for everyone:
    sweeping its expired request leaves fresh queue-mates queued until the
    normal flush condition (max_batch / max_wait) holds."""
    clock = FakeClock()
    svc = det_service(bf, clock, max_batch=8, max_wait_us=100_000.0)
    f_live = svc.submit("main", dataset[:1], 5)  # no deadline
    f_dead = svc.submit("main", dataset[1:2], 5, timeout_s=0.005)
    clock.advance(0.006)  # deadline passed, batching budget (100ms) not
    assert svc.pump() == 0  # expired swept, NOTHING flushed early
    with pytest.raises(DeadlineExceededError):
        f_dead.result(timeout=0)
    assert not f_live.done() and svc.queue_depth() == 1
    clock.advance(0.1)  # now the batching budget expires
    assert svc.pump() == 1
    assert f_live.result(timeout=0)[0].shape == (1, 5)


def test_publish_warm_data_sample(bf, dataset):
    """publish(warm_data=...) draws the warmup queries from the caller's
    sample (real data, not uniform noise — VERDICT r5 #5 threaded through
    serve): same bucket coverage, and a bad sample fails BEFORE the warm
    spend with a clear message."""
    reg = IndexRegistry(buckets=(1, 2))
    rep = reg.publish("main", bf, k=5, warm_data=dataset[:50])
    assert sorted(rep["warm"][5]) == [1, 2]
    from raft_tpu.core import RaftError

    with pytest.raises(RaftError, match="warm sample"):
        reg.publish("other", bf, k=5,
                    warm_data=np.zeros((10, dataset.shape[1] + 1),
                                       np.float32))
    with pytest.raises(RaftError, match="dtype"):
        # int8 sample against a float32-serving index (float64 would be
        # silently downcast by jnp.asarray under the x64-disabled default)
        reg.publish("other2", bf, k=5,
                    warm_data=dataset[:10].astype(np.int8))
