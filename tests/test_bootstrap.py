"""Multi-host bootstrap smoke test: two spawned CPU processes form a cluster
via jax.distributed.initialize and run a cross-host psum.

This is the 2-process CPU analogue of the reference's most battle-tested
distributed path — raft-dask's Comms.init over a Dask cluster
(python/raft-dask/raft_dask/common/comms.py:85-201) verified by
test_comms.py's LocalCUDACluster session. Marked slow (spawns interpreters,
~30-60 s); skips cleanly where subprocess networking is unavailable.
"""

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[1]

_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    from raft_tpu.core.platform import force_virtual_cpu
    force_virtual_cpu(2)                      # 2 virtual CPU devices per host
    import jax
    from raft_tpu.comms import bootstrap

    pid = int(sys.argv[1])
    bootstrap.initialize(coordinator_address={coord!r}, num_processes=2,
                         process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()   # 2 hosts x 2 devices

    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    mesh = bootstrap.global_mesh(("data",))
    from raft_tpu.comms import Comms
    comms = Comms(mesh, "data")

    # cross-host allreduce: every process contributes rank+1 per local device
    from jax.sharding import NamedSharding
    import numpy as np
    local = jnp.full((1, 4), float(pid + 1))
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), np.asarray(
            jnp.tile(local, (2, 1))), (4, 4))
    total = comms.shard_map(lambda x: comms.allreduce(x),
                            in_specs=P("data"), out_specs=P("data"))(arr)
    got = float(jax.device_get(total.addressable_shards[0].data)[0, 0])
    # sum over 4 device shards: 2 shards of host0 (1.0) + 2 of host1 (2.0)
    assert got == 6.0, got
    print("BOOTSTRAP_OK", pid, flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_bootstrap(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=str(REPO), coord=coord))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen([sys.executable, str(script), str(pid)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("distributed bootstrap timed out (environment forbids "
                    "local networking?)")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 and ("UNAVAILABLE" in out or "PermissionError" in out):
            pytest.skip(f"environment forbids the coordinator service: {out[-300:]}")
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-2000:]}"
        assert f"BOOTSTRAP_OK {pid}" in out
