"""Sparse layer tests vs scipy.sparse references.

Mirrors the reference's SPARSE_TEST gtest suite strategy (SURVEY.md §4):
results compared against a trusted host implementation (scipy here, naive
loops there).
"""

import numpy as np
import pytest
import scipy.sparse as sps

import jax.numpy as jnp

from raft_tpu import sparse


def _random_csr(rng, n, m, density=0.2, cap_extra=7):
    sp = sps.random(n, m, density=density, random_state=np.random.RandomState(rng.integers(1 << 30)), format="csr", dtype=np.float32)
    sp.data = sp.data.astype(np.float32) + 0.1  # avoid exact zeros
    return sp, sparse.from_scipy(sp, cap=sp.nnz + cap_extra)


class TestTypes:
    def test_coo_dense_roundtrip(self, rng):
        sp = sps.random(13, 9, density=0.3, format="coo", dtype=np.float32)
        coo = sparse.from_scipy(sp, cap=sp.nnz + 5)
        np.testing.assert_allclose(np.asarray(coo.todense()), sp.toarray(), rtol=1e-6)

    def test_csr_dense_roundtrip(self, rng):
        sp, csr = _random_csr(rng, 11, 17)
        np.testing.assert_allclose(np.asarray(csr.todense()), sp.toarray(), rtol=1e-6)

    def test_csr_row_ids(self, rng):
        sp, csr = _random_csr(rng, 8, 8)
        ids = np.asarray(csr.row_ids())
        expect = sp.tocoo().row
        np.testing.assert_array_equal(ids[: sp.nnz], expect)
        assert (ids[sp.nnz :] == 8).all()


class TestConvert:
    def test_coo_csr_roundtrip(self, rng):
        sp = sps.random(10, 12, density=0.25, format="coo", dtype=np.float32)
        coo = sparse.from_scipy(sp.tocoo(), cap=sp.nnz + 3)
        csr = sparse.coo_to_csr(coo)
        np.testing.assert_allclose(np.asarray(csr.todense()), sp.toarray(), rtol=1e-6)
        back = sparse.csr_to_coo(csr)
        np.testing.assert_allclose(np.asarray(back.todense()), sp.toarray(), rtol=1e-6)

    def test_dense_to_csr(self, rng):
        x = rng.random((9, 7), dtype=np.float32)
        x[x < 0.5] = 0
        csr = sparse.dense_to_csr(jnp.asarray(x))
        assert int(csr.nnz) == (x != 0).sum()
        np.testing.assert_allclose(np.asarray(csr.todense()), x, rtol=1e-6)

    def test_adj_to_csr(self, rng):
        adj = rng.random((6, 6)) < 0.4
        csr = sparse.adj_to_csr(jnp.asarray(adj))
        np.testing.assert_array_equal(np.asarray(csr.todense()) != 0, adj)


class TestLinalg:
    def test_spmm(self, rng):
        sp, csr = _random_csr(rng, 12, 15)
        b = rng.random((15, 6), dtype=np.float32)
        out = sparse.spmm(csr, jnp.asarray(b))
        np.testing.assert_allclose(np.asarray(out), sp @ b, rtol=1e-5, atol=1e-5)

    def test_spmv(self, rng):
        sp, csr = _random_csr(rng, 12, 15)
        v = rng.random(15, dtype=np.float32)
        np.testing.assert_allclose(np.asarray(sparse.spmv(csr, jnp.asarray(v))), sp @ v, rtol=1e-5, atol=1e-5)

    def test_add(self, rng):
        sa, ca = _random_csr(rng, 9, 9)
        sb, cb = _random_csr(rng, 9, 9)
        out = sparse.add(ca, cb)
        np.testing.assert_allclose(np.asarray(out.todense()), (sa + sb).toarray(), rtol=1e-5, atol=1e-6)

    def test_degree(self, rng):
        sp, csr = _random_csr(rng, 10, 10)
        np.testing.assert_array_equal(np.asarray(sparse.degree(csr)), np.diff(sp.indptr))

    @pytest.mark.parametrize("norm", ["l1", "l2", "linf"])
    def test_row_norm(self, rng, norm):
        sp, csr = _random_csr(rng, 10, 10)
        dense = sp.toarray()
        expect = {
            "l1": np.abs(dense).sum(1),
            "l2": (dense**2).sum(1),
            "linf": np.abs(dense).max(1),
        }[norm]
        np.testing.assert_allclose(np.asarray(sparse.row_norm(csr, norm)), expect, rtol=1e-5, atol=1e-6)

    def test_normalize_rows_l1(self, rng):
        sp, csr = _random_csr(rng, 10, 10, density=0.4)
        out = np.asarray(sparse.normalize_rows(csr, "l1").todense())
        sums = np.abs(out).sum(1)
        nz = np.abs(sp.toarray()).sum(1) > 0
        np.testing.assert_allclose(sums[nz], 1.0, rtol=1e-5)

    def test_transpose(self, rng):
        sp, csr = _random_csr(rng, 7, 12)
        out = sparse.transpose(csr)
        assert out.shape == (12, 7)
        np.testing.assert_allclose(np.asarray(out.todense()), sp.T.toarray(), rtol=1e-6)

    @pytest.mark.parametrize("mode", ["sum", "max"])
    def test_symmetrize(self, rng, mode):
        sp, csr = _random_csr(rng, 8, 8)
        out = np.asarray(sparse.symmetrize(csr, mode).todense())
        d = sp.toarray()
        expect = d + d.T if mode == "sum" else np.maximum(d, d.T)
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)

    def test_laplacian(self, rng):
        sp, csr = _random_csr(rng, 8, 8)
        # symmetrize first: laplacians are for undirected graphs
        sym = sparse.symmetrize(csr, "sum")
        lap = np.asarray(sparse.laplacian(sym).todense())
        a = np.asarray(sym.todense())
        expect = np.diag(a.sum(1)) - a
        np.testing.assert_allclose(lap, expect, rtol=1e-5, atol=1e-5)

    def test_laplacian_normalized(self, rng):
        sp, csr = _random_csr(rng, 8, 8)
        sym = sparse.symmetrize(csr, "sum")
        lap = np.asarray(sparse.laplacian(sym, normalized=True).todense())
        a = np.asarray(sym.todense())
        d = a.sum(1)
        dinv = np.where(d > 0, 1 / np.sqrt(d), 0)
        expect = np.eye(8) - dinv[:, None] * a * dinv[None, :]
        np.testing.assert_allclose(lap, expect, rtol=1e-5, atol=1e-5)


class TestOps:
    def test_sum_duplicates(self, rng):
        rows = np.array([0, 0, 1, 1, 1, 2], np.int32)
        cols = np.array([1, 1, 0, 0, 2, 2], np.int32)
        vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0], np.float32)
        coo = sparse.make_coo(rows, cols, vals, (3, 3), cap=10)
        out = sparse.sum_duplicates(sparse.sort_coo(coo))
        assert int(out.nnz) == 4
        expect = np.zeros((3, 3), np.float32)
        np.add.at(expect, (rows, cols), vals)
        np.testing.assert_allclose(np.asarray(out.todense()), expect, rtol=1e-6)

    def test_max_duplicates(self, rng):
        rows = np.array([0, 0, 2], np.int32)
        cols = np.array([1, 1, 0], np.int32)
        vals = np.array([5.0, 2.0, 7.0], np.float32)
        coo = sparse.make_coo(rows, cols, vals, (3, 3), cap=6)
        out = sparse.max_duplicates(sparse.sort_coo(coo))
        assert int(out.nnz) == 2
        dense = np.asarray(out.todense())
        assert dense[0, 1] == 5.0 and dense[2, 0] == 7.0

    def test_remove_zeros(self, rng):
        rows = np.array([0, 1, 2], np.int32)
        cols = np.array([0, 1, 2], np.int32)
        vals = np.array([1.0, 0.0, 3.0], np.float32)
        coo = sparse.make_coo(rows, cols, vals, (3, 3), cap=5)
        out = sparse.remove_zeros(coo)
        assert int(out.nnz) == 2

    def test_slice_rows(self, rng):
        sp, csr = _random_csr(rng, 10, 6)
        coo = sparse.csr_to_coo(csr)
        out = sparse.slice_rows(coo, 3, 8)
        np.testing.assert_allclose(np.asarray(out.todense()), sp.toarray()[3:8], rtol=1e-6)

    def test_ops_jittable(self, rng):
        import jax

        sp, csr = _random_csr(rng, 8, 8)

        @jax.jit
        def f(c, b):
            return sparse.spmm(c, b)

        b = jnp.asarray(rng.random((8, 4), dtype=np.float32))
        np.testing.assert_allclose(np.asarray(f(csr, b)), sp @ np.asarray(b), rtol=1e-5, atol=1e-5)
