"""Random generation tests (reference analogue: cpp/test/random/*, RANDOM_TEST)."""

import numpy as np
import pytest

from raft_tpu import random as rr
from raft_tpu.core import RaftError


class TestDistributions:
    def test_uniform_range_and_moments(self):
        x = np.asarray(rr.uniform(rr.RngState(1), (20000,), low=2.0, high=4.0))
        assert x.min() >= 2.0 and x.max() < 4.0
        assert abs(x.mean() - 3.0) < 0.02

    def test_normal_moments(self):
        x = np.asarray(rr.normal(rr.RngState(2), (20000,), mu=1.0, sigma=2.0))
        assert abs(x.mean() - 1.0) < 0.05
        assert abs(x.std() - 2.0) < 0.05

    def test_rngstate_advances(self):
        st = rr.RngState(3)
        a = np.asarray(rr.uniform(st, (10,)))
        b = np.asarray(rr.uniform(st, (10,)))
        assert not np.allclose(a, b)

    def test_seed_reproducible(self):
        a = np.asarray(rr.uniform(rr.RngState(7), (10,)))
        b = np.asarray(rr.uniform(rr.RngState(7), (10,)))
        np.testing.assert_array_equal(a, b)

    def test_bernoulli(self):
        x = np.asarray(rr.bernoulli(rr.RngState(4), (10000,), prob=0.25))
        assert abs(x.mean() - 0.25) < 0.02

    def test_discrete_weights(self):
        w = np.array([0.0, 1.0, 3.0])
        x = np.asarray(rr.discrete(rr.RngState(5), (12000,), w))
        assert (x > 0).all()
        assert abs((x == 2).mean() - 0.75) < 0.02

    @pytest.mark.parametrize("fn", ["lognormal", "gumbel", "logistic", "exponential", "rayleigh", "laplace"])
    def test_shapes_finite(self, fn):
        x = np.asarray(getattr(rr, fn)(rr.RngState(6), (100,)))
        assert x.shape == (100,) and np.isfinite(x).all()


class TestMakeBlobs:
    def test_shapes_and_labels(self):
        x, labels = rr.make_blobs(500, 8, n_clusters=5, seed=0)
        assert x.shape == (500, 8)
        assert labels.shape == (500,)
        assert set(np.unique(np.asarray(labels))) <= set(range(5))

    def test_tight_clusters_are_separable(self):
        x, labels = rr.make_blobs(400, 4, n_clusters=3, cluster_std=0.01, seed=1)
        x, labels = np.asarray(x), np.asarray(labels)
        # points with the same label should be far closer than different labels
        for lbl in range(3):
            pts = x[labels == lbl]
            if len(pts) > 1:
                assert np.std(pts, axis=0).max() < 0.1

    def test_given_centers(self):
        centers = np.array([[0.0, 0.0], [100.0, 100.0]], np.float32)
        x, labels = rr.make_blobs(100, 2, centers=centers, cluster_std=0.1, seed=2)
        x, labels = np.asarray(x), np.asarray(labels)
        np.testing.assert_allclose(x[labels == 1].mean(0), [100, 100], atol=1.0)


class TestMakeRegression:
    def test_recoverable_linear_model(self):
        x, y, coef = rr.make_regression(200, 5, noise=0.0, seed=0)
        x, y, coef = np.asarray(x), np.asarray(y), np.asarray(coef)
        np.testing.assert_allclose(x @ coef[:, 0], y, rtol=1e-3, atol=1e-2)


class TestMVG:
    def test_multi_variable_gaussian(self):
        mean = np.array([1.0, -2.0], np.float32)
        cov = np.array([[2.0, 0.6], [0.6, 1.0]], np.float32)
        s = np.asarray(rr.multi_variable_gaussian(0, mean, cov, 30000))
        np.testing.assert_allclose(s.mean(0), mean, atol=0.05)
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.1)


class TestSampling:
    def test_permute(self):
        x = np.arange(40).reshape(10, 4).astype(np.float32)
        out, perm = rr.permute(0, x)
        np.testing.assert_array_equal(np.asarray(out), x[np.asarray(perm)])
        assert sorted(np.asarray(perm)) == list(range(10))

    def test_sample_without_replacement_distinct(self):
        idx = np.asarray(rr.sample_without_replacement(1, 100, 50))
        assert len(np.unique(idx)) == 50
        assert idx.min() >= 0 and idx.max() < 100

    def test_weighted_sampling_respects_zero_weight(self):
        w = np.ones(20)
        w[7] = 0.0
        for seed in range(5):
            idx = np.asarray(rr.sample_without_replacement(seed, 20, 10, weights=w))
            assert 7 not in idx

    def test_oversample_raises(self):
        with pytest.raises(RaftError):
            rr.sample_without_replacement(0, 5, 6)


class TestRmat:
    def test_ranges_and_determinism(self):
        theta = [0.57, 0.19, 0.19, 0.05]
        src, dst = rr.rmat(0, theta, r_scale=10, c_scale=8, n_edges=5000)
        src, dst = np.asarray(src), np.asarray(dst)
        assert src.min() >= 0 and src.max() < 2**10
        assert dst.min() >= 0 and dst.max() < 2**8
        s2, d2 = rr.rmat(0, theta, 10, 8, 5000)
        np.testing.assert_array_equal(src, np.asarray(s2))

    def test_skew(self):
        # heavily a-biased theta concentrates edges near (0, 0)
        src, dst = rr.rmat(1, [0.9, 0.03, 0.03, 0.04], 12, 12, 4000)
        assert np.median(np.asarray(src)) < 2**12 / 8

    def test_per_level_theta(self):
        theta = np.tile(np.array([0.25, 0.25, 0.25, 0.25]), (12, 1))
        src, dst = rr.rmat(2, theta, 12, 12, 1000)
        assert np.asarray(src).max() < 2**12
