"""Beyond-HBM tiered storage tests (tier-1 ``tiering`` marker, ISSUE 15).

The contract under test: ``MutableIndex(storage="tiered")`` moves WHERE
the full-precision refine rows live (host RAM / disk mmap, device only as
double-buffered per-batch gathers), never what a query answers —

- **bit parity** with the all-HBM twin on ids AND distances for
  ``search_refined`` / ``exact_search`` / ``search`` under the same
  upsert/delete/compact script, float and byte dtypes;
- **spill-then-promote round trips** under an injected budget squeeze
  (the obs.mem gate's pressure handler drops the mirror instead of
  shedding the write; headroom lifts it back), every move a counted,
  ``/debug/mem``-visible event;
- **crash at the ``tier/fetch`` fault point** recovers via ``load()`` +
  WAL replay with id-for-id parity against an uncrashed twin;
- **zero cold compiles** across refine double-buffer cycles after the
  rehearsal warm (compile attribution);
- the canary's shadow-rerank (the exact oracle) adds **zero device row
  bytes** — the chunked scan streams through the constant slot ring
  instead of materializing a second full-precision copy;
- ``save()``/``load()`` round-trips the tier layout at raft_tpu/12 with
  /11 read-compat both directions;
- ``obs.mem.plan(storage="tiered")`` prices per tier within the ±20%
  contract (the dominant arrays are exact).

Heavy 1M+ twins live in the slow manifest. Deterministic: injected
clocks, seeded data, fault scopes — no wall-clock sleeps.
"""

import gc

import numpy as np
import pytest

from raft_tpu.core import serialize
from raft_tpu.core.resources import Resources, default_resources
from raft_tpu.neighbors import ivf_pq
from raft_tpu.obs import compile as obs_compile
from raft_tpu.obs import mem as obs_mem
from raft_tpu.serve.errors import MemoryBudgetError
from raft_tpu.stream import (MutableIndex, ShardedMutableIndex, TieredStore,
                             TierPolicy)
from raft_tpu.stream import load as stream_load
from raft_tpu.stream import save as stream_save
from raft_tpu.testing import faults

pytestmark = pytest.mark.tiering

N, D = 2048, 16
PARAMS = ivf_pq.IndexParams(n_lists=32, pq_bits=4, pq_dim=8, seed=0)
SP = ivf_pq.SearchParams(n_probes=8)
POLICY = TierPolicy(oracle_chunk=512, auto_promote=False)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


@pytest.fixture(scope="module")
def corpus(rng):
    X = rng.standard_normal((N, D)).astype(np.float32)
    Q = rng.standard_normal((32, D)).astype(np.float32)
    return X, Q


@pytest.fixture(scope="module")
def sealed(corpus):
    return ivf_pq.build(PARAMS, corpus[0])


def _wrap(sealed, X, storage, name, **kw):
    kw.setdefault("tier", POLICY if storage == "tiered" else None)
    return MutableIndex(sealed, search_params=SP, index_params=PARAMS,
                        dataset=X, storage=storage, name=name, **kw)


def _churn(m, rng_seed=3):
    """The one upsert/delete/compact script both twins replay."""
    r = np.random.default_rng(rng_seed)
    m.upsert(r.standard_normal((24, D)).astype(np.float32),
             ids=np.arange(50_000, 50_024))
    m.delete([1, 7, 50_003])
    m.compact()
    m.upsert(r.standard_normal((8, D)).astype(np.float32),
             ids=np.arange(60_000, 60_008))
    m.delete([60_001, 2])


def _assert_bit_equal(a, b, what):
    da, ia = np.asarray(a[0]), np.asarray(a[1])
    db, ib = np.asarray(b[0]), np.asarray(b[1])
    assert (ia == ib).all(), f"{what}: ids diverge"
    assert (da == db).all(), f"{what}: distances diverge"


def test_tiered_vs_hbm_bit_parity_f32(sealed, corpus):
    """Same script, two storage policies, identical answers — including
    through a compaction fold (tier residency migrates, results don't)."""
    X, Q = corpus
    a = _wrap(sealed, X, "hbm", "par_hbm")
    b = _wrap(sealed, X, "tiered", "par_tiered")
    assert b.tiered_store.residency == "host"
    _assert_bit_equal(a.search_refined(Q, 10, 4), b.search_refined(Q, 10, 4),
                      "refined pre-churn")
    _churn(a)
    _churn(b)
    _assert_bit_equal(a.search(Q, 10), b.search(Q, 10), "search post-churn")
    _assert_bit_equal(a.search_refined(Q, 10, 4), b.search_refined(Q, 10, 4),
                      "refined post-churn")
    _assert_bit_equal(a.exact_search(Q, 10), b.exact_search(Q, 10),
                      "oracle post-churn")
    # the fold carried the store over: still tiered, still cold
    assert isinstance(b._state.store, TieredStore)
    assert b.tiered_store.residency == "host"


@pytest.mark.parametrize("dtype", ["uint8", "int8"])
def test_tiered_vs_hbm_bit_parity_bytes(rng, dtype):
    """Byte-dtype twins: the store keeps rows in the serving dtype and
    the refine re-rank scores the raw domain exactly on both paths."""
    if dtype == "uint8":
        X = rng.integers(0, 255, (1024, D), dtype=np.uint8)
        Q = rng.integers(0, 255, (16, D), dtype=np.uint8)
    else:
        X = rng.integers(-127, 127, (1024, D), dtype=np.int8)
        Q = rng.integers(-127, 127, (16, D), dtype=np.int8)
    p = ivf_pq.IndexParams(n_lists=16, pq_bits=4, pq_dim=8, seed=0)
    idx = ivf_pq.build(p, X)
    a = MutableIndex(idx, search_params=SP, index_params=p, dataset=X,
                     name=f"pb_hbm_{dtype}")
    b = MutableIndex(idx, search_params=SP, index_params=p, dataset=X,
                     storage="tiered", tier=POLICY,
                     name=f"pb_tier_{dtype}")
    _assert_bit_equal(a.search_refined(Q, 5, 4), b.search_refined(Q, 5, 4),
                      f"{dtype} refined")
    _assert_bit_equal(a.exact_search(Q, 5), b.exact_search(Q, 5),
                      f"{dtype} oracle")


def test_spill_then_promote_round_trip(sealed, corpus):
    """An injected budget squeeze spills the mirror THROUGH the gate
    (pressure handler — the write is admitted, not shed), and headroom
    promotes it back; both moves are counted events and the ledger's
    device total reflects the mirror's bytes each way."""
    X, Q = corpus
    m = _wrap(sealed, X, "tiered", "squeeze")
    ts = m.tiered_store
    assert ts.promote(force=True) and ts.mirror_resident
    dev_with_mirror = obs_mem.totals()["device_bytes"]

    # squeeze: any delta growth exceeds the budget -> the gate reclaims
    # the mirror instead of refusing the upsert
    res = Resources(memory_budget_bytes=dev_with_mirror + 1)
    m.upsert(np.zeros((16, D), np.float32), ids=np.arange(70_000, 70_016),
             res=res)
    assert not ts.mirror_resident, "pressure must spill the mirror"
    assert ts.stats()["spills"] == 1
    assert ts.stats()["events"][-1]["reason"] == "pressure"
    assert (obs_mem.totals()["device_bytes"]
            < dev_with_mirror - ts.row_bytes // 2), (
        "the ledger must see the mirror's bytes freed")

    # the answers never changed
    hbm = _wrap(sealed, X, "hbm", "squeeze_twin")
    hbm.upsert(np.zeros((16, D), np.float32), ids=np.arange(70_000, 70_016))
    _assert_bit_equal(hbm.search_refined(Q, 10, 4),
                      m.search_refined(Q, 10, 4), "post-spill refined")

    # headroom: promote comes back, and a too-tight budget refuses it
    tight = Resources(memory_budget_bytes=obs_mem.totals()["device_bytes"]
                      + ts.row_bytes // 2)
    assert not ts.promote(res=tight), "promote without headroom must refuse"
    roomy = Resources(memory_budget_bytes=obs_mem.totals()["device_bytes"]
                      + 2 * ts.row_bytes)
    assert ts.promote(res=roomy) and ts.mirror_resident
    assert ts.stats()["promotes"] >= 1
    _assert_bit_equal(hbm.search_refined(Q, 10, 4),
                      m.search_refined(Q, 10, 4), "post-promote refined")


def test_hit_rate_auto_promote(sealed, corpus):
    """promote_min_hits cold fetches under an ARMED budget with headroom
    lift the mirror; with NO budget armed the store must stay cold (no
    safe ceiling — promoting a beyond-HBM store because it was queried
    three times is the OOM tiering exists to avoid)."""
    X, Q = corpus
    m = MutableIndex(sealed, search_params=SP, dataset=X, storage="tiered",
                     name="auto",
                     tier=TierPolicy(oracle_chunk=512, promote_min_hits=2))
    ts = m.tiered_store
    for _ in range(4):
        m.search_refined(Q, 10, 4)
    assert not ts.mirror_resident, "no budget armed -> no auto-promote"
    roomy = Resources(memory_budget_bytes=obs_mem.totals()["device_bytes"]
                      + 2 * ts.row_bytes)
    m.search_refined(Q, 10, 4, res=roomy)
    m.search_refined(Q, 10, 4, res=roomy)  # 2nd cold fetch trips promote
    assert ts.mirror_resident, "hit-rate promote under budget headroom"
    assert ts.stats()["events"][-1]["reason"] == "hit-rate"


def test_tier_fetch_crash_recovers_via_wal(sealed, corpus, tmp_path):
    """A crash mid-refine-hop (the ``tier/fetch`` fault point) recovers
    through load() + WAL replay with id-for-id parity against an
    uncrashed twin, and the restored index is still tiered."""
    X, Q = corpus
    snap = str(tmp_path / "t.idx")
    wal = str(tmp_path / "t.wal")
    m = _wrap(sealed, X, "tiered", "crash", wal=wal, snapshot_path=snap)
    stream_save(m, snap)  # baseline snapshot; the WAL covers what follows
    m.upsert(np.ones((4, D), np.float32), ids=[90_000, 90_001, 90_002,
                                               90_003])
    m.delete([90_001, 5])
    with faults.scope():
        faults.inject("tier/fetch", exc=faults.SimulatedCrash("die"))
        with pytest.raises(faults.SimulatedCrash):
            m.search_refined(Q, 10, 4)
        assert faults.fired("tier/fetch") == 1
    del m
    gc.collect()

    twin = _wrap(sealed, X, "tiered", "crash_twin")
    twin.upsert(np.ones((4, D), np.float32), ids=[90_000, 90_001, 90_002,
                                                  90_003])
    twin.delete([90_001, 5])
    rec = stream_load(snap, search_params=SP, wal=wal, tier=POLICY)
    assert rec.last_recovery["replayed"] == 2
    assert rec.storage == "tiered" and rec.tiered_store is not None
    _assert_bit_equal(twin.search_refined(Q, 10, 4),
                      rec.search_refined(Q, 10, 4), "recovered refined")
    _assert_bit_equal(twin.search(Q, 10), rec.search(Q, 10),
                      "recovered search")


def test_zero_cold_compiles_across_refine_cycles(sealed, corpus):
    """After the rehearsal warm (warm_refined), refine double-buffer
    cycles and oracle passes compile NOTHING — the slot-ring rotation and
    the fixed chunk shape keep every program hot."""
    X, Q = corpus
    m = _wrap(sealed, X, "tiered", "warmz")
    rep = m.warm_refined([Q.shape[0]], ks=(10,), refine_ratio=4)
    assert rep[10][Q.shape[0]]["wall_s"] >= 0.0
    import jax

    with obs_compile.attribution() as rec:
        for _ in range(4):  # > fetch_slots: the ring wraps and replaces
            jax.block_until_ready(m.search_refined(Q, 10, 4)[0])
        for _ in range(2):
            jax.block_until_ready(m.exact_search(Q, 10)[0])
    assert rec.cache_misses == 0 and rec.compile_s == 0.0, (
        f"cold compile on the warmed tiered path: {rec.summary()}")


def test_post_spill_oracle_compiles_nothing(sealed, corpus):
    """warm_refined warms the chunked-oracle program set even while the
    mirror is resident — a later pressure spill must not cold-compile
    the chunk knn/shift/merge set on the first post-spill shadow-rerank
    (regression: the warm skipped the chunked path when promoted)."""
    import jax

    X, Q = corpus
    m = _wrap(sealed, X, "tiered", "spillwarm")
    assert m.tiered_store.promote(force=True)
    m.warm_refined([Q.shape[0]], ks=(10,), refine_ratio=4)
    m.tiered_store.spill(reason="pressure")
    with obs_compile.attribution() as rec:
        jax.block_until_ready(m.exact_search(Q, 10)[0])
        jax.block_until_ready(m.search_refined(Q, 10, 4)[0])
    assert rec.cache_misses == 0 and rec.compile_s == 0.0, rec.summary()


def test_oracle_adds_zero_device_row_bytes(sealed, corpus):
    """The regression the shared store exists for: the canary's
    shadow-rerank (exact oracle) over a tiered store must not grow
    device bytes — the pre-tiering lazy oracle uploaded a FULL second
    row copy. Also pins the single attribution: the rows are ledgered
    once, under the tier entry, not again under the stream epoch."""
    from raft_tpu.obs.quality import exact_oracle

    X, Q = corpus
    m = _wrap(sealed, X, "tiered", "canary_store")
    oracle = exact_oracle(m)
    import jax

    jax.block_until_ready(oracle(Q, 10)[0])  # rehearsal: slots allocate
    before = obs_mem.totals()["device_bytes"]
    for _ in range(3):
        jax.block_until_ready(oracle(Q, 10)[0])
    assert obs_mem.totals()["device_bytes"] == before, (
        "shadow-rerank grew device bytes under a tiered store")
    assert m._state.store_dev is None, (
        "a tiered epoch must never materialize the lazy oracle copy")
    # one attribution: the tier entry owns the row bytes; the stream
    # epoch's host bytes must NOT include a second copy of them
    tier_rows = [r for r in obs_mem.breakdown()
                 if r["component"] == "tier" and r["name"] == "canary_store"]
    assert len(tier_rows) == 1
    assert tier_rows[0]["host_bytes"] >= X.nbytes
    stream_rows = [r for r in obs_mem.breakdown()
                   if r["component"] == "stream"
                   and r["name"] == "canary_store"]
    assert stream_rows and stream_rows[0]["host_bytes"] < X.nbytes


def test_compaction_migrates_residency_and_retires_old_store(sealed, corpus):
    """The fold-and-swap carries tier residency to the successor store
    and retires the predecessor's ledger entry — which must actually
    free once nothing pins the old epoch (the PR 10 audit contract)."""
    X, Q = corpus
    m = _wrap(sealed, X, "tiered", "fold")
    assert m.tiered_store.promote(force=True)
    m.upsert(np.zeros((4, D), np.float32), ids=[80_000, 80_001, 80_002,
                                                80_003])
    m.compact()
    ts = m.tiered_store
    assert ts is not None and ts._epoch == 1
    assert ts.mirror_resident, "residency must migrate through the fold"
    gc.collect()
    leaks = [r for r in obs_mem.audit(collect=True)["retired_unfreed"]
             if r["component"] == "tier"]
    assert not leaks, f"pre-fold tier entry leaked: {leaks}"


def test_sharded_per_shard_tiered_stores(corpus):
    """ShardedMutableIndex(storage="tiered") gives every shard its own
    store (mesh capacity = shards x (HBM + host)); the 1-shard mesh is
    bit-equal to the plain index's refined search."""
    X, Q = corpus

    def build(rows):
        return ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_bits=4,
                                               pq_dim=8, seed=0), rows)

    mesh = ShardedMutableIndex(X, n_shards=2, build=build, search_params=SP,
                               storage="tiered", tier=POLICY, name="mesh2")
    stores = [sh.tiered_store for sh in mesh._shards]
    assert all(ts is not None and ts.residency == "host" for ts in stores)
    tiers = [r for r in obs_mem.breakdown() if r["component"] == "tier"
             and r["name"].startswith("mesh2/")]
    assert sorted(r["shard"] for r in tiers) == [0, 1], tiers
    d_, i_ = mesh.search_refined(Q, 5, 4)
    assert np.asarray(i_).shape == (Q.shape[0], 5)
    assert (np.asarray(i_)[:, 0] >= 0).all()

    one = ShardedMutableIndex(X, n_shards=1, build=build, search_params=SP,
                              storage="tiered", tier=POLICY, name="mesh1")
    plain = MutableIndex(build(X), search_params=SP, dataset=X,
                         storage="tiered", tier=POLICY, name="mesh1_twin")
    _assert_bit_equal(one.search_refined(Q, 5, 4),
                      plain.search_refined(Q, 5, 4), "1-shard refined")


def test_reshard_tiered_mesh(corpus):
    """reshard() folds donor stores through the _store_rows seam — a
    tiered mesh doubles its topology without touching answer parity
    (regression: the donor fold indexed the TieredStore directly)."""
    X, Q = corpus

    def build(rows):
        return ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_bits=4,
                                               pq_dim=8, seed=0), rows)

    mesh = ShardedMutableIndex(X, n_shards=1, build=build, search_params=SP,
                               storage="tiered", tier=POLICY, name="rshrd")
    before = np.asarray(mesh.exact_search(Q, 10)[1])
    mesh.reshard(2)
    assert mesh.n_shards == 2
    assert all(sh.tiered_store is not None for sh in mesh._shards)
    # the exact oracle is quantization-free, so the doubled topology must
    # answer id-for-id (the PQ serving path legitimately differs: the
    # successors are fresh per-shard builds)
    after = np.asarray(mesh.exact_search(Q, 10)[1])
    assert (before == after).all()
    d_, i_ = mesh.search_refined(Q, 5, 4)
    assert (np.asarray(i_)[:, 0] >= 0).all()


def test_refined_hook_pins_its_epoch(sealed, corpus):
    """A leased refined hook keeps serving the pre-compaction view until
    its lease drains — the same epoch-pin contract as searcher()."""
    X, Q = corpus
    m = _wrap(sealed, X, "tiered", "pinned_hook")
    hook = m.refined_searcher(refine_ratio=4)
    before = np.asarray(hook(Q, 10)[1])
    m.upsert(np.full((4, D), 7.0, np.float32), ids=[95_000, 95_001,
                                                    95_002, 95_003])
    m.compact()
    # the leased hook still serves the frozen pre-compaction epoch...
    assert (np.asarray(hook(Q, 10)[1]) == before).all()
    # ...while a fresh hook (what a republish leases) sees the successor
    assert m.tiered_store._epoch == 1
    fresh = m.refined_searcher(refine_ratio=4)
    assert (np.asarray(fresh(Q, 10)[1])
            == np.asarray(m.search_refined(Q, 10, 4)[1])).all()


def test_disk_tier_mmap(sealed, corpus, tmp_path):
    """TierPolicy(disk_path=...) keeps the cold majority on disk: host
    ledger bytes ~0, tier bytes under "disk", answers unchanged."""
    X, Q = corpus
    pol = TierPolicy(disk_path=str(tmp_path / "cold"), oracle_chunk=512,
                     auto_promote=False)
    m = MutableIndex(sealed, search_params=SP, dataset=X, storage="tiered",
                     tier=pol, name="cold_store")
    ts = m.tiered_store
    assert ts.residency == "disk"
    tb = ts.tier_bytes()
    assert tb["disk"] == X.nbytes and tb["host"] == 0
    entry = [r for r in obs_mem.breakdown() if r["component"] == "tier"
             and r["name"] == "cold_store"][0]
    assert entry["host_bytes"] == 0, "mmap pages must not price as host RAM"
    hbm = _wrap(sealed, X, "hbm", "cold_twin")
    _assert_bit_equal(hbm.search_refined(Q, 10, 4),
                      m.search_refined(Q, 10, 4), "disk refined")
    # epoch files do not leak: the fold's successor writes .e1 and the
    # collected predecessor's .e0 unlinks (a periodically-compacting
    # disk-tiered index must not grow disk by store_bytes per fold)
    import os

    f0 = ts._disk_file
    del ts  # the test must not be the thing pinning the pre-fold store
    m.compact()
    assert m.tiered_store._disk_file != f0
    gc.collect()
    assert not os.path.exists(f0), "pre-fold epoch file leaked"
    assert os.path.exists(m.tiered_store._disk_file)


def test_save_load_roundtrips_tier_layout(sealed, corpus, tmp_path):
    """raft_tpu/12 persists (storage, residency); load restores the
    placement without re-deciding — a device-resident store comes back
    resident, a cold one cold."""
    X, Q = corpus
    path = str(tmp_path / "layout.idx")
    m = _wrap(sealed, X, "tiered", "layout")
    assert m.tiered_store.promote(force=True)
    stream_save(m, path)
    rec = stream_load(path, search_params=SP, tier=POLICY)
    assert rec.storage == "tiered"
    assert rec.tiered_store.mirror_resident, (
        "saved device residency must restore without re-deciding")
    # the restore threads into CONSTRUCTION (one placement event, no
    # re-decide-then-correct upload/spill churn)
    events = rec.tiered_store.stats()["events"]
    assert [e["event"] for e in events] == ["promote"], events
    assert events[0]["reason"] == "placement"
    _assert_bit_equal(m.search_refined(Q, 10, 4),
                      rec.search_refined(Q, 10, 4), "reloaded refined")

    m.tiered_store.spill()
    stream_save(m, path)
    # a cold-saved store restores cold even when a roomy budget would
    # have decided "device" — the layout is restored, never re-decided
    # (and with zero residency events: no upload-then-spill churn)
    roomy = default_resources()
    prev = roomy.memory_budget_bytes
    roomy.memory_budget_bytes = (obs_mem.totals()["device_bytes"]
                                 + 4 * m.tiered_store.row_bytes)
    try:
        rec2 = stream_load(path, search_params=SP, tier=POLICY)
    finally:
        roomy.memory_budget_bytes = prev
    assert not rec2.tiered_store.mirror_resident
    assert rec2.tiered_store.stats()["events"] == []


def test_serialize_11_read_compat_both_directions(sealed, corpus, tmp_path,
                                                  monkeypatch):
    """Both directions of the /11 compat contract: (a) bytes written by a
    writer PINNED to raft_tpu/11 (the old layout, no tier fields) load in
    this build as storage="hbm"; (b) this build's /12 bytes carry the
    layout and load back tiered. The sealed ivf_pq payload is unchanged
    either way."""
    X, Q = corpus
    old_path = str(tmp_path / "v11.idx")
    m = _wrap(sealed, X, "hbm", "compat")
    monkeypatch.setattr(serialize, "SERIALIZATION_VERSION", "raft_tpu/11")
    stream_save(m, old_path)
    monkeypatch.undo()
    assert serialize.version_number(serialize.SERIALIZATION_VERSION) >= 12
    rec = stream_load(old_path, search_params=SP)
    assert rec.storage == "hbm" and rec.tiered_store is None
    _assert_bit_equal(m.search(Q, 10), rec.search(Q, 10), "/11 search")

    new_path = str(tmp_path / "v12.idx")
    t = _wrap(sealed, X, "tiered", "compat12")
    stream_save(t, new_path)
    rec12 = stream_load(new_path, search_params=SP, tier=POLICY)
    assert rec12.storage == "tiered"
    _assert_bit_equal(t.search_refined(Q, 10, 4),
                      rec12.search_refined(Q, 10, 4), "/12 refined")


def test_plan_per_tier_contract(corpus):
    """plan(storage="tiered") prices per tier: device = the scan
    structures (the unchanged index_bytes figure), host/disk = the raw
    rows EXACTLY (rows x dim x B — measured-ledger equality, well inside
    the ±20% contract); hbm plans carry zeroed cold tiers."""
    X, _ = corpus
    p = obs_mem.plan("ivf_pq", PARAMS, N, D, storage="tiered")
    assert p["tiers"]["device"] == p["index_bytes"]
    assert p["tiers"]["host"] == N * D * 4 and p["tiers"]["disk"] == 0
    ts = TieredStore(X, name="plan_probe")
    entry = [r for r in obs_mem.breakdown() if r["component"] == "tier"
             and r["name"] == "plan_probe"][0]
    assert entry["host_bytes"] == p["tiers"]["host"], (
        "host tier estimate must match the measured ledger exactly")
    pd = obs_mem.plan("ivf_pq", PARAMS, N, D, storage="tiered",
                      tier=TierPolicy(disk_path="/tmp/x"))
    assert pd["tiers"]["disk"] == N * D * 4 and pd["tiers"]["host"] == 0
    ph = obs_mem.plan("ivf_pq", PARAMS, N, D)
    assert ph["tiers"] == {"device": ph["index_bytes"], "host": 0, "disk": 0}
    pb = obs_mem.plan("brute_force", None, 1000, 32, dtype="int8",
                      storage="tiered")
    assert pb["tiers"]["host"] == 1000 * 32


def test_host_budget_gate(corpus):
    """Resources.host_budget_bytes refuses a RAM-resident store that
    would blow the host budget (whole-or-nothing, the OverloadedError
    taxonomy), while a disk-backed store prices nothing against it."""
    X, _ = corpus
    used_h = obs_mem.totals()["host_bytes"]
    res = Resources(host_budget_bytes=used_h + X.nbytes // 2)
    with pytest.raises(MemoryBudgetError) as ei:
        TieredStore(X, name="hb_refused", res=res)
    assert ei.value.site == "tier/host"
    # same budget, disk-backed: admitted (pages are disk-backed)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        ts = TieredStore(X, name="hb_disk", res=res,
                         policy=TierPolicy(disk_path=f"{td}/cold"))
        assert ts.residency == "disk"


def test_debug_mem_tiers_section(sealed, corpus):
    """/debug/mem carries the tiers section: per-store residency, tier
    bytes and the spill/promote event trail."""
    X, _ = corpus
    m = _wrap(sealed, X, "tiered", "dbg")
    ts = m.tiered_store
    ts.promote(force=True)
    ts.spill()
    payload = obs_mem.debug_payload()
    assert "tiers" in payload
    mine = [s for s in payload["tiers"]["stores"] if s["name"] == "dbg"]
    assert mine and mine[0]["residency"] == "host"
    kinds = [e["event"] for e in mine[0]["events"]]
    assert "promote" in kinds and "spill" in kinds
    assert payload["tiers"]["totals"].get("host", 0) >= X.nbytes


@pytest.mark.slow
def test_tiered_parity_1m():
    """1M-row twin of the parity test (slow manifest): the chunked oracle
    walks 100+ real chunks and refined parity holds at scale."""
    rng = np.random.default_rng(0)
    n, d = 1_000_000, 16
    X = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((64, d)).astype(np.float32)
    p = ivf_pq.IndexParams(n_lists=1024, pq_bits=4, pq_dim=8, seed=0)
    idx = ivf_pq.build(p, X)
    a = MutableIndex(idx, search_params=SP, index_params=p, dataset=X,
                     name="m1_hbm")
    b = MutableIndex(idx, search_params=SP, index_params=p, dataset=X,
                     storage="tiered", name="m1_tier",
                     tier=TierPolicy(oracle_chunk=8192, auto_promote=False))
    assert b.tiered_store.n_oracle_chunks() >= 100
    _assert_bit_equal(a.search_refined(Q, 10, 4), b.search_refined(Q, 10, 4),
                      "1m refined")
    _assert_bit_equal(a.exact_search(Q, 10), b.exact_search(Q, 10),
                      "1m oracle")
