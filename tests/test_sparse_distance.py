"""Sparse distance + sparse kNN tests.

Mirrors the reference's SPARSE_DIST_TEST / SPARSE_NEIGHBORS_TEST suites
(SURVEY.md §4): sparse results must match the dense layer on densified
inputs (the reference compares against host loops)."""

import numpy as np
import pytest
import scipy.sparse as sps

import jax.numpy as jnp

from raft_tpu import sparse
from raft_tpu.distance import pairwise_distance as dense_pairwise

from raft_tpu.distance.types import DistanceType

METRICS = [
    "sqeuclidean",
    "euclidean",
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    "inner_product",
    "l1",
    "canberra",
    "chebyshev",
    "lp",
    "jaccard",
    "cosine",
    "hellinger",
    "dice",
    "correlation",
    "russellrao",
    "hamming",
    "jensenshannon",
    "kl_divergence",
]


def _rand_csr(rng, n, d, density=0.3, binary=False, positive=True):
    raw = sps.random(n, d, density=density, random_state=np.random.RandomState(rng.integers(1 << 30)), format="csr", dtype=np.float32)
    if binary:
        raw.data = np.ones_like(raw.data)
    elif positive:
        raw.data = np.abs(raw.data) + 0.05
    return raw, sparse.from_scipy(raw, cap=raw.nnz + 3)


@pytest.mark.parametrize("metric", METRICS)
def test_sparse_matches_dense(rng, metric):
    binary = metric in ("jaccard", "dice", "russellrao", "hamming")
    x_sp, x = _rand_csr(rng, 18, 25, binary=binary)
    y_sp, y = _rand_csr(rng, 14, 25, binary=binary)
    out = np.asarray(sparse.pairwise_distance(x, y, metric=metric))
    expect = np.asarray(dense_pairwise(jnp.asarray(x_sp.toarray()), jnp.asarray(y_sp.toarray()), metric=metric))
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-4)


def test_sparse_self_distance(rng):
    _, x = _rand_csr(rng, 12, 10)
    out = np.asarray(sparse.pairwise_distance(x, metric="sqeuclidean"))
    assert out.shape == (12, 12)
    np.testing.assert_allclose(np.diag(out), 0, atol=1e-5)


def test_csr_to_ell_roundtrip(rng):
    sp, csr = _rand_csr(rng, 9, 13)
    idx, val = sparse.csr_to_ell(csr)
    dense = np.zeros((9, 14), np.float32)
    np.add.at(dense, (np.arange(9)[:, None], np.asarray(idx)), np.asarray(val))
    np.testing.assert_allclose(dense[:, :13], sp.toarray(), rtol=1e-6)


def test_unsupported_metric_raises(rng):
    _, x = _rand_csr(rng, 5, 5)
    from raft_tpu.core.errors import RaftError

    with pytest.raises(RaftError):
        sparse.pairwise_distance(x, metric="haversine")


class TestSparseKnn:
    def test_knn_vs_numpy(self, rng):
        ds_sp, ds = _rand_csr(rng, 60, 20)
        q_sp, q = _rand_csr(rng, 9, 20)
        d, i = sparse.knn(ds, q, k=5, metric="sqeuclidean")
        full = ((q_sp.toarray()[:, None, :] - ds_sp.toarray()[None]) ** 2).sum(-1)
        expect_i = np.argsort(full, axis=1, kind="stable")[:, :5]
        expect_d = np.take_along_axis(full, expect_i, axis=1)
        np.testing.assert_allclose(np.sort(np.asarray(d), axis=1), expect_d, rtol=1e-4, atol=1e-4)
        # index sets must match (ties aside, data is generic)
        for r in range(9):
            assert set(np.asarray(i)[r]) == set(expect_i[r])

    def test_knn_inner_product_descending(self, rng):
        ds_sp, ds = _rand_csr(rng, 40, 15)
        q_sp, q = _rand_csr(rng, 6, 15)
        d, i = sparse.knn(ds, q, k=4, metric="inner_product")
        full = q_sp.toarray() @ ds_sp.toarray().T
        expect_i = np.argsort(-full, axis=1, kind="stable")[:, :4]
        for r in range(6):
            assert set(np.asarray(i)[r]) == set(expect_i[r])

    def test_knn_graph(self, rng):
        ds_sp, ds = _rand_csr(rng, 30, 12)
        g = sparse.knn_graph(ds, k=3, metric="sqeuclidean")
        assert g.shape == (30, 30)
        assert int(g.nnz) == 90
        rows = np.asarray(g.rows)[: int(g.nnz)]
        cols = np.asarray(g.cols)[: int(g.nnz)]
        assert (rows != cols).all(), "self edges must be excluded"
        # every row has exactly k edges
        np.testing.assert_array_equal(np.bincount(rows, minlength=30), 3)
