"""Gram/kernel matrix, masked NN, epsilon neighborhood, haversine kNN tests.

Analogues of the reference's cpp/test/distance/gram.cu (+gram_base.cuh),
test/distance/masked_nn.cu, test/neighbors/epsilon_neighborhood.cu and
test/neighbors/haversine.cu fixtures: each result is compared against an
independent numpy host reference.
"""

import numpy as np
import pytest

from raft_tpu.distance import KernelParams, KernelType, gram_matrix, kernel_factory, masked_l2_nn
from raft_tpu.neighbors import eps_neighbors_l2sq
from raft_tpu.sparse.types import from_scipy
from raft_tpu.spatial import haversine_knn

ATOL = 2e-4


def _np_gram(params, x, y):
    dot = x @ y.T
    if params.kernel == KernelType.LINEAR:
        return dot
    if params.kernel == KernelType.POLYNOMIAL:
        return (params.gamma * dot + params.coef0) ** params.degree
    if params.kernel == KernelType.TANH:
        return np.tanh(params.gamma * dot + params.coef0)
    d2 = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    return np.exp(-params.gamma * d2)


KERNELS = [
    KernelParams(KernelType.LINEAR),
    KernelParams(KernelType.POLYNOMIAL, degree=2, gamma=0.5, coef0=1.0),
    KernelParams(KernelType.TANH, gamma=0.3, coef0=0.1),
    KernelParams(KernelType.RBF, gamma=0.7),
]


@pytest.mark.parametrize("params", KERNELS, ids=[k.kernel.value for k in KERNELS])
def test_gram_dense(rng, params):
    x = rng.random((23, 11)).astype(np.float32)
    y = rng.random((17, 11)).astype(np.float32)
    got = np.asarray(gram_matrix(params, x, y))
    want = _np_gram(params, x.astype(np.float64), y.astype(np.float64))
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=2e-4)


def test_gram_self_and_factory(rng):
    x = rng.random((15, 7)).astype(np.float32)
    params = KernelParams(KernelType.RBF, gamma=1.3)
    f = kernel_factory(params)
    got = np.asarray(f(x))
    want = _np_gram(params, x.astype(np.float64), x.astype(np.float64))
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=2e-4)
    # self-gram diagonal of RBF is exactly 1
    np.testing.assert_allclose(np.diag(got), 1.0, atol=1e-5)


def test_gram_sparse_input(rng):
    import scipy.sparse as sp

    x = sp.random(20, 12, density=0.3, random_state=1, dtype=np.float32)
    y = rng.random((9, 12)).astype(np.float32)
    params = KernelParams(KernelType.POLYNOMIAL, degree=3, gamma=0.2, coef0=0.5)
    got = np.asarray(gram_matrix(params, from_scipy(x.tocsr()), y))
    want = _np_gram(params, x.toarray().astype(np.float64), y.astype(np.float64))
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=2e-4)


def test_masked_l2_nn(rng):
    m, n, d, g = 25, 40, 8, 4
    x = rng.random((m, d)).astype(np.float32)
    y = rng.random((n, d)).astype(np.float32)
    # groups = 4 contiguous chunks of 10
    group_ends = np.array([10, 20, 30, 40], np.int32)
    adj = rng.random((m, g)) > 0.4

    dists, idx = masked_l2_nn(x, y, adj, group_ends, sqrt=False)
    dists, idx = np.asarray(dists), np.asarray(idx)

    d2 = ((x[:, None, :].astype(np.float64) - y[None, :, :]) ** 2).sum(-1)
    col_group = np.searchsorted(group_ends, np.arange(n), side="right")
    mask = adj[:, col_group]
    d2m = np.where(mask, d2, np.inf)
    want_idx = d2m.argmin(1)
    want_val = d2m.min(1)
    none = ~mask.any(1)
    assert np.all(idx[none] == -1) and np.all(np.isinf(dists[none]))
    ok = ~none
    np.testing.assert_array_equal(idx[ok], want_idx[ok])
    np.testing.assert_allclose(dists[ok], want_val[ok], atol=ATOL, rtol=1e-4)


def test_eps_neighbors(rng):
    x = rng.random((30, 5)).astype(np.float32)
    y = rng.random((22, 5)).astype(np.float32)
    eps = 0.4  # squared radius
    adj, vd = eps_neighbors_l2sq(x, y, eps=eps)
    adj, vd = np.asarray(adj), np.asarray(vd)
    d2 = ((x[:, None, :].astype(np.float64) - y[None, :, :]) ** 2).sum(-1)
    want = d2 <= eps
    np.testing.assert_array_equal(adj, want)
    np.testing.assert_array_equal(vd[:-1], want.sum(1))
    assert vd[-1] == want.sum()


def test_haversine_knn(rng):
    n, m, k = 50, 8, 5
    pts = np.stack(
        [rng.uniform(-np.pi / 2, np.pi / 2, n), rng.uniform(-np.pi, np.pi, n)], axis=1
    ).astype(np.float32)
    q = np.stack(
        [rng.uniform(-np.pi / 2, np.pi / 2, m), rng.uniform(-np.pi, np.pi, m)], axis=1
    ).astype(np.float32)

    dists, idx = haversine_knn(pts, q, k)
    dists, idx = np.asarray(dists), np.asarray(idx)

    def hav(a, b):
        s1 = np.sin(0.5 * (b[:, 0] - a[0]))
        s2 = np.sin(0.5 * (b[:, 1] - a[1]))
        h = s1**2 + np.cos(a[0]) * np.cos(b[:, 0]) * s2**2
        return 2 * np.arcsin(np.sqrt(np.clip(h, 0, 1)))

    for i in range(m):
        all_d = hav(q[i].astype(np.float64), pts.astype(np.float64))
        want = np.sort(all_d)[:k]
        np.testing.assert_allclose(np.sort(dists[i]), want, atol=1e-4)
