"""Label utility tests.

Reference strategy: cpp/test/label/label.cu (make_monotonic vs expected
arrays) and cpp/test/label/merge_labels.cu (hand-built labellings with core
masks and expected merged output) — SURVEY.md §4.
"""

import numpy as np
import jax.numpy as jnp

from raft_tpu import label


class TestClassLabels:
    def test_unique_labels(self, rng):
        y = rng.integers(0, 10, 100)
        got = np.asarray(label.unique_labels(jnp.asarray(y)))
        np.testing.assert_array_equal(got, np.unique(y))

    def test_unique_labels_padded(self, rng):
        y = rng.integers(0, 7, 50).astype(np.int32)
        padded, n_unique = label.unique_labels_padded(jnp.asarray(y))
        ref = np.unique(y)
        assert int(n_unique) == len(ref)
        np.testing.assert_array_equal(np.asarray(padded)[: len(ref)], ref)

    def test_make_monotonic_one_based(self):
        y = jnp.asarray([5, 5, 12, 7, 12, 5])
        out = np.asarray(label.make_monotonic(y))
        np.testing.assert_array_equal(out, [1, 1, 3, 2, 3, 1])

    def test_make_monotonic_zero_based(self, rng):
        y = rng.choice([3, 17, 42, 99], 64)
        out = np.asarray(label.make_monotonic(jnp.asarray(y), zero_based=True))
        _, ref = np.unique(y, return_inverse=True)
        np.testing.assert_array_equal(out, ref)

    def test_make_monotonic_filter(self):
        # sentinel 99 must pass through untouched (reference filter_op contract)
        y = jnp.asarray([10, 99, 20, 10, 99])
        out = np.asarray(label.make_monotonic(y, filter_op=lambda v: v != 99))
        np.testing.assert_array_equal(out, [1, 99, 2, 1, 99])

    def test_ovr_labels(self):
        y = jnp.asarray([2, 4, 4, 8, 2])
        uniq = label.unique_labels(y)
        out = np.asarray(label.get_ovr_labels(y, uniq, 1))
        np.testing.assert_array_equal(out, [0, 1, 1, 0, 0])


class TestMergeLabels:
    MAX = np.iinfo(np.int32).max

    def test_merge_basic(self):
        # A: {0,1} {2,3}; B: {1,2} {3,4-ish} — mask merges everything via 1,2
        la = jnp.asarray([1, 1, 3, 3], jnp.int32)
        lb = jnp.asarray([1, 2, 2, 4], jnp.int32)
        mask = jnp.asarray([True, True, True, True])
        out = np.asarray(label.merge_labels(la, lb, mask))
        np.testing.assert_array_equal(out, [1, 1, 1, 1])

    def test_merge_respects_mask(self):
        la = jnp.asarray([1, 1, 3, 3], jnp.int32)
        lb = jnp.asarray([1, 3, 3, 3], jnp.int32)
        mask = jnp.asarray([True, False, True, True])
        out = np.asarray(label.merge_labels(la, lb, mask))
        # point 1 is not core: its B label does not merge groups 1 and 3,
        # but it still adopts min(R[la], R[lb]) like the reference reassign
        np.testing.assert_array_equal(out, [1, 1, 3, 3])

    def test_merge_vs_connected_components(self, rng):
        # reference doc: merging CC labellings of G_A and G_B gives CC of the
        # union graph — validate against scipy on random graphs
        import scipy.sparse as sps
        import scipy.sparse.csgraph as csgraph

        n = 60
        for seed in range(3):
            r = np.random.default_rng(seed)
            a = sps.random(n, n, density=0.02, random_state=seed, format="csr")
            b = sps.random(n, n, density=0.02, random_state=seed + 100, format="csr")
            _, ca = csgraph.connected_components(a + a.T, directed=False)
            _, cb = csgraph.connected_components(b + b.T, directed=False)
            _, cu = csgraph.connected_components(a + a.T + b + b.T, directed=False)
            # canonical 1..N labelling: min vertex id + 1 per component
            la = np.asarray([np.min(np.where(ca == ca[i])[0]) + 1 for i in range(n)], np.int32)
            lb = np.asarray([np.min(np.where(cb == cb[i])[0]) + 1 for i in range(n)], np.int32)
            out = np.asarray(
                label.merge_labels(jnp.asarray(la), jnp.asarray(lb), jnp.ones(n, bool))
            )
            # same partition as the union graph's components
            for i in range(n):
                for j in range(n):
                    assert (out[i] == out[j]) == (cu[i] == cu[j])
